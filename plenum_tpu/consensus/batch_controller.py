"""Closed-loop AIMD steering of the 3PC batching knobs.

The reference runs `Max3PCBatchSize` / `Max3PCBatchWait` / the in-flight
window as static config: right for exactly one pool shape and wrong for
every other. This controller closes the loop the tracing plane opened
(ROADMAP item 2): the ordering hot path stamps each batch's lifecycle on
the node's INJECTABLE timer — queue wait at cut, cut → commit-quorum span,
group-commit flush span — and every `BATCH_CONTROL_INTERVAL` the
controller folds those samples into rolling per-stage p50/p95 attribution
and moves the knobs toward the latency SLO:

  * **queueing dominates** (queue-wait p95 is the largest stage and the
    SLO is violated): requests sit waiting to be batched — shrink the
    partial-batch wait multiplicatively, and the batch size too when
    batches are being cut full (latency is spent FILLING them).
  * **fixed per-batch costs dominate** (SLO violated, batches underfull,
    3PC/durable spans dominate): per-batch overhead — n² vote floods, BLS
    sign/verify, the flush — is being paid on batches that carry few
    requests. Grow the wait so more requests coalesce per batch, and
    raise group-commit coalescing so flushes amortize.
  * **saturated** (SLO violated, batches full, service spans dominate):
    genuinely too much work in flight — multiplicatively shrink the
    speculative in-flight depth.
  * **headroom** (p95 under SLO): additive increase — deepen the
    pipeline, grow batch size when batches are cut full, and decay an
    episode-grown wait back toward its configured default.

Determinism: every timestamp the controller sees comes from the node's
TimerService and every decision is a pure function of those samples, so a
MockTimer-driven pool adapts identically on every run — there is NO
wall-clock read anywhere in the control path. Decisions are recorded as
tracer span events (`tracing.CONTROLLER`) so `tools/trace_report.py` can
render the control trajectory next to the latency waterfalls it steered.
"""
from __future__ import annotations

from collections import deque
from typing import Optional

from plenum_tpu.common import tracing
from plenum_tpu.common.metrics import MetricsName, percentile
from plenum_tpu.common.timer import TimerService
from plenum_tpu.config import Config

# rolling-window length per stage: long enough that p95 is meaningful,
# short enough that the loop tracks a load shift within a few intervals
_WINDOW = 256


class BatchController:
    """One per node (wired into the MASTER ordering service and the node's
    group-commit drain). Only the node currently acting as master primary
    produces cut/ordered samples, so only its controller actually steers;
    the others idle at their defaults until a view change hands them the
    batching decisions."""

    def __init__(self, config: Config, timer: TimerService,
                 tracer=None, metrics=None):
        self._config = config
        self._timer = timer
        self._tracer = tracer if tracer is not None else tracing.NULL_TRACER
        self._metrics = metrics

        # steered knobs (read by OrderingService / Node every cycle).
        # Coalescing starts WELL BELOW its cap so the grow actions have
        # room to act (starting at the cap made both '+4' paths no-ops);
        # headroom decays it back toward this start value.
        self._coalesce_start = max(1, min(8, config.GROUP_COMMIT_MAX_BATCHES))
        self.batch_size = config.Max3PCBatchSize
        self.batch_wait = config.Max3PCBatchWait
        self.depth = config.Max3PCBatchesInFlight
        self.group_commit_max = self._coalesce_start

        # bounds
        self._size_min = min(config.BATCH_SIZE_MIN, config.Max3PCBatchSize)
        self._size_max = config.Max3PCBatchSize
        self._wait_min = config.BATCH_WAIT_MIN
        self._wait_max = max(config.BATCH_WAIT_MAX, config.Max3PCBatchWait)
        self._depth_min = min(4, config.Max3PCBatchesInFlight)
        self._depth_max = config.Max3PCBatchesInFlight
        self._size_step = max(16, config.Max3PCBatchSize // 16)

        # per-stage samples since the LAST decision, all stamped on the
        # injectable timer (bounded; drained at each tick so a load shift
        # is judged on the current interval's samples, not last epoch's)
        self._queue: deque = deque(maxlen=_WINDOW)    # enqueue -> batch cut
        self._ordering: deque = deque(maxlen=_WINDOW)  # cut -> commit quorum
        self._durable: deque = deque(maxlen=_WINDOW)  # drain -> flush closed
        self._fills: deque = deque(maxlen=_WINDOW)    # reqs per cut batch
        self._fresh = 0          # samples since the last decision

        self.decisions = 0
        self.last_decision: dict = {}
        # batch-SLO ledger for the telemetry plane's burn-rate tracking:
        # one check per decision, a violation when the attributed e2e p95
        # exceeded BATCH_SLO_P95 at that decision (cumulative; snapshot
        # sources take deltas)
        self.slo_checks = 0
        self.slo_violations = 0
        # Decisions are driven by SAMPLE ARRIVALS past the interval
        # deadline, NOT by a free-running RepeatingTimer: a repeating
        # timer fires at clock-STEPPING-dependent instants (a live pool
        # services it mid-prod, the replayer at recorded-event jumps), so
        # timer-driven decisions would break the record/replay
        # byte-identical span guarantee AND could change which batch cut
        # sees a new knob value. A sample arrival happens at a
        # message-processing point whose frozen timestamp is identical in
        # live and replay — decisions keyed to it replay exactly. An idle
        # pool therefore makes no decisions, which is also correct: there
        # is nothing to steer.
        self._next_decision = (timer.get_current_time()
                               + config.BATCH_CONTROL_INTERVAL)

    # --- observations (hot path: append-only, no allocation beyond it) ---

    def note_batch_cut(self, queue_wait: float, n_reqs: int) -> None:
        """A batch was cut: how long its oldest request waited in the
        queue, and how many requests it carries."""
        self._queue.append(max(0.0, queue_wait))
        self._fills.append(n_reqs)
        self._fresh += 1
        self._maybe_tick()

    def note_ordered(self, span: float) -> None:
        """Cut -> commit quorum for one batch (the 3PC span)."""
        self._ordering.append(max(0.0, span))
        self._fresh += 1
        self._maybe_tick()

    def note_durable(self, span: float, n_batches: int) -> None:
        """One group-commit scope closed: flush span over n_batches.
        Timer-stamped — and the QueueTimer latches one timestamp per prod
        cycle, so a scope that opens and closes within one cycle reads 0.
        The durable stage therefore only registers when a flush spills
        across cycles (a genuinely slow flush); the routine flush cost
        rides inside the cut->quorum ordering span of the NEXT batches,
        which is the span the controller steers against."""
        self._durable.append(max(0.0, span))
        self._fresh += 1
        self._maybe_tick()

    def _maybe_tick(self) -> None:
        now = self._timer.get_current_time()
        if now >= self._next_decision:
            self._next_decision = now + self._config.BATCH_CONTROL_INTERVAL
            self.tick()

    # --- the control loop -------------------------------------------------

    def stage_p95(self) -> dict:
        return {
            "queue": percentile(self._queue, 0.95) if self._queue else 0.0,
            "ordering": (percentile(self._ordering, 0.95)
                         if self._ordering else 0.0),
            "durable": (percentile(self._durable, 0.95)
                        if self._durable else 0.0),
        }

    def stage_p50(self) -> dict:
        return {
            "queue": percentile(self._queue, 0.5) if self._queue else 0.0,
            "ordering": (percentile(self._ordering, 0.5)
                         if self._ordering else 0.0),
            "durable": (percentile(self._durable, 0.5)
                        if self._durable else 0.0),
        }

    def tick(self) -> None:
        """One AIMD decision from the rolling attribution. Pure function
        of timer-stamped samples — no wall-clock reads."""
        if not self._fresh:
            return                      # idle pool: hold every knob
        self._fresh = 0
        st = self.stage_p95()
        # decision-time attribution snapshot: trajectory() reports THESE
        # (the windows are drained below, so reading them later would show
        # only the post-decision tail)
        self._decided_p50 = self.stage_p50()
        self._decided_p95 = st
        q, o, d = st["queue"], st["ordering"], st["durable"]
        e2e = q + o + d
        slo = self._config.BATCH_SLO_P95
        fill = (sum(self._fills) / len(self._fills) / max(1, self.batch_size)
                if self._fills else 0.0)
        self.slo_checks += 1
        if e2e > slo:
            self.slo_violations += 1
            if q >= max(o, d):
                # requests spend their latency WAITING to be batched
                verdict = "shrink:queueing"
                self.batch_wait = max(self._wait_min, self.batch_wait * 0.5)
                if fill >= 0.9:
                    self.batch_size = max(self._size_min,
                                          int(self.batch_size * 0.7))
            elif fill < 0.5:
                # per-batch overhead paid on underfull batches: coalesce
                verdict = "grow:fixed-cost"
                self.batch_wait = min(self._wait_max, self.batch_wait * 1.5)
                self.group_commit_max = min(
                    self._config.GROUP_COMMIT_MAX_BATCHES,
                    self.group_commit_max + 4)
            else:
                # full batches, service-side spans over SLO: back off depth
                verdict = "shrink:depth"
                self.depth = max(self._depth_min, int(self.depth * 0.7))
                self.group_commit_max = min(
                    self._config.GROUP_COMMIT_MAX_BATCHES,
                    self.group_commit_max + 4)
        else:
            verdict = "grow:headroom"
            self.depth = min(self._depth_max, self.depth + 1)
            if fill >= 0.9:
                self.batch_size = min(self._size_max,
                                      self.batch_size + self._size_step)
            # decay episode-grown knobs back toward their starting values
            if self.batch_wait > self._config.Max3PCBatchWait:
                self.batch_wait = max(self._config.Max3PCBatchWait,
                                      self.batch_wait * 0.9)
            if self.group_commit_max > self._coalesce_start:
                self.group_commit_max -= 1
        self.decisions += 1
        # judged: the next interval starts from its own samples, so a
        # load SHIFT moves the knobs within one control interval instead
        # of waiting for stale samples to age out of a rolling window
        self._queue.clear()
        self._ordering.clear()
        self._durable.clear()
        self._fills.clear()
        self.last_decision = {
            "verdict": verdict,
            "batch_size": self.batch_size,
            "wait_ms": round(self.batch_wait * 1000, 3),
            "depth": self.depth,
            "coalesce": self.group_commit_max,
            "p95_ms": {k: round(v * 1000, 3) for k, v in st.items()},
            "e2e_p95_ms": round(e2e * 1000, 3),
            "slo_ms": round(slo * 1000, 3),
            "fill": round(fill, 3),
        }
        if self._tracer.enabled:
            self._tracer.emit(tracing.CONTROLLER, "", self.last_decision)
        if self._metrics is not None:
            self._metrics.add_event(MetricsName.BATCH_CTL_SIZE,
                                    self.batch_size)
            self._metrics.add_event(MetricsName.BATCH_CTL_WAIT,
                                    self.batch_wait)
            self._metrics.add_event(MetricsName.BATCH_CTL_DEPTH, self.depth)
            self._metrics.add_event(MetricsName.BATCH_CTL_COALESCE,
                                    self.group_commit_max)
            # cumulative gauge (read back via max, like breaker_opens)
            self._metrics.add_event(MetricsName.BATCH_CTL_DECISIONS,
                                    self.decisions)

    # --- reporting (bench line / validator info) --------------------------

    def trajectory(self) -> dict:
        """Compact summary for the bench line: where the knobs ENDED and
        the rolling attribution that put them there — the LAST DECISION's
        snapshot (the live windows are drained at each decision, so they
        only hold the post-decision tail; before any decision they are
        the whole story and are used directly)."""
        p50 = getattr(self, "_decided_p50", None) or self.stage_p50()
        p95 = getattr(self, "_decided_p95", None) or self.stage_p95()
        return {
            "decisions": self.decisions,
            "batch_size": self.batch_size,
            "wait_ms": round(self.batch_wait * 1000, 3),
            "depth": self.depth,
            "coalesce": self.group_commit_max,
            "slo_ms": round(self._config.BATCH_SLO_P95 * 1000, 3),
            "stage_p50_ms": {k: round(v * 1000, 3) for k, v in p50.items()},
            "stage_p95_ms": {k: round(v * 1000, 3) for k, v in p95.items()},
            **({"last": self.last_decision} if self.last_decision else {}),
        }


def make_controller(config: Config, timer: TimerService, tracer=None,
                    metrics=None) -> Optional[BatchController]:
    """Config-gated construction seam: BATCH_CONTROLLER=False -> None, and
    every consumer falls back to the static config knobs."""
    if not getattr(config, "BATCH_CONTROLLER", True):
        return None
    return BatchController(config, timer, tracer=tracer, metrics=metrics)
