"""Primary-health watchdog: detect a dead or stalling primary and vote.

Reference behavior: the reference detects a bad master primary three ways —
primary disconnect (plenum/server/consensus/monitoring/
primary_connection_monitor_service.py, node.py:511), ordering stalls on
finalized requests (unordered-request checks via the monitor,
monitor.py:425), and state-freshness stalls (ordering_service.py:1991 +
suspicion STATE_SIGS_ARE_NOT_UPDATED). This service folds all three into
one watchdog on the master instance of every non-primary node:

- DISCONNECT (fast path): transport Connected/Disconnected events arrive
  on the ExternalBus; losing the primary's connection schedules a vote
  PRIMARY_DISCONNECT_TIMEOUT later (seconds, not the 30s+ stall windows),
  cancelled if the primary comes back first.
- ordering stall: work to order but no 3PC progress within
  ORDERING_PROGRESS_TIMEOUT.
- freshness: nothing ordered at all beyond the freshness interval.

Every vote rides the normal InstanceChange f+1 quorum, so a single slow
or partitioned node cannot force a view change alone.
"""
from __future__ import annotations

from typing import Callable, Optional

from plenum_tpu.common.event_bus import ExternalBus, InternalBus
from plenum_tpu.common.internal_messages import VoteForViewChange
from plenum_tpu.common.suspicion_codes import Suspicions
from plenum_tpu.common.timer import RepeatingTimer, TimerService
from plenum_tpu.config import Config

from .consensus_shared_data import ConsensusSharedData


class PrimaryHealthService:
    def __init__(self,
                 data: ConsensusSharedData,
                 timer: TimerService,
                 bus: InternalBus,
                 has_pending_work: Callable[[], bool],
                 config: Optional[Config] = None,
                 network: Optional[ExternalBus] = None,
                 rtt=None):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._has_pending_work = has_pending_work
        self._config = config or Config()
        self._network = network
        # shared RTT estimate: a stall window tuned for a LAN reads a
        # merely-slow WAN primary as dead and storms view changes. The
        # configured timeouts stay the FLOOR (clean networks unchanged);
        # a measured slow network stretches them (VC_ADAPTIVE_TIMEOUTS).
        self._rtt = rtt

        self._progress_marker = data.last_ordered_3pc
        self._stall_since: Optional[float] = None
        now = timer.get_current_time()
        self._last_order_time = now
        self._ticker = RepeatingTimer(
            timer, self._config.PRIMARY_HEALTH_CHECK_FREQ, self.check)
        if network is not None:
            network.subscribe(ExternalBus.Disconnected,
                              self._on_peer_disconnected)
            network.subscribe(ExternalBus.Connected, self._on_peer_connected)

    def stop(self) -> None:
        self._ticker.stop()
        self._timer.cancel(self._disconnect_check)

    # --- primary-disconnect fast path --------------------------------- #

    def _on_peer_disconnected(self, msg, frm=None) -> None:
        if msg.name == self._data.primary_name and not self._data.is_primary:
            self._timer.cancel(self._disconnect_check)   # no double votes
            self._timer.schedule(self._config.PRIMARY_DISCONNECT_TIMEOUT,
                                 self._disconnect_check)

    def _on_peer_connected(self, msg, frm=None) -> None:
        if msg.name == self._data.primary_name:
            self._timer.cancel(self._disconnect_check)

    def _disconnect_check(self) -> None:
        """Fires PRIMARY_DISCONNECT_TIMEOUT after losing the primary; the
        state is re-validated at fire time (a view change may have picked a
        new primary, or the old one may be back), and the vote repeats on
        the same cadence while the primary stays gone."""
        primary = self._data.primary_name
        if (self._network is None or primary is None
                or self._data.is_primary
                or primary in self._network.connecteds):
            return      # resolved: reconnected, new primary, or we lead now
        if (not self._data.is_participating
                or self._data.waiting_for_new_view):
            # TRANSIENT (catchup / view change in flight): re-arm rather
            # than disarm — no new Disconnected event will fire for an
            # already-lost connection, and the primary may still be gone
            # when we finish syncing
            self._timer.schedule(self._config.PRIMARY_DISCONNECT_TIMEOUT,
                                 self._disconnect_check)
            return
        self._vote(Suspicions.PRIMARY_DISCONNECTED)
        self._timer.schedule(self._config.PRIMARY_DISCONNECT_TIMEOUT,
                             self._disconnect_check)

    # ------------------------------------------------------------------ #

    def check(self) -> None:
        now = self._timer.get_current_time()
        if self._data.last_ordered_3pc != self._progress_marker:
            self._progress_marker = self._data.last_ordered_3pc
            self._last_order_time = now
            self._stall_since = None
        if (not self._data.is_participating
                or self._data.waiting_for_new_view
                or self._data.is_primary):
            self._stall_since = None
            self._last_order_time = now
            return
        self._check_ordering_progress(now)
        self._check_freshness(now)

    def _stretch(self, flat: float, mult: float) -> float:
        """RTT-informed stall window: max(configured flat value, mult
        measured round trips) — ordering a batch is a few sequential
        broadcasts, so `mult * rto` bounds how long a HEALTHY primary can
        legitimately take on this network."""
        if (self._rtt is None or self._rtt.srtt is None
                or not getattr(self._config, "VC_ADAPTIVE_TIMEOUTS", False)):
            return flat
        cap = getattr(self._config, "VC_TIMEOUT_MAX", 4 * flat)
        return min(max(flat, cap), max(
            flat, mult * self._rtt.timeout(floor=0.0, cap=cap,
                                           fallback=flat)))

    def _check_ordering_progress(self, now: float) -> None:
        """Finalized-but-unordered work + no 3PC progress = stalled primary."""
        if not self._has_pending_work():
            self._stall_since = None
            return
        if self._stall_since is None:
            self._stall_since = now
            return
        timeout = self._stretch(self._config.ORDERING_PROGRESS_TIMEOUT,
                                mult=10.0)
        if now - self._stall_since >= timeout:
            self._vote(Suspicions.PRIMARY_STALLED)
            self._stall_since = now          # re-vote each timeout period

    def _check_freshness(self, now: float) -> None:
        """A live primary orders SOMETHING (a freshness batch at minimum)
        every STATE_FRESHNESS_UPDATE_INTERVAL; silence far beyond that means
        the primary is gone even if no client traffic is pending."""
        interval = self._config.STATE_FRESHNESS_UPDATE_INTERVAL
        if interval <= 0:
            return        # freshness disabled: mirror _send_freshness_batches
        limit = self._stretch(interval * 1.5, mult=10.0)
        if now - self._last_order_time >= limit:
            self._vote(Suspicions.STATE_SIGS_ARE_NOT_UPDATED)
            self._last_order_time = now      # re-vote cadence, not a reset

    def _vote(self, suspicion) -> None:
        self._bus.send(VoteForViewChange(suspicion_code=suspicion.code))
