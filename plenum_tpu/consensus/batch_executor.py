"""Execution seam between consensus and the request-execution layer.

Reference behavior: the OrderingService applies requests to *uncommitted*
ledger/state before consensus completes (ordering_service.py:1138
_apply_pre_prepare via write_manager.apply_request) and reverts them on
rejection or view change (:1229 _revert). Consensus only sees this narrow
protocol; the real implementation is the WriteRequestManager + batch handlers
(plenum_tpu/execution/), and tests drive consensus with the in-memory stub.
"""
from __future__ import annotations

import hashlib
from abc import ABC, abstractmethod
from contextlib import nullcontext
from typing import NamedTuple, Optional, Sequence

from plenum_tpu.common.request import Request


class AppliedBatch(NamedTuple):
    state_root: str                 # uncommitted state root AFTER apply (base58/hex)
    txn_root: str                   # uncommitted txn-ledger root AFTER apply
    pool_state_root: str
    audit_txn_root: str
    valid_digests: tuple[str, ...]  # requests applied
    discarded: tuple[str, ...]      # requests rejected by dynamic validation


class BatchExecutor(ABC):
    """What consensus needs from the execution layer — nothing more."""

    @abstractmethod
    def apply_batch(self, ledger_id: int, requests: Sequence[Request],
                    pp_time: float, view_no: int, pp_seq_no: int,
                    primaries=None) -> AppliedBatch:
        """Dynamic-validate + apply to uncommitted ledger/state; returns
        roots. view_no/primaries are the batch's ORIGINAL view and its
        primaries (audit-txn reproducibility across re-ordering)."""

    @abstractmethod
    def revert_last_batch(self, ledger_id: int) -> None:
        """Undo the most recently applied uncommitted batch for this ledger."""

    @abstractmethod
    def ledger_id_for(self, request: Request) -> int:
        """Which ledger a request's txn type writes to."""

    def group_commit(self):
        """Context manager grouping every durable write issued inside into
        one atomic flush per store. Executors without durable storage
        (this default) make it a no-op scope."""
        return nullcontext(self)


class SimBatchExecutor(BatchExecutor):
    """Deterministic in-memory executor for consensus unit/sim tests: the
    'state' is a hash chain over applied request digests, so identical request
    streams yield identical roots on every node — and nothing else."""

    def __init__(self, reject: Optional[set[str]] = None):
        self.applied: list[tuple[int, tuple[str, ...]]] = []   # (ledger_id, digests)
        self.committed: list[tuple[str, ...]] = []
        self.reject = reject or set()
        self._roots: dict[int, str] = {}

    def _root(self, ledger_id: int) -> str:
        return self._roots.get(ledger_id, "genesis")

    def apply_batch(self, ledger_id, requests, pp_time, view_no, pp_seq_no,
                    primaries=None):
        valid, discarded = [], []
        for req in requests:
            (discarded if req.digest in self.reject else valid).append(req.digest)
        mix = self._root(ledger_id) + "".join(valid) + str(pp_seq_no)
        new_root = hashlib.sha256(mix.encode()).hexdigest()
        self.applied.append((ledger_id, tuple(valid)))
        prev = self._roots.copy()
        self._roots[ledger_id] = new_root
        self._prev_roots = getattr(self, "_prev_roots", [])
        self._prev_roots.append(prev)
        return AppliedBatch(state_root=new_root,
                            txn_root=new_root[:32],
                            pool_state_root=self._root(0),
                            audit_txn_root=new_root[32:],
                            valid_digests=tuple(valid),
                            discarded=tuple(discarded))

    def revert_last_batch(self, ledger_id: int) -> None:
        for i in range(len(self.applied) - 1, -1, -1):
            if self.applied[i][0] == ledger_id:
                self.applied.pop(i)
                self._roots = self._prev_roots.pop(i)
                return
        raise ValueError(f"no applied batch for ledger {ledger_id}")

    def ledger_id_for(self, request: Request) -> int:
        from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
        return DOMAIN_LEDGER_ID
