"""Replica: one protocol instance = shared data + the consensus services wired
over a private internal bus.

Reference behavior: plenum/server/replica.py:84 (service wiring :151-171) and
replicas.py:19 (the master + backup collection; RBFT runs f+1 instances and
the monitor compares master vs backup throughput, SURVEY.md §2.3). Event glue
reproduced here: NewViewAccepted → checkpoint reset → NewViewCheckpointsApplied
→ ordering re-orders; CheckpointStabilized → ordering GC.
"""
from __future__ import annotations

from typing import Callable, Optional

from plenum_tpu.common.event_bus import ExternalBus, InternalBus
from plenum_tpu.common.internal_messages import (CheckpointStabilized,
                                                 NewViewAccepted,
                                                 NewViewCheckpointsApplied,
                                                 ViewChangeStarted)
from plenum_tpu.common.request import Request
from plenum_tpu.common.timer import TimerService
from plenum_tpu.config import Config

from .batch_executor import BatchExecutor
from .bls_bft_replica import BlsBftReplica
from .checkpoint_service import CheckpointService
from .consensus_shared_data import ConsensusSharedData, replica_name
from .ordering_service import OrderingService
from .primary_health_service import PrimaryHealthService
from .primary_selector import RoundRobinPrimariesSelector
from .view_change_service import ViewChangeService
from .view_change_trigger_service import ViewChangeTriggerService


class Replica:
    def __init__(self,
                 node_name: str,
                 inst_id: int,
                 validators: list[str],
                 timer: TimerService,
                 network: ExternalBus,
                 executor: Optional[BatchExecutor] = None,
                 bls: Optional[BlsBftReplica] = None,
                 config: Optional[Config] = None,
                 get_request: Optional[Callable[[str], Optional[Request]]] = None,
                 checkpoint_digest_provider=None,
                 instance_count: int = 1,
                 external_internal_bus: Optional[InternalBus] = None,
                 metrics=None,
                 ic_vote_store=None,
                 tracer=None,
                 controller=None,
                 rtt=None):
        self.name = replica_name(node_name, inst_id)
        self.inst_id = inst_id
        self.config = config or Config()
        self.internal_bus = external_internal_bus or InternalBus()
        self.network = network

        self._data = ConsensusSharedData(self.name, validators, inst_id,
                                         is_master=(inst_id == 0))
        selector = RoundRobinPrimariesSelector()
        self._data.primaries = selector.select_primaries(
            0, instance_count, validators)

        self.bls = bls
        if bls is not None:
            bls.set_quorums(self._data.quorums)

        # closed-loop batch controller: a MASTER-instance concern (backup
        # instances shadow-order the same traffic; steering their batching
        # would fight the monitor's master-vs-backup comparison)
        self.batch_controller = controller if self._data.is_master else None
        self.ordering = OrderingService(
            data=self._data, timer=timer, bus=self.internal_bus,
            network=network, executor=executor, bls=bls, config=self.config,
            get_request=get_request, metrics=metrics, tracer=tracer,
            controller=self.batch_controller)
        self.checkpointer = CheckpointService(
            data=self._data, bus=self.internal_bus, network=network,
            config=self.config,
            checkpoint_digest_provider=checkpoint_digest_provider)
        # View change is a NODE-level event driven by the MASTER instance only:
        # ViewChange/ViewChangeAck/NewView/InstanceChange carry no inst_id on
        # the wire (matching the reference), so giving every backup its own
        # view-change machinery on the shared bus makes instances impersonate
        # each other's votes. Backups follow the master's completed view change
        # via Replica.adopt_new_view (driven by the node).
        self.view_changer: Optional[ViewChangeService] = None
        self.vc_trigger: Optional[ViewChangeTriggerService] = None
        self.primary_health: Optional[PrimaryHealthService] = None
        if self._data.is_master:
            self.view_changer = ViewChangeService(
                data=self._data, timer=timer, bus=self.internal_bus,
                network=network, config=self.config, selector=selector,
                instance_count=instance_count, rtt=rtt)
            self.vc_trigger = ViewChangeTriggerService(
                data=self._data, timer=timer, bus=self.internal_bus,
                network=network, config=self.config,
                vote_store=ic_vote_store)
            self.primary_health = PrimaryHealthService(
                data=self._data, timer=timer, bus=self.internal_bus,
                has_pending_work=self.has_unordered_work, config=self.config,
                network=network, rtt=rtt)

        self.internal_bus.subscribe(NewViewAccepted, self._on_new_view_accepted)
        self.internal_bus.subscribe(CheckpointStabilized, self._on_checkpoint_stable)

    def stop(self) -> None:
        """Detach this instance from the shared node buses and timers. A
        replica removed as faulty (node._process_backup_faulty) must become
        inert — a popped-but-subscribed instance would keep processing 3PC
        traffic as a zombie and, once the view change re-creates the id,
        two replicas would speak with one name."""
        self.ordering.stop()
        self.checkpointer.stop()
        if self.primary_health is not None:
            self.primary_health.stop()

    def has_unordered_work(self) -> bool:
        """Finalized requests queued, or batches pre-prepared but unordered.
        preprepared CERTIFICATES survive ordering until checkpoint GC (they
        back view-change proofs), so only batches BEYOND last_ordered count
        as pending — a stabilization-lagged cert must not read as a stalled
        primary."""
        if any(self.ordering.request_queues.values()):
            return True
        last = self._data.last_ordered_3pc
        return any((b.view_no, b.pp_seq_no) > last
                   for b in self._data.preprepared)

    def adopt_new_view(self, view_no: int, primaries: list[str]) -> None:
        """Backup instance follows a master-completed view change: take the
        new view and primaries, drop in-flight 3PC work, and realign the
        batch counter so the instance's new primary continues the sequence
        (ref: node-level primary re-selection on view change; backups restart
        from their own last ordered position)."""
        if self._data.is_master or view_no <= self._data.view_no:
            return
        self._data.view_no = view_no
        self._data.primaries = list(primaries)
        self._data.waiting_for_new_view = False
        self.ordering.process_view_change_started(
            ViewChangeStarted(view_no=view_no))
        # Continue numbering from this instance's own ordered prefix.
        floor = self._data.last_ordered_3pc[1]
        self.ordering.process_new_view_checkpoints_applied(
            NewViewCheckpointsApplied(view_no=view_no,
                                      checkpoint=(0, 0, floor, ""),
                                      batches=()))

    # --- event glue -------------------------------------------------------

    def _on_new_view_accepted(self, msg: NewViewAccepted) -> None:
        self.checkpointer.process_new_view_accepted(msg.checkpoint)
        self.internal_bus.send(NewViewCheckpointsApplied(
            view_no=msg.view_no, checkpoint=msg.checkpoint, batches=msg.batches))

    def _on_checkpoint_stable(self, msg: CheckpointStabilized) -> None:
        self.ordering.gc(msg.last_stable_3pc)

    # --- accessors --------------------------------------------------------

    @property
    def data(self) -> ConsensusSharedData:
        return self._data

    @property
    def is_master(self) -> bool:
        return self._data.is_master

    @property
    def is_primary(self) -> bool:
        return self._data.is_primary

    @property
    def view_no(self) -> int:
        return self._data.view_no

    @property
    def last_ordered_3pc(self) -> tuple[int, int]:
        return self._data.last_ordered_3pc

    def set_validators(self, validators: list[str]) -> None:
        self._data.set_validators(validators)
        if self.bls is not None:
            self.bls.set_quorums(self._data.quorums)

    def service(self) -> None:
        """One prod cycle: primaries flush queued requests into batches."""
        self.ordering.service()


class Replicas:
    """The RBFT instance collection: instance 0 is the master, the rest shadow
    (ref replicas.py:19, adjustReplicas node.py:1260).

    Keyed by inst_id (not list position): removing a faulty backup (ref
    backup_instance_faulty_processor) leaves a GAP, and the surviving
    instances must keep their ids — 3PC messages carry inst_id on the wire.
    `grow_to` fills gaps, which is also how a removed backup is re-added
    fresh at the next view change."""

    def __init__(self, make_replica: Callable[[int], Replica]):
        self._make = make_replica
        self._replicas: dict[int, Replica] = {}

    def grow_to(self, count: int, skip: set[int] = frozenset()) -> None:
        """Create every missing instance below `count`, except ids in
        `skip` (backups removed as faulty stay out until a view change
        clears them)."""
        for inst_id in range(count):
            if inst_id not in self._replicas and inst_id not in skip:
                self._replicas[inst_id] = self._make(inst_id)

    def shrink_to(self, count: int) -> None:
        for inst_id in [i for i in self._replicas if i >= count]:
            self._replicas.pop(inst_id).stop()

    def remove_instance(self, inst_id: int) -> Optional[Replica]:
        """Drop a faulty BACKUP instance (master is never removable). The
        dropped replica is detached (stop()) so it cannot keep processing
        shared-bus traffic as a zombie."""
        if inst_id == 0:
            raise ValueError("the master instance cannot be removed")
        removed = self._replicas.pop(inst_id, None)
        if removed is not None:
            removed.stop()
        return removed

    @property
    def master(self) -> Replica:
        return self._replicas[0]

    @property
    def instance_ids(self) -> list[int]:
        return sorted(self._replicas)

    def __iter__(self):
        return iter(self._replicas[i] for i in sorted(self._replicas))

    def __len__(self):
        return len(self._replicas)

    def __contains__(self, inst_id: int) -> bool:
        return inst_id in self._replicas

    def __getitem__(self, inst_id: int) -> Replica:
        return self._replicas[inst_id]

    def service_all(self) -> None:
        for replica in self:
            replica.service()
