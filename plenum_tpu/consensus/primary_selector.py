"""Primary selection: deterministic round-robin over the validator registry.

Reference behavior: plenum/server/consensus/primary_selector.py:11,52 — the
master primary for view v is validators[v mod N]; backup instance i takes the
next rank (v + i) mod N. All nodes compute the same list locally; nothing is
negotiated.
"""
from __future__ import annotations


class RoundRobinPrimariesSelector:
    def select_primaries(self, view_no: int, instance_count: int,
                         validators: list[str]) -> list[str]:
        n = len(validators)
        if n == 0:
            return []
        return [validators[(view_no + i) % n] for i in range(instance_count)]

    def select_master_primary(self, view_no: int, validators: list[str]) -> str:
        return self.select_primaries(view_no, 1, validators)[0]
