from .batch_id import BatchID
from .consensus_shared_data import ConsensusSharedData
from .primary_selector import RoundRobinPrimariesSelector

__all__ = ["BatchID", "ConsensusSharedData", "RoundRobinPrimariesSelector"]
