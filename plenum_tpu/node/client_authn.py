"""Client request authentication — the primary Ed25519 hot spot, batch-first.

Reference behavior: plenum/server/client_authn.py (NaclAuthNr:82 scalar verify
per signer, CoreAuthNr:273 resolving DID→verkey from domain state) and
req_authenticator.py:11 — every node verifies every propagated request
(node.py:2624), which is why SURVEY.md §3.2 marks this n×-per-request path as
the throughput ceiling.

TPU-first design difference: the API is batch-shaped end to end.
`authenticate_batch` collects every (message, signature, verkey) triple across
a whole quota of requests and issues ONE device dispatch through the
Ed25519Verifier seam; per-request verdicts map back to accept/reject exactly
like the reference's per-message path (SURVEY.md §7 hard part 1).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from plenum_tpu.common.request import Request
from plenum_tpu.crypto.ed25519 import Ed25519Verifier, make_verifier
from plenum_tpu.utils.base58 import b58decode


class AuthError(Exception):
    pass


class MissingSignature(AuthError):
    pass


class InvalidSignature(AuthError):
    pass


class UnknownIdentifier(AuthError):
    pass


class CoreAuthNr:
    """Verifies request signatures against DID verkeys from domain state.

    get_verkey(did) -> base58 verkey or None; abbreviated verkeys ("~xxx")
    are completed with the DID prefix bytes, as indy DIDs do.
    """

    def __init__(self, verifier: Optional[Ed25519Verifier] = None,
                 get_verkey=None):
        self.verifier = verifier or make_verifier("cpu")
        self._get_verkey = get_verkey or (lambda did: None)

    def _resolve_verkey(self, idr: str) -> Optional[bytes]:
        vk = self._get_verkey(idr)
        if vk is None:
            # self-certifying DID: identifier IS the verkey (or its prefix)
            try:
                raw = b58decode(idr)
            except Exception:
                return None
            return raw if len(raw) == 32 else None
        try:
            if vk.startswith("~"):     # abbreviated: DID bytes || suffix
                return b58decode(idr) + b58decode(vk[1:])
            return b58decode(vk)
        except Exception:
            return None

    def collect_items(self, request: Request) -> Optional[list[tuple[bytes, bytes, bytes]]]:
        """(msg, sig, vk) per signer, or None if any signer is unresolvable.
        Raises MissingSignature when no signature is present at all."""
        sigs = request.all_signatures()
        if not sigs:
            raise MissingSignature(f"request {request.req_id} is unsigned")
        # a named endorser MUST be a signer: authorization will count the
        # endorser's role, so an unsigned endorsement would let anyone
        # borrow a trustee's permissions by just naming them
        if request.endorser is not None and request.endorser not in sigs:
            return None
        msg = request.signing_bytes()
        items = []
        for idr, sig_b58 in sigs.items():
            vk = self._resolve_verkey(idr)
            if vk is None:
                return None
            try:
                sig = b58decode(sig_b58)
            except Exception:
                return None
            items.append((msg, sig, vk))
        return items

    def authenticate(self, request: Request) -> list[str]:
        """-> list of verified identifiers; raises on failure."""
        verdicts = self.authenticate_batch([request])
        if not verdicts[0]:
            raise InvalidSignature(f"request {request.req_id} failed auth")
        return list(request.all_signatures())

    def submit_batch(self, requests: Sequence[Request]):
        """Stage ONE device dispatch for all signatures of all requests;
        returns an opaque token for collect_batch. The dispatch is
        asynchronous on the jax backend — callers can overlap the device
        round-trip with other work (the node's pipelined prod loop does)."""
        spans: list[tuple[int, int]] = []       # [start, end) into items
        items: list[tuple[bytes, bytes, bytes]] = []
        hard_fail = np.zeros(len(requests), dtype=bool)
        for i, req in enumerate(requests):
            try:
                got = self.collect_items(req)
            except MissingSignature:
                got = None
            if got is None:
                hard_fail[i] = True
                spans.append((len(items), len(items)))
                continue
            spans.append((len(items), len(items) + len(got)))
            items.extend(got)
        vtoken = self.verifier.submit_batch(items) if items else None
        return (spans, hard_fail, vtoken, len(requests))

    def collect_batch(self, token, wait: bool = True) -> Optional[np.ndarray]:
        """-> bool[N] verdicts, or None if wait=False and the device is
        still computing. A request passes only if EVERY signer's signature
        verifies (multi-sig endorsement, ref authenticate_multi:84)."""
        spans, hard_fail, vtoken, n = token
        if vtoken is not None:
            ok = self.verifier.collect_batch(vtoken, wait=wait)
            if ok is None:
                return None
        else:
            ok = np.zeros(0, dtype=bool)
        out = np.zeros(n, dtype=bool)
        for i, (start, end) in enumerate(spans):
            out[i] = (not hard_fail[i]) and bool(ok[start:end].all()) \
                and end > start
        return out

    def authenticate_batch(self, requests: Sequence[Request]) -> np.ndarray:
        return self.collect_batch(self.submit_batch(requests), wait=True)

    @staticmethod
    def token_item_count(token) -> int:
        """Signature items staged behind one submit_batch token — the
        measured auth batch size (in device-verify items, which exceeds
        the request count for multi-signed requests)."""
        spans, _hard_fail, _vtoken, _n = token
        return spans[-1][1] if spans else 0


class ReqAuthenticator:
    """Registry of authenticators; all registered must accept
    (ref req_authenticator.py:23)."""

    def __init__(self):
        self._authnrs: list[CoreAuthNr] = []

    def register_authenticator(self, authnr: CoreAuthNr) -> None:
        self._authnrs.append(authnr)

    @property
    def core_authenticator(self) -> CoreAuthNr:
        return self._authnrs[0]

    def authenticate(self, request: Request) -> list[str]:
        out: list[str] = []
        for a in self._authnrs:
            out = a.authenticate(request)
        return out

    def authenticate_batch(self, requests: Sequence[Request]) -> np.ndarray:
        verdict = np.ones(len(requests), dtype=bool)
        for a in self._authnrs:
            verdict &= a.authenticate_batch(requests)
        return verdict

    def submit_batch(self, requests: Sequence[Request]):
        return [a.submit_batch(requests) for a in self._authnrs]

    def token_item_count(self, tokens) -> int:
        """Device-verify items staged by the FIRST (core) authenticator's
        dispatch for a submit_batch token list — the figure the ingress
        plane publishes as its measured auth batch size."""
        if not tokens:
            return 0
        return CoreAuthNr.token_item_count(tokens[0])

    def collect_batch(self, tokens, wait: bool = True) -> Optional[np.ndarray]:
        """None while ANY registered authenticator's device is busy."""
        verdicts = []
        for a, token in zip(self._authnrs, tokens):
            v = a.collect_batch(token, wait=wait)
            if v is None:
                return None
            verdicts.append(v)
        out = verdicts[0]
        for v in verdicts[1:]:
            out &= v
        return out
