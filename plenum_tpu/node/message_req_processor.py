"""MessageReq/MessageRep: ask peers for a message we missed.

Reference behavior: plenum/server/message_req_processor.py:13 +
consensus/message_request/ — a node that detects a gap (a PRE-PREPARE it
only knows through PREPARE votes, a PROPAGATE it never received, a cited
VIEW_CHANGE vote it lacks, a NEW_VIEW that never arrived) broadcasts
MessageReq(msg_type, params); any peer holding the message answers with
MessageRep carrying it. Replies are never taken on trust: each type has a
validation anchor (prepare-quorum digest for PRE-PREPARE, client signature
via the normal propagate pipeline for PROPAGATE, the NewView's cited digest
for VIEW_CHANGE, full re-derivation for NEW_VIEW), so a lying responder
can waste bandwidth but not inject state.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from plenum_tpu.common.internal_messages import MissingMessage
from plenum_tpu.common.message_base import message_from_dict
from plenum_tpu.common.node_messages import (MessageRep, MessageReq, NewView,
                                             PrePrepare, Propagate, ViewChange)

PROPAGATE = "PROPAGATE"
PREPREPARE = "PREPREPARE"
OLD_VIEW_PREPREPARE = "OLD_VIEW_PREPREPARE"
VIEW_CHANGE = "VIEW_CHANGE"
NEW_VIEW = "NEW_VIEW"


class MessageReqProcessor:
    """Node-level service: serves peers' MessageReqs from local stores and
    turns local MissingMessage events into MessageReqs."""

    THROTTLE = 3.0          # at most one identical request per this many secs

    def __init__(self, node):
        self._node = node
        self._recent: dict[tuple, float] = {}
        node.node_bus.subscribe(MessageReq, self.process_message_req)
        node.node_bus.subscribe(MessageRep, self.process_message_rep)

    # ------------------------------------------------------------------ #
    # requesting                                                         #
    # ------------------------------------------------------------------ #

    def request(self, msg_type: str, params: dict, dst=None) -> None:
        # dst is part of the throttle key: the body-fetch loop cycles
        # through CANDIDATE responders, and asking the next peer must not
        # be suppressed because the previous one was just asked
        key = (msg_type, tuple(sorted(params.items())),
               tuple(dst) if dst is not None else None)
        now = self._node.timer.get_current_time()
        if now - self._recent.get(key, float("-inf")) < self.THROTTLE:
            return
        self._recent[key] = now
        if len(self._recent) > 10000:       # bounded memory under spam
            cutoff = now - self.THROTTLE
            self._recent = {k: t for k, t in self._recent.items() if t >= cutoff}
        self._node.node_bus.send(MessageReq(msg_type=msg_type, params=params),
                                 dst)

    def process_missing(self, msg: MissingMessage) -> None:
        """Internal MissingMessage event → wire MessageReq."""
        self.request(msg.msg_type, dict(msg.key), dst=msg.dst)

    # ------------------------------------------------------------------ #
    # serving                                                            #
    # ------------------------------------------------------------------ #

    def process_message_req(self, msg: MessageReq, frm: str) -> None:
        server = {
            PROPAGATE: self._serve_propagate,
            PREPREPARE: self._serve_preprepare,
            OLD_VIEW_PREPREPARE: self._serve_old_view_preprepare,
            VIEW_CHANGE: self._serve_view_change,
            NEW_VIEW: self._serve_new_view,
        }.get(msg.msg_type)
        if server is None:
            return
        try:
            found = server(msg.params)
        except Exception:
            return                      # malformed params are not our problem
        if found is not None:
            self._node.node_bus.send(
                MessageRep(msg_type=msg.msg_type, params=msg.params,
                           msg=found.to_dict()), [frm])

    def _serve_propagate(self, params: dict) -> Optional[Propagate]:
        state = self._node.propagator.requests.get(str(params["digest"]))
        if state is None or state.request is None:
            # digest-gossip: we may hold only digest VOTES for this request
            # — never answer a body fetch with a bodyless state
            return None
        return Propagate(request=state.request.to_dict(),
                         sender_client=state.client_name)

    def _serve_preprepare(self, params: dict) -> Optional[PrePrepare]:
        inst_id = int(params["inst_id"])
        key = (int(params["view_no"]), int(params["pp_seq_no"]))
        if inst_id not in self._node.replicas:
            return None
        ordering = self._node.replicas[inst_id].ordering
        return ordering.prePrepares.get(key) or \
            ordering.sent_preprepares.get(key)

    def _serve_old_view_preprepare(self, params: dict) -> Optional[PrePrepare]:
        """Old-view pre-prepare cited by a NewView (ref
        OldViewPrePrepareRequest, ordering_service.py:2409); keyed by
        ORIGINAL view — peers that ordered it keep it in old_view_preprepares
        after view_change_started, or still in prePrepares if they ordered it
        in the cited view itself."""
        inst_id = int(params["inst_id"])
        key = (int(params["view_no"]), int(params["pp_seq_no"]))
        if inst_id not in self._node.replicas:
            return None
        ordering = self._node.replicas[inst_id].ordering
        found = ordering.old_view_preprepares.get(key)
        if found is not None:
            return found
        pp = ordering.prePrepares.get(key) or ordering.sent_preprepares.get(key)
        if pp is not None:
            orig = pp.original_view_no if pp.original_view_no is not None \
                else pp.view_no
            if orig == key[0]:
                return pp
        return None

    def _serve_view_change(self, params: dict) -> Optional[ViewChange]:
        vc_service = self._node.replicas.master.view_changer
        if vc_service is None:
            return None
        return vc_service._view_changes.get(
            int(params["view_no"]), {}).get(str(params["author"]))

    def _serve_new_view(self, params: dict) -> Optional[NewView]:
        vc_service = self._node.replicas.master.view_changer
        if vc_service is None:
            return None
        nv = vc_service._new_view
        if nv is not None and nv.view_no == int(params["view_no"]):
            return nv
        return None

    # ------------------------------------------------------------------ #
    # consuming replies                                                  #
    # ------------------------------------------------------------------ #

    def process_message_rep(self, msg: MessageRep, frm: str) -> None:
        if msg.msg is None:
            return
        try:
            inner = message_from_dict(dict(msg.msg))
        except Exception:
            return
        if msg.msg_type == PROPAGATE and isinstance(inner, Propagate):
            # the normal pipeline authenticates the client signature, counts
            # the responder's propagate vote, and dedups — exactly as if the
            # original PROPAGATE had arrived from this peer
            self._node._receive_propagate(inner, frm)
        elif msg.msg_type == PREPREPARE and isinstance(inner, PrePrepare):
            if inner.inst_id in self._node.replicas:
                self._node.replicas[inner.inst_id].ordering \
                    .process_requested_preprepare(inner)
        elif msg.msg_type == OLD_VIEW_PREPREPARE and \
                isinstance(inner, PrePrepare):
            if inner.inst_id in self._node.replicas:
                self._node.replicas[inner.inst_id].ordering \
                    .process_requested_old_view_preprepare(inner)
        elif msg.msg_type == VIEW_CHANGE and isinstance(inner, ViewChange):
            vc_service = self._node.replicas.master.view_changer
            if vc_service is not None:
                vc_service.process_requested_view_change(
                    inner, str(msg.params.get("author", "")))
        elif msg.msg_type == NEW_VIEW and isinstance(inner, NewView):
            vc_service = self._node.replicas.master.view_changer
            if vc_service is not None:
                vc_service.process_requested_new_view(inner)
