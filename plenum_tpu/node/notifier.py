"""Notifier events: push operational anomalies to registered handlers.

Reference behavior: plenum/server/notifier_plugin_manager.py — a plugin
manager that detects suspicious throughput spikes against historical bounds
(sendMessageUponSuspiciousSpike:54, spike math :92-117, thresholds
config.py:165-184 notifierEventTriggeringConfig) and fans topic'd messages
out to whatever notifier plugins are installed; the monitor triggers the
cluster-throughput check on a freq interval (monitor.py:227).

Redesign: one `NotifierEventManager` with register/send, plus the two
event sources the reference wires in production — a cluster-throughput
spike detector fed by the monitor's master EMA, and view-change
notifications from the node. Handlers are plain callables
(topic, message-dict), so the plugins.py seam (or tests, or an ops
process tailing these into alerting) can subscribe without a package
discovery mechanism.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

TOPIC_SPIKE = "clusterThroughputSpike"
TOPIC_VIEW_CHANGE = "viewChange"
TOPIC_NODE_EVENT = "nodeEvent"


class NotifierEventManager:
    def __init__(self,
                 bounds_coeff: float = 10.0,
                 min_cnt: int = 15,
                 min_activity_threshold: float = 10.0,
                 enabled: bool = True):
        self._handlers: list[Callable[[str, dict], Any]] = []
        self.enabled = enabled
        # spike detection state (ref notifier_plugin_manager.py:92-117):
        # a spike = current value outside bounds_coeff x the historical
        # average, once at least min_cnt samples of history exist and the
        # traffic is above the noise floor
        self._bounds_coeff = bounds_coeff
        self._min_cnt = min_cnt
        self._min_activity = min_activity_threshold
        self._hist_avg: Optional[float] = None
        self._hist_cnt = 0

    def register_handler(self, handler: Callable[[str, dict], Any]) -> None:
        self._handlers.append(handler)

    def send(self, topic: str, message: dict) -> int:
        """Fan out to every handler; a failing handler must never take the
        node down (same contract as the reference's plugin sends)."""
        if not self.enabled:
            return 0
        sent = 0
        for handler in self._handlers:
            try:
                handler(topic, dict(message))
                sent += 1
            except Exception:
                pass
        return sent

    # --- spike detection ------------------------------------------------

    def check_throughput(self, value: Optional[float], node_name: str,
                         now: float) -> bool:
        """Feed one throughput sample; emits TOPIC_SPIKE when it falls
        outside the historical bounds. -> spike emitted?

        A detected spike is NOT folded into the history: one extreme
        outlier must flag once and leave the baseline intact, not poison
        the average into alerting on every subsequent normal sample."""
        if not self.enabled or value is None:
            return False
        prev_avg, prev_cnt = self._hist_avg, self._hist_cnt
        is_spike = (prev_avg is not None
                    and prev_cnt >= self._min_cnt
                    and max(value, prev_avg) >= self._min_activity
                    and not (prev_avg / self._bounds_coeff <= value
                             <= prev_avg * self._bounds_coeff))
        if is_spike:
            self.send(TOPIC_SPIKE, {
                "node": node_name, "time": now, "value": value,
                "historical_avg": prev_avg,
                "bounds": (prev_avg / self._bounds_coeff,
                           prev_avg * self._bounds_coeff)})
            return True
        self._hist_cnt += 1
        self._hist_avg = (value if prev_avg is None
                          else prev_avg + (value - prev_avg) / self._hist_cnt)
        return False
