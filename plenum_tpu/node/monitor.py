"""Performance monitor — the "R" in RBFT.

Reference behavior: plenum/server/monitor.py:136 (Monitor,
RequestTimeTracker:30), common/monitor_strategies.py,
throughput_measurements.py — every instance's ordered traffic is measured
(EMA throughput with a revival-spike-safe warmup, latency from request
finalization to ordering); the master is DEGRADED when its throughput falls
below DELTA × the best backup's (instance_throughput_ratio:456,
isMasterDegraded:425) or its request latency exceeds the backups' by OMEGA.
A degraded master costs the pool its performance without being provably
Byzantine — exactly what the f+1 redundant instances exist to detect — and
is answered with a view-change vote (Node.checkPerformance:2501).
"""
from __future__ import annotations

from typing import Callable, Optional

from plenum_tpu.config import Config


class EMAThroughput:
    """Windowed exponential-moving-average events/second.

    Events are accumulated into fixed windows of `window_size` seconds; each
    completed window folds into the EMA. A `min_activity_windows` warmup keeps
    a just-revived (or just-created) instance from reading as degraded/spiking
    before it has real history (ref throughput_measurements.py
    EMAThroughputMeasurement + safe-start wrapper, config.py:149-154).
    """

    def __init__(self, window_size: float = 15.0, alpha: float = 0.5,
                 min_activity_windows: int = 2):
        self.window_size = window_size
        self.alpha = alpha
        self.min_activity_windows = min_activity_windows
        self._started: Optional[float] = None
        self._window_start = 0.0
        self._window_count = 0
        self._ema: Optional[float] = None
        self._windows_seen = 0

    def start(self, now: float) -> None:
        self._started = now
        self._window_start = now

    def add(self, count: int, now: float) -> None:
        if self._started is None:
            self.start(now)
        self._advance(now)
        self._window_count += count

    def _advance(self, now: float) -> None:
        while now >= self._window_start + self.window_size:
            rate = self._window_count / self.window_size
            self._ema = rate if self._ema is None else \
                self.alpha * rate + (1.0 - self.alpha) * self._ema
            self._window_count = 0
            self._window_start += self.window_size
            self._windows_seen += 1

    def throughput(self, now: float) -> Optional[float]:
        """None while warming up (no safe reading yet)."""
        if self._started is None:
            return None
        self._advance(now)
        if self._windows_seen < self.min_activity_windows:
            return None
        return self._ema


class RequestTimeTracker:
    """Request digest -> finalization time; yields per-instance ordering
    latencies (ref monitor.py RequestTimeTracker:30)."""

    def __init__(self):
        self._added: dict[str, float] = {}
        # per-instance EMA of ordering latency
        self._latency: dict[int, float] = {}
        self._alpha = 0.3

    def add(self, digest: str, now: float) -> None:
        self._added.setdefault(digest, now)

    def cleanup(self, now: float, max_age: float) -> None:
        """Drop stale entries (requests that never ordered — discarded,
        stalled, or lost): without this the map grows without bound."""
        self._added = {d: ts for d, ts in self._added.items()
                       if now - ts <= max_age}

    def ordered(self, inst_id: int, digests, now: float,
                release: bool = False) -> None:
        for digest in digests:
            ts = self._added.get(digest)
            if ts is None:
                continue
            sample = now - ts
            prev = self._latency.get(inst_id)
            self._latency[inst_id] = sample if prev is None else \
                self._alpha * sample + (1 - self._alpha) * prev
            if release:
                del self._added[digest]

    def latency(self, inst_id: int) -> Optional[float]:
        return self._latency.get(inst_id)

    def drop(self, digest: str) -> None:
        self._added.pop(digest, None)

    @property
    def tracked_count(self) -> int:
        return len(self._added)


class Monitor:
    """Per-instance throughput/latency bookkeeping + the degradation verdict.

    The node feeds it: `request_finalized` when the propagate quorum fires,
    `request_ordered` on every instance's Ordered event. `is_master_degraded`
    implements the RBFT comparison; the node's checkPerformance loop turns a
    True into VoteForViewChange(PRIMARY_DEGRADED).
    """

    MASTER = 0

    def __init__(self, config: Optional[Config] = None,
                 now: Callable[[], float] = lambda: 0.0):
        self._config = config or Config()
        self._now = now
        self.throughputs: dict[int, EMAThroughput] = {}
        self.req_tracker = RequestTimeTracker()
        self.total_ordered: dict[int, int] = {}
        self.ordered_batches: dict[int, int] = {}

    def _tp(self, inst_id: int) -> EMAThroughput:
        if inst_id not in self.throughputs:
            tp = EMAThroughput(
                window_size=self._config.throughput_first_ts_window,
                min_activity_windows=2)
            tp.start(self._now())
            self.throughputs[inst_id] = tp
        return self.throughputs[inst_id]

    def reset(self) -> None:
        """View change / instance-set change: all history is void
        (ref monitor.reset on view change)."""
        self.throughputs.clear()
        self.req_tracker = RequestTimeTracker()

    # --- feeding ----------------------------------------------------------

    def request_finalized(self, digest: str) -> None:
        self.req_tracker.add(digest, self._now())

    def request_ordered(self, inst_id: int, digests) -> None:
        now = self._now()
        self._tp(inst_id).add(len(digests), now)
        self.total_ordered[inst_id] = \
            self.total_ordered.get(inst_id, 0) + len(digests)
        self.ordered_batches[inst_id] = self.ordered_batches.get(inst_id, 0) + 1
        # only the master's ordering releases the tracker entry: backups
        # ordering the same request later must still find it for latency
        self.req_tracker.ordered(inst_id, digests, now,
                                 release=(inst_id == self.MASTER))

    # --- verdicts ---------------------------------------------------------

    def instance_throughput_ratio(self) -> Optional[float]:
        """master_throughput / best_backup_throughput; None while warming up
        or with no backups (ref instance_throughput_ratio:456)."""
        now = self._now()
        master = self._tp(self.MASTER).throughput(now)
        backups = [tp for i, t in self.throughputs.items()
                   if i != self.MASTER
                   and (tp := t.throughput(now)) is not None]
        if master is None or not backups:
            return None
        best = max(backups)
        if best == 0:
            return None
        return master / best

    def master_latency_excess(self) -> Optional[float]:
        master = self.req_tracker.latency(self.MASTER)
        backups = [lat for i in self.req_tracker._latency
                   if i != self.MASTER
                   and (lat := self.req_tracker.latency(i)) is not None]
        if master is None or not backups:
            return None
        return master - min(backups)

    def is_master_degraded(self) -> bool:
        """ref isMasterDegraded:425 — throughput ratio below DELTA, or
        latency excess beyond OMEGA."""
        ratio = self.instance_throughput_ratio()
        if ratio is not None and ratio < self._config.DELTA:
            return True
        excess = self.master_latency_excess()
        if excess is not None and excess > self._config.OMEGA:
            return True
        return False

    # --- stats (bench + validator-info surface) ---------------------------

    def master_throughput(self) -> Optional[float]:
        return self._tp(self.MASTER).throughput(self._now())

    def stats(self) -> dict:
        now = self._now()
        return {
            "throughput": {i: tp.throughput(now)
                           for i, tp in self.throughputs.items()},
            "latency": {i: self.req_tracker.latency(i)
                        for i in self.req_tracker._latency},
            "total_ordered": dict(self.total_ordered),
            "ordered_batches": dict(self.ordered_batches),
            "master_degraded": self.is_master_degraded(),
        }
