"""Network-inconsistency watcher: detect losing quorum connectivity.

Reference behavior: plenum/server/inconsistency_watchers.py:5
(NetworkInconsistencyWatcher) — once a node has SEEN strong-quorum
connectivity (n-f peers up, i.e. consensus was reachable), dropping below
weak-quorum connectivity (f+1) means the node can no longer tell a
functioning pool from a partition: it must stop trusting its own liveness
view and resynchronize. The reference routes the callback to a node
restart; here the node wires it to `start_catchup` (our recovery path —
catchup pauses ordering, reverts uncommitted work and resyncs, which is
the restart path's actual payload) and a metrics event.

The "had it, lost it" edge matters: a node that never reached strong
connectivity (e.g. still dialing at startup) must NOT fire — otherwise
every cold start would loop through spurious recoveries.
"""
from __future__ import annotations

from typing import Callable, Iterable, Optional

from plenum_tpu.common.event_bus import ExternalBus
from plenum_tpu.common.quorums import Quorums


class NetworkInconsistencyWatcher:
    """Tracks peer connectivity against the pool's quorum thresholds.

    Counts CONNECTED PEERS (self excluded, exactly the transport's view);
    thresholds come from Quorums(n) over the full membership, mirroring
    the reference's accounting: strong = commit quorum (n-f), weak =
    propagate quorum (f+1).
    """

    def __init__(self, callback: Callable[[], None],
                 network: Optional[ExternalBus] = None):
        self.callback = callback
        self._connected: set[str] = set()
        self._nodes: set[str] = set()
        self._quorums = Quorums(0)
        self._reached_strong = False
        if network is not None:
            network.subscribe(ExternalBus.Connected, self._on_connected)
            network.subscribe(ExternalBus.Disconnected, self._on_disconnected)

    # --- membership -------------------------------------------------------

    def set_nodes(self, nodes: Iterable[str]) -> None:
        """Pool membership changed (pool-ledger commit): recompute the
        thresholds; connectivity already gathered keeps counting."""
        self._nodes = set(nodes)
        self._quorums = Quorums(len(self._nodes))

    @property
    def nodes(self) -> set[str]:
        return self._nodes

    # --- transport events -------------------------------------------------

    def _on_connected(self, msg, frm: str = "") -> None:
        self.connect(msg.name)

    def _on_disconnected(self, msg, frm: str = "") -> None:
        self.disconnect(msg.name)

    def connect(self, name: str) -> None:
        self._connected.add(name)
        if not self._nodes:
            return      # membership unknown: Quorums(0) is trivially true
        if self._quorums.commit.is_reached(len(self._connected)):
            self._reached_strong = True

    def disconnect(self, name: str) -> None:
        self._connected.discard(name)
        if (self._nodes and self._reached_strong
                and not self._quorums.propagate.is_reached(
                    len(self._connected))):
            # lost weak-quorum connectivity after having had consensus
            # connectivity: one shot until strong connectivity returns
            self._reached_strong = False
            self.callback()

    def has_weak_connectivity(self) -> bool:
        return self._quorums.propagate.is_reached(len(self._connected))
