"""Peer blacklisting: convert protocol suspicions into ignored peers.

Reference behavior: plenum/server/blacklister.py (SimpleBlacklister) +
node.py:2854-2944 (reportSuspiciousNode) — suspicions that implicate the
PRIMARY become view-change votes; suspicions that implicate an ordinary peer
get that peer blacklisted (its traffic dropped at ingress). Tests whitelist
intentionally-faulty nodes so scenarios don't cascade (test_node.py:88-98).

Unlike the reference's forever-blacklist, entries here EXPIRE after a TTL:
a node that blacklists f+1 peers (e.g. a wave of spoofed traffic before
transport auth caught up, or a bug on the peer's side) would otherwise
sever itself from quorum PERMANENTLY — self-inflicted isolation is a worse
failure mode than re-admitting a misbehaving peer for another round of
suspicion. Found by the wire-protocol fuzz.
"""
from __future__ import annotations

from typing import Callable, Optional

DEFAULT_TTL = 120.0


class Blacklister:
    def __init__(self, whitelist: tuple[str, ...] = (),
                 ttl: float = DEFAULT_TTL,
                 now: Optional[Callable[[], float]] = None):
        # peer -> (expiry, suspicion codes)
        self._blacklisted: dict[str, tuple[float, list[int]]] = {}
        self._whitelist: set[str] = set(whitelist)
        self._ttl = ttl
        self._now = now or (lambda: 0.0)

    def blacklist(self, peer: str, code: int = 0) -> bool:
        """Returns True if the peer is now (or already was) blacklisted."""
        if peer in self._whitelist:
            return False
        expiry = self._now() + self._ttl
        _, codes = self._blacklisted.get(peer, (0.0, []))
        self._blacklisted[peer] = (expiry, codes + [code])
        return True

    def is_blacklisted(self, peer: str) -> bool:
        entry = self._blacklisted.get(peer)
        if entry is None:
            return False
        if self._now() >= entry[0]:
            del self._blacklisted[peer]       # TTL expired: re-admit
            return False
        return True

    def whitelist(self, peer: str) -> None:
        """Forgive + exempt a peer (test fault-injection needs this)."""
        self._whitelist.add(peer)
        self._blacklisted.pop(peer, None)

    @property
    def blacklisted(self) -> dict[str, list[int]]:
        now = self._now()
        return {p: codes for p, (exp, codes) in self._blacklisted.items()
                if now < exp}
