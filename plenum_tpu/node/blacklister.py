"""Peer blacklisting: convert protocol suspicions into ignored peers.

Reference behavior: plenum/server/blacklister.py (SimpleBlacklister) +
node.py:2854-2944 (reportSuspiciousNode) — suspicions that implicate the
PRIMARY become view-change votes; suspicions that implicate an ordinary peer
get that peer blacklisted (its traffic dropped at ingress). Tests whitelist
intentionally-faulty nodes so scenarios don't cascade (test_node.py:88-98).
"""
from __future__ import annotations


class Blacklister:
    def __init__(self, whitelist: tuple[str, ...] = ()):
        self._blacklisted: dict[str, list[int]] = {}   # peer -> suspicion codes
        self._whitelist: set[str] = set(whitelist)

    def blacklist(self, peer: str, code: int = 0) -> bool:
        """Returns True if the peer is now (or already was) blacklisted."""
        if peer in self._whitelist:
            return False
        self._blacklisted.setdefault(peer, []).append(code)
        return True

    def is_blacklisted(self, peer: str) -> bool:
        return peer in self._blacklisted

    def whitelist(self, peer: str) -> None:
        """Forgive + exempt a peer (test fault-injection needs this)."""
        self._whitelist.add(peer)
        self._blacklisted.pop(peer, None)

    @property
    def blacklisted(self) -> dict[str, list[int]]:
        return dict(self._blacklisted)
