"""Observer framework: push committed batches to non-validator followers.

Reference behavior: plenum/server/observer/observable.py:11 (the node-side
registry + policy that fans BatchCommitted out to registered observers) and
observer/observer_node.py + observer_sync_policy_each_batch.py (the follower
that applies each batch to its own ledger copy).

The node-side Observable subscribes nothing by itself: Node._execute_batch
calls append_input() after commit, and the policy decides who gets the
message. The follower side (NodeObserver) re-derives the ledger from the
batch's request list and REFUSES batches whose claimed txn root does not
match what its own Merkle tree computes — an observer is untrusted-input
tolerant even though it trusts the pool's ordering.
"""
from __future__ import annotations

import hashlib
from typing import Any, Callable, Optional

from plenum_tpu.common.node_messages import BatchCommitted
from plenum_tpu.common.serialization import signing_serialize


class Observable:
    """Node-side observer registry + each-batch send policy.

    Registrations arrive over the client stack (OBSERVER_REGISTER op, see
    Node._service_client_msgs) keyed by the client connection id; pushes to
    a disconnected id are silently dropped by the stack. The registry is
    capped — anyone can connect a client socket, so unbounded registration
    would be a free memory/egress amplifier — with FIFO eviction (a dead
    registration can't block live ones forever; an evicted live observer
    re-registers on its next reconnect).
    """

    MAX_OBSERVERS = 16

    def __init__(self, send: Callable[[Any, str], None],
                 close: Optional[Callable[[str], None]] = None,
                 send_many: Optional[Callable[[Any, list], None]] = None):
        self._send = send
        self._close = close          # drops the evicted CONNECTION so the
        # observer's redial+re-register loop fires; without it an evicted
        # follower would sit on a silent socket forever
        # pack-once broadcast seam (ClientStack.send_many); falls back to
        # per-observer send when the transport offers none
        self._send_many = send_many
        self._observers: dict[str, str] = {}      # observer id -> policy

    def add_observer(self, observer_id: str,
                     policy: str = "each_batch") -> None:
        if policy != "each_batch":
            raise ValueError(f"unknown observer policy {policy!r}")
        # re-registration refreshes recency (pop + insert moves to the
        # dict's end), so FIFO eviction removes the LONGEST-UNREFRESHED
        # id, not the longest-lived legitimate observer
        self._observers.pop(observer_id, None)
        if len(self._observers) >= self.MAX_OBSERVERS:
            oldest = next(iter(self._observers))
            del self._observers[oldest]
            if self._close is not None:
                self._close(oldest)
        self._observers[observer_id] = policy

    def remove_observer(self, observer_id: str) -> None:
        self._observers.pop(observer_id, None)

    @property
    def observer_ids(self) -> list[str]:
        return list(self._observers)

    def append_input(self, batch: BatchCommitted) -> None:
        if self._send_many is not None:
            self._send_many(batch, list(self._observers))
            return
        for observer_id in self._observers:
            self._send(batch, observer_id)


class NodeObserver:
    """Follower: applies each pushed batch to its own ledgers/states.

    Built from the same NodeBootstrap components as a validator (minus
    consensus); process_batch is idempotent and gap-safe: batches at or
    below the ledger's size are ignored, a batch leaving a gap is rejected
    (the caller should catch up out of band, same as the reference's
    can_process check in observer_sync_policy_each_batch.py).

    Data quorum (ref plenum/server/quorums.py:38 observer_data = f+1): a
    batch is applied only once f+1 DISTINCT validators have pushed
    CONTENT-IDENTICAL copies — root re-derivation alone binds the chain
    but cannot stop a lone Byzantine validator from feeding a
    self-consistent fabricated batch; with f+1 matching pushes at least
    one comes from an honest validator. Each validator holds one vote per
    (ledger, seq range) — a re-push with different content replaces its
    earlier vote, so one peer can never grow the buffer. f=0 (the default,
    for single-trusted-feed library use) applies on the first push.
    """

    def __init__(self, components, f: int = 0):
        self.c = components
        self.f = f
        self.last_applied: dict[int, int] = {}
        # (ledger, seq_no_start) -> {validator: (content digest, batch)}.
        # Keyed by START only (which the gap check pins to ledger.size+1),
        # so the buffer holds at most one entry per ledger and one vote per
        # validator — a Byzantine peer varying seq_no_end just replaces its
        # own vote instead of minting new buckets
        self._votes: dict[tuple, dict[str, tuple[str, BatchCommitted]]] = {}

    def process_batch(self, batch: BatchCommitted, frm: str = "") -> bool:
        ledger = self.c.db.get_ledger(batch.ledger_id)
        if ledger is None:
            return False
        if batch.seq_no_end <= ledger.size:
            return False                            # already have it
        if batch.seq_no_start != ledger.size + 1:
            return False                            # gap: needs catchup

        key = (batch.ledger_id, batch.seq_no_start)
        # quorum content excludes the advisory multi_sig attachment:
        # honest validators legitimately aggregate DIFFERENT commit-sig
        # subsets, and voting on it would split identical batches into
        # separate buckets and starve the f+1 quorum. The sig is
        # self-verifying (checked against the pool BLS keys by the
        # observer's read gate), so it needs verification, not agreement.
        digest = hashlib.sha256(
            signing_serialize(batch.quorum_dict())).hexdigest()
        votes = self._votes.setdefault(key, {})
        votes[frm] = (digest, batch)
        if sum(1 for d, _ in votes.values() if d == digest) < self.f + 1:
            return False                            # buffered, no quorum yet
        applied = self._apply_batch(batch)
        if applied:
            # quorum consumed; every start now at or below the ledger size
            # is settled, so the buffer stays bounded by in-flight ranges
            self._votes = {k: v for k, v in self._votes.items()
                           if not (k[0] == batch.ledger_id
                                   and k[1] <= batch.seq_no_end)}
        return applied

    def _apply_batch(self, batch: BatchCommitted) -> bool:
        from plenum_tpu.common.request import Request
        from plenum_tpu.execution.write_manager import ThreePcBatch

        # re-run the write pipeline: apply -> compare roots -> commit
        requests = [Request.from_dict(r) for r in batch.requests]
        valid, _rejected, roots = self.c.write_manager.apply_batch(
            batch.ledger_id, requests, batch.pp_time, batch.view_no,
            batch.pp_seq_no)
        if roots["txn_root"] != batch.txn_root or \
                roots["state_root"] != batch.state_root:
            # claimed roots don't match recomputation: refuse and revert.
            # (The audit ledger is NOT compared: its txns snapshot primaries,
            # which a follower has no view of — same scope as the reference's
            # each-batch policy, which replays domain/pool data only.)
            self.c.write_manager.revert_last_batch(batch.ledger_id)
            return False
        self.c.write_manager.commit_batch(ThreePcBatch(
            ledger_id=batch.ledger_id, view_no=batch.view_no,
            pp_seq_no=batch.pp_seq_no, pp_time=batch.pp_time,
            valid_digests=tuple(r.digest for r in valid),
            state_root=bytes.fromhex(roots["state_root"])
            if roots["state_root"] else b"",
            txn_root=bytes.fromhex(roots["txn_root"])
            if roots["txn_root"] else b"",
            audit_txn_root=bytes.fromhex(roots["audit_txn_root"])
            if roots["audit_txn_root"] else b"",
            primaries=(), node_reg=()))
        self.last_applied[batch.ledger_id] = batch.seq_no_end
        return True

    def catch_up(self, batch: BatchCommitted, fetch_txn,
                 limit: int = 10_000) -> bool:
        """Fill the gap below `batch` and apply the batch ATOMICALLY.

        fetch_txn(ledger_id, seq_no) -> committed txn dict or None (the
        transport is typically a GET_TXN query via PoolClient). Fetched txns
        are staged UNCOMMITTED; the pushed batch is then applied on top and
        its roots — which bind the ENTIRE preceding chain through the Merkle
        tree — are compared against the push. Nothing commits until the
        comparison passes; on any mismatch or missing txn every staged
        change is discarded, so a Byzantine fetch peer can stall this
        observer but never corrupt it (same invariant as validator catchup:
        plenum_tpu/catchup/rep.py verify-before-commit).
        """
        from plenum_tpu.common.request import Request
        from plenum_tpu.execution.write_manager import ThreePcBatch

        ledger = self.c.db.get_ledger(batch.ledger_id)
        state = self.c.db.get_state(batch.ledger_id)
        if ledger is None or batch.seq_no_end <= ledger.size:
            return False
        prev_state_root = state.head_hash if state is not None else None

        def discard(n_pulled: int) -> bool:
            if n_pulled:
                ledger.discard_txns(n_pulled)
                if state is not None and prev_state_root is not None:
                    state.revert_to_head(prev_state_root)
            return False

        pulled = 0
        while ledger.size + pulled + 1 < batch.seq_no_start:
            if pulled >= limit:
                return discard(pulled)
            txn = fetch_txn(batch.ledger_id, ledger.size + pulled + 1)
            if txn is None:
                return discard(pulled)
            ledger.append_txns_to_uncommitted([txn])
            self.c.write_manager.apply_committed_txn(
                batch.ledger_id, txn, committed=False)
            pulled += 1

        requests = [Request.from_dict(r) for r in batch.requests]
        valid, _rejected, roots = self.c.write_manager.apply_batch(
            batch.ledger_id, requests, batch.pp_time, batch.view_no,
            batch.pp_seq_no)
        if roots["txn_root"] != batch.txn_root or \
                roots["state_root"] != batch.state_root:
            self.c.write_manager.revert_last_batch(batch.ledger_id)
            return discard(pulled)
        if pulled:
            ledger.commit_txns(pulled)
        self.c.write_manager.commit_batch(ThreePcBatch(
            ledger_id=batch.ledger_id, view_no=batch.view_no,
            pp_seq_no=batch.pp_seq_no, pp_time=batch.pp_time,
            valid_digests=tuple(r.digest for r in valid),
            state_root=bytes.fromhex(roots["state_root"])
            if roots["state_root"] else b"",
            txn_root=bytes.fromhex(roots["txn_root"])
            if roots["txn_root"] else b"",
            audit_txn_root=bytes.fromhex(roots["audit_txn_root"])
            if roots["audit_txn_root"] else b"",
            primaries=(), node_reg=()))
        self.last_applied[batch.ledger_id] = batch.seq_no_end
        return True
