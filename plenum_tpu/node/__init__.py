from .client_authn import CoreAuthNr, ReqAuthenticator
from .propagator import Propagator, Requests
from .pool_manager import TxnPoolManager
from .bootstrap import NodeBootstrap
from .node import Node

__all__ = ["CoreAuthNr", "ReqAuthenticator", "Propagator", "Requests",
           "TxnPoolManager", "NodeBootstrap", "Node"]
