"""Node bootstrap: storages, ledgers, states, handlers, managers, BLS, authN.

Reference behavior: plenum/server/node_bootstrap.py:17 + ledgers_bootstrap.py —
build the 4 base ledgers in catchup order (audit, pool, config, domain,
node.py:142), a state trie per non-audit ledger, register request + batch
handlers, wire BLS, and replay committed txns into state so a restarted (or
genesis-seeded) node starts from consistent roots.
"""
from __future__ import annotations

import os
from typing import NamedTuple, Optional, Sequence

from plenum_tpu.common.node_messages import (AUDIT_LEDGER_ID,
                                             CONFIG_LEDGER_ID,
                                             DOMAIN_LEDGER_ID, POOL_LEDGER_ID)
from plenum_tpu.consensus.bls_bft_replica import (BlsBftReplica, BlsKeyRegister,
                                                  BlsStore)
from plenum_tpu.crypto.bls import BlsCryptoSigner, BlsCryptoVerifier
from plenum_tpu.crypto.ed25519 import make_verifier
from plenum_tpu.execution import (DatabaseManager, LedgerBatchExecutor,
                                  ReadRequestManager, WriteRequestManager)
from plenum_tpu.execution.database_manager import (BLS_STORE_LABEL,
                                                   NODE_STATUS_DB_LABEL,
                                                   SEQ_NO_DB_LABEL,
                                                   TS_STORE_LABEL)
from plenum_tpu.execution.handlers import (GetFrozenLedgersHandler,
                                           GetNymHandler,
                                           GetTxnAuthorAgreementAmlHandler,
                                           GetTxnAuthorAgreementHandler,
                                           GetTxnHandler, LedgersFreezeHandler,
                                           NodeHandler, NymHandler,
                                           TxnAuthorAgreementAmlHandler,
                                           TxnAuthorAgreementDisableHandler,
                                           TxnAuthorAgreementHandler)
from plenum_tpu.execution.txn import NODE, NYM
from plenum_tpu.execution import txn as txn_lib
from plenum_tpu.storage.state_ts_store import StateTsStore
from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
from plenum_tpu.ledger.hash_store import HashStore
from plenum_tpu.ledger.ledger import Ledger
from plenum_tpu.ledger.tree_hasher import make_tree_hasher
from plenum_tpu.node.client_authn import CoreAuthNr, ReqAuthenticator
from plenum_tpu.node.pool_manager import TxnPoolManager
from plenum_tpu.storage.kv_file import KvFile
from plenum_tpu.storage.kv_memory import KvMemory


class NodeComponents(NamedTuple):
    db: DatabaseManager
    write_manager: WriteRequestManager
    read_manager: ReadRequestManager
    executor: LedgerBatchExecutor
    authenticator: ReqAuthenticator
    pool_manager: TxnPoolManager
    nym_handler: NymHandler
    node_handler: NodeHandler
    bls_signer: Optional[BlsCryptoSigner]
    bls_register: BlsKeyRegister
    bls_store: BlsStore
    plugins: list = []          # effective plugin objects (init'd by Node)
    action_manager: object = None
    # fused crypto pipeline (parallel/pipeline.py) the node's crypto
    # seams ride when constructed with one; co-hosted nodes share it
    pipeline: object = None


class NodeBootstrap:
    """Builds everything below the Node orchestrator."""

    def __init__(self, name: str,
                 genesis_txns: Optional[dict[int, Sequence[dict]]] = None,
                 data_dir: Optional[str] = None,
                 crypto_backend: str = "cpu",
                 bls_seed: Optional[bytes] = None,
                 verifier_min_batch: int = 128,
                 storage_backend: str = "native",
                 plugins=None,
                 verifier=None,
                 pipeline=None,
                 pipeline_lane: Optional[int] = None,
                 state_commitment: str = "mpt",
                 state_commitment_per_ledger: Optional[dict] = None,
                 verkle_width: Optional[int] = None):
        self.name = name
        self.genesis = genesis_txns or {}
        self.data_dir = data_dir
        self.crypto_backend = crypto_backend
        # durable stores: "native" = the C++ log-structured engine
        # (LevelDB/RocksDB slot), "file" = the pure-python append log
        self.storage_backend = storage_backend
        # extension handlers (ref plugin_loader.py); merged with the
        # globally-registered set at build time
        self.plugins = list(plugins or [])
        self.bls_seed = bls_seed or name.encode().ljust(32, b"\0")[:32]
        # one fixed device-program shape covering the receive quotas: novel
        # shapes recompile, which costs minutes on a tunneled TPU
        self.verifier_min_batch = verifier_min_batch
        # explicit verifier override: co-hosted nodes pass ONE shared
        # CoalescingVerifier so their dispatches ride a single device
        # program per cycle (crypto/ed25519.py CoalescingVerifier)
        self.verifier = verifier
        # fused crypto pipeline (parallel/pipeline.py): when given, the
        # authenticator, every ledger's tree hasher, and the BLS batch
        # checks all stage into its shared ring (co-hosted nodes pass ONE
        # instance — that sharing IS the cross-node coalescing/dedup)
        self.pipeline = pipeline
        # multi-device ring placement pin: this node's submissions stage
        # into the named chip lane (sharded fabrics pin co-hosted
        # sub-pool shards to DISTINCT chips; None = ring-chosen lane)
        self.pipeline_lane = pipeline_lane
        # per-ledger state commitment scheme (state/commitment/): 'mpt'
        # default, 'verkle' for aggregated multi-key openings; the whole
        # pool must agree (the backend defines the signed root anchors)
        self.state_commitment = state_commitment
        self.state_commitment_per_ledger = \
            dict(state_commitment_per_ledger or {})
        self.verkle_width = verkle_width

    # --- storage factories -------------------------------------------------

    def _kv(self, label: str):
        if self.data_dir is None:
            return KvMemory()
        os.makedirs(self.data_dir, exist_ok=True)
        path = os.path.join(self.data_dir, label)
        has_native = os.path.exists(os.path.join(path, "kv.kvn"))
        has_file = os.path.exists(os.path.join(path, "kv.kvlog"))
        if self.storage_backend == "chunked" and not (has_native or has_file):
            # unbounded append logs split across sealed chunk files
            # (ref chunked_file_store.py); existing single-file/native
            # data keeps its on-disk format
            from plenum_tpu.storage.kv_chunked import KvChunked
            return KvChunked(path)
        if self.storage_backend == "native" or has_native:
            from plenum_tpu.storage.kv_native import (KvNative,
                                                      native_available)
            if native_available():
                if has_file and not has_native:
                    # existing KvFile data: honor the on-disk format rather
                    # than silently opening an empty native store
                    return KvFile(path)
                return KvNative(path)
            if has_native:
                # NEVER silently restart from genesis because the toolchain
                # went away: the durable data is in the native format
                raise RuntimeError(
                    f"{path} holds native-engine data but the native "
                    f"kvstore is unavailable (g++ build failed?)")
            import logging
            logging.getLogger(__name__).warning(
                "native kvstore unavailable; falling back to the "
                "pure-python file log for %s", path)
        return KvFile(path)

    def _ledger(self, ledger_id: int, label: str) -> Ledger:
        # crypto_backend routes to EVERY ledger's tree hasher — with "jax"
        # the batch appends/proof paths run on device (the north-star seam;
        # ref tree_hasher.py:4 + SURVEY.md §7 stage 2/3); with a pipeline,
        # hashing coalesces/dedups through its shared SHA lane instead
        hasher = (self.pipeline.tree_hasher() if self.pipeline is not None
                  else make_tree_hasher(self.crypto_backend))
        tree = CompactMerkleTree(
            hasher,
            hash_store=HashStore(self._kv(f"{label}_hashes")))
        return Ledger(tree, self._kv(f"{label}_log"),
                      genesis_txns=self.genesis.get(ledger_id, ()))

    # --- build -------------------------------------------------------------

    def _state(self, ledger_id: int, label: str):
        """Per-ledger state through the commitment seam: the configured
        scheme ('mpt' default; the Verkle backend additionally stages its
        batch commitment updates through the shared pipeline's commitment
        wave kind when one is wired)."""
        from plenum_tpu.state.commitment import (backend_for_ledger,
                                                 make_state)
        backend = backend_for_ledger(ledger_id, self.state_commitment,
                                     self.state_commitment_per_ledger)
        return make_state(backend, db=self._kv(label),
                          width=self.verkle_width, pipeline=self.pipeline)

    def build(self) -> NodeComponents:
        db = DatabaseManager()
        # the commit drain's fused wave seam (execution/write_manager.py
        # `_commit_wave`): same pipeline the states commit through
        db.pipeline = self.pipeline
        # catchup order: audit, pool, config, domain (ref node.py:142)
        db.register_ledger(AUDIT_LEDGER_ID, self._ledger(AUDIT_LEDGER_ID, "audit"))
        db.register_ledger(POOL_LEDGER_ID, self._ledger(POOL_LEDGER_ID, "pool"),
                           self._state(POOL_LEDGER_ID, "pool_state"))
        db.register_ledger(CONFIG_LEDGER_ID, self._ledger(CONFIG_LEDGER_ID, "config"),
                           self._state(CONFIG_LEDGER_ID, "config_state"))
        db.register_ledger(DOMAIN_LEDGER_ID, self._ledger(DOMAIN_LEDGER_ID, "domain"),
                           self._state(DOMAIN_LEDGER_ID, "domain_state"))
        db.register_store(TS_STORE_LABEL,
                          StateTsStore(self._kv("ts_store")))
        db.register_store(SEQ_NO_DB_LABEL, self._kv("seq_no_db"))
        db.register_store(NODE_STATUS_DB_LABEL, self._kv("node_status_db"))
        bls_store = BlsStore(self._kv("bls_store"))
        db.register_store(BLS_STORE_LABEL, bls_store)

        # handlers + managers
        write_manager = WriteRequestManager(db)
        nym = NymHandler(db)
        bls_verifier = BlsCryptoVerifier()
        node_handler = NodeHandler(db, nym, bls_verifier=bls_verifier)
        write_manager.register_handler(nym)
        write_manager.register_handler(node_handler)
        write_manager.register_handler(TxnAuthorAgreementHandler(db, nym))
        write_manager.register_handler(TxnAuthorAgreementAmlHandler(db, nym))
        write_manager.register_handler(TxnAuthorAgreementDisableHandler(db, nym))
        write_manager.register_handler(LedgersFreezeHandler(db, nym))
        from plenum_tpu.execution.handlers.attrib import (
            ATTRIB_STORE_LABEL, AttribHandler, GetAttrHandler)
        db.register_store(ATTRIB_STORE_LABEL, self._kv("attrib_db"))
        write_manager.register_handler(AttribHandler(db))
        read_manager = ReadRequestManager()
        read_manager.register_handler(GetAttrHandler(db))
        read_manager.register_handler(GetNymHandler(db))
        read_manager.register_handler(GetTxnHandler(db))
        read_manager.register_handler(GetTxnAuthorAgreementHandler(db))
        read_manager.register_handler(GetTxnAuthorAgreementAmlHandler(db))
        read_manager.register_handler(GetFrozenLedgersHandler(db))

        # action requests: privileged, node-local, no consensus
        # (ref action_request_manager.py; Node registers its own handlers)
        from plenum_tpu.execution.action_manager import ActionRequestManager
        action_manager = ActionRequestManager(get_role=nym.get_role)

        # plugins contribute extra txn types before genesis replay so
        # plugin txns can even appear in genesis (ref plugin_loader.py)
        from plenum_tpu.plugins import install_plugins
        self.effective_plugins = install_plugins(
            db, write_manager, read_manager, self.plugins)

        self._replay_genesis_state(db, nym, node_handler, write_manager)

        # client authN over the Ed25519 provider seam (cpu | jax); with a
        # pipeline the batches stage into the shared ring instead of
        # dispatching alone
        if self.verifier is not None:
            authn_verifier = self.verifier
        elif self.pipeline is not None:
            authn_verifier = self.pipeline.verifier(
                lane=self.pipeline_lane)
        else:
            authn_verifier = make_verifier(
                self.crypto_backend, min_batch=self.verifier_min_batch)
        authnr = ReqAuthenticator()
        authnr.register_authenticator(CoreAuthNr(
            authn_verifier, get_verkey=nym.get_verkey))

        # BLS: signer from seed; registry fed from pool state
        bls_signer = BlsCryptoSigner(seed=self.bls_seed)
        bls_register = BlsKeyRegister()
        pool_manager = TxnPoolManager(node_handler)
        self._sync_bls_register(bls_register, pool_manager)

        executor = LedgerBatchExecutor(write_manager)
        return NodeComponents(db, write_manager, read_manager, executor,
                              authnr, pool_manager, nym, node_handler,
                              bls_signer, bls_register, bls_store,
                              self.effective_plugins, action_manager,
                              self.pipeline)

    def _replay_genesis_state(self, db, nym, node_handler, wm) -> None:
        """Replay committed ledger txns through handlers into state (restart
        recovery / genesis seeding; ref ledgers_bootstrap init_state_from_ledger)."""
        handlers = {NYM: nym, NODE: node_handler}
        for h in wm._handlers.values():
            handlers.setdefault(h.txn_type, h)
        for lid in (POOL_LEDGER_ID, CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID):
            ledger = db.get_ledger(lid)
            state = db.get_state(lid)
            if state is None or ledger.size == 0:
                continue
            if len(state.as_dict(committed=True)) > 0:
                continue                      # persistent state already built
            for seq_no in range(1, ledger.size + 1):
                txn = ledger.get_by_seq_no(seq_no)
                handler = handlers.get(txn_lib.txn_type_of(txn))
                if handler is not None:
                    handler.update_state(txn, is_committed=True)
            state.commit(state.head_hash)

    @staticmethod
    def _sync_bls_register(register: BlsKeyRegister,
                           pool_manager: TxnPoolManager) -> None:
        for name in pool_manager.node_names:
            register.set_key(name, pool_manager.bls_key_of(name))
