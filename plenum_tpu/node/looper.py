"""Real-time runner: drives a Node (or several) on an asyncio event loop.

Reference behavior: stp_core/loop/looper.py — a Looper owns Prodables and
calls prod() on each in a run-forever loop, interleaved with the event loop
so socket I/O and timers stay live. Here the transport IS asyncio, so the
Looper is small: one task per node that services the shared QueueTimer,
drains the node's transport stacks, and prods the node, sleeping
prod_interval between cycles (long sleeps would add ordering latency; the
interval is the reference's prodable loop granularity).
"""
from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

from plenum_tpu.common.timer import QueueTimer


class Prodable:
    """One runnable unit: a node plus its transport stacks."""

    def __init__(self, node, node_stack=None, client_stack=None,
                 timer: Optional[QueueTimer] = None):
        self.node = node
        self.node_stack = node_stack
        self.client_stack = client_stack
        self.timer = timer

    async def start(self) -> None:
        if self.node_stack is not None:
            await self.node_stack.start()
        if self.client_stack is not None:
            await self.client_stack.bind()

    async def stop(self) -> None:
        if self.node_stack is not None:
            await self.node_stack.stop()
        if self.client_stack is not None:
            await self.client_stack.stop()

    def prod(self) -> int:
        count = 0
        if self.timer is not None:
            count += self.timer.service()
        if self.node_stack is not None:
            count += self.node_stack.drain()
        if self.client_stack is not None:
            count += self.client_stack.drain()
        count += self.node.prod()
        return count


class Looper:
    """Runs Prodables until stopped; usable as an async context manager
    inside an existing event loop (tests) or via run() standalone (the
    start-node script)."""

    def __init__(self, prod_interval: float = 0.002):
        self.prod_interval = prod_interval
        self._prodables: list[Prodable] = []
        self._tasks: list[asyncio.Task] = []
        self._running = False

    def add(self, prodable: Prodable) -> None:
        self._prodables.append(prodable)
        if self._running:
            # late-added prodables must bind/dial their stacks first
            async def start_then_drive():
                await prodable.start()
                await self._drive(prodable)

            self._tasks.append(
                asyncio.get_running_loop().create_task(start_then_drive()))

    async def __aenter__(self):
        await self.start()
        return self

    async def __aexit__(self, *exc):
        await self.shutdown()

    async def start(self) -> None:
        self._running = True
        for p in self._prodables:
            await p.start()
        loop = asyncio.get_running_loop()
        self._tasks = [loop.create_task(self._drive(p))
                       for p in self._prodables]

    async def _drive(self, prodable: Prodable) -> None:
        while self._running:
            busy = prodable.prod()
            # busy cycles yield to the loop but don't sleep the full interval
            await asyncio.sleep(0 if busy else self.prod_interval)

    async def run_until(self, predicate: Callable[[], bool],
                        timeout: float) -> bool:
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if predicate():
                return True
            await asyncio.sleep(self.prod_interval)
        return predicate()

    async def shutdown(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        for p in self._prodables:
            await p.stop()
        self._tasks.clear()

    def run(self, coro) -> None:
        """Standalone entry: run a main coroutine with this looper started."""
        async def _main():
            async with self:
                await coro

        asyncio.run(_main())
