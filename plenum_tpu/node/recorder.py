"""Recorder: capture a node's complete input stream for deterministic
single-node replay.

Reference behavior: plenum/recorder/recorder.py:13 — every incoming node
message and client request is appended to a KV store with a time offset;
a replayer later feeds the stream back into a freshly-bootstrapped node,
reproducing its exact state evolution (the debugging story for "what did
this node see before it broke").

Design: the recorder wraps the two ingress points (ExternalBus
process_incoming + Node.handle_client_message) rather than the socket layer,
so records are wire-decoded messages — replay does not need a network stack
at all, only a MockTimer. Connection events are recorded too (they drive
primary-health and view-change logic).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from plenum_tpu.common.event_bus import ExternalBus
from plenum_tpu.common.message_base import MessageBase, message_from_dict
from plenum_tpu.common.serialization import pack, unpack

INCOMING = "R"       # node protocol message
CLIENT = "C"         # client request dict
CONNECTED = "+"      # peer connection event
DISCONNECTED = "-"
TICK = "T"           # a prod cycle ran at this timer time
RUN_START = "B"      # a process (re)started recording

IDLE_HEARTBEAT = 1.0     # max silence between recorded ticks


class Recorder:
    """Appends timestamped ingress + prod-tick records to a KV store.

    Ticks matter for determinism: the primary's batch flush happens INSIDE
    prod (Replica.service), so pp_time — which enters the 3PC digest the
    peers' recorded COMMITs certify — is the timer time of the prod cycle
    that cut the batch. Replay must therefore re-run prods at the recorded
    cycle times, not at input-arrival times. Consecutive idle ticks at the
    same timestamp are deduplicated.
    """

    def __init__(self, store, now: Callable[[], float]):
        self._store = store
        self._now = now
        self._seq = store.size if hasattr(store, "size") else 0
        self._last_tick_ts: Optional[float] = None
        self._input_since_tick = True
        self._sends_since_tick = 0

    def record(self, kind: str, frm: str, data: Any) -> None:
        if kind != TICK:
            self._input_since_tick = True
        key = self._seq.to_bytes(8, "big")
        self._seq += 1
        self._store.put(key, pack([self._now(), kind, frm, data]))

    def note_send(self) -> None:
        self._sends_since_tick += 1

    def record_tick(self, work: int = 0) -> None:
        """Record a tick only when the cycle DID something (ingress, node
        work, or outbound sends — outbound catches time-driven actions like
        freshness batches) or at a coarse idle heartbeat. A real-time node
        prods ~500x/s; recording every idle cycle would write gigabytes a
        day for nothing and make replay re-run them all."""
        ts = self._now()
        busy = (self._input_since_tick or work > 0
                or self._sends_since_tick > 0)
        if not busy and self._last_tick_ts is not None and \
                ts - self._last_tick_ts < IDLE_HEARTBEAT:
            return
        if ts == self._last_tick_ts and not busy:
            return
        self._last_tick_ts = ts
        self._input_since_tick = False
        self._sends_since_tick = 0
        self.record(TICK, "", None)

    def iter_records(self):
        """-> (ts, kind, frm, data) in ingress order."""
        for key, value in self._store.iterator():
            ts, kind, frm, data = unpack(value)
            yield ts, kind, frm, data


def attach_recorder(node, recorder: Recorder) -> None:
    """Instrument a node's ingress + prod + egress seams. Must run before
    traffic. Appends a RUN_START boundary: replay stops at a second boundary
    (a restarted process starts a fresh perf_counter epoch, and one replayed
    node cannot cross it — replay the FIRST run; later runs start from the
    restart's durable state, not genesis)."""
    recorder.record(RUN_START, node.name, None)
    bus = node.node_bus
    orig_incoming = bus.process_incoming
    orig_client = node.handle_client_message
    orig_prod = node.prod
    orig_send = bus.send

    def counting_send(message, dst=None):
        recorder.note_send()
        orig_send(message, dst)

    bus.send = counting_send

    def recording_incoming(message, frm):
        if isinstance(message, ExternalBus.Connected):
            recorder.record(CONNECTED, frm, None)
        elif isinstance(message, ExternalBus.Disconnected):
            recorder.record(DISCONNECTED, frm, None)
        elif isinstance(message, MessageBase):
            recorder.record(INCOMING, frm, message.to_dict())
        orig_incoming(message, frm)

    def recording_client(msg, frm):
        recorder.record(CLIENT, frm, msg)
        orig_client(msg, frm)

    def recording_prod():
        work = orig_prod()
        # ts is the cycle's FROZEN clock value, unchanged since the cycle
        # began, so appending the tick after the fact keeps log order
        recorder.record_tick(work)
        return work

    bus.process_incoming = recording_incoming
    node.handle_client_message = recording_client
    node.prod = recording_prod


def replay(records, node, timer) -> int:
    """Feed a recorded stream into a fresh node under a MockTimer.

    The timer is advanced to each record's timestamp before delivery, and
    prod cycles re-run exactly at TICK records, so every time-driven
    behavior (batch cuts and their pp_time, view-change timeouts, freshness
    probes) fires in replay exactly where it fired live. Returns the number
    of records replayed. The node must be bootstrapped from the same genesis
    as the recorded run; its sends go wherever its bus points (typically a
    sink) — replay only reproduces STATE, not traffic.
    """
    n = 0
    runs_seen = 0
    connected: set[str] = set(node.node_bus.connecteds)
    for ts, kind, frm, data in records:
        if kind == RUN_START:
            runs_seen += 1
            if runs_seen > 1:
                break     # next process epoch: fresh clock, fresh node state
            continue
        # jump WITHOUT stepping through intermediate deadlines, then service
        # once: live QueueTimer fires due callbacks in a batch at the frozen
        # cycle time, never at their exact deadlines — replay must match
        timer.set_time_no_service(ts)
        timer.service()
        if kind == TICK:
            node.prod()
        elif kind == CONNECTED:
            connected.add(frm)
            node.node_bus.update_connecteds(connected)
        elif kind == DISCONNECTED:
            connected.discard(frm)
            node.node_bus.update_connecteds(connected)
        elif kind == INCOMING:
            node.node_bus.process_incoming(message_from_dict(data), frm)
        elif kind == CLIENT:
            node.handle_client_message(data, frm)
        n += 1
    # drain whatever the last inputs queued
    node.prod()
    return n
