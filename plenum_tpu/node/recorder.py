"""Recorder: capture a node's complete input stream for deterministic
single-node replay.

Reference behavior: plenum/recorder/recorder.py:13 — every incoming node
message and client request is appended to a KV store with a time offset;
a replayer later feeds the stream back into a freshly-bootstrapped node,
reproducing its exact state evolution (the debugging story for "what did
this node see before it broke").

Design: the recorder wraps the two ingress points (ExternalBus
process_incoming + Node.handle_client_message) rather than the socket layer,
so records are wire-decoded messages — replay does not need a network stack
at all, only a MockTimer. Connection events are recorded too (they drive
primary-health and view-change logic).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from plenum_tpu.common.event_bus import ExternalBus
from plenum_tpu.common.message_base import MessageBase, message_from_dict
from plenum_tpu.common.serialization import pack, unpack

INCOMING = "R"       # node protocol message
CLIENT = "C"         # client request dict
CONNECTED = "+"      # peer connection event
DISCONNECTED = "-"
TICK = "T"           # a prod cycle ran at this timer time


class Recorder:
    """Appends timestamped ingress + prod-tick records to a KV store.

    Ticks matter for determinism: the primary's batch flush happens INSIDE
    prod (Replica.service), so pp_time — which enters the 3PC digest the
    peers' recorded COMMITs certify — is the timer time of the prod cycle
    that cut the batch. Replay must therefore re-run prods at the recorded
    cycle times, not at input-arrival times. Consecutive idle ticks at the
    same timestamp are deduplicated.
    """

    def __init__(self, store, now: Callable[[], float]):
        self._store = store
        self._now = now
        self._seq = store.size if hasattr(store, "size") else 0
        self._last_tick_ts: Optional[float] = None
        self._input_since_tick = True

    def record(self, kind: str, frm: str, data: Any) -> None:
        if kind != TICK:
            self._input_since_tick = True
        key = self._seq.to_bytes(8, "big")
        self._seq += 1
        self._store.put(key, pack([self._now(), kind, frm, data]))

    def record_tick(self) -> None:
        ts = self._now()
        if ts == self._last_tick_ts and not self._input_since_tick:
            return
        self._last_tick_ts = ts
        self._input_since_tick = False
        self.record(TICK, "", None)

    def iter_records(self):
        """-> (ts, kind, frm, data) in ingress order."""
        for key, value in self._store.iterator():
            ts, kind, frm, data = unpack(value)
            yield ts, kind, frm, data


def attach_recorder(node, recorder: Recorder) -> None:
    """Instrument a node's ingress + prod seams. Must run before traffic."""
    bus = node.node_bus
    orig_incoming = bus.process_incoming
    orig_client = node.handle_client_message
    orig_prod = node.prod

    def recording_incoming(message, frm):
        if isinstance(message, ExternalBus.Connected):
            recorder.record(CONNECTED, frm, None)
        elif isinstance(message, ExternalBus.Disconnected):
            recorder.record(DISCONNECTED, frm, None)
        elif isinstance(message, MessageBase):
            recorder.record(INCOMING, frm, message.to_dict())
        orig_incoming(message, frm)

    def recording_client(msg, frm):
        recorder.record(CLIENT, frm, msg)
        orig_client(msg, frm)

    def recording_prod():
        recorder.record_tick()
        return orig_prod()

    bus.process_incoming = recording_incoming
    node.handle_client_message = recording_client
    node.prod = recording_prod


def replay(records, node, timer) -> int:
    """Feed a recorded stream into a fresh node under a MockTimer.

    The timer is advanced to each record's timestamp before delivery, and
    prod cycles re-run exactly at TICK records, so every time-driven
    behavior (batch cuts and their pp_time, view-change timeouts, freshness
    probes) fires in replay exactly where it fired live. Returns the number
    of records replayed. The node must be bootstrapped from the same genesis
    as the recorded run; its sends go wherever its bus points (typically a
    sink) — replay only reproduces STATE, not traffic.
    """
    n = 0
    connected: set[str] = set(node.node_bus.connecteds)
    for ts, kind, frm, data in records:
        timer.advance_until(ts)
        if kind == TICK:
            node.prod()
        elif kind == CONNECTED:
            connected.add(frm)
            node.node_bus.update_connecteds(connected)
        elif kind == DISCONNECTED:
            connected.discard(frm)
            node.node_bus.update_connecteds(connected)
        elif kind == INCOMING:
            node.node_bus.process_incoming(message_from_dict(data), frm)
        elif kind == CLIENT:
            node.handle_client_message(data, frm)
        n += 1
    # drain whatever the last inputs queued
    node.prod()
    return n
