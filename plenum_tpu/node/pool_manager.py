"""Pool membership: node registry derived from the pool ledger.

Reference behavior: plenum/server/pool_manager.py:99 (TxnPoolManager) +
common/stack_manager.py — the validator registry (name → addresses, verkeys,
services, BLS keys) is read out of pool-ledger state; NODE txns add, demote,
re-key, or re-address validators; every change recomputes f and all quorums
(node.py:731 setPoolParams) and is announced so stacks/replicas can adjust.
"""
from __future__ import annotations

from typing import Callable, Optional

from plenum_tpu.common.quorums import Quorums
from plenum_tpu.execution.handlers.node import VALIDATOR, NodeHandler


class TxnPoolManager:
    def __init__(self, node_handler: NodeHandler,
                 on_pool_changed: Optional[Callable[[], None]] = None):
        self._nodes = node_handler
        self._on_changed = on_pool_changed or (lambda: None)
        self._cached_reg: dict[str, dict] = {}
        self.reload()

    # --- registry ---------------------------------------------------------

    def reload(self) -> bool:
        """Re-derive the registry from committed pool state; True if changed."""
        reg = {}
        known = set()
        for dest, rec in self._nodes.all_nodes(committed=True).items():
            known.add(rec.get("alias", dest))
            if VALIDATOR in rec.get("services", [VALIDATOR]):
                reg[rec.get("alias", dest)] = {**rec, "dest": dest}
        changed = reg != self._cached_reg
        self._cached_reg = reg
        # every node the pool ledger KNOWS, validator or not: a demoted/
        # not-yet-promoted member may still be served catchup (it must be
        # able to sync before it can be promoted into the validator set)
        self._known_aliases = known
        return changed

    @property
    def known_node_names(self) -> set[str]:
        """Aliases of every pool-ledger node regardless of services."""
        return set(getattr(self, "_known_aliases", set()))

    def pool_changed(self) -> None:
        """Call after a pool-ledger batch commits (ref poolTxnCommitted)."""
        if self.reload():
            self._on_changed()

    @property
    def node_names(self) -> list[str]:
        return sorted(self._cached_reg)

    @property
    def node_count(self) -> int:
        return len(self._cached_reg)

    @property
    def quorums(self) -> Quorums:
        return Quorums(max(self.node_count, 1))

    def node_info(self, name: str) -> Optional[dict]:
        return self._cached_reg.get(name)

    def bls_key_of(self, name: str) -> Optional[str]:
        info = self._cached_reg.get(name)
        return info.get("blskey") if info else None

    def node_ha(self, name: str) -> Optional[tuple[str, int]]:
        info = self._cached_reg.get(name)
        if not info or "node_ip" not in info:
            return None
        return (info["node_ip"], info["node_port"])

    def client_ha(self, name: str) -> Optional[tuple[str, int]]:
        info = self._cached_reg.get(name)
        if not info or "client_ip" not in info:
            return None
        return (info["client_ip"], info["client_port"])
