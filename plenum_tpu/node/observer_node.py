"""ObserverNode: a deployable, self-sufficient read follower.

Reference behavior: plenum/server/observer/observer_node.py — a node-like
process with its own storage and transport that receives committed batches
from the validator pool and keeps a full ledger/state copy without taking
part in consensus.

Redesign: instead of subclassing the validator (the reference's observer is
a Node subclass carrying the whole stack), the follower is a small asyncio
process built from three existing parts:

  - NodeBootstrap components (the same ledgers/states/write-manager a
    validator gets — minus consensus, which it never runs);
  - NodeObserver (observer.py): f+1 content-identical push quorum, root
    re-derivation, atomic gap-fill;
  - plain client connections to each validator's client port. One
    OBSERVER_REGISTER op subscribes a connection to BatchCommitted pushes
    (Node._service_client_msgs); gap transactions are pulled with ordinary
    GET_TXN queries over the same connections — no side channel, no
    caller-supplied fetch_txn.

Liveness model: pushes only cover live traffic, so a follower that was down
catches up on the FIRST push after restart — the batch's Merkle/state roots
bind the entire gap below it, and NodeObserver.catch_up stages + verifies
the pulled range before committing anything. A Byzantine validator can
stall (feed nothing) but never corrupt (quorum + root checks).

    obs = ObserverNode("obs1", genesis, addrs, f=1, data_dir=...)
    await obs.run(stop)        # or ObserverNode.main() as a process
"""
from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, Optional

from plenum_tpu.common.message_base import MessageValidationError, message_from_dict
from plenum_tpu.common.node_messages import BatchCommitted
from plenum_tpu.common.serialization import pack, unpack
from plenum_tpu.execution.txn import GET_TXN
from plenum_tpu.node.observer import NodeObserver

logger = logging.getLogger(__name__)


# one wire-framing implementation for the whole package: length-prefixed
# frames with the transport's max-frame guard
from plenum_tpu.ingress.observer_reads import FROM_CONFIG
from plenum_tpu.network.tcp_stack import HandshakeError, _read_frame


class ObserverNode:
    RECONNECT_DELAY = 2.0
    QUERY_TIMEOUT = 10.0
    GAP_LIMIT = 10_000

    def __init__(self, name: str, genesis_txns: dict,
                 addrs: dict[str, tuple[str, int]], f: int = 1,
                 data_dir: Optional[str] = None,
                 storage_backend: str = "memory",
                 client_port: Optional[int] = None,
                 client_host: str = "0.0.0.0",
                 anchor_lag_max=FROM_CONFIG,
                 state_commitment: str = "mpt",
                 state_commitment_per_ledger: Optional[dict] = None,
                 verkle_width: Optional[int] = None):
        import time as _time

        from plenum_tpu.ingress.observer_reads import ObserverReadGate
        from plenum_tpu.node.bootstrap import NodeBootstrap
        self.name = name
        self.addrs = dict(addrs)
        # replicated state rides the validators' commitment scheme (the
        # multi-signed anchors are scheme-defined; see SimObserver note)
        components = NodeBootstrap(
            name, genesis_txns=genesis_txns, data_dir=data_dir,
            storage_backend=storage_backend,
            state_commitment=state_commitment,
            state_commitment_per_ledger=state_commitment_per_ledger,
            verkle_width=verkle_width).build()
        self.observer = NodeObserver(components, f=f)
        # read fan-out (ROADMAP item 3): serve PR 4 read_proof envelopes
        # from the replicated state at the last VERIFIED BLS anchor;
        # clients dial client_port exactly like a validator's client port
        self.client_port = client_port
        self.client_host = client_host
        self.read_gate = ObserverReadGate(
            components, self._genesis_bls_keys(genesis_txns),
            n_nodes=len(self.addrs), now=_time.time,
            anchor_lag_max=anchor_lag_max)
        self._conns: dict[str, tuple] = {}         # validator -> (reader, writer)
        self._batches: asyncio.Queue = asyncio.Queue(maxsize=1000)
        # (validator, ledger_id, seq_no) -> Future for in-flight GET_TXN
        self._pending: dict[tuple, asyncio.Future] = {}
        self._req_ids = itertools.count(1)
        # gapped batches need their own f+1 push quorum BEFORE gap-fill:
        # NodeObserver.process_batch only votes on gap-free batches, and
        # catch_up applies unconditionally — without this gate a single
        # Byzantine validator could feed a self-consistent fabricated
        # chain through the gap path. (ledger, start) -> {validator: (digest, batch)}
        self._gap_votes: dict[tuple, dict[str, tuple[str, BatchCommitted]]] = {}
        self.batches_applied = 0

    @staticmethod
    def _genesis_bls_keys(genesis_txns: dict) -> dict[str, str]:
        """alias -> BLS verkey from the pool genesis NODE txns — the keys
        the read gate verifies pushed multi-sigs against. (Static genesis
        keys: key-rotation-aware observers would re-derive from their
        replicated pool state; rotation is out of scope here, matching
        the verifying read clients' static key map.)"""
        from plenum_tpu.common.node_messages import POOL_LEDGER_ID
        keys: dict[str, str] = {}
        for txn in genesis_txns.get(POOL_LEDGER_ID, ()):
            try:
                data = txn["txn"]["data"]["data"]
                if data.get("blskey"):
                    keys[data["alias"]] = data["blskey"]
            except (KeyError, TypeError):
                continue
        return keys

    # --- connection management -------------------------------------------

    async def _maintain(self, validator: str, stop: asyncio.Event) -> None:
        """Dial, register, read until drop; repeat until stopped."""
        host, port = self.addrs[validator]
        while not stop.is_set():
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), 5.0)
            except (OSError, asyncio.TimeoutError):
                await _sleep_or_stop(self.RECONNECT_DELAY, stop)
                continue
            self._conns[validator] = (reader, writer)
            try:
                payload = pack({"op": "OBSERVER_REGISTER"})
                writer.write(len(payload).to_bytes(4, "big") + payload)
                await writer.drain()
                await self._read_loop(validator, reader)
            except (OSError, asyncio.IncompleteReadError):
                pass
            except HandshakeError as e:
                # shared _read_frame rejects oversize/desynced frames; drop
                # the stream and redial rather than killing this
                # validator's maintain task (which would silently shrink
                # the f+1 push quorum)
                logger.warning("%s: bad frame from %s (%s); reconnecting",
                               self.name, validator, e)
            finally:
                self._conns.pop(validator, None)
                try:
                    writer.close()
                except Exception:
                    pass
            await _sleep_or_stop(self.RECONNECT_DELAY, stop)

    async def _read_loop(self, validator: str,
                         reader: asyncio.StreamReader) -> None:
        while True:
            frame = await _read_frame(reader)
            try:
                msg = unpack(frame)
            except Exception:
                return                             # desynced stream: redial
            if not isinstance(msg, dict):
                continue
            op = msg.get("op")
            if op == "BATCH_COMMITTED":
                try:
                    bc = message_from_dict(msg)
                except MessageValidationError:
                    continue
                if isinstance(bc, BatchCommitted):
                    try:
                        self._batches.put_nowait((validator, bc))
                    except asyncio.QueueFull:
                        pass                       # applier behind: drop;
                        # the next push re-triggers gap-fill
            elif op == "REPLY":
                self._resolve_reply(validator, msg.get("result"))

    def _resolve_reply(self, validator: str, result: Any) -> None:
        if not isinstance(result, dict) or result.get("type") != GET_TXN:
            return
        key = (validator, result.get("ledgerId"), result.get("seqNo"))
        fut = self._pending.pop(key, None)
        if fut is not None and not fut.done():
            fut.set_result(result.get("data"))

    # --- pulling gap txns -------------------------------------------------

    async def _get_txn(self, validator: str, ledger_id: int,
                       seq_no: int) -> Optional[dict]:
        conn = self._conns.get(validator)
        if conn is None:
            return None
        _, writer = conn
        key = (validator, ledger_id, seq_no)
        fut = self._pending.setdefault(
            key, asyncio.get_running_loop().create_future())
        query = {"identifier": self.name, "reqId": next(self._req_ids),
                 "operation": {"type": GET_TXN, "ledgerId": ledger_id,
                               "data": seq_no}}
        try:
            payload = pack(query)
            writer.write(len(payload).to_bytes(4, "big") + payload)
            await writer.drain()
            return await asyncio.wait_for(fut, self.QUERY_TIMEOUT)
        except (OSError, asyncio.TimeoutError):
            self._pending.pop(key, None)
            return None

    # --- applying ---------------------------------------------------------

    async def _apply_loop(self, stop: asyncio.Event) -> None:
        while not stop.is_set():
            try:
                validator, batch = await asyncio.wait_for(
                    self._batches.get(), 0.5)
            except asyncio.TimeoutError:
                continue
            ledger = self.observer.c.db.get_ledger(batch.ledger_id)
            if ledger is None:
                continue
            if batch.seq_no_start > ledger.size + 1:
                if self._gap_quorum(validator, batch):
                    await self._fill_gap(validator, batch)
            else:
                applied = self.observer.process_batch(batch, frm=validator)
                if applied:
                    self.batches_applied += 1
                # every push feeds the read gate: applied batches record
                # roots + invalidate cached reads, and ANY push's
                # multi-sig advances the serving anchor once it verifies
                self.read_gate.on_push(batch, applied)

    def _gap_quorum(self, validator: str, batch: BatchCommitted) -> bool:
        """One vote per validator per (ledger, start); f+1 content-identical
        pushes arm the gap-fill (mirrors NodeObserver.process_batch)."""
        import hashlib
        from plenum_tpu.common.serialization import signing_serialize
        key = (batch.ledger_id, batch.seq_no_start)
        # multi_sig excluded, same as NodeObserver.process_batch: honest
        # validators attach different (all-valid) aggregations
        digest = hashlib.sha256(
            signing_serialize(batch.quorum_dict())).hexdigest()
        # one in-flight gap vote per validator per ledger: a new start from
        # the same validator supersedes its old one, so the bucket count is
        # bounded by pool size — a Byzantine pusher minting ever-new starts
        # can no longer grow the buffer without bound
        for other_key, other_votes in list(self._gap_votes.items()):
            if other_key[0] == batch.ledger_id and other_key != key:
                other_votes.pop(validator, None)
                if not other_votes:
                    del self._gap_votes[other_key]
        votes = self._gap_votes.setdefault(key, {})
        votes[validator] = (digest, batch)
        if sum(1 for d, _ in votes.values()
               if d == digest) < self.observer.f + 1:
            return False
        # settled ranges leave the buffer (bounded by in-flight starts)
        ledger = self.observer.c.db.get_ledger(batch.ledger_id)
        self._gap_votes = {k: v for k, v in self._gap_votes.items()
                           if not (k[0] == batch.ledger_id
                                   and k[1] <= max(ledger.size,
                                                   batch.seq_no_start))}
        return True

    async def _fill_gap(self, validator: str, batch: BatchCommitted) -> None:
        """Prefetch the missing range from the pushing validator, then hand
        NodeObserver.catch_up a lookup into it. Verification (roots bind
        the whole chain; nothing commits on mismatch) lives in catch_up."""
        ledger = self.observer.c.db.get_ledger(batch.ledger_id)
        first, last = ledger.size + 1, batch.seq_no_start - 1
        if last - first + 1 > self.GAP_LIMIT:
            logger.warning("%s: gap of %d txns exceeds limit; skipping",
                           self.name, last - first + 1)
            return
        prefetched: dict[int, dict] = {}
        for seq in range(first, last + 1):
            txn = await self._get_txn(validator, batch.ledger_id, seq)
            if txn is None:
                return                             # puller unreachable: the
                # next push retries against whoever sent it
            prefetched[seq] = txn
        if self.observer.catch_up(
                batch, lambda lid, seq: prefetched.get(seq)):
            self.batches_applied += 1
            self.read_gate.on_push(batch, True)

    # --- serving verified reads to clients --------------------------------

    async def _serve_client(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        """One client connection on the observer's client port: the same
        length-prefixed framing as a validator's client port, answering
        READ queries from the replicated state through the read gate
        (ObserverReadGate.serve — the one serving path the in-process
        SimObserver shares, so the twins cannot diverge)."""
        try:
            while True:
                frame = await _read_frame(reader)
                try:
                    msg = unpack(frame)
                except Exception:
                    return                     # desynced stream: drop it
                if not isinstance(msg, dict):
                    continue
                payload = pack(self.read_gate.serve(msg).to_dict())
                writer.write(len(payload).to_bytes(4, "big") + payload)
                await writer.drain()
        except (OSError, HandshakeError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    # --- lifecycle --------------------------------------------------------

    async def run(self, stop: asyncio.Event) -> None:
        tasks = [asyncio.create_task(self._maintain(v, stop))
                 for v in self.addrs]
        tasks.append(asyncio.create_task(self._apply_loop(stop)))
        server = None
        try:
            # inside the try: a bind failure (port in use) must still
            # cancel the maintain/apply tasks on the way out
            if self.client_port is not None:
                server = await asyncio.start_server(
                    self._serve_client, self.client_host, self.client_port)
            await stop.wait()
        finally:
            if server is not None:
                server.close()
                await server.wait_closed()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            for _, writer in self._conns.values():
                try:
                    writer.close()
                except Exception:
                    pass
            self._conns.clear()


async def _sleep_or_stop(delay: float, stop: asyncio.Event) -> None:
    try:
        await asyncio.wait_for(stop.wait(), delay)
    except asyncio.TimeoutError:
        pass
