"""Request dissemination + propagate quorum (digest-gossip).

Reference behavior: plenum/server/propagator.py — on first sight of a client
REQUEST a node broadcasts PROPAGATE (:204); a request finalizes when f+1
matching propagates are seen (req_with_acceptable_quorum:132, set_finalised
:136) and is then forwarded to every replica's queue as a ReqKey. Matching
means same digest from distinct senders; a node's own propagate counts.

Redesign (digest-gossip): the reference floods the FULL request body
n*(n-1) times per transaction — the measured dominant wire cost past small
pools (docs/performance.md 7-node table: 87% of bytes). Here at most ONE
node broadcasts the body: the digest-DESIGNATED disseminator (derived from
the request digest over the sorted validator list, so every node picks the
same one with no coordination; clients broadcast to the whole pool, so
"the node that took the client request" is not unique). Every other vote
is a ~100-byte (digest, sender_client) pair. Votes count toward the f+1
finalization quorum regardless of which shape carried them; a node that
reaches quorum (or is asked to order) before holding the body pulls it
through MessageReq from one of the voters — the node-side fetch loop
retries the NEXT voter on timeout/bad reply. Forwarding to replicas — and
therefore batching/ordering — still requires the verified body: digest
votes can never finalize content nobody holds.

Outbound propagates buffer in an outbox the node flushes once per prod
tick as a single PropagateBatch, so the n^2 message COUNT (framing,
from_dict validation, inbox handling) amortizes across every request in
flight in the same tick.
"""
from __future__ import annotations

from typing import Callable, Optional

from plenum_tpu.common.node_messages import Propagate, PropagateBatch
from plenum_tpu.common.quorums import Quorums
from plenum_tpu.common.request import Request
from plenum_tpu.common.tracing import NULL_TRACER, PROPAGATE_QUORUM


class RequestState:
    __slots__ = ("request", "propagates", "finalised", "forwarded",
                 "client_name", "executed", "added_at", "executed_at",
                 "fetch_started")

    def __init__(self, request: Optional[Request], added_at: float = 0.0):
        self.request = request                     # None until a body lands
        self.propagates: dict[str, bool] = {}      # sender node -> seen
        self.finalised = False
        self.forwarded = False
        self.executed = False
        self.client_name: Optional[str] = None     # who to REPLY to
        self.added_at = added_at                   # for unfinalized-state TTL
        self.executed_at: Optional[float] = None   # for executed-state TTL
        self.fetch_started = False                 # body fetch already queued


class Requests(dict):
    """digest -> RequestState (ref propagator.py Requests)."""

    def __init__(self, now: Callable[[], float]):
        super().__init__()
        self._now = now

    def add(self, request: Request) -> RequestState:
        state = self.get(request.digest)
        if state is None:
            state = self[request.digest] = RequestState(
                request, added_at=self._now())
        elif state.request is None:
            # digest votes arrived first; the body just landed (verified)
            state.request = request
        return state

    def add_digest(self, digest: str) -> RequestState:
        if digest not in self:
            self[digest] = RequestState(None, added_at=self._now())
        return self[digest]

    def add_propagate(self, request: Request, sender: str) -> RequestState:
        state = self.add(request)
        state.propagates[sender] = True
        return state

    def votes(self, digest: str) -> int:
        state = self.get(digest)
        return len(state.propagates) if state else 0

    def get_request(self, digest: str) -> Optional[Request]:
        state = self.get(digest)
        return state.request if state else None

    def has_body(self, digest: str) -> bool:
        state = self.get(digest)
        return state is not None and state.request is not None

    def mark_executed(self, digest: str) -> None:
        state = self.get(digest)
        if state:
            state.executed = True
            state.executed_at = self._now()

    def free(self, digest: str) -> None:
        self.pop(digest, None)


class Propagator:
    def __init__(self, name: str, quorums: Quorums,
                 send_to_nodes: Callable,
                 forward_to_replicas: Callable[[str], None],
                 now: Callable[[], float],
                 validators: Optional[Callable[[], list]] = None,
                 request_body: Optional[Callable[[str, bool], None]] = None,
                 digest_gossip: bool = True,
                 tracer=None):
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self.name = name
        self.quorums = quorums
        self.requests = Requests(now)
        self._send = send_to_nodes
        self._forward = forward_to_replicas
        self._validators = validators or (lambda: [name])
        # request_body(digest, urgent): node-side fetch loop (MessageReq to
        # a voter, retrying the next voter on timeout/bad reply). urgent
        # skips the grace delay that lets the client's own broadcast land.
        self._request_body = request_body or (lambda digest, urgent: None)
        self.digest_gossip = digest_gossip
        # outbox of (Propagate, is_body) flushed once per prod tick
        self._outbox: list[Propagate] = []

    def set_quorums(self, quorums: Quorums) -> None:
        self.quorums = quorums

    # ------------------------------------------------------------------ #
    # dissemination policy                                               #
    # ------------------------------------------------------------------ #

    def is_disseminator(self, digest: str) -> bool:
        """One deterministic body-broadcaster per digest: every node maps
        the digest onto the sorted validator list the same way. If the
        designated node never saw the request, the body still spreads via
        the per-digest fetch loop — liveness never hinges on one node."""
        validators = sorted(self._validators())
        if not validators:
            return True
        try:
            idx = int(digest[:8], 16) % len(validators)
        except ValueError:
            idx = 0
        return validators[idx] == self.name

    def _vote(self, request: Optional[Request], digest: str,
              sender_client: Optional[str]) -> None:
        """Queue our own propagate: the full body only when we hold it AND
        are the designated disseminator (or gossip is off); a compact
        digest vote otherwise."""
        if request is not None and (not self.digest_gossip
                                    or self.is_disseminator(digest)):
            self._outbox.append(Propagate(request=request.to_dict(),
                                          sender_client=sender_client))
        else:
            self._outbox.append(Propagate(digest=digest,
                                          sender_client=sender_client))

    def flush_outbox(self) -> None:
        """Coalesce this tick's queued propagates into one PropagateBatch
        broadcast (single messages go out bare — no envelope tax)."""
        if not self._outbox:
            return
        queued, self._outbox = self._outbox, []
        if len(queued) == 1:
            self._send(queued[0])
            return
        votes = tuple((p.digest, p.sender_client)
                      for p in queued if p.request is None)
        bodies = tuple(p.to_dict() for p in queued if p.request is not None)
        self._send(PropagateBatch(votes=votes, bodies=bodies))

    # ------------------------------------------------------------------ #
    # ingress                                                            #
    # ------------------------------------------------------------------ #

    def propagate(self, request: Request, client_name: Optional[str]) -> None:
        """First sight of a finalizable request: record own vote + broadcast.
        Body is present and signature-verified (client ingress path)."""
        state = self.requests.add(request)
        if client_name is not None:
            state.client_name = client_name
        if self.name not in state.propagates:
            state.propagates[self.name] = True
            self._vote(request, request.digest, client_name)
        self._try_finalize(request.digest)

    def process_propagate(self, msg: Propagate, frm: str) -> None:
        """A peer's body-carrying propagate (signature already verified by
        the node pipeline)."""
        request = Request.from_dict(msg.request)
        state = self.requests.add_propagate(request, frm)
        if state.client_name is None and msg.sender_client:
            state.client_name = msg.sender_client
        # relay our own vote the first time we see the request at all
        if self.name not in state.propagates:
            state.propagates[self.name] = True
            self._vote(request, request.digest, msg.sender_client)
        self._try_finalize(request.digest)

    def process_digest_vote(self, digest: str, frm: str,
                            sender_client: Optional[str]) -> None:
        """A peer's digest-only vote. Counts toward the quorum exactly like
        a body-carrying one; we do NOT echo a vote of our own until we hold
        the verified body (an honest vote always vouches for content its
        sender verified). A vote for a body we lack arms the fetch loop on
        a grace delay — the client's own broadcast usually outruns it."""
        state = self.requests.add_digest(digest)
        state.propagates[frm] = True
        if state.client_name is None and sender_client:
            state.client_name = sender_client
        if state.request is None and not state.fetch_started:
            state.fetch_started = True
            self._request_body(digest, False)
        self._try_finalize(digest)

    # ------------------------------------------------------------------ #
    # finalization                                                       #
    # ------------------------------------------------------------------ #

    def _try_finalize(self, digest: str) -> None:
        state = self.requests.get(digest)
        if state is None or state.finalised:
            return
        if not self.quorums.propagate.is_reached(len(state.propagates)):
            return
        if state.request is None:
            # quorum of digest votes with no body: ordering is waiting on
            # this request — fetch NOW (f+1 distinct voters guarantee at
            # least one honest body holder to pull from)
            state.fetch_started = True
            self._request_body(digest, True)
            return
        state.finalised = True
        if self._tracer.enabled:
            self._tracer.emit(PROPAGATE_QUORUM, digest,
                              {"votes": len(state.propagates)})
        if not state.forwarded:
            state.forwarded = True
            self._forward(digest)
