"""Request dissemination + propagate quorum.

Reference behavior: plenum/server/propagator.py — on first sight of a client
REQUEST a node broadcasts PROPAGATE (:204); a request finalizes when f+1
matching propagates are seen (req_with_acceptable_quorum:132, set_finalised
:136) and is then forwarded to every replica's queue as a ReqKey. Matching
means same digest from distinct senders; a node's own propagate counts.
"""
from __future__ import annotations

from typing import Callable, Optional

from plenum_tpu.common.node_messages import Propagate
from plenum_tpu.common.quorums import Quorums
from plenum_tpu.common.request import Request


class RequestState:
    __slots__ = ("request", "propagates", "finalised", "forwarded",
                 "client_name", "executed", "added_at", "executed_at")

    def __init__(self, request: Request, added_at: float = 0.0):
        self.request = request
        self.propagates: dict[str, bool] = {}      # sender node -> seen
        self.finalised = False
        self.forwarded = False
        self.executed = False
        self.client_name: Optional[str] = None     # who to REPLY to
        self.added_at = added_at                   # for unfinalized-state TTL
        self.executed_at: Optional[float] = None   # for executed-state TTL


class Requests(dict):
    """digest -> RequestState (ref propagator.py Requests)."""

    def __init__(self, now: Callable[[], float]):
        super().__init__()
        self._now = now

    def add(self, request: Request) -> RequestState:
        if request.digest not in self:
            self[request.digest] = RequestState(request, added_at=self._now())
        return self[request.digest]

    def add_propagate(self, request: Request, sender: str) -> RequestState:
        state = self.add(request)
        state.propagates[sender] = True
        return state

    def votes(self, digest: str) -> int:
        state = self.get(digest)
        return len(state.propagates) if state else 0

    def get_request(self, digest: str) -> Optional[Request]:
        state = self.get(digest)
        return state.request if state else None

    def mark_executed(self, digest: str) -> None:
        state = self.get(digest)
        if state:
            state.executed = True
            state.executed_at = self._now()

    def free(self, digest: str) -> None:
        self.pop(digest, None)


class Propagator:
    def __init__(self, name: str, quorums: Quorums,
                 send_to_nodes: Callable,
                 forward_to_replicas: Callable[[str], None],
                 now: Callable[[], float]):
        self.name = name
        self.quorums = quorums
        self.requests = Requests(now)
        self._send = send_to_nodes
        self._forward = forward_to_replicas

    def set_quorums(self, quorums: Quorums) -> None:
        self.quorums = quorums

    def propagate(self, request: Request, client_name: Optional[str]) -> None:
        """First sight of a finalizable request: record own vote + broadcast."""
        state = self.requests.add(request)
        if client_name is not None:
            state.client_name = client_name
        if self.name not in state.propagates:
            state.propagates[self.name] = True
            self._send(Propagate(request=request.to_dict(),
                                 sender_client=client_name))
        self._try_finalize(request.digest)

    def process_propagate(self, msg: Propagate, frm: str) -> None:
        request = Request.from_dict(msg.request)
        state = self.requests.add_propagate(request, frm)
        if state.client_name is None and msg.sender_client:
            state.client_name = msg.sender_client
        # relay our own propagate the first time we see the request at all
        if self.name not in state.propagates:
            state.propagates[self.name] = True
            self._send(Propagate(request=request.to_dict(),
                                 sender_client=msg.sender_client))
        self._try_finalize(request.digest)

    def _try_finalize(self, digest: str) -> None:
        state = self.requests.get(digest)
        if state is None or state.finalised:
            return
        if self.quorums.propagate.is_reached(len(state.propagates)):
            state.finalised = True
            if not state.forwarded:
                state.forwarded = True
                self._forward(digest)
