"""Node orchestrator: message pipelines, propagation, ordering, execution.

Reference behavior: plenum/server/node.py (Node:129) — the prod() event loop
(:1037) services client and node inboxes under quotas, validates + propagates
client requests (processRequest:2000, processPropagate:2099), forwards
finalized requests to replicas, executes ordered batches
(processOrdered:2167, executeBatch:2661) and replies to clients
(:2753-2788). Signature checking (verifySignature:2624) happens on every
propagated request on every node.

TPU-first design difference: the pipelines are batch-shaped. Each prod cycle
drains its inbox quota FIRST, then authenticates every pending signature in
ONE batched Ed25519 dispatch (the accumulate-then-flush design of SURVEY.md §7
stage 6), then routes per-request verdicts exactly as the reference's scalar
path would (ack/nack/reject/suspicion).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from plenum_tpu.common.event_bus import ExternalBus
from plenum_tpu.common.internal_messages import ReqKey
from plenum_tpu.common.node_messages import (Ordered, Propagate, Reject,
                                             Reply, RequestAck, RequestNack)
from plenum_tpu.common.request import Request
from plenum_tpu.common.timer import TimerService
from plenum_tpu.config import Config
from plenum_tpu.consensus.bls_bft_replica import BlsBftReplica
from plenum_tpu.consensus.replica import Replica, Replicas
from plenum_tpu.crypto.bls import BlsCryptoVerifier
from plenum_tpu.execution import txn as txn_lib
from plenum_tpu.execution.exceptions import (InvalidClientRequest,
                                             UnauthorizedClientRequest)
from plenum_tpu.execution.write_manager import ThreePcBatch
from plenum_tpu.node.bootstrap import NodeComponents
from plenum_tpu.node.propagator import Propagator


class Node:
    def __init__(self, name: str, timer: TimerService, node_bus: ExternalBus,
                 components: NodeComponents,
                 client_send: Optional[Callable[[Any, str], None]] = None,
                 config: Optional[Config] = None,
                 instance_count: Optional[int] = None):
        self.name = name
        self.timer = timer
        self.node_bus = node_bus
        self.config = config or Config()
        self.c = components
        self._client_send = client_send or (lambda msg, client: None)

        self.pool_manager = components.pool_manager
        self.pool_manager._on_changed = self._on_pool_changed
        self.validators = self.pool_manager.node_names or [name]
        self.quorums = self.pool_manager.quorums

        self.propagator = Propagator(
            name, self.quorums,
            send_to_nodes=lambda msg: self.node_bus.send(msg),
            forward_to_replicas=self._forward_to_replicas)

        # RBFT: f+1 protocol instances (ref replicas.py:19)
        n_inst = instance_count if instance_count is not None \
            else self.quorums.f + 1
        self.replicas = Replicas(self._make_replica)
        self.replicas.grow_to(max(1, n_inst))

        # audit txns snapshot the current primaries + node reg
        # (ref audit_batch_handler.py:83-231)
        components.write_manager._primaries_provider = (
            lambda: list(self.replicas.master.data.primaries))
        components.write_manager._node_reg_provider = (
            lambda: list(self.validators))

        # inboxes (quota-drained each prod; ref zstack quotas config.py:250)
        self._client_inbox: list[tuple[dict, str]] = []
        self._propagate_inbox: list[tuple[Propagate, str]] = []
        self._ordered_queue: list[Ordered] = []
        self._seen_propagates: set[tuple[str, str]] = set()   # (digest, frm)

        self.node_bus.subscribe(Propagate, self._receive_propagate)
        self.spylog: list[tuple[str, Any]] = []    # lightweight event trace

    # --- wiring -----------------------------------------------------------

    def _make_replica(self, inst_id: int) -> Replica:
        bls = BlsBftReplica(
            node_name=self.name, bls_signer=self.c.bls_signer,
            bls_verifier=BlsCryptoVerifier(),
            key_register=self.c.bls_register,
            bls_store=self.c.bls_store if inst_id == 0 else None)
        audit = self.c.db.get_ledger(3)
        replica = Replica(
            node_name=self.name, inst_id=inst_id,
            validators=self.validators, timer=self.timer,
            network=self.node_bus,
            executor=self.c.executor if inst_id == 0 else None,
            bls=bls, config=self.config,
            get_request=self.propagator.requests.get_request,
            checkpoint_digest_provider=(
                lambda seq: audit.uncommitted_root_hash.hex()),
            instance_count=max(1, self.pool_manager.quorums.f + 1))
        replica.internal_bus.subscribe(Ordered, self._on_ordered)
        return replica

    def _forward_to_replicas(self, digest: str) -> None:
        for replica in self.replicas:
            replica.internal_bus.send(ReqKey(digest))

    def _on_ordered(self, msg: Ordered) -> None:
        self._ordered_queue.append(msg)

    def _on_pool_changed(self) -> None:
        """Pool-ledger commit changed membership: recompute quorums, update
        validators and BLS keys (ref node.py:731 setPoolParams)."""
        self.validators = self.pool_manager.node_names or [self.name]
        self.quorums = self.pool_manager.quorums
        self.propagator.set_quorums(self.quorums)
        for replica in self.replicas:
            replica.set_validators(self.validators)
        for n in self.pool_manager.node_names:
            self.c.bls_register.set_key(n, self.pool_manager.bls_key_of(n))

    # --- ingress ----------------------------------------------------------

    def handle_client_message(self, msg: dict, frm: str) -> None:
        self._client_inbox.append((msg, frm))

    def _receive_propagate(self, msg: Propagate, frm: str) -> None:
        self._propagate_inbox.append((msg, frm))

    # --- the prod loop ----------------------------------------------------

    def prod(self) -> int:
        """One event-loop cycle (ref node.py:1037). Returns work count."""
        count = 0
        count += self._service_client_msgs()
        count += self._service_propagates()
        self.replicas.service_all()
        count += self._service_ordered()
        return count

    # --- client pipeline --------------------------------------------------

    def _service_client_msgs(self) -> int:
        quota = self.config.LISTENER_MESSAGE_QUOTA
        batch, self._client_inbox = (self._client_inbox[:quota],
                                     self._client_inbox[quota:])
        to_auth: list[tuple[Request, str]] = []
        for msg, frm in batch:
            try:
                request = Request.from_dict(msg)
            except Exception:
                self._client_send(RequestNack(
                    identifier=str(msg.get("identifier")),
                    req_id=msg.get("reqId") or 0,
                    reason="malformed request"), frm)
                continue
            if self.c.read_manager.is_query_type(request.txn_type):
                self._answer_query(request, frm)
            elif self.c.write_manager.is_write_type(request.txn_type):
                to_auth.append((request, frm))
            else:
                self._client_send(RequestNack(
                    identifier=request.identifier, req_id=request.req_id,
                    reason=f"unknown txn type {request.txn_type!r}"), frm)
        if to_auth:
            self._auth_and_propagate(to_auth)
        return len(batch)

    def _answer_query(self, request: Request, frm: str) -> None:
        try:
            self.c.read_manager.static_validation(request)
            result = self.c.read_manager.get_result(request)
        except InvalidClientRequest as e:
            self._client_send(RequestNack(identifier=request.identifier,
                                          req_id=request.req_id,
                                          reason=e.reason), frm)
            return
        self._client_send(Reply(result=result), frm)

    def _auth_and_propagate(self, items: list[tuple[Request, str]]) -> None:
        """Batch-verify client signatures, then ack+propagate the valid ones
        (ref processRequest:2000 → recordAndPropagate)."""
        requests = [r for r, _ in items]
        statics_ok = []
        for req, frm in items:
            try:
                self.c.write_manager.static_validation(req)
                statics_ok.append(True)
            except InvalidClientRequest as e:
                self._client_send(RequestNack(identifier=req.identifier,
                                              req_id=req.req_id,
                                              reason=e.reason), frm)
                statics_ok.append(False)
        verdicts = self.c.authenticator.authenticate_batch(requests)
        for (req, frm), ok, st in zip(items, verdicts, statics_ok):
            if not st:
                continue
            if not ok:
                self._client_send(RequestNack(identifier=req.identifier,
                                              req_id=req.req_id,
                                              reason="signature verification failed"),
                                  frm)
                continue
            # dedup: already-executed request -> resend the Reply
            state = self.propagator.requests.get(req.digest)
            if state is not None and state.executed:
                continue
            self._client_send(RequestAck(identifier=req.identifier,
                                         req_id=req.req_id), frm)
            self.propagator.propagate(req, frm)

    # --- node pipeline ----------------------------------------------------

    def _service_propagates(self) -> int:
        quota = self.config.REMOTES_MESSAGE_QUOTA
        batch, self._propagate_inbox = (self._propagate_inbox[:quota],
                                        self._propagate_inbox[quota:])
        verified: list[tuple[Propagate, str, Request]] = []
        to_auth: list[tuple[Propagate, str, Request]] = []
        for msg, frm in batch:
            try:
                request = Request.from_dict(msg.request)
            except Exception:
                continue
            key = (request.digest, frm)
            if key in self._seen_propagates:
                continue
            self._seen_propagates.add(key)
            if request.digest in self.propagator.requests:
                # signature was already verified when first seen
                verified.append((msg, frm, request))
            else:
                to_auth.append((msg, frm, request))
        if to_auth:
            verdicts = self.c.authenticator.authenticate_batch(
                [r for _, _, r in to_auth])
            for (msg, frm, req), ok in zip(to_auth, verdicts):
                if ok:
                    verified.append((msg, frm, req))
                else:
                    self.spylog.append(("suspicious_propagate", frm))
        for msg, frm, _ in verified:
            self.propagator.process_propagate(msg, frm)
        return len(batch)

    # --- ordered batches --------------------------------------------------

    def _service_ordered(self) -> int:
        done = 0
        while self._ordered_queue:
            msg = self._ordered_queue.pop(0)
            done += 1
            if msg.inst_id != 0:
                self.spylog.append(("backup_ordered", msg))
                continue
            self._execute_batch(msg)
        return done

    def _execute_batch(self, msg: Ordered) -> None:
        """Commit the ordered batch and REPLY (ref executeBatch:2661)."""
        batch = ThreePcBatch(
            ledger_id=msg.ledger_id, view_no=msg.view_no,
            pp_seq_no=msg.pp_seq_no, pp_time=msg.pp_time,
            valid_digests=tuple(msg.req_idr),
            state_root=bytes.fromhex(msg.state_root) if msg.state_root else b"",
            txn_root=bytes.fromhex(msg.txn_root) if msg.txn_root else b"",
            audit_txn_root=(bytes.fromhex(msg.audit_txn_root)
                            if msg.audit_txn_root else b""),
            primaries=tuple(self.replicas.master.data.primaries),
            node_reg=tuple(self.validators))
        committed = self.c.executor.commit_batch(batch)
        self.spylog.append(("executed", (msg.view_no, msg.pp_seq_no)))
        for txn in committed:
            digest = txn_lib.txn_digest(txn)
            state = self.propagator.requests.get(digest) if digest else None
            self.propagator.requests.mark_executed(digest)
            if state is not None and state.client_name is not None:
                self._client_send(Reply(result=txn), state.client_name)
        for digest in msg.discarded:
            state = self.propagator.requests.get(digest)
            if state is not None and state.client_name is not None:
                self._client_send(Reject(identifier=state.request.identifier,
                                         req_id=state.request.req_id,
                                         reason="rejected by dynamic validation"),
                                  state.client_name)
        if msg.ledger_id == 0:
            self.pool_manager.pool_changed()

    # --- accessors --------------------------------------------------------

    @property
    def master_replica(self) -> Replica:
        return self.replicas.master

    @property
    def f(self) -> int:
        return self.quorums.f
