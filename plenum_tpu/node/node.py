"""Node orchestrator: message pipelines, propagation, ordering, execution.

Reference behavior: plenum/server/node.py (Node:129) — the prod() event loop
(:1037) services client and node inboxes under quotas, validates + propagates
client requests (processRequest:2000, processPropagate:2099), forwards
finalized requests to replicas, executes ordered batches
(processOrdered:2167, executeBatch:2661) and replies to clients
(:2753-2788). Signature checking (verifySignature:2624) happens on every
propagated request on every node.

TPU-first design difference: the pipelines are batch-shaped. Each prod cycle
drains its inbox quota FIRST, then authenticates every pending signature in
ONE batched Ed25519 dispatch (the accumulate-then-flush design of SURVEY.md §7
stage 6), then routes per-request verdicts exactly as the reference's scalar
path would (ack/nack/reject/suspicion).
"""
from __future__ import annotations

import os
import time
from typing import Any, Callable, Optional

from plenum_tpu.catchup import NodeLeecherService, SeederService
from plenum_tpu.common.event_bus import ExternalBus
from plenum_tpu.common.internal_messages import (MissingMessage,
                                                 NeedMasterCatchup,
                                                 NeedViewChange,
                                                 NewViewAccepted,
                                                 RaisedSuspicion, ReqKey,
                                                 RequestPropagates,
                                                 VoteForViewChange)
from plenum_tpu.common.suspicion_codes import Suspicions
from plenum_tpu.common.node_messages import (AUDIT_LEDGER_ID,
                                             BackupInstanceFaulty,
                                             BatchCommitted,
                                             CatchupRep, CatchupReq,
                                             Commit, ConsistencyProof,
                                             DOMAIN_LEDGER_ID,
                                             LedgerStatus, NewView,
                                             Ordered, POOL_LEDGER_ID,
                                             Prepare, PrePrepare,
                                             Propagate, PropagateBatch,
                                             Reject, Reply,
                                             RequestAck, RequestNack,
                                             Telemetry, ViewChange)
from plenum_tpu.common.serialization import pack, unpack
from plenum_tpu.execution.database_manager import (NODE_STATUS_DB_LABEL,
                                                   SEQ_NO_DB_LABEL)
from plenum_tpu.consensus.view_change_trigger_service import \
    InstanceChangeVoteStore
from plenum_tpu.common.request import Request
from plenum_tpu.common.timer import RepeatingTimer, TimerService
from plenum_tpu.config import Config
from plenum_tpu.consensus.bls_bft_replica import BlsBftReplica
from plenum_tpu.consensus.replica import Replica, Replicas
from plenum_tpu.execution import txn as txn_lib
from plenum_tpu.execution.exceptions import (InvalidClientRequest,
                                             UnauthorizedClientRequest)
from plenum_tpu.execution.write_manager import ThreePcBatch
from plenum_tpu.common.metrics import (KvMetricsCollector, MetricsCollector,
                                       MetricsName)
from plenum_tpu.common import tracing

# footprint gauge key (Node.footprint()) -> flushed MetricsName; the
# schema's "footprint" section and tools/metrics_lint.py cover each name
_FOOTPRINT_METRIC_NAMES = {
    "kv_entries": MetricsName.FOOTPRINT_KV_ENTRIES,
    "kv_disk_bytes": MetricsName.FOOTPRINT_KV_DISK_BYTES,
    "flight_ring_entries": MetricsName.FOOTPRINT_FLIGHT_RING,
    "stashed_entries": MetricsName.FOOTPRINT_STASHED,
    "request_state_entries": MetricsName.FOOTPRINT_REQUEST_STATE,
    "dedup_map_entries": MetricsName.FOOTPRINT_DEDUP_MAP,
    "read_cache_entries": MetricsName.FOOTPRINT_READ_CACHE,
    "vc_vote_entries": MetricsName.FOOTPRINT_VC_VOTES,
    "bls_sig_entries": MetricsName.FOOTPRINT_BLS_SIGS,
    "bls_verdict_cache_entries": MetricsName.FOOTPRINT_BLS_VERDICT_CACHE,
}
from plenum_tpu.node.blacklister import Blacklister
from plenum_tpu.node.bootstrap import NodeComponents
from plenum_tpu.node.message_req_processor import MessageReqProcessor
from plenum_tpu.node.monitor import Monitor
from plenum_tpu.node.notifier import (NotifierEventManager,
                                      TOPIC_VIEW_CHANGE)
from plenum_tpu.node.observer import Observable
from plenum_tpu.node.propagator import Propagator

# Suspicions whose message only the primary can have authored: these implicate
# the primary and become view-change votes rather than blacklistings
# (ref node.py:2854-2944 reportSuspiciousNode).
PRIMARY_FAULT_CODES = frozenset(s.code for s in (
    Suspicions.DUPLICATE_PPR_SENT, Suspicions.PPR_DIGEST_WRONG,
    Suspicions.PPR_REJECT_WRONG, Suspicions.PPR_STATE_WRONG,
    Suspicions.PPR_TXN_WRONG, Suspicions.PPR_TIME_WRONG,
    Suspicions.PPR_BLS_MULTISIG_WRONG, Suspicions.PPR_AUDIT_TXN_ROOT_WRONG))

# Primary-fault subset meaning "the primary's claimed roots don't match
# what we derive locally" — ambiguous between a lying primary and OUR OWN
# divergence. One primary implicated is a vote; f+1 distinct primaries
# implicated without progress means we are the diverged party (see
# Node._note_root_mismatch).
ROOT_MISMATCH_CODES = frozenset(s.code for s in (
    Suspicions.PPR_STATE_WRONG, Suspicions.PPR_TXN_WRONG,
    Suspicions.PPR_BLS_MULTISIG_WRONG, Suspicions.PPR_AUDIT_TXN_ROOT_WRONG))

# Unambiguous peer misbehavior that blacklists the sender. Deliberately tiny:
# digest/BLS mismatches against OUR pre-prepare (PR_DIGEST_WRONG, CM_BLS_WRONG)
# are NOT here — an equivocating primary makes honest peers produce exactly
# those, and blacklisting them would let the primary partition its validators.
BLACKLIST_CODES = frozenset(s.code for s in (
    Suspicions.PPR_FRM_NON_PRIMARY, Suspicions.INVALID_REQ_SIGNATURE))


class LastSentPpStore:
    """Durable {inst_id: (view_no, pp_seq_no)} of the last PRE-PREPARE each
    BACKUP primary on this node sent (ref last_sent_pp_store_helper.py:1).
    The master primary needs no such record — its position is restored from
    the audit ledger — but a restarting backup primary would otherwise
    re-issue pp_seq_no 1 and collide with its shadows' 3PC logs."""

    KEY = b"last_sent_pp"

    def __init__(self, kv):
        self._kv = kv
        # write-through cache: store() fires once per backup batch on the
        # ordering hot path, and a KV get+unpack per call would be a
        # read-modify-write tax for data only this object writes
        self._cur: dict = self._load_from_kv()

    def _load_from_kv(self) -> dict:
        try:
            got = unpack(self._kv.get(self.KEY))
            return got if isinstance(got, dict) else {}
        except Exception:
            return {}

    def store(self, inst_id: int, view_no: int, pp_seq_no: int) -> None:
        self._cur[str(inst_id)] = [view_no, pp_seq_no]
        self._kv.put(self.KEY, pack(self._cur))

    def load_raw(self) -> dict:
        return dict(self._cur)

    def erase(self) -> None:
        self._cur = {}
        try:
            self._kv.remove(self.KEY)
        except KeyError:
            pass


class Node:
    def __init__(self, name: str, timer: TimerService, node_bus: ExternalBus,
                 components: NodeComponents,
                 client_send: Optional[Callable[[Any, str], None]] = None,
                 config: Optional[Config] = None,
                 instance_count: Optional[int] = None,
                 metrics: Optional[MetricsCollector] = None,
                 tracer=None):
        self.name = name
        self.timer = timer
        self.node_bus = node_bus
        self.config = config or Config()
        self.c = components
        self._client_send = client_send or (lambda msg, client: None)
        self.started_at = timer.get_current_time()
        # tracing plane (common/tracing.py): span events at every pipeline
        # hop + protocol anomalies, in a bounded flight-recorder ring.
        # Every emission below is guarded by `tracer.enabled` so the
        # default NullTracer costs one attribute check per site.
        self.tracer = tracer if tracer is not None else tracing.NULL_TRACER
        if self.config.GC_SERVER_TUNING:
            from plenum_tpu.common.metrics import tune_gc_for_server
            tune_gc_for_server()

        # named-metric accumulators (ref common/metrics_collector.py:331);
        # KV-backed collectors get a periodic flush so history survives
        self.metrics = metrics or MetricsCollector()
        if isinstance(self.metrics, KvMetricsCollector):
            self._metrics_flush_timer = RepeatingTimer(
                timer, self.config.METRICS_FLUSH_INTERVAL,
                self._flush_metrics)
            # queue depths are sampled well below the flush cadence so the
            # flushed fold's max/mean reflect depth UNDER load, not the
            # drained snapshot at flush time (ref node.py:2289 dumps queue
            # gauges the same way)
            self._gauge_sample_timer = RepeatingTimer(
                timer, self.config.QUEUE_GAUGE_SAMPLE_INTERVAL,
                self._sample_queue_gauges)
        # shared crypto plane reports through the last-attached collector
        # (fill latency, dispatch wall time, batch size)
        verifier = getattr(components.authenticator.core_authenticator,
                           "verifier", None)
        if hasattr(verifier, "metrics"):
            verifier.metrics = self.metrics
        # breaker state transitions are protocol anomalies: the flight
        # recorder must hold the device-plane story of the seconds before
        # a fuzz failure or view change (co-hosted nodes share one plane;
        # the last-attached tracer records for the host, same convention
        # as the shared plane's metrics hook above)
        if self.tracer.enabled:
            from plenum_tpu.parallel.supervisor import find_supervisor
            sup = find_supervisor(verifier)
            if sup is not None:
                sup.breaker.on_transition = (
                    lambda old, new: self.tracer.anomaly(
                        "breaker", {"from": old, "to": new}))
        # fused crypto pipeline: the last-attached node's tracer records
        # the shared ring's `device` wave spans (same convention as the
        # shared plane's metrics hook above), and the ring's flush window
        # + controller run on this node's injectable clock so sims and
        # replays steer identically
        if components.pipeline is not None:
            components.pipeline.set_clock(timer.get_current_time)
            if self.tracer.enabled:
                components.pipeline.tracer = self.tracer
        # commit-wave stage timer (execution/write_manager.py): the
        # drain's wave duration feeds commit_wave_ms_p50/p95
        components.write_manager.metrics = self.metrics

        self.pool_manager = components.pool_manager
        self.pool_manager._on_changed = self._on_pool_changed
        self.on_pool_changed_callbacks: list[Callable[[], None]] = []
        self.validators = self.pool_manager.node_names or [name]
        self.quorums = self.pool_manager.quorums

        # suspicions → blacklist, and sender-is-a-validator, both enforced
        # at bus ingress so no service ever sees traffic from a blacklisted
        # or non-member peer — otherwise a demoted/unknown sender's votes
        # would still count toward 3PC/checkpoint/propagate quorums
        # (ref server/blacklister.py + validateNodeMsg sender checks).
        # EXCEPTION (membership churn): catchup QUERIES — LedgerStatus
        # asks and CatchupReq range fetches — are admitted from any node
        # the POOL LEDGER knows even while it is not a validator, so a
        # joining/rejoining node can sync before promotion. Only the
        # query side passes: replies and votes from non-validators stay
        # filtered, so they can never feed a cons-proof or 3PC quorum.
        self.blacklister = Blacklister(
            ttl=self.config.BLACKLIST_TTL, now=timer.get_current_time)
        self.node_bus.set_incoming_filter(
            lambda frm: frm in self.validators
            and not self.blacklister.is_blacklisted(frm),
            accept_msg=self._accept_joiner_msg)

        self.propagator = Propagator(
            name, self.quorums,
            send_to_nodes=lambda msg: self.node_bus.send(msg),
            forward_to_replicas=self._forward_to_replicas,
            now=timer.get_current_time,
            validators=lambda: self.validators,
            request_body=self._request_body,
            digest_gossip=self.config.DIGEST_GOSSIP,
            tracer=self.tracer)
        # digest -> targeted body-fetch tries so far (digest-gossip: a
        # quorum can complete before any body-carrying propagate arrives)
        self._body_fetches: dict[str, int] = {}

        # verified read plane (reads/plane.py): proof envelopes + a
        # per-signed-root result cache in front of the read manager; its
        # anchors advance from the commit path and from (possibly late)
        # multi-sig aggregation (_make_replica wires bls.on_multi_sig).
        # The domain ledger's tree hasher is reused so envelope digests
        # batch through the configured (possibly device-backed) SHA-256.
        from plenum_tpu.reads import ReadPlane
        domain_ledger = self.c.db.get_ledger(DOMAIN_LEDGER_ID)
        self.read_plane = ReadPlane(
            self.c.db, self.c.read_manager, metrics=self.metrics,
            hasher=domain_ledger.hasher if domain_ledger else None,
            tracer=self.tracer)

        # closed-loop batch controller (consensus/batch_controller.py):
        # steers batch size / wait / in-flight depth / group-commit
        # coalescing from timer-stamped stage samples; one per node,
        # wired into the MASTER ordering service and the drain loop below
        from plenum_tpu.consensus.batch_controller import make_controller
        self.batch_controller = make_controller(
            self.config, timer, tracer=self.tracer, metrics=self.metrics)

        # one network RTT estimate for the whole node (common/backoff.py):
        # fed by catchup round trips, read by catchup retry pacing AND the
        # view-change escalation timeout — both must agree on what "slow"
        # means on this link before either declares anything dead
        from plenum_tpu.common.backoff import RttEstimator
        self.catchup_rtt = RttEstimator()

        # RBFT: f+1 protocol instances by default (ref replicas.py:19),
        # recomputed as pool membership changes f; an explicit
        # instance_count PINS the count (BASELINE config 2 runs 3)
        self._pinned_instances = instance_count
        n_inst = self._n_instances()
        status_kv = self.c.db.get_store(NODE_STATUS_DB_LABEL)
        self._last_sent_pp = \
            LastSentPpStore(status_kv) if status_kv is not None else None
        self.replicas = Replicas(self._make_replica)
        self.replicas.grow_to(n_inst)

        # audit txns snapshot the current primaries + node reg
        # (ref audit_batch_handler.py:83-231). The registry MUST come from
        # UNCOMMITTED pool state — the registry at this batch's position in
        # the chain — never from the committed view (`self.validators`):
        # with a deep in-flight window, a NODE txn can sit applied-but-
        # uncommitted under later batches, and commit-time registries
        # differ node to node (one commits the NODE txn before applying
        # batch B, another applies B speculatively first), forking the
        # audit root of the SAME batch (churn soak: committed audit
        # prefixes conflicting beyond append-repair)
        components.write_manager._primaries_provider = (
            lambda: list(self.replicas.master.data.primaries))

        def uncommitted_node_reg():
            from plenum_tpu.execution.handlers.node import VALIDATOR
            reg = [rec.get("alias", dest) for dest, rec
                   in self.c.node_handler.all_nodes(committed=False).items()
                   if VALIDATOR in rec.get("services", [VALIDATOR])]
            return sorted(reg) or [name]
        components.write_manager._node_reg_provider = uncommitted_node_reg

        # highest pp_seq_no this node has executed (via ordering OR catchup);
        # an Ordered re-emitted for a re-certified batch must not double-commit
        self._last_executed_pp_seq = 0
        # pipelined signature verification: one in-flight device dispatch per
        # pipeline; while a dispatch is computing, the prod loop keeps doing
        # consensus work instead of blocking on the device round-trip
        # (accumulate-then-flush, SURVEY.md §7 stage 6). After MAX_AUTH_POLLS
        # unproductive polls the collect BLOCKS: prod loops that spin faster
        # than the device computes (MockTimer sims) must not starve the
        # pipeline forever, and a wedged dispatch must surface, not hang the
        # inbox silently.
        self.MAX_AUTH_POLLS = 50
        self._auth_inflight = None      # (token, items, polls)
        self._prop_inflight = None
        # inboxes (quota-drained each prod; ref zstack quotas config.py:250)
        self._client_inbox: list[tuple[dict, str]] = []
        self._propagate_inbox: list[tuple[Propagate, str]] = []
        self._ordered_queue: list[Ordered] = []
        # digest -> {sender: body_seen}: which propagates we already counted
        # per sender, and whether that sender has delivered a BODY yet (a
        # digest-only vote may legitimately be followed by the same peer's
        # body-carrying MessageRep fetch reply — that upgrade must not be
        # dropped as a duplicate). The whole entry is freed when the request
        # executes (durable dedup then lives in the seq-no DB keyed by
        # payload digest).
        self._seen_propagates: dict[str, dict[str, bool]] = {}
        # digest -> entries parked while that digest's signature dispatch
        # is in flight (client or propagate path): each node verifies a
        # given request's signature at most once per arrival wave. Entries
        # are ("prop", Propagate, frm) — peers' propagates that become
        # votes on the landed verdict — or ("client", Request, frm) — the
        # client's own copy racing a peer's dispatch. Popped at verdict.
        self._authing: dict[str, list[tuple]] = {}

        # catchup: seeder answers peers; leecher drives our own sync
        # (ref ledger_manager.py:21 + server/catchup/*)
        self.seeder = SeederService(
            components.db, send=self.node_bus.send,
            last_3pc=lambda: self.master_replica.last_ordered_3pc)
        self.leecher = NodeLeecherService(
            components.db, send=self.node_bus.send, timer=timer,
            quorums_provider=lambda: self.quorums,
            peers_provider=lambda: [n for n in self.validators
                                    if n != self.name],
            on_txn_added=self._on_catchup_txn,
            on_catchup_complete=self._on_catchup_complete,
            config=self.config, salt=name, rtt=self.catchup_rtt)
        # catchup progress watchdog: a stalled round (frozen progress key
        # across one interval) gets kicked — forced provider rotation +
        # immediate re-request; repeated kicks restart the round outright.
        # Paired with graceful degradation: rounds that keep ending in
        # divergence park the node in READ-ONLY mode (ordering stays
        # paused, PR 4 verified reads keep serving at the last anchored
        # root) instead of a silent retry-forever wedge.
        self._catchup_started_at: Optional[float] = None
        self._catchup_progress_mark = None
        self._catchup_kicks = 0
        self._diverged_rounds = 0
        self.read_only_degraded = False
        self._read_only_reason: Optional[str] = None
        self._catchup_watchdog_timer = RepeatingTimer(
            timer, self.config.CATCHUP_WATCHDOG_INTERVAL,
            self._catchup_watchdog)
        self.node_bus.subscribe(LedgerStatus, self._receive_ledger_status)
        self.node_bus.subscribe(ConsistencyProof,
                                self.leecher.process_consistency_proof)
        self.node_bus.subscribe(CatchupReq, self.seeder.process_catchup_req)
        self.node_bus.subscribe(CatchupRep, self.leecher.process_catchup_rep)

        self.node_bus.subscribe(Propagate, self._receive_propagate)
        self.node_bus.subscribe(PropagateBatch, self._receive_propagate_batch)
        # "ask peers for a missing message" (ref message_req_processor.py:13)
        self.message_req = MessageReqProcessor(self)
        # observers are remote followers addressed like clients
        # (ref server/observer/observable.py:11; push in _execute_batch)
        self.observable = Observable(send=self._client_send)
        from collections import deque
        self.spylog: Any = deque(maxlen=1000)      # bounded event trace

        # periodic GC of request state that never reached the propagate
        # quorum — without it spam propagates leak memory forever
        # (ref node.py _clean_req cleanup on OUTDATED_REQS_CHECK_INTERVAL)
        self._outdated_reqs_timer = RepeatingTimer(
            timer, self.config.OUTDATED_REQS_CHECK_INTERVAL,
            self._clean_outdated_reqs)

        # RBFT monitor: compare master vs backup instances, vote out a
        # degraded master (ref monitor.py:136, node.checkPerformance:2501)
        self.monitor = Monitor(self.config, now=timer.get_current_time)
        # ops notifications: throughput spikes + view changes fan out to
        # registered handlers (ref server/notifier_plugin_manager.py)
        self.notifier = NotifierEventManager(
            bounds_coeff=self.config.NOTIFIER_SPIKE_BOUNDS_COEFF,
            min_cnt=self.config.NOTIFIER_SPIKE_MIN_CNT,
            min_activity_threshold=self.config.NOTIFIER_SPIKE_MIN_ACTIVITY,
            enabled=self.config.NOTIFIER_EVENTS_ENABLED)
        self._perf_check_timer = RepeatingTimer(
            timer, self.config.PerfCheckFreq, self.check_performance)

        # faulty BACKUP instances: a backup that stops ordering while work
        # is pending poisons the monitor's master-vs-backup comparison; an
        # f+1 quorum of BackupInstanceFaulty removes it, and the next view
        # change re-adds it fresh (ref backup_instance_faulty_processor.py
        # + node.py:2580-2596)
        self.node_bus.subscribe(BackupInstanceFaulty,
                                self._process_backup_faulty)
        self._backup_wedge_markers: dict[int, tuple[tuple, float]] = {}
        self._backup_faulty_votes: dict[tuple[int, int], set[str]] = {}
        self._removed_backups: set[int] = set()
        self._backup_check_timer = RepeatingTimer(
            timer, self.config.BACKUP_INSTANCE_FAULTY_CHECK_FREQ,
            self._check_backup_instances)

        # quorum-connectivity self-check (ref inconsistency_watchers.py:5):
        # having once seen strong-quorum connectivity, dropping below weak
        # quorum means we cannot distinguish pool failure from our own
        # partition — resynchronize via catchup when connectivity returns
        from plenum_tpu.node.inconsistency_watcher import \
            NetworkInconsistencyWatcher
        self.network_watcher = NetworkInconsistencyWatcher(
            self._on_lost_quorum_connectivity, network=self.node_bus)
        self.network_watcher.set_nodes(self.validators)
        self._needs_resync = False
        self.node_bus.subscribe(ExternalBus.Connected,
                                self._maybe_resync_after_partition)
        # straggler self-check: a node stuck in an old view while the pool
        # moved on (it was mid-catchup through the view change; its lone
        # InstanceChange vote can never reach quorum, and below CHK_FREQ
        # no checkpoint-lag signal exists) would wait forever on stashed
        # FUTURE_VIEW messages. Once f+1 DISTINCT peers are seen talking
        # in higher views, the pool has provably moved on without us:
        # resync via catchup, which adopts the audit ledger's view (found
        # by the partition-heal fuzz; ref: the f+1 future-view lag checks
        # in the reference's message stashing/CurrentState handling).
        self._ahead_views: dict[str, int] = {}
        self._straggler_fired_view = -1
        self._straggler_fired_at = float("-inf")
        for mt in (PrePrepare, Prepare, Commit, ViewChange, NewView):
            self.node_bus.subscribe(mt, self._note_peer_view)
        # seq-lag twin of the view-lag check: a commit quorum sitting
        # ahead of a position that made no progress across one interval
        self._behind_marker: Optional[int] = None
        # divergence self-check: distinct primaries whose pre-prepares WE
        # rejected for root mismatches since our last ordering progress.
        # f+1 distinct primaries contain an honest one, so at that point
        # the diverged party is provably us, not them — resync (found by
        # the churn soak: a node whose uncommitted state diverged during
        # a view-change storm rejected every subsequent batch — no
        # commits recorded, so behind_evidence stayed None — and wedged
        # at its last ordered position while voting endless suspicions)
        self._divergence_primaries: set = set()
        self._divergence_fired_at = float("-inf")
        # view-change storm self-check (config.VC_STORM_RESYNC_STARTS):
        # consecutive view-change starts with no completion between them.
        # A storm no escalation can end usually means primary selection
        # itself diverges — a membership txn (demotion, removal) committed
        # on part of the pool while OUR pool ledger still lacks it, so
        # every view we propose names a different primary than our peers'
        # (flood+demotion churn fuzz: a 2v2 registry split left no view
        # able to gather a NEW_VIEW quorum, ever). The cure is a pool-
        # ledger resync, not another vote.
        self._vc_starts_streak = 0
        self._vc_resync_fired_at = float("-inf")
        self._behind_check_timer = RepeatingTimer(
            timer, self.config.STUCK_BEHIND_CHECK_FREQ,
            self._check_stuck_behind)
        # VC stall decomposition: detection stamp on primary disconnect
        self._vc_phase_ts: dict[str, float] = {}
        self.node_bus.subscribe(
            ExternalBus.Disconnected,
            lambda m, frm="": self._vc_mark("detect")
            if m.name == self.master_replica.data.primary_name else None)

        # crash-restart: a node rebuilt over durable storage resumes at the
        # audit ledger's 3PC position and primaries instead of view 0 / seq 0
        # (ref node.py:1830,1875 — the same restore catchup applies later)
        self._restore_3pc_from_audit()
        self._restore_backup_last_sent_pp()

        # live fleet telemetry (observability/snapshot.py): a periodic
        # replay-deterministic snapshot of this node's counters + health
        # state on the injectable timer. Disabled (TELEMETRY=False) this
        # is the shared NULL_TELEMETRY — one attribute check per call
        # site, no timer registered. Other subsystems (IngressPlane, the
        # sharded fabric) add their own sources/sinks after construction.
        from plenum_tpu.observability import CumulativeDelta, make_telemetry
        self.telemetry = make_telemetry(
            name, self.metrics, timer.get_current_time, config=self.config,
            timer=timer)
        if self.telemetry.enabled:
            self._telemetry_deltas = CumulativeDelta()
            self.telemetry.add_source("node", self._telemetry_node_state)
            self.telemetry.add_source("crypto", self._telemetry_crypto_state)
            self.telemetry.add_source("footprint",
                                      self._telemetry_footprint_state)
            if self.c.pipeline is not None:
                self.telemetry.add_source(
                    "pipeline", self._telemetry_pipeline_state)
            ship_to = getattr(self.config, "TELEMETRY_SHIP_TO", "")
            if ship_to and ship_to != name:
                self.ship_telemetry_to(ship_to)
        # inbound TELEMETRY snapshots (best-effort) feed an
        # attached FleetAggregator; without one they drop on the floor
        self.fleet_aggregator = None
        self.node_bus.subscribe(Telemetry, self._receive_telemetry)

        # built-in actions need the finished node (ref validator_info_tool)
        from plenum_tpu.execution.action_manager import ValidatorInfoAction
        self.action_manager = components.action_manager
        if self.action_manager is not None:
            self.action_manager.register_handler(ValidatorInfoAction(self))

        # plugins get the finished node last (ref plugin init hooks)
        from plenum_tpu.plugins import init_plugins
        init_plugins(self, getattr(components, "plugins", []))

    def _restore_3pc_from_audit(self) -> None:
        from plenum_tpu.execution.handlers import audit as audit_lib
        audit = self.c.db.get_ledger(AUDIT_LEDGER_ID)
        view_no, pp_seq_no, primaries = audit_lib.last_audited_view(audit)
        if (view_no, pp_seq_no) == (0, 0):
            return
        for replica in self.replicas:
            replica.data.view_no = view_no
            if primaries:
                replica.data.primaries = list(primaries)
            replica.ordering.caught_up_till_3pc(
                (view_no, pp_seq_no) if replica.is_master
                else replica.last_ordered_3pc)
        # the duplicate-Ordered execution guard must survive restart too
        self._last_executed_pp_seq = max(self._last_executed_pp_seq,
                                         pp_seq_no)
        # persisted InstanceChange votes were loaded against view 0; now
        # that the audited view is known, retire proposals it supersedes
        trigger = self.master_replica.vc_trigger
        if trigger is not None:
            trigger.purge_stale()
        self.spylog.append(("restored_from_audit", (view_no, pp_seq_no)))

    def _restore_backup_last_sent_pp(self) -> None:
        """Resume each backup primary at its persisted last-sent seq-no
        (ref last_sent_pp_store_helper.try_restore_last_sent_pp_seq_no):
        only for instances where this node IS the primary, only when the
        stored view matches the restored view — a row from an older view is
        stale (numbering restarted) and is dropped."""
        if self._last_sent_pp is None:
            return
        stored = self._last_sent_pp.load_raw()
        if not stored:
            return
        stale = False
        survivors: list[tuple[int, int, int]] = []
        for inst_str, pair in stored.items():
            try:
                inst_id, (view_no, pp_seq_no) = int(inst_str), pair
            except (ValueError, TypeError):
                stale = True
                continue
            if inst_id == 0 or inst_id not in self.replicas:
                stale = True
                continue
            data = self.replicas[inst_id].data
            if view_no != data.view_no or not data.is_primary:
                stale = True
                continue
            data.pp_seq_no = max(data.pp_seq_no, pp_seq_no)
            data.last_ordered_3pc = max(data.last_ordered_3pc,
                                        (view_no, pp_seq_no))
            survivors.append((inst_id, view_no, pp_seq_no))
            self.spylog.append(("restored_backup_pp", (inst_id, pp_seq_no)))
        if stale:
            # rewrite exactly the rows the restore loop accepted — a dead
            # row (wrong view OR not primary here) must not resurrect
            self._last_sent_pp.erase()
            for inst_id, view_no, pp_seq_no in survivors:
                self._last_sent_pp.store(inst_id, view_no, pp_seq_no)

    def _sample_queue_gauges(self) -> None:
        self.metrics.add_event(MetricsName.CLIENT_INBOX_DEPTH,
                               len(self._client_inbox))
        self.metrics.add_event(MetricsName.PROPAGATE_INBOX_DEPTH,
                               len(self._propagate_inbox))
        self.metrics.add_event(
            MetricsName.REQUEST_QUEUE_DEPTH,
            sum(len(q) for q in
                self.master_replica.ordering.request_queues.values()))

    def _sample_crypto_gauges(self) -> None:
        """Pairing accounting + device-plane dispatch counters as cumulative
        gauges (read back via max, like gc_pause_time). PAIRING_STATS is
        process-wide — per-node exactness holds in the one-process-per-node
        topology the flushed history exists for."""
        from plenum_tpu.crypto.bn254 import PAIRING_STATS
        self.metrics.add_event(MetricsName.BLS_PAIRING_CHECKS,
                               PAIRING_STATS["checks"])
        self.metrics.add_event(MetricsName.BLS_PAIRINGS,
                               PAIRING_STATS["pairings"])
        self.metrics.add_event(MetricsName.BLS_PAIRINGS_NATIVE,
                               PAIRING_STATS["native"])
        # ShardedJaxEd25519Verifier.dispatches, possibly wrapped by the
        # CoalescingVerifier (walk one level of ._inner)
        verifier = getattr(self.c.authenticator.core_authenticator,
                           "verifier", None)
        for obj in (verifier, getattr(verifier, "_inner", None)):
            dispatches = getattr(obj, "dispatches", None)
            if dispatches is not None:
                self.metrics.add_event(MetricsName.SIG_PLANE_DISPATCHES,
                                       dispatches)
                break
        # plane supervisor: breaker state gauge + fallback/hedge/deadline
        # cumulative counters + the dispatch-budget distribution — the
        # degraded-mode story must be VISIBLE in the flushed history
        # (docs/robustness.md "Degraded modes of the crypto plane")
        from plenum_tpu.parallel.supervisor import find_supervisor
        sup = find_supervisor(verifier)
        if sup is not None:
            st = sup.supervisor_stats()
            self.metrics.add_event(MetricsName.CRYPTO_BREAKER_STATE,
                                   st["breaker_state_code"])
            self.metrics.add_event(MetricsName.CRYPTO_BREAKER_OPENS,
                                   st["breaker_opens"])
            self.metrics.add_event(MetricsName.CRYPTO_FALLBACK_BATCHES,
                                   st["fallback_batches"])
            self.metrics.add_event(MetricsName.CRYPTO_FALLBACK_ITEMS,
                                   st["fallback_items"])
            self.metrics.add_event(MetricsName.CRYPTO_HEDGE_WINS,
                                   st["hedge_wins"])
            self.metrics.add_event(MetricsName.CRYPTO_DEADLINE_MISSES,
                                   st["deadline_misses"])
            for budget_s in sup.drain_budget_samples():
                self.metrics.add_event(MetricsName.CRYPTO_DISPATCH_BUDGET,
                                       budget_s)
        # BLS plane health: combined-check fallbacks (process-wide) and,
        # with the service plane, local-IPC fallback counts
        from plenum_tpu.crypto.bls import BATCH_STATS
        self.metrics.add_event(MetricsName.BLS_BATCH_FALLBACKS,
                               BATCH_STATS["fallbacks"])
        bls = getattr(self.replicas.master, "bls", None)
        bls_stats = getattr(getattr(bls, "_verifier", None), "stats", None)
        if isinstance(bls_stats, dict) and "local_fallbacks" in bls_stats:
            self.metrics.add_event(MetricsName.BLS_LOCAL_FALLBACKS,
                                   bls_stats["local_fallbacks"])
        # read-plane health as cumulative gauges (read back via max):
        # cache effectiveness + the proofless rate an operator watches —
        # proofless replies are the ones that cost clients an f+1 fanout
        rp = self.read_plane.stats
        self.metrics.add_event(MetricsName.READ_CACHE_HITS,
                               rp["cache_hits"])
        self.metrics.add_event(MetricsName.READ_PROOFS_STATE,
                               rp["proofs_state"])
        self.metrics.add_event(MetricsName.READ_PROOFS_MERKLE,
                               rp["proofs_merkle"])
        self.metrics.add_event(MetricsName.READ_PROOFS_VERKLE,
                               rp["proofs_verkle"])
        self.metrics.add_event(MetricsName.READ_PROOFLESS,
                               rp["proofless"])
        self.metrics.add_event(MetricsName.READ_ANCHOR_UPDATES,
                               rp["anchor_updates"])
        # fused crypto pipeline: dispatch/dedup/bucket gauges (the ring is
        # shared, so like PAIRING_STATS these are host-wide figures)
        if self.c.pipeline is not None:
            self.c.pipeline.sample_metrics(self.metrics)

    # --- live fleet telemetry (observability/) ---------------------------

    def _telemetry_node_state(self) -> dict:
        """The node's live health gauges for the telemetry snapshot's
        state section. Everything here derives from counters or the
        injectable timer — no wall reads — so a replayed node emits a
        byte-identical snapshot stream."""
        master = self.master_replica.data
        domain = self.c.db.get_ledger(DOMAIN_LEDGER_ID)
        out = {
            "ordered_total": (domain.size - 1) if domain is not None else 0,
            "view_no": master.view_no,
            "vc_in_progress": bool(master.waiting_for_new_view),
            "catchup_running": bool(self.leecher.is_running),
            "read_only_degraded": bool(self.read_only_degraded),
            "validators": len(self.validators),
        }
        anchor = self.read_plane.anchor_for(DOMAIN_LEDGER_ID)
        if anchor is not None:
            out["anchor_age"] = round(
                max(0.0, self.timer.get_current_time()
                    - anchor.ms.value.timestamp), 6)
        # batch-SLO ledger deltas (controller decisions vs BATCH_SLO_P95)
        ctl = self.batch_controller
        if ctl is not None:
            d_v = self._telemetry_deltas.take("slo_v", ctl.slo_violations)
            d_n = self._telemetry_deltas.take("slo_n", ctl.slo_checks)
            if d_n > 0:
                out["slo"] = [d_v, d_n]
        return out

    def _telemetry_crypto_state(self) -> dict:
        """Crypto-plane breaker state in its own section so the
        aggregator's health fold reads one canonical key."""
        from plenum_tpu.parallel.supervisor import find_supervisor
        verifier = getattr(self.c.authenticator.core_authenticator,
                           "verifier", None)
        sup = find_supervisor(verifier)
        if sup is None:
            return {}
        return {"breaker_state": sup.breaker.state,
                "fallback_batches": sup.stats.get("fallback_batches", 0)}

    def _telemetry_pipeline_state(self) -> dict:
        pipe = self.c.pipeline
        if pipe is None:
            return {}
        st = pipe.stats
        dispatches = st.get("dispatches", 0)
        out = {
            "occupancy": pipe.occupancy(),
            "dispatches": dispatches,
            "bucket_hit_rate": round(
                st.get("bucket_hits", 0) / dispatches, 3)
            if dispatches else None,
        }
        # multi-device ring: per-chip lane gauges so the fleet console
        # can show WHICH chip is sick (breaker per lane), plus the open
        # count the aggregator's health fold reads
        devices = pipe.device_state()
        if devices:
            out["devices"] = devices
            out["breakers_open"] = sum(
                1 for d in devices
                if d.get("breaker") not in ("closed", "none"))
        return out

    def footprint(self) -> dict:
        """Size-now of every bounded in-memory/on-disk structure — the
        resource-footprint gauges the fleet history plane fits growth
        trends over (observability/history.py), and the ONE inventory
        the soaks assert bounded growth through. Every value is an
        integer size, deterministic given the same ordered stream —
        except the two wall/host-derived gauges the telemetry source
        strips under ``wall_sums=False``."""
        out = {"kv_entries": 0, "kv_disk_bytes": 0}
        for kv in self.c.db.iter_kv_stores():
            try:
                size = kv.size
                out["kv_entries"] += int(size() if callable(size) else size)
            except Exception:
                pass
            path = getattr(kv, "_file_path", None)
            if path:
                try:
                    out["kv_disk_bytes"] += os.path.getsize(path)
                except OSError:
                    pass
        out["flight_ring_entries"] = (
            len(self.tracer.ring) if self.tracer.enabled else 0)
        stashed = 0
        for replica in self.replicas:
            for svc in (replica.ordering, replica.checkpointer,
                        replica.view_changer):
                stasher = getattr(svc, "_stasher", None)
                if stasher is not None:
                    stashed += sum(len(q) for q in stasher._queues.values())
                    stashed += len(stasher.discarded)
        out["stashed_entries"] = stashed
        out["request_state_entries"] = len(self.propagator.requests)
        out["dedup_map_entries"] = len(self._seen_propagates)
        out["read_cache_entries"] = sum(
            len(s) for s in self.read_plane._cache.values())
        vcs = self.master_replica.view_changer
        votes = sum(len(d) for d in vcs._view_changes.values())
        trigger = self.master_replica.vc_trigger
        if trigger is not None:
            votes += sum(len(d) for d in trigger._votes.values())
        out["vc_vote_entries"] = votes
        bls = self.master_replica.bls
        out["bls_sig_entries"] = (
            len(bls._sigs) + len(bls._pending_order)
            if bls is not None else 0)
        # process-wide verdict cache: real size, but NOT per-run
        # deterministic (shared across every node in the process)
        from plenum_tpu.crypto.bls import _BLS_VERDICTS
        out["bls_verdict_cache_entries"] = len(_BLS_VERDICTS)
        return out

    def _telemetry_footprint_state(self) -> dict:
        """Footprint gauges for the snapshot's state section. Under
        ``wall_sums=False`` (record/replay comparisons) the host- and
        process-derived gauges are stripped — RSS reads the HOST and the
        BLS verdict cache is process-wide across nodes — so the replayed
        stream stays byte-identical; everything left derives from the
        ordered stream alone."""
        out = self.footprint()
        if getattr(self.telemetry, "wall_sums", True):
            from plenum_tpu.common.metrics import process_rss_bytes
            rss = process_rss_bytes()
            if rss is not None:
                out["process_rss_bytes"] = rss
        else:
            out.pop("bls_verdict_cache_entries", None)
        return out

    def _sample_footprint_gauges(self) -> None:
        """Footprint sizes as ordinary metric events at flush cadence, so
        the on-disk metrics history carries the same growth story the
        live telemetry plane trends (footprint.* names, lint-covered)."""
        fp = self.footprint()
        for key, name in _FOOTPRINT_METRIC_NAMES.items():
            if key in fp:
                self.metrics.add_event(name, fp[key])

    def attach_fleet_aggregator(self, aggregator) -> None:
        """Route inbound TELEMETRY snapshots (and this node's own) into
        `aggregator` — the seam fleet_console/tests/fabrics use to host
        the pool-wide view on one designated node."""
        self.fleet_aggregator = aggregator
        if self.telemetry.enabled:
            self.telemetry.add_sink(aggregator.ingest)

    def ship_telemetry_to(self, peer: str) -> None:
        """Ship this node's snapshots to `peer` as the best-effort
        TELEMETRY wire message — the production counterpart of
        attach_fleet_aggregator: every other node ships to the node
        hosting the aggregator (TELEMETRY_SHIP_TO wires this from
        config at construction)."""
        if self.telemetry.enabled:
            self.telemetry.ship = lambda snap: self.node_bus.send(
                Telemetry(snapshot=snap), peer)

    def _receive_telemetry(self, msg: Telemetry, frm: str) -> None:
        if self.fleet_aggregator is None:
            return
        # bind the snapshot to the AUTHENTICATED sender: one byzantine
        # peer must not overwrite another node's health story (a forged
        # healthy "Alpha" stream would mask Alpha's real outage)
        if msg.snapshot.get("node") != frm:
            return
        self.fleet_aggregator.ingest(msg.snapshot)

    def _flush_metrics(self) -> None:
        """Sample process RSS/GC gauges + one last queue sample, then flush
        accumulators to the KV store. The in-flush flag lets signal
        handlers (start_node's SIGTERM tail-flush) skip the call instead
        of re-entering a KV append already on the stack."""
        self._in_metrics_flush = True
        try:
            from plenum_tpu.common.metrics import sample_process_gauges
            sample_process_gauges(self.metrics)
            self._sample_queue_gauges()
            self._sample_crypto_gauges()
            self._sample_footprint_gauges()
            self.metrics.flush()
        finally:
            self._in_metrics_flush = False

    def check_performance(self) -> None:
        if self.leecher.is_running:
            return
        self.notifier.check_throughput(
            self.monitor.master_throughput(), self.name,
            self.timer.get_current_time())
        if self.monitor.is_master_degraded():
            self.spylog.append(("master_degraded", self.monitor.stats()))
            self.replicas.master.internal_bus.send(
                VoteForViewChange(
                    suspicion_code=Suspicions.PRIMARY_DEGRADED.code))
            # history is void once we've called for a new master
            self.monitor.reset()

    def _check_backup_instances(self) -> None:
        """Detect wedged BACKUP instances: queued work but no 3PC progress
        for BACKUP_INSTANCE_FAULTY_TIMEOUT -> broadcast a
        BackupInstanceFaulty vote (and count our own). The master has its
        own watchdog (PrimaryHealthService) — view change, not removal."""
        now = self.timer.get_current_time()
        master = self.replicas.master.data
        if (self.leecher.is_running or not master.is_participating
                or master.waiting_for_new_view):
            # catchup / an in-flight view change legitimately freezes every
            # instance: restart the stall clocks instead of counting the
            # pause as a wedge (same gate as PrimaryHealthService.check)
            self._backup_wedge_markers.clear()
            return
        live = set()
        for replica in list(self.replicas):
            iid = replica.data.inst_id
            if iid == 0:
                continue
            live.add(iid)
            has_work = replica.has_unordered_work()
            marker = replica.data.last_ordered_3pc
            prev = self._backup_wedge_markers.get(iid)
            if not has_work or prev is None or prev[0] != marker:
                self._backup_wedge_markers[iid] = (marker, now)
                continue
            if now - prev[1] >= self.config.BACKUP_INSTANCE_FAULTY_TIMEOUT:
                vote = BackupInstanceFaulty(
                    view_no=self.replicas.master.data.view_no, inst_id=iid,
                    reason=Suspicions.BACKUP_INSTANCE_STALLED.code)
                self.node_bus.send(vote)                 # broadcast to peers
                self._process_backup_faulty(vote, self.name)
                self._backup_wedge_markers[iid] = (marker, now)  # re-vote
        for iid in list(self._backup_wedge_markers):
            if iid not in live:
                del self._backup_wedge_markers[iid]

    def _process_backup_faulty(self, msg: BackupInstanceFaulty,
                               frm: str) -> None:
        """f+1 DISTINCT voters (ref quorums backup_instance_faulty) agree a
        backup stalled -> remove the instance. Ids are stable across the
        gap; the instance is re-created fresh by the next view change."""
        view = self.replicas.master.data.view_no
        if msg.view_no != view or msg.inst_id == 0 \
                or msg.inst_id not in self.replicas:
            return
        voters = self._backup_faulty_votes.setdefault(
            (view, msg.inst_id), set())
        voters.add(frm)
        if not self.quorums.backup_instance_faulty.is_reached(len(voters)):
            return
        self.replicas.remove_instance(msg.inst_id)   # stop()s the zombie
        self._removed_backups.add(msg.inst_id)
        self._backup_wedge_markers.pop(msg.inst_id, None)
        # stale votes (this instance, and anything from older views) go too
        self._backup_faulty_votes = {
            k: v for k, v in self._backup_faulty_votes.items()
            if k[0] == view and k[1] != msg.inst_id}
        self.monitor.reset()    # comparison basis changed
        self.metrics.add_event(MetricsName.BACKUP_INSTANCE_REMOVED)
        self.spylog.append(("backup_instance_removed", msg.inst_id))

    def _clean_outdated_reqs(self) -> None:
        now = self.timer.get_current_time()
        ttl = self.config.PROPAGATES_PHASE_REQ_TIMEOUT
        bodyless_ttl = self.config.PROPAGATE_BODYLESS_REQ_TIMEOUT
        retention = self.config.EXECUTED_REQ_RETENTION
        for digest, state in list(self.propagator.requests.items()):
            expired = (
                (state.executed and state.executed_at is not None
                 and now - state.executed_at > retention)
                or (not state.finalised and now - state.added_at > ttl)
                # digest votes with no verified body behind them are the
                # one state a peer can mint for free: short leash
                or (state.request is None
                    and now - state.added_at > bodyless_ttl))
            if expired:
                self.propagator.requests.free(digest)
                self._seen_propagates.pop(digest, None)
                self._body_fetches.pop(digest, None)
        # _seen_propagates entries whose request never made it into the
        # propagator (failed signature, late propagate of an executed txn)
        # have no RequestState carrying a timestamp — they are orphans the
        # moment they exist, and the cheapest spam vector if kept
        for digest in list(self._seen_propagates):
            if digest not in self.propagator.requests:
                del self._seen_propagates[digest]
        self.monitor.req_tracker.cleanup(now, ttl)

    # --- wiring -----------------------------------------------------------

    def _n_instances(self) -> int:
        """Effective RBFT instance count: pinned if the constructor said
        so, else f+1 from the CURRENT quorums (tracks pool membership)."""
        if self._pinned_instances is not None:
            return max(1, self._pinned_instances)
        return max(1, self.quorums.f + 1)

    def _make_replica(self, inst_id: int) -> Replica:
        from plenum_tpu.execution.handlers import audit as audit_lib
        audit = self.c.db.get_ledger(AUDIT_LEDGER_ID)
        reg_memo: dict[str, Optional[list]] = {}

        def node_reg_at(pool_root: str) -> Optional[list]:
            got = reg_memo.get(pool_root)
            if got is None:
                # misses are NOT memoized: a root absent now can appear
                # later (staged audit txns revert and re-apply around view
                # changes), and a stale None would mis-judge the sig
                got = audit_lib.node_reg_at_pool_root(audit, pool_root)
                if got is not None:
                    if len(reg_memo) > 64:
                        reg_memo.clear()
                    reg_memo[pool_root] = got
            return got

        def key_at(name: str, pool_root_hex: str):
            try:
                return self.c.node_handler.bls_key_at_root(
                    name, bytes.fromhex(pool_root_hex))
            except (ValueError, KeyError):
                return None

        # BLS multi-signatures are a MASTER-instance concern: only master
        # batches carry state roots worth certifying. Backups signing over
        # empty roots would be wasted pairings AND their root-less sigs
        # cannot cite a pool-state epoch for rotation-aware validation.
        bls = None
        if inst_id == 0:
            # with the service plane, the per-batch aggregate pairing is
            # deduped host-wide (every co-hosted node runs the identical
            # check); otherwise verify locally — the factory encodes both
            from plenum_tpu.parallel.crypto_service import \
                make_bls_verifier
            if (self.c.pipeline is not None
                    and self.config.crypto_backend != "service"):
                # commit-path batch checks ride the pipeline ring: one
                # deduped combined pairing check per flush window instead
                # of one per co-hosted node (the service plane keeps its
                # own host-wide dedup path)
                bls_verifier = self.c.pipeline.bls_verifier()
            else:
                bls_verifier = make_bls_verifier(self.config.crypto_backend)
            bls = BlsBftReplica(
                node_name=self.name, bls_signer=self.c.bls_signer,
                bls_verifier=bls_verifier,
                key_register=self.c.bls_register,
                bls_store=self.c.bls_store,
                node_reg_at=node_reg_at, key_at=key_at)
            # commit-path stage timer + pairings-per-batch counter
            bls.metrics = self.metrics
            # freshly aggregated multi-sigs advance the read plane's
            # signed-root anchor (late pending-order retries included)
            bls.on_multi_sig = self.read_plane.on_multi_sig
        # InstanceChange votes survive restart via the node-status DB
        # (ref instance_change_provider.py:34-69); master-only — backups
        # have no view-change machinery (see Replica)
        ic_store = None
        if inst_id == 0:
            status_kv = self.c.db.get_store(NODE_STATUS_DB_LABEL)
            if status_kv is not None:
                ic_store = InstanceChangeVoteStore(status_kv)
        replica = Replica(
            node_name=self.name, inst_id=inst_id,
            validators=self.validators, timer=self.timer,
            network=self.node_bus,
            executor=self.c.executor if inst_id == 0 else None,
            bls=bls, config=self.config,
            get_request=self.propagator.requests.get_request,
            checkpoint_digest_provider=(
                lambda seq: audit.uncommitted_root_hash.hex()),
            instance_count=self._n_instances(),
            metrics=self.metrics if inst_id == 0 else None,
            ic_vote_store=ic_store,
            tracer=self.tracer if inst_id == 0 else None,
            controller=self.batch_controller if inst_id == 0 else None,
            rtt=self.catchup_rtt if inst_id == 0 else None)
        if bls is not None:
            bls.report_bad_signature = lambda sender, r=replica: \
                r.internal_bus.send(RaisedSuspicion(
                    inst_id=inst_id, code=Suspicions.CM_BLS_WRONG.code,
                    reason="bad COMMIT BLS signature (batch-check fallback)",
                    sender=sender))
        if inst_id != 0 and self._last_sent_pp is not None:
            replica.ordering.on_backup_pp_sent = self._last_sent_pp.store
        replica.internal_bus.subscribe(Ordered, self._on_ordered)
        replica.internal_bus.subscribe(RaisedSuspicion, self._on_suspicion)
        # lambdas: message_req is constructed after the replicas
        replica.internal_bus.subscribe(
            MissingMessage, lambda m: self.message_req.process_missing(m))
        replica.internal_bus.subscribe(
            RequestPropagates, self._on_request_propagates)
        if inst_id == 0:
            replica.internal_bus.subscribe(
                NeedMasterCatchup, lambda _msg: self.start_catchup())
            replica.internal_bus.subscribe(NewViewAccepted,
                                           self._on_master_new_view)
            # VC stall decomposition: stamp the vote and the IC-quorum
            # start as they pass through the master's bus
            replica.internal_bus.subscribe(
                VoteForViewChange,
                lambda _m: self._vc_mark("vote"))
            replica.internal_bus.subscribe(
                NeedViewChange,
                lambda _m: self._vc_mark("start"))
        return replica

    # --- view-change stall decomposition (VERDICT r4 item 5) ------------
    # Phase stamps ride the node timer: primary-disconnect detection ->
    # our IC vote -> IC quorum (NeedViewChange) -> NewViewAccepted ->
    # first post-VC order. Durations are emitted as metrics events so
    # tools/metrics_report can print the breakdown of a fault's cost.

    _VC_PHASES = (("detect", "vote", MetricsName.VC_DETECT_TO_VOTE),
                  ("vote", "start", MetricsName.VC_VOTE_TO_START),
                  ("start", "new_view", MetricsName.VC_START_TO_NEW_VIEW),
                  ("new_view", "order", MetricsName.VC_NEW_VIEW_TO_ORDER))

    _VC_ORDER = ("detect", "vote", "start", "new_view", "order")

    def _vc_mark(self, phase: str) -> None:
        """A stamp REFRESHES (latest wins) as long as no later phase has
        been stamped: a transient blip's 'detect' or a degradation vote's
        'vote' from an episode that never progressed must not anchor the
        durations of the real episode that follows. Once a later phase
        exists, earlier stamps freeze; phase metrics are emitted when the
        later endpoint of each pair is stamped."""
        if phase == "start":
            self._vc_starts_streak += 1
            self._maybe_vc_storm_resync()
        elif phase in ("new_view", "order"):
            self._vc_starts_streak = 0
        ts = self._vc_phase_ts
        rank = self._VC_ORDER.index(phase)
        if any(p in ts for p in self._VC_ORDER[rank + 1:]):
            return                      # episode already past this phase
        ts[phase] = self.timer.get_current_time()
        if phase == "start" and self.tracer.enabled:
            self.tracer.anomaly("view_change_start", None)
        if phase == "order":
            # metrics emit ONCE, at completion (refreshed stamps would
            # otherwise emit duplicate, drifting durations)
            for frm, to, metric in self._VC_PHASES:
                if frm in ts and to in ts:
                    self.metrics.add_event(metric, ts[to] - ts[frm])
            # whole-episode duration (earliest stamp -> first post-VC
            # order), sampled so metrics_report prints churn p50/p95
            first = min(ts[p] for p in self._VC_ORDER if p in ts)
            self.metrics.add_event(MetricsName.VC_DURATION,
                                   ts["order"] - first)
            if self.tracer.enabled:
                self.tracer.anomaly("view_change_recovered",
                                    {"duration_s": ts["order"] - first})
            self.spylog.append(("vc_stall_phases", dict(ts)))
            ts.clear()                  # episode complete

    def _on_request_propagates(self, msg: RequestPropagates) -> None:
        """Ordering stashed a pre-prepare (or the primary skipped batching)
        on MISSING_REQUESTS: pull the request bodies from peers. Digests
        with known voters go through the targeted fetch loop; digests
        nobody has vouched for yet fall back to a broadcast MessageReq."""
        for digest in msg.bad_requests:
            if self.propagator.requests.has_body(digest):
                continue
            state = self.propagator.requests.get(digest)
            if state is not None and any(s != self.name
                                         for s in state.propagates):
                self._request_body(digest, urgent=True)
            else:
                self.message_req.request("PROPAGATE", {"digest": digest})

    # --- targeted request-body fetch (digest-gossip) --------------------

    def _request_body(self, digest: str, urgent: bool) -> None:
        """Arm the per-digest body-fetch loop. Non-urgent arms it on a
        grace delay (the client's own broadcast or the disseminator's body
        usually outruns it); urgent (quorum reached / ordering blocked)
        fires NOW — escalating an already-armed-but-still-delayed loop by
        bumping its generation, so exactly one retry chain stays live."""
        fetch = self._body_fetches.get(digest)
        if fetch is not None:
            if urgent and fetch["tries"] == 0:
                fetch["gen"] += 1           # orphan the delayed first tick
                self.timer.schedule(
                    0.0, lambda: self._body_fetch_tick(digest, fetch["gen"]))
            return
        fetch = self._body_fetches[digest] = {"tries": 0, "gen": 0}
        delay = 0.0 if urgent else self.config.PROPAGATE_BODY_FETCH_DELAY
        self.timer.schedule(delay,
                            lambda: self._body_fetch_tick(digest, 0))

    def _body_fetch_tick(self, digest: str, gen: int) -> None:
        """One fetch attempt: ask the NEXT propagate voter for the body,
        re-arming until the body lands (bad/garbage replies simply leave
        the body absent, so the retry covers both timeout and lies)."""
        fetch = self._body_fetches.get(digest)
        if fetch is None or fetch["gen"] != gen:
            return                          # stood down or escalated past us
        state = self.propagator.requests.get(digest)
        if state is None or state.request is not None:
            del self._body_fetches[digest]
            if state is not None:
                state.fetch_started = False
            return
        senders = sorted(s for s in state.propagates if s != self.name)
        if fetch["tries"] >= 2 * max(len(senders), 1) + 2:
            # every voter tried twice and nobody produced a body that
            # verifies: give up; a fresh vote re-arms the loop, and the
            # bodyless-state TTL sweeps the orphan
            del self._body_fetches[digest]
            state.fetch_started = False
            self.spylog.append(("body_fetch_exhausted", digest))
            return
        dst = [senders[fetch["tries"] % len(senders)]] if senders else None
        fetch["tries"] += 1
        self.message_req.request("PROPAGATE", {"digest": digest}, dst=dst)
        self.timer.schedule(self.config.PROPAGATE_BODY_FETCH_RETRY,
                            lambda: self._body_fetch_tick(digest, gen))

    def _on_master_new_view(self, msg: NewViewAccepted) -> None:
        """The master completed a view change: every backup instance follows
        (view change is node-level; backups have no VC machinery of their own).
        Backups removed as faulty are re-created fresh here (ref
        restore_backup_replicas on view change)."""
        n_inst = self._n_instances()
        self._removed_backups.clear()       # a new view restores everything
        if self._last_sent_pp is not None:
            # backup numbering restarts with the view; stale rows must not
            # resume a future restart at an old view's heights
            self._last_sent_pp.erase()
        # partial vote sets from superseded views can never complete (view
        # is checked at receipt) — drop them or they leak one per view
        self._backup_faulty_votes = {
            k: v for k, v in self._backup_faulty_votes.items()
            if k[0] >= msg.view_no}
        fresh = [i for i in range(n_inst) if i not in self.replicas]
        self.replicas.grow_to(n_inst)
        primaries = list(self.replicas.master.data.primaries)
        for replica in self.replicas:
            if replica.data.inst_id in fresh:
                replica.set_validators(self.validators)
            replica.adopt_new_view(msg.view_no, primaries)
        self.monitor.reset()
        self.metrics.add_event(MetricsName.VIEW_CHANGES)
        self._vc_mark("new_view")
        self.notifier.send(TOPIC_VIEW_CHANGE, {
            "node": self.name, "view_no": msg.view_no,
            "primaries": primaries,
            "time": self.timer.get_current_time()})
        self.spylog.append(("view_change_complete", msg.view_no))
        if self.tracer.enabled:
            self.tracer.anomaly("view_change_complete",
                                {"view": msg.view_no})

    def _on_suspicion(self, msg: RaisedSuspicion) -> None:
        """Route a protocol suspicion: primary-authored faults become
        view-change votes; unambiguous peer misbehavior blacklists the
        sender (ref node.py:2854-2944)."""
        self.metrics.add_event(MetricsName.SUSPICIONS)
        self.spylog.append(("suspicion", (msg.code, msg.sender)))
        if self.tracer.enabled:
            self.tracer.anomaly("suspicion", {"code": msg.code,
                                              "sender": msg.sender})
        if msg.inst_id not in self.replicas:
            return
        replica = self.replicas[msg.inst_id]
        if msg.code in PRIMARY_FAULT_CODES and \
                msg.sender == replica.data.primary_name:
            if msg.inst_id == 0:
                replica.internal_bus.send(
                    VoteForViewChange(suspicion_code=msg.code))
                self._note_root_mismatch(msg)
            return
        if (msg.code in BLACKLIST_CODES and msg.sender
                and msg.sender != self.name):
            if self.blacklister.blacklist(msg.sender, msg.code):
                self.spylog.append(("blacklisted", msg.sender))

    def _note_root_mismatch(self, msg: RaisedSuspicion) -> None:
        """Divergence self-check. Each root-mismatch rejection implicates
        ONE primary — possibly byzantine. But once f+1 DISTINCT primaries'
        batches have failed our root derivation with no ordering progress
        in between, at least one of them was honest, so our own state is
        the diverged one: resync instead of wedging on suspicion votes.
        (The set resets on every master order and on catchup complete.)"""
        if msg.code not in ROOT_MISMATCH_CODES:
            return
        self._divergence_primaries.add(msg.sender)
        # only self-suspect while the pool is in a SETTLED view we share:
        # mid-view-change both sides legitimately disagree on roots for a
        # moment, and a resync here exits consensus exactly when our vote
        # is needed — the churn soak showed that splitting the pool into
        # view factions. A cooldown keeps a genuinely wedged node from
        # re-entering catchup faster than one round can complete.
        now = self.timer.get_current_time()
        cooldown = 2 * self.config.STUCK_BEHIND_CHECK_FREQ
        if (len(self._divergence_primaries) >= self.quorums.weak.value
                and not self.master_replica.data.waiting_for_new_view
                and now - self._divergence_fired_at > cooldown
                and not self.leecher.is_running
                and not self.read_only_degraded):
            self._divergence_fired_at = now
            suspects = sorted(self._divergence_primaries)
            self._divergence_primaries.clear()
            self.spylog.append(("divergence_resync", suspects))
            if self.tracer.enabled:
                self.tracer.anomaly("divergence_resync",
                                    {"primaries": suspects})
            # DEFERRED: suspicions surface inside consensus dispatch;
            # catchup reverts uncommitted state and must not run under
            # the 3PC processing stack (same rule as _note_peer_view)
            self.timer.schedule(0.0, self.start_catchup)

    # --- catchup ----------------------------------------------------------

    def _check_stuck_behind(self) -> None:
        """A live pool committed past us and we made no ordering progress
        for a full check interval: resync. Covers the mid-view straggler
        (rejoined after missing batches; no checkpoint below CHK_FREQ, no
        quorum behind its lone InstanceChange vote)."""
        r = self.master_replica
        evidence = r.ordering.behind_evidence()
        if evidence is None or self.leecher.is_running:
            self._behind_marker = None
            return
        last = r.last_ordered_3pc[1]
        if self._behind_marker == last:
            self._behind_marker = None
            self.spylog.append(("stuck_behind_resync", (last, evidence)))
            self.start_catchup()
        else:
            self._behind_marker = last

    def _note_peer_view(self, msg, frm: str) -> None:
        """Track the highest view each peer is demonstrably IN (master-
        instance consensus messages only); f+1 peers ahead -> resync.
        ViewChange/NewView for exactly my+1 do NOT count: during an
        ordinary view change every peer broadcasts those moments before
        we enter the view ourselves — only 3PC traffic (proof a higher
        view is ORDERING) or a jump of >= 2 views is straggler evidence."""
        view = getattr(msg, "view_no", None)
        if view is None or getattr(msg, "inst_id", 0) != 0:
            return
        my = self.master_replica.data.view_no
        if view <= my:
            self._ahead_views.pop(frm, None)
            return
        if isinstance(msg, (ViewChange, NewView)) and view == my + 1:
            return
        self._ahead_views[frm] = view
        ahead = [s for s, v in self._ahead_views.items() if v > my]
        now = self.timer.get_current_time()
        # damping: once per stuck view, UNLESS a previous attempt already
        # came and went without unsticking us (a catchup that raced the
        # rest of the pool's own recovery can conclude at a stale target;
        # the lag evidence persisting past a cooldown earns a retry)
        cooldown = 2 * self.config.STUCK_BEHIND_CHECK_FREQ
        if (len(ahead) >= self.quorums.propagate.value
                and (my > self._straggler_fired_view
                     or now - self._straggler_fired_at > cooldown)
                and not self.leecher.is_running):
            self._straggler_fired_view = my
            self._straggler_fired_at = now
            # DEFERRED: this handler runs inside consensus message
            # dispatch — starting catchup here would revert uncommitted
            # state under the 3PC processing stack mid-message. The
            # callback RE-VERIFIES the lag: a view change that completed
            # in the gap (we caught up on our own) must not pay a
            # needless catchup.
            self.timer.schedule(0.0, self._straggler_catchup)

    def _straggler_catchup(self) -> None:
        my = self.master_replica.data.view_no
        ahead = [s for s, v in self._ahead_views.items() if v > my]
        if (len(ahead) >= self.quorums.propagate.value
                and not self.leecher.is_running):
            self.spylog.append(("straggler_resync", (my, sorted(ahead))))
            self.start_catchup()

    def _on_lost_quorum_connectivity(self) -> None:
        """The watcher fired: we HAD consensus connectivity and now sit
        below the weak quorum. The reference restarts the node here; the
        payload of that restart is a resync, so mark one and run it as
        soon as enough peers are back (catching up with no peers would
        just time out)."""
        self.metrics.add_event(MetricsName.SUSPICIONS)
        self.spylog.append(("lost_quorum_connectivity",
                            sorted(self.node_bus.connecteds)))
        self._needs_resync = True
        self._maybe_resync_after_partition()

    def _maybe_resync_after_partition(self, *_a) -> None:
        if (getattr(self, "_needs_resync", False)
                and self.network_watcher.has_weak_connectivity()):
            self._needs_resync = False
            self.spylog.append(("resync_after_partition", None))
            self.start_catchup()

    def _maybe_vc_storm_resync(self) -> None:
        """Storm breaker: VC_STORM_RESYNC_STARTS consecutive view-change
        starts without a completion → resync the pool ledger. Escalating
        views only helps when everyone agrees WHO each view's primary is;
        with a registry split it never can, while catchup always can.
        Deferred (ViewChangeStarted surfaces inside consensus dispatch)
        and cooldown-damped like the other resync triggers — a genuine
        long outage keeps voting, paying at most one catchup round per
        cooldown window."""
        if self._vc_starts_streak < self.config.VC_STORM_RESYNC_STARTS:
            return
        now = self.timer.get_current_time()
        cooldown = 2 * self.config.STUCK_BEHIND_CHECK_FREQ
        if (now - self._vc_resync_fired_at <= cooldown
                or self.leecher.is_running or self.read_only_degraded):
            return
        self._vc_resync_fired_at = now
        self.spylog.append(("vc_storm_resync", self._vc_starts_streak))
        if self.tracer.enabled:
            self.tracer.anomaly("vc_storm_resync",
                                {"starts": self._vc_starts_streak})
        self.timer.schedule(0.0, self.start_catchup)

    def _accept_joiner_msg(self, msg, frm: str) -> bool:
        """Bus-filter escape hatch for membership churn: catchup QUERIES
        from a node the pool ledger knows but the validator set does not
        (yet). Strictly the seeder-serving subset — a LedgerStatus ask or
        a CatchupReq range fetch — so a non-validator can sync to join
        but can never vote into a cons-proof/3PC/propagate quorum."""
        if not (isinstance(msg, CatchupReq)
                or (isinstance(msg, LedgerStatus) and not msg.is_reply)):
            return False
        return (frm in self.pool_manager.known_node_names
                and not self.blacklister.is_blacklisted(frm))

    def _catchup_watchdog(self) -> None:
        """Kick a stalled catchup round: if the leecher's progress key is
        frozen across a full interval, force provider rotation + an
        immediate re-request; after CATCHUP_WATCHDOG_RESTART_KICKS
        consecutive fruitless kicks, restart the whole round (a target
        agreed with since-vanished peers can be genuinely unfinishable)."""
        if not self.leecher.is_running:
            self._catchup_progress_mark = None
            self._catchup_kicks = 0
            return
        mark = self.leecher.progress_key()
        if mark != self._catchup_progress_mark:
            self._catchup_progress_mark = mark
            self._catchup_kicks = 0
            return
        self._catchup_kicks += 1
        self.metrics.add_event(MetricsName.CATCHUP_WATCHDOG_KICKS)
        self.spylog.append(("catchup_watchdog_kick", self._catchup_kicks))
        if self.tracer.enabled:
            self.tracer.anomaly("catchup_stall",
                                {"kicks": self._catchup_kicks})
        if self._catchup_kicks >= self.config.CATCHUP_WATCHDOG_RESTART_KICKS:
            self._catchup_kicks = 0
            self.leecher.stop()
            self.leecher.start()        # fresh targets, fresh providers
        else:
            self.leecher.kick()

    def _degrade_read_only(self) -> None:
        """Catchup cannot complete soundly (divergent committed prefix,
        repeatedly): park in READ-ONLY mode. Ordering stays paused and no
        further catchup rounds start, but the verified read plane keeps
        serving state-proof reads at the last BLS-anchored root — clients
        get honest (if increasingly stale) proofs instead of a wedged
        node, and the freshness bound tells them exactly how stale."""
        if self.read_only_degraded:
            return
        self.read_only_degraded = True
        self._read_only_reason = "catchup_diverged"
        self.metrics.add_event(MetricsName.CATCHUP_DEGRADED, 1)
        self.spylog.append(("degraded_read_only", None))
        if self.tracer.enabled:
            self.tracer.anomaly("degraded_read_only",
                                {"diverged_rounds": self._diverged_rounds})

    def set_read_only(self, on: bool, reason: str = "autopilot") -> bool:
        """Orchestrated degradation (the autopilot's ladder, level 2):
        park/unpark read-only mode EXTERNALLY. Entering is refused while
        catchup divergence already parked the node (that state is not
        the orchestrator's to own); leaving only clears a read-only the
        SAME reason entered — a catchup-diverged node can never be
        un-degraded by a recovering autopilot. Returns True when the
        state changed."""
        if on:
            if self.read_only_degraded:
                return False
            self.read_only_degraded = True
            self._read_only_reason = reason
            self.spylog.append(("degraded_read_only", reason))
            if self.tracer.enabled:
                self.tracer.anomaly("degraded_read_only",
                                    {"reason": reason})
            return True
        if not self.read_only_degraded \
                or getattr(self, "_read_only_reason", None) != reason:
            return False
        self.read_only_degraded = False
        self._read_only_reason = None
        self.spylog.append(("undegraded_read_only", reason))
        return True

    def start_catchup(self) -> None:
        """Pause ordering, revert uncommitted work, sync all ledgers
        (ref node.py:2610 start_catchup → NodeLeecherService.start)."""
        if self.leecher.is_running or self.read_only_degraded:
            return
        # Quorum-ordered batches awaiting execution MUST execute before
        # catchup reverts the uncommitted stack they sit on (ref
        # force_process_ordered before starting the leecher): popping
        # them later against a reverted stack raised "commit with no
        # applied batches" and dropped ordered work (partition-heal fuzz).
        self._service_ordered()
        self.metrics.add_event(MetricsName.CATCHUPS)
        self._catchup_started_at = self.timer.get_current_time()
        self._catchup_progress_mark = None
        self._catchup_kicks = 0
        self.spylog.append(("catchup_started", None))
        if self.tracer.enabled:
            self.tracer.anomaly("catchup", None)
        for replica in self.replicas:
            replica.ordering.catchup_started()
        self.leecher.start()

    def _receive_ledger_status(self, msg: LedgerStatus, frm: str) -> None:
        # queries go to the seeder; acknowledgments feed our cons-proof
        # quorum — but only VALIDATORS' acknowledgments: a known-but-
        # demoted joiner's status may reach us through the joiner filter
        # and must not count toward the "we are current" quorum
        self.seeder.process_ledger_status(msg, frm)
        if frm in self.validators:
            self.leecher.process_ledger_status(msg, frm)

    def _on_catchup_txn(self, ledger_id: int, txn: dict) -> None:
        """A catchup txn was committed to the ledger: replay it into state
        and bookkeeping (ref node.py:1748 postTxnFromCatchupAddedToLedger)."""
        self.c.write_manager.apply_committed_txn(ledger_id, txn)
        digest = txn_lib.txn_digest(txn)
        if digest:
            self.propagator.requests.mark_executed(digest)
            # the request may sit RE-QUEUED in a replica (catchup_started's
            # revert returns unordered batches' requests to the queues, and
            # the pool ordered this one without us): leaving it queued lets
            # a primary re-batch an already-committed request (fuzz seed 45
            # double-order)
            for replica in self.replicas:
                for q in replica.ordering.request_queues.values():
                    q.pop(digest, None)

    def _on_catchup_complete(self, last_3pc) -> None:
        """All ledgers synced: adopt the audit ledger's 3PC position and
        primaries, rejoin consensus (ref allLedgersCaughtUp node.py:1790,
        select_primaries_on_catchup_complete :1830)."""
        from plenum_tpu.execution.handlers import audit as audit_lib
        # churn observability: duration + request rounds + provider
        # switches, as sampled metrics AND as flight-recorder context, so
        # a WAN-degraded catchup regression is a p95 shift in
        # metrics_report, not an anecdote
        rounds = self.leecher.round_stats()
        duration = None
        if self._catchup_started_at is not None:
            duration = (self.timer.get_current_time()
                        - self._catchup_started_at)
            self._catchup_started_at = None
            self.metrics.add_event(MetricsName.CATCHUP_DURATION, duration)
        self.metrics.add_event(MetricsName.CATCHUP_ROUNDS,
                               rounds["rounds"])
        if rounds["provider_switches"]:
            self.metrics.add_event(MetricsName.CATCHUP_PROVIDER_SWITCHES,
                                   rounds["provider_switches"])
        if self.tracer.enabled:
            self.tracer.anomaly("catchup_complete",
                                {"duration_s": duration, **rounds})
        if self.leecher.diverged:
            # the committed prefix conflicts with the quorum target:
            # re-joining consensus on this ledger would fork. Retry a
            # bounded number of rounds (the conflict may have been a
            # transient lie), then degrade to read-only serving.
            self._diverged_rounds += 1
            if self._diverged_rounds >= \
                    self.config.CATCHUP_MAX_DIVERGED_ROUNDS:
                self._degrade_read_only()
            else:
                self.timer.schedule(
                    self.config.CATCHUP_WATCHDOG_INTERVAL,
                    self.start_catchup)
            return                      # ordering stays paused either way
        self._diverged_rounds = 0
        self._divergence_primaries.clear()
        audit = self.c.db.get_ledger(AUDIT_LEDGER_ID)
        view_no, pp_seq_no, primaries = audit_lib.last_audited_view(audit)
        if last_3pc is not None and last_3pc > (view_no, pp_seq_no):
            view_no, pp_seq_no = last_3pc
        self.pool_manager.pool_changed()
        self._last_executed_pp_seq = max(self._last_executed_pp_seq,
                                         pp_seq_no)
        for replica in self.replicas:
            if view_no > replica.data.view_no:
                replica.data.view_no = view_no
                if primaries:
                    replica.data.primaries = list(primaries)
            replica.ordering.caught_up_till_3pc(
                (view_no, pp_seq_no) if replica.is_master
                else replica.last_ordered_3pc)
        self.spylog.append(("catchup_complete", (view_no, pp_seq_no)))

    def _forward_to_replicas(self, digest: str) -> None:
        self.monitor.request_finalized(digest)
        for replica in self.replicas:
            replica.internal_bus.send(ReqKey(digest))

    def _on_ordered(self, msg: Ordered) -> None:
        if msg.inst_id == 0 and "new_view" in self._vc_phase_ts:
            # first post-VC MASTER order closes the episode (backups'
            # ordering is not client-visible recovery)
            self._vc_mark("order")
        self._ordered_queue.append(msg)

    def _on_pool_changed(self) -> None:
        """Pool-ledger commit changed membership: recompute quorums, update
        validators and BLS keys (ref node.py:731 setPoolParams)."""
        old_validators = list(self.validators)
        self.validators = self.pool_manager.node_names or [self.name]
        self.quorums = self.pool_manager.quorums
        self.propagator.set_quorums(self.quorums)
        self.network_watcher.set_nodes(self.validators)
        for replica in self.replicas:
            replica.set_validators(self.validators)
        self._adjust_replicas()
        rotated: list[str] = []
        for n in self.pool_manager.node_names:
            new_key = self.pool_manager.bls_key_of(n)
            old_key = self.c.bls_register.get_key_by_name(n)
            if old_key is not None and new_key is not None \
                    and old_key != new_key:
                rotated.append(n)
                # the rotated-OUT key must leave every crypto-plane key
                # table: fresh commits citing it are liars now, and a
                # warm decode/verdict row for a dead key is cache budget
                # a Byzantine signer can lean on (PR 8 key-table contract)
                for plane in (self.c.pipeline,
                              getattr(self.replicas.master, "bls",
                                      None) and
                              self.replicas.master.bls._verifier):
                    evict = getattr(plane, "evict_key", None)
                    if callable(evict):
                        evict(old_key)
            self.c.bls_register.set_key(n, new_key)
        # membership churn observability: every registry change counted,
        # the validator-count gauge refreshed, rotations called out in
        # the flight-recorder ring (a view change seconds later should
        # read as "the primary was demoted", not as a mystery)
        self.metrics.add_event(MetricsName.MEMBERSHIP_POOL_CHANGES)
        self.metrics.add_event(MetricsName.MEMBERSHIP_VALIDATORS,
                               len(self.validators))
        if rotated:
            self.metrics.add_event(MetricsName.MEMBERSHIP_KEY_ROTATIONS,
                                   len(rotated))
        if self.tracer.enabled:
            self.tracer.anomaly("pool_changed", {
                "validators": len(self.validators),
                "added": sorted(set(self.validators) - set(old_validators)),
                "removed": sorted(set(old_validators)
                                  - set(self.validators)),
                "rotated_keys": rotated})
        self.spylog.append(("pool_changed",
                            (len(old_validators), len(self.validators))))
        # a demoted PRIMARY cannot be waited out: its 3PC messages are
        # now filtered at every honest bus, so ordering is dead until a
        # view change — vote immediately instead of burning the ordering-
        # progress timeout (ref: the reference triggers VC on primary
        # demotion through its node-reg diff the same way)
        master = self.replicas.master
        primary = master.data.primary_name
        if (primary is not None and primary not in self.validators
                and self.name in self.validators
                and not master.data.waiting_for_new_view):
            self.spylog.append(("primary_demoted", primary))
            if self.tracer.enabled:
                self.tracer.anomaly("primary_demoted", {"primary": primary})
            master.internal_bus.send(VoteForViewChange(
                suspicion_code=Suspicions.PRIMARY_DEMOTED.code))
        # SELF-promotion: we just (re)entered the validator set after
        # sitting out. Anything the pool ordered in between is a gap our
        # stashed-commit window cannot see (commits far past the watermark
        # never land in behind_evidence) — resync BEFORE participating, or
        # we vote suspicions against every batch we cannot re-derive
        # (churn soak: a re-promoted straggler wedged at its demotion-era
        # ledger while the pool counted it toward quorums again)
        if (self.name in self.validators
                and self.name not in old_validators
                and not self.leecher.is_running
                and not self.read_only_degraded):
            self.spylog.append(("self_promoted_resync", None))
            if self.tracer.enabled:
                self.tracer.anomaly("self_promoted_resync", {})
            self.timer.schedule(0.0, self.start_catchup)
        # transport reacts too (TCP runner syncs its NodeRegistry + dials
        # new members here; ref kit_zstack connectToMissing)
        for cb in self.on_pool_changed_callbacks:
            cb()

    def _adjust_replicas(self) -> None:
        """Follow f across membership changes: RBFT runs f+1 protocol
        instances, so growing the pool past a 3f+1 boundary adds a backup
        instance and shrinking removes one (ref adjustReplicas
        node.py:1260). Existing primary ranks are kept mid-view; NEW ranks
        extend deterministically — round-robin on the CURRENT view over
        the committed validator list — so every honest node derives the
        same assignment from the same pool txn. The full set is reselected
        at the next view change (set_instance_count)."""
        n_inst = self._n_instances()
        master = self.replicas.master
        if master.view_changer is not None:
            master.view_changer.set_instance_count(n_inst)
        existing = set(self.replicas.instance_ids)
        target = set(range(n_inst)) - self._removed_backups
        if existing == target:
            return
        if max(existing) >= n_inst:
            self.replicas.shrink_to(n_inst)
            self._removed_backups -= {i for i in self._removed_backups
                                      if i >= n_inst}
            if set(self.replicas.instance_ids) == target:
                return          # pure shrink; a gap below n_inst still
                                # falls through to be re-filled
        # Deterministic extension: base the assignment on the COMMITTED
        # audit trail (view + primaries of the batch that changed
        # membership), never on master.data — a node mid-view-change has
        # proposal-scoped primaries that would diverge across the pool.
        # New ranks take the next round-robin validators not already
        # holding a rank (one faulty node must not control 2 instances).
        from plenum_tpu.execution.handlers import audit as audit_lib
        audit = self.c.db.get_ledger(AUDIT_LEDGER_ID)
        view, _, primaries = audit_lib.last_audited_view(audit)
        primaries = list(primaries) or list(master.data.primaries)
        used = set(primaries)
        for rank in range(len(primaries), n_inst):
            n = len(self.validators)
            for j in range(n):
                cand = self.validators[(view + rank + j) % n]
                if cand not in used:
                    break
            else:
                # every validator already holds a rank — impossible while
                # n_inst = f+1 < n, but a future quorum-math change must
                # fail loudly, not silently give one node two instances
                raise RuntimeError(
                    f"no unranked validator for instance {rank}: "
                    f"{n_inst} instances over {n} validators")
            primaries.append(cand)
            used.add(cand)
        self.replicas.grow_to(n_inst, skip=self._removed_backups)
        # EVERY instance (master included) takes the extended canonical
        # list: the audit provider snapshots master.data.primaries, so a
        # short master list would be recorded durably and a restarted node
        # would restore one entry short (instance with no primary). The
        # list is derived purely from committed audit state, so a node
        # mid-view-change assigns the same value as everyone else — and
        # the view change's own completion re-selects it anyway.
        for replica in self.replicas:
            replica.data.primaries = list(primaries)
            if replica.data.inst_id not in existing:
                replica.set_validators(self.validators)
                # fresh backups join the audited view with a clean 3PC log
                replica.data.view_no = view
        self.spylog.append(
            ("replicas_adjusted", (sorted(existing), n_inst)))

    # --- ingress ----------------------------------------------------------

    def handle_client_message(self, msg: dict, frm: str) -> None:
        self._client_inbox.append((msg, frm))

    def submit_preverified(self, request: Request, frm: str) -> None:
        """Ingress-plane seam (ingress/plane.py): the request's signatures
        were already verified through THIS node's own authenticator in the
        plane's batched dispatch, and its static validation already ran at
        admission — re-dispatching here would double the device work. Pays
        the same settle pipeline as the in-node client path (ack / dedup
        Reply / propagate, or action execution), so everything downstream
        is indistinguishable from a request the node verified itself."""
        if self.c.read_manager.is_query_type(request.txn_type):
            self._answer_queries([(request, frm)])
            return
        if self.tracer.enabled:
            self.tracer.emit(tracing.INGRESS, request.digest, {"frm": frm})
        self._settle_client(request, frm, True)

    def _receive_propagate(self, msg: Propagate, frm: str) -> None:
        self._propagate_inbox.append((msg, frm))

    def _receive_propagate_batch(self, msg: PropagateBatch, frm: str) -> None:
        """Unpack a coalesced propagate envelope into the ordinary inbox:
        each entry pays the normal quota/dedup/auth pipeline."""
        for digest, sender_client in msg.votes:
            self._propagate_inbox.append(
                (Propagate(digest=digest, sender_client=sender_client), frm))
        for body in msg.bodies:
            try:
                inner = Propagate.from_dict(dict(body))
            except Exception:
                continue                   # one bad entry must not void the rest
            self._propagate_inbox.append((inner, frm))

    # --- the prod loop ----------------------------------------------------

    def prod(self) -> int:
        """One event-loop cycle (ref node.py:1037). Returns work count."""
        count = 0
        if self.c.pipeline is not None:
            # pump the shared ring: resolve a finished device wave,
            # promote the double-buffered packed one, pack the next
            self.c.pipeline.service()
        n = self._service_client_msgs()
        if n:
            self.metrics.add_event(MetricsName.CLIENT_MSGS, n)
        count += n
        n = self._service_propagates()
        if n:
            self.metrics.add_event(MetricsName.PROPAGATES, n)
        count += n
        self.replicas.service_all()
        count += self._service_ordered()
        # one PropagateBatch per tick instead of one wire message per vote:
        # the n^2 propagate message COUNT amortizes across the whole tick
        self.propagator.flush_outbox()
        return count

    # --- client pipeline --------------------------------------------------

    def _service_client_msgs(self) -> int:
        # finish last cycle's device dispatch first; while it's still
        # computing, leave the inbox queued (natural backpressure) and let
        # the rest of the prod cycle run
        self._auth_inflight, count = self._poll_inflight(
            self._auth_inflight, self._finish_client_auth)
        if self._auth_inflight is not None:
            return 0
        quota = self.config.LISTENER_MESSAGE_QUOTA
        batch, self._client_inbox = (self._client_inbox[:quota],
                                     self._client_inbox[quota:])
        to_auth: list[tuple[Request, str]] = []
        queries: list[tuple[Request, str]] = []
        for msg, frm in batch:
            if msg.get("op") == "OBSERVER_REGISTER":
                # a follower on this client connection wants BatchCommitted
                # pushes (ref observer/observable.py; the reference wires
                # registration through node plugins, here it is a first-
                # class client op so an ObserverNode needs no side channel)
                self.observable.add_observer(frm)
                self._client_send({"op": "OBSERVER_ACK"}, frm)
                continue
            try:
                request = Request.from_dict(msg)
            except Exception:
                self._client_send(RequestNack(
                    identifier=str(msg.get("identifier")),
                    req_id=msg.get("reqId") or 0,
                    reason="malformed request"), frm)
                continue
            if self.c.read_manager.is_query_type(request.txn_type):
                # answered together after the drain loop: the read plane
                # batches proof generation across the tick's query set
                queries.append((request, frm))
            elif self.action_manager is not None and \
                    self.action_manager.is_action_type(request.txn_type):
                # actions authenticate like writes but execute locally
                to_auth.append((request, frm))
            elif self.c.write_manager.is_write_type(request.txn_type):
                try:
                    self.c.write_manager.static_validation(request)
                except InvalidClientRequest as e:
                    self._client_send(RequestNack(
                        identifier=request.identifier,
                        req_id=request.req_id, reason=e.reason), frm)
                    continue
                if self.tracer.enabled:
                    self.tracer.emit(tracing.INGRESS, request.digest,
                                     {"frm": frm})
                to_auth.append((request, frm))
            else:
                self._client_send(RequestNack(
                    identifier=request.identifier, req_id=request.req_id,
                    reason=f"unknown txn type {request.txn_type!r}"), frm)
        if queries:
            self._answer_queries(queries)
        deduped: list[tuple[Request, str]] = []
        for req, frm in to_auth:
            if req.digest in self._authing:
                # a dispatch for these very bytes is already in flight
                # (peer propagate raced ahead): park the client copy and
                # settle it on that verdict instead of re-verifying
                self._authing[req.digest].append(("client", req, frm))
            else:
                self._authing[req.digest] = []
                deduped.append((req, frm))
        to_auth = deduped
        if to_auth:
            self._auth_inflight = self._submit_auth(
                to_auth, [r for r, _ in to_auth], self._finish_client_auth)
            if self._auth_inflight is not None:
                # deferred items are counted when their verdicts land, so
                # the work count (and CLIENT_MSGS/PROPAGATES metrics) stay
                # 1x regardless of backend
                return count + len(batch) - len(to_auth)
        return count + len(batch)

    def _answer_queries(self, queries: list[tuple[Request, str]]) -> None:
        """One read-plane batch for the tick's whole query set: cache
        hits, proof envelopes, and the batched digest hash happen once
        per tick, not once per query (reads/plane.py)."""
        outcomes = self.read_plane.answer_batch([q for q, _ in queries])
        for (request, frm), out in zip(queries, outcomes):
            if isinstance(out, InvalidClientRequest):
                self._client_send(RequestNack(identifier=request.identifier,
                                              req_id=request.req_id,
                                              reason=out.reason), frm)
            elif isinstance(out, Exception):
                # a malformed query must never take the prod loop down
                self._client_send(RequestNack(identifier=request.identifier,
                                              req_id=request.req_id,
                                              reason="malformed query"), frm)
            else:
                self._client_send(Reply(result=out), frm)

    def _answer_query(self, request: Request, frm: str) -> None:
        """Single-query seam kept for callers outside the prod loop."""
        self._answer_queries([(request, frm)])

    def _finish_client_auth(self, items: list[tuple[Request, str]],
                            verdicts) -> None:
        """Ack + propagate statically-valid requests whose signatures the
        device accepted (ref processRequest:2000 → recordAndPropagate)."""
        for (req, frm), ok in zip(items, verdicts):
            self._settle_client(req, frm, ok)
            self._settle_parked(req, ok)

    def _settle_parked(self, req: Request, ok: bool) -> None:
        """Deliver a landed verdict to everything parked on that digest:
        peer propagates become votes (same signed bytes — the digest covers
        the signature), parked client copies get the full client settle.
        Propagates of an already-executed request are dropped, NOT
        processed — process_propagate would resurrect request state for a
        committed txn (same hazard _finish_propagate_auth re-checks)."""
        parked = self._authing.pop(req.digest, [])
        if not parked:
            return
        executed = ok and req.digest not in self.propagator.requests \
            and self._executed_txn(req) is not None
        for entry in parked:
            if entry[0] == "prop":
                _, pmsg, pfrm = entry
                if not ok:
                    self.spylog.append(("suspicious_propagate", pfrm))
                elif not executed:
                    self.propagator.process_propagate(pmsg, pfrm)
            else:
                _, preq, pfrm = entry
                self._settle_client(preq, pfrm, ok)

    def _settle_client(self, req: Request, frm: str, ok: bool) -> None:
        if self.tracer.enabled:
            self.tracer.emit(tracing.AUTH, req.digest, {"ok": bool(ok)})
        if not ok:
            self._client_send(RequestNack(identifier=req.identifier,
                                          req_id=req.req_id,
                                          reason="signature verification failed"),
                              frm)
            return
        if self.action_manager is not None and \
                self.action_manager.is_action_type(req.txn_type):
            # actions execute on THIS node only: no propagate, no 3PC
            try:
                result = self.action_manager.process(req)
            except InvalidClientRequest as e:
                self._client_send(RequestNack(
                    identifier=req.identifier, req_id=req.req_id,
                    reason=e.reason), frm)
                return
            except UnauthorizedClientRequest as e:
                # well-formed but refused -> REJECT, never NACK
                self._client_send(Reject(
                    identifier=req.identifier, req_id=req.req_id,
                    reason=e.reason), frm)
                return
            self._client_send(Reply(result=result), frm)
            return
        # dedup: an already-executed request gets its Reply resent
        # (durable lookup via the seq-no DB, ref node.py:2000 seqNoMap)
        executed = self._executed_txn(req)
        if executed is not None:
            self._client_send(Reply(result=executed), frm)
            return
        self._client_send(RequestAck(identifier=req.identifier,
                                     req_id=req.req_id), frm)
        self.propagator.propagate(req, frm)

    def _executed_txn(self, req: Request) -> Optional[dict]:
        """Committed txn for a request that already executed, else None."""
        seq_no_db = self.c.db.get_store(SEQ_NO_DB_LABEL)
        if seq_no_db is None:
            return None
        raw = seq_no_db.try_get(req.payload_digest.encode())
        if raw is None:
            return None
        try:
            ledger_id, seq_no, _ = unpack(raw)
            return self.c.db.get_ledger(ledger_id).get_by_seq_no(seq_no)
        except Exception:
            return None

    # --- node pipeline ----------------------------------------------------

    def _service_propagates(self) -> int:
        # pipelined like the client path: finish the in-flight device
        # dispatch; while busy, keep the inbox queued (propagate votes are
        # order-insensitive, so interleaving with fresh drains is safe)
        self._prop_inflight, count = self._poll_inflight(
            self._prop_inflight, self._finish_propagate_auth)
        if self._prop_inflight is not None:
            return 0
        quota = self.config.REMOTES_MESSAGE_QUOTA
        batch, self._propagate_inbox = (self._propagate_inbox[:quota],
                                        self._propagate_inbox[quota:])
        verified: list[tuple[Propagate, str, Request]] = []
        to_auth: list[tuple[Propagate, str, Request]] = []
        for msg, frm in batch:
            if msg.request is None:
                # digest-only vote: nothing to authenticate (the sender is
                # transport-authenticated; the CONTENT is vouched for only
                # once a verified body lands) — count it directly
                digest = msg.digest
                if not digest:
                    continue
                seen = self._seen_propagates.setdefault(digest, {})
                if frm in seen:
                    continue
                seen[frm] = False
                state = self.propagator.requests.get(digest)
                if state is not None and state.executed:
                    continue     # late vote for an already-executed request
                self.propagator.process_digest_vote(digest, frm,
                                                    msg.sender_client)
                continue
            try:
                request = Request.from_dict(msg.request)
            except Exception:
                continue
            if msg.digest and msg.digest != request.digest:
                # body does not hash to the claimed digest: a lying
                # fetch responder or relay — drop, the fetch loop retries
                self.spylog.append(("suspicious_propagate", frm))
                continue
            seen = self._seen_propagates.setdefault(request.digest, {})
            if seen.get(frm):
                continue         # this sender already delivered a body
            seen[frm] = True
            state = self.propagator.requests.get(request.digest)
            if state is not None and state.request is not None:
                # signature was already verified when the body first landed
                verified.append((msg, frm, request))
            elif request.digest in self._authing:
                # same digest = same signed bytes (digest covers the
                # signature): a dispatch is already in flight, so park
                # this as a vote for when that verdict lands
                self._authing[request.digest].append(("prop", msg, frm))
            elif self._executed_txn(request) is not None:
                continue     # late propagate of an already-executed request
            else:
                # register BEFORE scanning the rest of the drain so later
                # same-digest propagates in this very batch park instead
                # of duplicating the device work
                self._authing[request.digest] = []
                to_auth.append((msg, frm, request))
        for msg, frm, _ in verified:
            self.propagator.process_propagate(msg, frm)
        if to_auth:
            self._prop_inflight = self._submit_auth(
                to_auth, [r for _, _, r in to_auth],
                self._finish_propagate_auth)
            if self._prop_inflight is not None:
                return count + len(batch) - len(to_auth)
        return count + len(batch)

    def _finish_propagate_auth(self, pending, verdicts) -> None:
        for (msg, frm, req), ok in zip(pending, verdicts):
            if not ok:
                self.spylog.append(("suspicious_propagate", frm))
                self._settle_parked(req, False)
                continue
            # verdicts can be up to MAX_AUTH_POLLS prods stale: a catchup
            # may have committed the request meanwhile — re-check the
            # executed guard the drain applied, or a late propagate would
            # resurrect request state for an already-executed txn
            # (_settle_parked applies the same guard: parked props drop,
            # parked clients get their executed-Reply via _settle_client)
            if req.digest not in self.propagator.requests and \
                    self._executed_txn(req) is not None:
                self._settle_parked(req, True)
                continue
            self.propagator.process_propagate(msg, frm)
            self._settle_parked(req, True)

    # --- pipelined device-auth plumbing -----------------------------------

    def _poll_inflight(self, inflight, finish):
        """-> (inflight', n_finished): poll a pending device dispatch,
        blocking once it has been polled MAX_AUTH_POLLS times (prod loops
        that spin faster than the device computes — MockTimer sims — must
        not starve the pipeline, and a wedged dispatch must surface)."""
        if inflight is None:
            return None, 0
        token, pending, polls = inflight
        verdicts = self.c.authenticator.collect_batch(
            token, wait=polls >= self.MAX_AUTH_POLLS)
        if verdicts is None:
            return (token, pending, polls + 1), 0
        finish(pending, verdicts)
        return None, len(pending)

    def _submit_auth(self, items, requests, finish):
        """Dispatch a signature batch; -> in-flight state or None if the
        verdicts were ready immediately (CPU backend)."""
        token = self.c.authenticator.submit_batch(requests)
        if self.tracer.enabled:
            # dispatch provenance: a supervised plane's token names its
            # route (dev / cpu / hedge); a plain CPU backend resolves
            # synchronously ("sync")
            self.tracer.emit(tracing.CRYPTO_DISPATCH, "",
                             {"n": len(requests),
                              "kind": getattr(token, "kind", "sync")})
        verdicts = self.c.authenticator.collect_batch(token, wait=False)
        if verdicts is None:
            return (token, items, 0)
        finish(items, verdicts)
        return None

    # --- ordered batches --------------------------------------------------

    def _service_ordered(self) -> int:
        done = 0
        while self._ordered_queue:
            drained, self._ordered_queue = self._ordered_queue, []
            to_exec: list[Ordered] = []
            # tracks the filter floor WITHIN this drain too: two copies of
            # the same re-certified batch can land in one drain window, and
            # comparing both against the pre-drain _last_executed_pp_seq
            # would double-commit (commit-out-of-order crash)
            exec_floor = self._last_executed_pp_seq
            for msg in drained:
                done += 1
                self.monitor.request_ordered(msg.inst_id, msg.req_idr)
                if msg.inst_id == 0:
                    for digest in msg.discarded:
                        self.monitor.req_tracker.drop(digest)
                if msg.inst_id != 0:
                    self.metrics.add_event(MetricsName.BACKUP_ORDERED)
                    self.spylog.append(("backup_ordered", msg))
                    continue
                if msg.pp_seq_no <= exec_floor:
                    # a batch ordered pre-view-change and re-certified after
                    # it can surface twice; the ledger effects are already
                    # durable
                    self.spylog.append(("duplicate_ordered_skipped",
                                        (msg.view_no, msg.pp_seq_no)))
                    continue
                to_exec.append(msg)
                exec_floor = msg.pp_seq_no
            if not to_exec:
                continue
            # GROUP COMMIT: ready batches commit under ONE write_batch
            # scope per store — the flush coalesces across batches
            # (catchup-style multi-batch commit). REPLIES go out only after
            # the scope closes: a client ack must never precede the durable
            # flush backing it. Coalescing is CAPPED (controller-steered):
            # a deep pipeline can stack dozens of ready batches, and an
            # unbounded scope would put the first batch's replies behind
            # the whole stack's flush.
            limit = max(1, (self.batch_controller.group_commit_max
                            if self.batch_controller is not None
                            else self.config.GROUP_COMMIT_MAX_BATCHES))
            while to_exec:
                chunk, to_exec = to_exec[:limit], to_exec[limit:]
                committed_per_msg: list[list[dict]] = []
                t0 = time.perf_counter()
                t0_timer = self.timer.get_current_time()
                with self.c.executor.group_commit():
                    for msg in chunk:
                        self.metrics.add_event(MetricsName.ORDERED_BATCH_SIZE,
                                               len(msg.req_idr))
                        with self.metrics.measure_time(
                                MetricsName.EXECUTE_BATCH_TIME):
                            committed_per_msg.append(self._commit_ordered(msg))
                        self._last_executed_pp_seq = msg.pp_seq_no
                self.metrics.add_event(MetricsName.COMMIT_DURABLE_TIME,
                                       time.perf_counter() - t0)
                self.metrics.add_event(MetricsName.GROUP_COMMIT_BATCHES,
                                       len(chunk))
                if (self.batch_controller is not None
                        and self.replicas.master.data.is_primary):
                    # flush span on the injectable timer (0 under mock
                    # time — deterministic): the controller's durable
                    # stage. Only the acting master primary feeds its
                    # controller — on every other node the loop would
                    # otherwise tick on durable-only samples and drift
                    # the knobs nobody reads there.
                    self.batch_controller.note_durable(
                        self.timer.get_current_time() - t0_timer,
                        len(chunk))
                if self.tracer.enabled:
                    # batch linkage rides pp_seq_no (Ordered carries no batch
                    # digest); wall duration only when the tracer allows it —
                    # perf_counter deltas are not replay-deterministic
                    data = {"seqs": [m.pp_seq_no for m in chunk]}
                    if self.tracer.wall_durations:
                        data["dur"] = time.perf_counter() - t0
                    self.tracer.emit(tracing.DURABLE, "", data)
                with self.metrics.measure_time(MetricsName.COMMIT_REPLY_TIME):
                    for msg, committed in zip(chunk, committed_per_msg):
                        self._reply_batch(msg, committed)
        return done

    def _commit_ordered(self, msg: Ordered) -> list[dict]:
        """Durable half of executeBatch:2661 — commit the ordered batch's
        writes (inside the caller's group-commit scope)."""
        batch = ThreePcBatch(
            ledger_id=msg.ledger_id, view_no=msg.view_no,
            pp_seq_no=msg.pp_seq_no, pp_time=msg.pp_time,
            valid_digests=tuple(msg.req_idr),
            state_root=bytes.fromhex(msg.state_root) if msg.state_root else b"",
            txn_root=bytes.fromhex(msg.txn_root) if msg.txn_root else b"",
            audit_txn_root=(bytes.fromhex(msg.audit_txn_root)
                            if msg.audit_txn_root else b""),
            primaries=tuple(self.replicas.master.data.primaries),
            node_reg=tuple(self.validators))
        committed = self.c.executor.commit_batch(batch)
        # advance the read plane: the txn root's tree size is knowable
        # only now (post-commit), and the batch's multi-sig — if the
        # aggregation already produced it — becomes the serving anchor;
        # either way the ledger's cached read results are invalidated
        self.read_plane.on_batch_committed(msg.ledger_id, msg.state_root,
                                           msg.txn_root)
        # ordering progress: any root-mismatch rejections before this
        # point no longer evidence OUR divergence
        self._divergence_primaries.clear()
        self.spylog.append(("executed", (msg.view_no, msg.pp_seq_no)))
        return committed

    def _reply_batch(self, msg: Ordered, committed: list[dict]) -> None:
        """Client-visible half of executeBatch: observer push, REPLY/Reject
        fan-out, request-state retirement — after the durable flush."""
        if committed and self.observable.observer_ids:
            reqs = []
            complete = True
            for digest in msg.req_idr:
                if digest in msg.discarded:
                    continue
                state = self.propagator.requests.get(digest)
                if state is None:
                    complete = False      # swept request: a partial push
                    break                 # would wedge observers on a root
                reqs.append(state.request.to_dict())      # mismatch forever
            if complete:
                # newest multi-sig for this ledger rides the push so
                # observers can anchor VERIFIED reads (they check it
                # against the pool BLS keys before adopting; it is
                # excluded from their f+1 push-content quorum — see
                # BatchCommitted.multi_sig). Prefer this batch's own
                # sig; a lagging aggregation falls back to the read
                # plane's current anchor.
                ms = None
                bls_store = self.c.db.bls_store
                if bls_store is not None and msg.state_root:
                    ms = bls_store.get(msg.state_root)
                if ms is None:
                    anchor = self.read_plane.anchor_for(msg.ledger_id)
                    ms = anchor.ms if anchor is not None else None
                self.observable.append_input(BatchCommitted(
                    requests=tuple(reqs), ledger_id=msg.ledger_id, inst_id=0,
                    view_no=msg.view_no, pp_seq_no=msg.pp_seq_no,
                    pp_time=msg.pp_time, state_root=msg.state_root,
                    txn_root=msg.txn_root,
                    seq_no_start=txn_lib.txn_seq_no(committed[0]),
                    seq_no_end=txn_lib.txn_seq_no(committed[-1]),
                    multi_sig=tuple(ms.to_list()) if ms is not None
                    else None))
            else:
                self.spylog.append(("observer_push_skipped",
                                    (msg.view_no, msg.pp_seq_no)))
        for txn in committed:
            digest = txn_lib.txn_digest(txn)
            state = self.propagator.requests.get(digest) if digest else None
            if state is not None and state.client_name is not None:
                self._client_send(Reply(result=txn), state.client_name)
                if self.tracer.enabled and digest:
                    self.tracer.emit(tracing.REPLY, digest,
                                     {"seq": msg.pp_seq_no})
            # Executed state is RETAINED (freed later by the TTL sweep):
            # peers may still MessageReq this PROPAGATE. Durable client-resend
            # dedup lives in the seq-no DB regardless.
            if digest:
                self.propagator.requests.mark_executed(digest)
                self._seen_propagates.pop(digest, None)
        for digest in msg.discarded:
            state = self.propagator.requests.get(digest)
            if state is not None and state.client_name is not None:
                self._client_send(Reject(identifier=state.request.identifier,
                                         req_id=state.request.req_id,
                                         reason="rejected by dynamic validation"),
                                  state.client_name)
            # discarded digests are still part of req_idr: lagging validators
            # must be able to fetch them to re-apply the batch, so they get
            # the same retention as executed ones
            self.propagator.requests.mark_executed(digest)
            self._seen_propagates.pop(digest, None)
        if msg.ledger_id == POOL_LEDGER_ID:
            self.pool_manager.pool_changed()

    # --- accessors --------------------------------------------------------

    @property
    def master_replica(self) -> Replica:
        return self.replicas.master

    @property
    def f(self) -> int:
        return self.quorums.f

    def validator_info(self) -> dict:
        """Operational snapshot (ref plenum/server/validator_info_tool.py):
        identity, pool view, per-ledger sizes/roots, 3PC position, catchup
        and connection state, metrics summary. Everything here is cheap to
        read — safe to poll."""
        master = self.master_replica
        ledgers = {}
        for ledger_id, ledger in self.c.db.ledgers():
            state = self.c.db.get_state(ledger_id)
            ledgers[ledger_id] = {
                "size": ledger.size,
                "uncommitted": ledger.uncommitted_size - ledger.size,
                "root": ledger.root_hash.hex(),
                "state_root": state.committed_head_hash.hex()
                if state is not None else None,
            }
        return {
            "name": self.name,
            "uptime": self.timer.get_current_time() - self.started_at,
            "validators": list(self.validators),
            "f": self.quorums.f,
            "connected": sorted(self.node_bus.connecteds),
            "blacklisted": sorted(self.blacklister.blacklisted),
            "view_no": master.data.view_no,
            "primaries": list(master.data.primaries),
            "is_primary": {r.inst_id: r.data.is_primary
                           for r in self.replicas},
            "last_ordered_3pc": tuple(master.last_ordered_3pc),
            "catchup_in_progress": self.leecher.is_running,
            "read_only_degraded": self.read_only_degraded,
            "instances": len(self.replicas),
            "ledgers": ledgers,
            "metrics": self.metrics.summary(),
            "monitor": self.monitor.stats(),
            "batch_controller": (self.batch_controller.trajectory()
                                 if self.batch_controller is not None
                                 else None),
        }
