"""plenum_tpu — a TPU-native BFT-consensus ledger framework.

A from-scratch redesign of the capability set of hyperledger indy-plenum
(RBFT consensus, Merkle-tree ledgers, MPT state, Ed25519 client auth, BLS
multi-signatures) with the crypto hot path — batched Ed25519 verification,
BLS aggregation/verification, and vectorized SHA-256 Merkle appends —
offloaded to TPU through JAX/XLA/Pallas behind provider seams.

Layering (see SURVEY.md §1 for the reference's layer map):

    storage/   key-value storage abstraction              (ref: storage/)
    ledger/    append-only Merkle transaction log         (ref: ledger/)
    state/     Merkle Patricia Trie with proofs           (ref: state/)
    network/   transport: sim network + TCP stacks        (ref: stp_zmq/)
    common/    messages, buses, timer, quorums, config    (ref: plenum/common/)
    crypto/    Ed25519 / BLS / hashing provider seams     (ref: crypto/, stp_core/crypto/)
    ops/       JAX/Pallas device kernels (the TPU plane)  (new: tpu-native)
    parallel/  device mesh & sharding of the crypto plane (new: tpu-native)
    consensus/ ordering/checkpoint/view-change services   (ref: plenum/server/consensus/)
    server/    node orchestration + execution layer       (ref: plenum/server/)
    client/    wallet & client                            (ref: plenum/client/)
"""

__version__ = "0.1.0"
