"""Base58 (bitcoin alphabet) — verkeys/DIDs on the wire use it, as in the
reference (indy identifiers are base58-encoded Ed25519 keys)."""

_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(_ALPHABET)}


def b58encode(data: bytes) -> str:
    n = int.from_bytes(data, "big")
    out = []
    while n:
        n, r = divmod(n, 58)
        out.append(_ALPHABET[r])
    pad = 0
    for b in data:
        if b == 0:
            pad += 1
        else:
            break
    return "1" * pad + "".join(reversed(out))


def b58decode(s: str) -> bytes:
    n = 0
    for c in s:
        if c not in _INDEX:
            raise ValueError(f"invalid base58 char {c!r}")
        n = n * 58 + _INDEX[c]
    raw = n.to_bytes((n.bit_length() + 7) // 8, "big")
    pad = 0
    for c in s:
        if c == "1":
            pad += 1
        else:
            break
    return b"\x00" * pad + raw
