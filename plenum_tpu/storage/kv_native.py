"""Durable KV over the native C++ log-structured engine.

Reference behavior: storage/kv_store_leveldb.py:14 / kv_store_rocksdb.py:15
— the production durable backends behind the KeyValueStorage ABC. The
engine (plenum_tpu/native/kvstore.cpp) is bitcask-shaped: append-only
CRC-checked log, in-memory ordered index, torn-tail tolerance, and
compaction; this wrapper adds the ABC surface and compacts on close when
the garbage ratio warrants it.
"""
from __future__ import annotations

import ctypes
import os
from contextlib import contextmanager
from typing import Iterator, Optional

from .kv_store import KeyValueStorage, encode_key

COMPACT_GARBAGE_RATIO = 0.5


def _load():
    from plenum_tpu.native import _build
    lib = _build("kvstore.cpp", "kvstore")
    if lib is None:
        return None
    lib.kvn_open.argtypes = [ctypes.c_char_p]
    lib.kvn_open.restype = ctypes.c_void_p
    lib.kvn_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_uint32, ctypes.c_char_p,
                            ctypes.c_uint32]
    lib.kvn_put.restype = ctypes.c_int
    lib.kvn_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_uint32, ctypes.c_char_p,
                            ctypes.c_uint32]
    lib.kvn_get.restype = ctypes.c_long
    lib.kvn_get_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32]
    lib.kvn_get_len.restype = ctypes.c_long
    lib.kvn_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_uint32]
    lib.kvn_del.restype = ctypes.c_int
    lib.kvn_count.argtypes = [ctypes.c_void_p]
    lib.kvn_count.restype = ctypes.c_long
    lib.kvn_iter_keys.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint32, ctypes.c_char_p,
                                  ctypes.c_uint32,
                                  ctypes.POINTER(ctypes.c_uint64)]
    lib.kvn_iter_keys.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.kvn_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.kvn_compact.argtypes = [ctypes.c_void_p]
    lib.kvn_compact.restype = ctypes.c_int
    for name in ("kvn_begin_batch", "kvn_end_batch"):
        fn = getattr(lib, name, None)
        if fn is not None:       # older cached .so without batch support
            fn.argtypes = [ctypes.c_void_p]
            fn.restype = ctypes.c_int
    lib.kvn_garbage_ratio.argtypes = [ctypes.c_void_p]
    lib.kvn_garbage_ratio.restype = ctypes.c_double
    lib.kvn_close.argtypes = [ctypes.c_void_p]
    return lib


_LIB = None
_LIB_TRIED = False


def native_available() -> bool:
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        _LIB_TRIED = True
        _LIB = _load()
    return _LIB is not None


class KvNative(KeyValueStorage):
    def __init__(self, path: str, name: str = "kv"):
        if not native_available():
            raise RuntimeError("native kvstore engine unavailable")
        os.makedirs(path, exist_ok=True)
        self._file_path = os.path.join(path, name + ".kvn")
        self._h = _LIB.kvn_open(self._file_path.encode())
        if not self._h:
            raise IOError(f"kvn_open failed for {self._file_path}")

    def put(self, key, value: bytes) -> None:
        k = encode_key(key)
        if _LIB.kvn_put(self._h, k, len(k), bytes(value), len(value)) != 0:
            raise IOError("kvn_put failed")

    @contextmanager
    def write_batch(self):
        """Engine-level group commit: puts/removes in the scope skip the
        per-record flush, one flush lands at scope exit (kvn_end_batch).
        Reads inside the scope stay exact (the engine flushes lazily on
        read). Nesting joins the outer scope."""
        begin = getattr(_LIB, "kvn_begin_batch", None)
        if begin is None or getattr(self, "_in_batch", False):
            yield self
            return
        self._in_batch = True
        begin(self._h)
        try:
            yield self
        finally:
            self._in_batch = False
            if _LIB.kvn_end_batch(self._h) != 0:
                raise IOError("kvn_end_batch failed")

    def get(self, key) -> bytes:
        k = encode_key(key)
        n = _LIB.kvn_get_len(self._h, k, len(k))
        if n < 0:
            raise KeyError(key)
        buf = ctypes.create_string_buffer(int(n) or 1)
        got = _LIB.kvn_get(self._h, k, len(k), buf, int(n) or 1)
        if got != n:
            raise IOError("kvn_get failed")
        return buf.raw[:n]

    def remove(self, key) -> None:
        k = encode_key(key)
        if _LIB.kvn_del(self._h, k, len(k)) != 0:
            raise IOError("kvn_del failed")

    def iterator(self, start=None, end=None,
                 include_value: bool = True) -> Iterator:
        s = encode_key(start) if start is not None else b""
        e = encode_key(end) if end is not None else b""
        total = ctypes.c_uint64()
        raw = _LIB.kvn_iter_keys(self._h, s, len(s), e, len(e),
                                 ctypes.byref(total))
        try:
            blob = ctypes.string_at(raw, total.value) if total.value else b""
        finally:
            _LIB.kvn_free(raw)
        keys = []
        off = 0
        while off < len(blob):
            klen = int.from_bytes(blob[off:off + 4], "little")
            off += 4
            keys.append(blob[off:off + klen])
            off += klen
        for k in keys:
            yield (k, self.get(k)) if include_value else k

    @property
    def size(self) -> int:
        return int(_LIB.kvn_count(self._h))

    def compact(self) -> None:
        if _LIB.kvn_compact(self._h) != 0:
            raise IOError("kvn_compact failed")

    @property
    def garbage_ratio(self) -> float:
        return float(_LIB.kvn_garbage_ratio(self._h))

    def close(self) -> None:
        if self._h:
            if self.garbage_ratio > COMPACT_GARBAGE_RATIO:
                try:
                    self.compact()
                except IOError:
                    pass                 # compaction is an optimization
            _LIB.kvn_close(self._h)
            self._h = None

    def __del__(self):
        # a dropped store must release its native handle (an open fd + C
        # buffers) even without an explicit close: a long-lived process
        # cycling stores — the crash-restart fuzz runs hundreds of node
        # lifecycles in one interpreter — exhausted the fd table through
        # GC'd-but-never-closed handles. Skip compaction: __del__ runs at
        # unpredictable times (interpreter teardown included) and must
        # only release resources.
        try:
            if getattr(self, "_h", None):
                _LIB.kvn_close(self._h)
                self._h = None
        except Exception:
            pass
