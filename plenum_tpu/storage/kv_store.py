"""Key-value storage abstraction.

Reference behavior: storage/kv_store.py:5 — KeyValueStorage ABC with
put/get/remove/iterator/do_ops_in_batch over LevelDB/RocksDB/memory/file
backends. Keys and values are bytes; int keys are encoded big-endian so
lexicographic iteration equals numeric order (ref kv_store_leveldb_int_keys.py).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from contextlib import contextmanager
from typing import Iterable, Iterator, Optional, Tuple


def encode_key(key) -> bytes:
    if isinstance(key, bytes):
        return key
    if isinstance(key, str):
        return key.encode()
    if isinstance(key, int):
        return key.to_bytes(8, "big")
    raise TypeError(f"unsupported key type {type(key)}")


def decode_int_key(key: bytes) -> int:
    return int.from_bytes(key, "big")


class KeyValueStorage(ABC):
    @abstractmethod
    def put(self, key, value: bytes) -> None: ...

    @abstractmethod
    def get(self, key) -> bytes: ...   # raises KeyError if absent

    @abstractmethod
    def remove(self, key) -> None: ...

    @abstractmethod
    def iterator(self, start=None, end=None, include_value: bool = True) -> Iterator: ...

    @abstractmethod
    def close(self) -> None: ...

    def try_get(self, key) -> Optional[bytes]:
        try:
            return self.get(key)
        except KeyError:
            return None

    def has_key(self, key) -> bool:
        return self.try_get(key) is not None

    @contextmanager
    def write_batch(self):
        """Group every put/remove issued inside the scope into one backend
        write. Durable backends override this to emit a SINGLE atomic batch
        record (one syscall, one flush, all-or-nothing on crash replay) —
        the group-commit primitive the 3PC durable path rides. Default:
        no-op grouping (each op applies immediately), which is exact for
        memory-only stores. Reads inside the scope observe the writes.
        Nested scopes join the outermost batch."""
        yield self

    def do_ops_in_batch(self, batch: Iterable[Tuple[str, object, bytes]]) -> None:
        """batch of ('put'|'remove', key, value) applied as ONE atomic
        backend write where the backend supports it (write_batch)."""
        with self.write_batch():
            for op, key, value in batch:
                if op == "put":
                    self.put(key, value)
                elif op == "remove":
                    self.remove(key)
                else:
                    raise ValueError(f"unknown op {op}")

    @property
    @abstractmethod
    def size(self) -> int: ...
