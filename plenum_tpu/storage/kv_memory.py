"""In-memory KV store (ref storage/kv_in_memory.py) backed by a sorted dict."""
from __future__ import annotations

from bisect import bisect_left, insort
from typing import Iterator, Optional

from .kv_store import KeyValueStorage, encode_key


class KvMemory(KeyValueStorage):
    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._keys: list[bytes] = []

    def put(self, key, value: bytes) -> None:
        k = encode_key(key)
        if k not in self._data:
            insort(self._keys, k)
        self._data[k] = bytes(value)

    def get(self, key) -> bytes:
        k = encode_key(key)
        if k not in self._data:
            raise KeyError(key)
        return self._data[k]

    def remove(self, key) -> None:
        k = encode_key(key)
        if k in self._data:
            del self._data[k]
            i = bisect_left(self._keys, k)
            if i < len(self._keys) and self._keys[i] == k:
                self._keys.pop(i)

    def iterator(self, start=None, end=None, include_value: bool = True) -> Iterator:
        lo = 0 if start is None else bisect_left(self._keys, encode_key(start))
        hi = None if end is None else encode_key(end)
        for i in range(lo, len(self._keys)):
            k = self._keys[i]
            if hi is not None and k > hi:
                return
            yield (k, self._data[k]) if include_value else k

    def close(self) -> None:
        pass

    @property
    def size(self) -> int:
        return len(self._data)
