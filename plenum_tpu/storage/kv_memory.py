"""In-memory KV store (ref storage/kv_in_memory.py).

Writes are O(1): new keys go to a pending list instead of being
insort'ed into the sorted key list (the previous design paid an O(n)
memmove per write, which made long-running pools fade — a 10-minute
soak spent more time maintaining these lists for the million-row
txn/state stores than verifying signatures). Sorted iteration merges
the pending run in on demand: `list.sort()` on [sorted-run, sorted-run]
is a C-level Timsort gallop-merge, so a read after a write burst costs
~O(n) with memcpy-like constants, and reads on a clean store cost
nothing extra.
"""
from __future__ import annotations

from bisect import bisect_left
from typing import Iterator, Optional

from .kv_store import KeyValueStorage, encode_key


class KvMemory(KeyValueStorage):
    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._sorted_keys: Optional[list[bytes]] = []   # None = full rebuild
        self._pending: list[bytes] = []                 # new keys, unsorted

    def put(self, key, value: bytes) -> None:
        k = encode_key(key)
        if k not in self._data:
            self._pending.append(k)
        self._data[k] = bytes(value)

    def get(self, key) -> bytes:
        k = encode_key(key)
        if k not in self._data:
            raise KeyError(key)
        return self._data[k]

    def remove(self, key) -> None:
        k = encode_key(key)
        if k in self._data:
            del self._data[k]
            self._sorted_keys = None    # rare: full rebuild on next scan

    def _keys(self) -> list[bytes]:
        # always build a NEW list: a live iterator holds the previous one
        # as its snapshot, and mutating it in place would re-yield or skip
        # keys under the iterator's cursor
        if self._sorted_keys is None:
            self._sorted_keys = sorted(self._data)
            self._pending = []
        elif self._pending:
            self._pending.sort()        # no iterator ever holds _pending
            merged = self._sorted_keys + self._pending
            merged.sort()               # two sorted runs: C gallop-merge
            self._sorted_keys = merged
            self._pending = []
        return self._sorted_keys

    def iterator(self, start=None, end=None, include_value: bool = True) -> Iterator:
        keys = self._keys()
        lo = 0 if start is None else bisect_left(keys, encode_key(start))
        hi = None if end is None else encode_key(end)
        for i in range(lo, len(keys)):
            k = keys[i]
            if hi is not None and k > hi:
                return
            # a put/remove during iteration leaves this snapshot list
            # consistent; keys deleted mid-iteration are skipped
            if k in self._data:
                yield (k, self._data[k]) if include_value else k

    def close(self) -> None:
        pass

    @property
    def size(self) -> int:
        return len(self._data)
