"""Timestamp -> state-root index for historical state reads.

Reference behavior: storage/state_ts_store.py:24,38 — StateTsDbStorage maps
(ledger_id, timestamp) to the state root committed at that time, and serves
`get_equal_or_prev(ts, ledger_id)`: the root of the LAST batch committed at
or before `ts`. Request handlers use it to answer "state as of time T"
queries (request_handlers/get_txn_author_agreement_handler.py:46).

Key layout: 2-byte big-endian ledger_id || 8-byte big-endian unix seconds,
so lexicographic KV order equals (ledger, time) order. Writes are a single
KV put (commit_batch is the hot path); `get_equal_or_prev` is a bounded
range scan taking the max qualifying key — historical queries are rare, so
the scan cost lives on the read side and nothing is cached in memory.
"""
from __future__ import annotations

from typing import Optional

from .kv_store import KeyValueStorage


def _key(ledger_id: int, ts: int) -> bytes:
    return ledger_id.to_bytes(2, "big") + int(ts).to_bytes(8, "big")


class StateTsStore:
    def __init__(self, kv: KeyValueStorage):
        self._kv = kv

    @property
    def kv(self) -> KeyValueStorage:
        return self._kv

    def set(self, ledger_id: int, ts: float, root: bytes) -> None:
        self._kv.put(_key(ledger_id, int(ts)), root)

    def get(self, ledger_id: int, ts: float) -> Optional[bytes]:
        return self._kv.try_get(_key(ledger_id, int(ts)))

    def get_equal_or_prev(self, ts: float, ledger_id: int) -> Optional[bytes]:
        """Root of the last batch committed at or before `ts` (None if the
        ledger had no committed batch yet at that time). Max-key over the
        range scan, so backend iteration order doesn't matter."""
        prefix = ledger_id.to_bytes(2, "big")
        target = _key(ledger_id, int(ts))
        best_key, best_root = None, None
        for k, v in self._kv.iterator(start=prefix + bytes(8), end=target):
            if k[:2] == prefix and k <= target and \
                    (best_key is None or k > best_key):
                best_key, best_root = k, v
        return best_root

    def close(self) -> None:
        self._kv.close()
