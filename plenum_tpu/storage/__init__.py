from .kv_store import KeyValueStorage
from .kv_memory import KvMemory
from .kv_file import KvFile
from .kv_chunked import KvChunked


def init_kv_store(backend: str, path=None, name: str = "kv") -> KeyValueStorage:
    """Factory mirroring storage/helper.py initKeyValueStorage in the reference."""
    if backend == "memory":
        return KvMemory()
    if backend == "file":
        assert path is not None, "file backend needs a path"
        return KvFile(path, name)
    if backend == "chunked":
        assert path is not None, "chunked backend needs a path"
        return KvChunked(path, name)
    raise ValueError(f"unknown kv backend {backend!r}")
