"""Chunked durable file-backed KV store for unbounded append logs.

Reference behavior: storage/chunked_file_store.py:1 — a long-lived
append log (ledger txn logs grow forever) split across fixed-size chunk
files instead of one unbounded file, so a multi-year ledger never pays
whole-file rewrites, old history can be archived/shipped per chunk, and
a torn tail only ever concerns the LAST chunk.

Same `KeyValueStorage` ABC and record format as KvFile (this slots in
as a `Ledger` txn_log unchanged); records append to the live tail
chunk, which SEALS at `chunk_records` and rotates. Sealed chunks are
never rewritten — close() does NOT compact (an append-mostly history
log has nothing to compact; rewriting every chunk would defeat the
chunking), unlike KvFile whose single file earns its close-time rewrite.
"""
from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Iterator, Optional

from .kv_file import (apply_records, pack_record, scan_records, _BATCH,
                      _HDR, _PUT, _DEL)
from .kv_memory import KvMemory
from .kv_store import KeyValueStorage, encode_key


class KvChunked(KeyValueStorage):
    def __init__(self, path: str, name: str = "kv",
                 chunk_records: int = 1000):
        if chunk_records < 1:
            raise ValueError("chunk_records must be >= 1")
        os.makedirs(path, exist_ok=True)
        self._dir = path
        self._name = name
        self._chunk_records = chunk_records
        self._mem = KvMemory()
        self._fh = None
        self._batch: Optional[list[bytes]] = None   # staged records in scope
        self._tail_no = 0          # number of the live chunk
        self._tail_records = 0     # records in the live chunk
        self._replay()
        self._fh = open(self._chunk_path(self._tail_no), "ab")

    # --- chunk files ------------------------------------------------------

    def _chunk_path(self, no: int) -> str:
        return os.path.join(self._dir, f"{self._name}.{no:06d}.chunk")

    def _chunk_numbers(self) -> list[int]:
        prefix, suffix = self._name + ".", ".chunk"
        out = []
        for fn in os.listdir(self._dir):
            if fn.startswith(prefix) and fn.endswith(suffix):
                mid = fn[len(prefix):-len(suffix)]
                if mid.isdigit():
                    out.append(int(mid))
        return sorted(out)

    def _replay(self) -> None:
        chunks = self._chunk_numbers()
        if not chunks:
            self._tail_no, self._tail_records = 1, 0
            return
        for no in chunks:
            fpath = self._chunk_path(no)
            with open(fpath, "rb") as fh:
                data = fh.read()
            entries, off = scan_records(data)   # shared format scanner
            apply_records(self._mem, entries)
            records, n = len(entries), len(data)
            if off < n:
                if no != chunks[-1]:
                    # a sealed chunk must parse end to end; a torn TAIL
                    # chunk is the one crash case this format expects
                    raise IOError(
                        f"corrupt sealed chunk {fpath!r} at offset {off}")
                with open(fpath, "r+b") as fh:
                    fh.truncate(off)   # drop the torn record
            self._tail_no, self._tail_records = no, records

    def _rotate_if_full(self) -> None:
        if self._tail_records < self._chunk_records:
            return
        self._fh.close()
        self._tail_no += 1
        self._tail_records = 0
        self._fh = open(self._chunk_path(self._tail_no), "ab")

    def _append(self, op: int, key: bytes, value: bytes = b"") -> None:
        if self._batch is not None:
            self._batch.append(pack_record(op, key, value))
            return
        self._rotate_if_full()
        self._fh.write(pack_record(op, key, value))
        self._fh.flush()
        self._tail_records += 1

    @contextmanager
    def write_batch(self):
        """One atomic _BATCH record in the tail chunk per scope (torn tail
        drops the whole batch, same as KvFile). The batch counts as its
        inner record count toward chunk rotation — replay expands it to the
        inner entries, so the accounting must match on reopen. A batch
        larger than chunk_records overflows its chunk rather than split:
        atomicity beats the soft chunk-size target."""
        if self._batch is not None:         # nested: join the outer batch
            yield self
            return
        self._batch = []
        try:
            yield self
        finally:
            records, self._batch = self._batch, None
            if records:
                self._rotate_if_full()
                if len(records) == 1:
                    self._fh.write(records[0])
                else:
                    self._fh.write(pack_record(_BATCH, b"",
                                               b"".join(records)))
                self._fh.flush()
                self._tail_records += len(records)

    # --- KeyValueStorage --------------------------------------------------

    def put(self, key, value: bytes) -> None:
        k = encode_key(key)
        self._append(_PUT, k, bytes(value))
        self._mem.put(k, value)

    def get(self, key) -> bytes:
        return self._mem.get(key)

    def remove(self, key) -> None:
        k = encode_key(key)
        self._append(_DEL, k)
        self._mem.remove(k)

    def iterator(self, start=None, end=None,
                 include_value: bool = True) -> Iterator:
        return self._mem.iterator(start, end, include_value)

    def close(self) -> None:
        if self._fh is None:
            return
        self._fh.close()
        self._fh = None

    @property
    def size(self) -> int:
        return self._mem.size

    # --- chunk maintenance (operator tooling) -----------------------------

    @property
    def chunk_count(self) -> int:
        return len(self._chunk_numbers())

    def drop_sealed_chunks_before(self, chunk_no: int) -> int:
        """Archive hook: delete sealed chunk files numbered < chunk_no
        (the in-memory view keeps serving; on the NEXT open the dropped
        records are gone — only meaningful for logs whose old records
        the caller has archived elsewhere, e.g. a snapshotted ledger).
        -> number of files deleted."""
        dropped = 0
        for no in self._chunk_numbers():
            if no >= min(chunk_no, self._tail_no):
                break
            os.remove(self._chunk_path(no))
            dropped += 1
        return dropped
