"""Durable file-backed KV store.

Append-only log of (op, key, value) records with an in-memory index, compacted
on close. Fills the role of the reference's LevelDB/RocksDB backends
(storage/kv_store_leveldb.py:14, kv_store_rocksdb.py:15) for crash-resume
without native DB deps; a C++ LSM backend can slot in behind the same ABC.
"""
from __future__ import annotations

import os
import struct
from contextlib import contextmanager
from typing import Iterator, Optional

from .kv_store import KeyValueStorage, encode_key
from .kv_memory import KvMemory

# _BATCH is the group-commit record: key empty, value = the concatenated
# inner put/del records, written (and flushed) as ONE append. Crash
# atomicity falls out of the framing: the outer header's value_len covers
# every inner record, so a torn write drops the WHOLE batch on replay —
# there is no prefix of a batch.
_PUT, _DEL, _BATCH = 0, 1, 2
_HDR = struct.Struct(">BII")  # op, key_len, value_len


def pack_record(op: int, key: bytes, value: bytes = b"") -> bytes:
    return _HDR.pack(op, len(key), len(value)) + key + value


def scan_records(data: bytes) -> tuple[list[tuple[int, bytes, bytes]], int]:
    """THE record-scan for this on-disk format, shared by every reader
    (KvFile replay, read-only replay, KvChunked replay — a format or
    validation change happens HERE once). Parses until the first corrupt
    header or truncated (torn-tail) record; batch records expand to their
    inner put/del entries (whose framing the outer length already
    validated — a batch whose payload doesn't parse exactly is corrupt and
    ends the scan). -> ([(op, key, value)], good_prefix_length)."""
    entries = []
    off, n = 0, len(data)
    while off + _HDR.size <= n:
        op, klen, vlen = _HDR.unpack_from(data, off)
        if op not in (_PUT, _DEL, _BATCH) or off + _HDR.size + klen + vlen > n:
            break
        rec_end = off + _HDR.size + klen + vlen
        key = data[off + _HDR.size:off + _HDR.size + klen]
        val = data[off + _HDR.size + klen:rec_end]
        if op == _BATCH:
            inner, inner_off = scan_records(val)
            if inner_off != len(val) or any(o == _BATCH for o, _, _ in inner):
                break                      # corrupt batch payload
            entries.extend(inner)
        else:
            entries.append((op, key, val))
        off = rec_end
    return entries, off


def apply_records(mem: KvMemory, entries) -> None:
    for op, key, val in entries:
        if op == _PUT:
            mem.put(key, val)
        else:
            mem.remove(key)


def read_log_readonly(path: str, name: str = "kv") -> list[tuple[bytes, bytes]]:
    """Replay a KvFile log WITHOUT opening it for append, truncating a torn
    tail, or compacting — safe against a store another process is writing.
    Torn/corrupt tails are simply ignored. -> sorted [(key, value)]."""
    file_path = os.path.join(path, name + ".kvlog")
    mem = KvMemory()
    if not os.path.exists(file_path):
        return []
    with open(file_path, "rb") as fh:
        data = fh.read()
    apply_records(mem, scan_records(data)[0])
    return list(mem.iterator())


class KvFile(KeyValueStorage):
    def __init__(self, path: str, name: str = "kv"):
        os.makedirs(path, exist_ok=True)
        self._file_path = os.path.join(path, name + ".kvlog")
        self._mem = KvMemory()
        self._fh = None
        self._batch: Optional[list[bytes]] = None   # staged records in scope
        self._replay()
        self._fh = open(self._file_path, "ab")

    def _replay(self) -> None:
        if not os.path.exists(self._file_path):
            return
        with open(self._file_path, "rb") as fh:
            data = fh.read()
        entries, off = scan_records(data)
        apply_records(self._mem, entries)
        n = len(data)
        if off < n:
            # Drop the torn record so appended records aren't misparsed by the
            # next replay.
            with open(self._file_path, "r+b") as fh:
                fh.truncate(off)

    def _append(self, op: int, key: bytes, value: bytes = b"") -> None:
        if self._batch is not None:
            self._batch.append(pack_record(op, key, value))
            return
        self._fh.write(pack_record(op, key, value))
        self._fh.flush()

    def _flush_batch(self, records: list[bytes]) -> None:
        """One append, one flush, all-or-nothing on replay."""
        if not records:
            return
        if len(records) == 1:
            self._fh.write(records[0])      # a 1-op batch IS atomic already
        else:
            self._fh.write(pack_record(_BATCH, b"", b"".join(records)))
        self._fh.flush()

    @contextmanager
    def write_batch(self):
        if self._batch is not None:         # nested: join the outer batch
            yield self
            return
        self._batch = []
        try:
            yield self
        finally:
            # flushed even if the scope raised: the in-memory view already
            # holds these writes, and memory/disk must not diverge
            records, self._batch = self._batch, None
            self._flush_batch(records)

    def put(self, key, value: bytes) -> None:
        k = encode_key(key)
        self._append(_PUT, k, bytes(value))
        self._mem.put(k, value)

    def get(self, key) -> bytes:
        return self._mem.get(key)

    def remove(self, key) -> None:
        k = encode_key(key)
        self._append(_DEL, k)
        self._mem.remove(k)

    def iterator(self, start=None, end=None, include_value: bool = True) -> Iterator:
        return self._mem.iterator(start, end, include_value)

    def close(self) -> None:
        if self._fh is None:
            return
        self._fh.close()
        self._fh = None
        # Compact: rewrite only live records.
        tmp = self._file_path + ".compact"
        with open(tmp, "wb") as fh:
            for k, v in self._mem.iterator():
                fh.write(_HDR.pack(_PUT, len(k), len(v)) + k + v)
        os.replace(tmp, self._file_path)

    @property
    def size(self) -> int:
        return self._mem.size
