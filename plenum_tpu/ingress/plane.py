"""The pool's front door: admission control, fair queueing, batched auth.

A validator's raw client inbox treats every arriving message alike: a
million mostly-idle clients and one flooding hot client both land in ONE
list the prod loop drains under a quota, and every write pays its own
signature verification. ``IngressPlane`` multiplexes a huge client
population onto the node pipeline with three mechanisms:

1. **Admission control + fair queueing.** Each client gets a BOUNDED
   queue (``INGRESS_CLIENT_QUEUE_CAP``); a weighted-fair (deficit
   round-robin) dequeue drains the active clients into the node pipeline,
   so one hot client's backlog cannot starve everyone else's single
   request. The SUM of all queues rides a watermark pair: above the
   (controller-steered) shed watermark, NEW arrivals get an explicit
   ``LoadShed`` reply until the total drains below the low mark
   (hysteresis) — shed-before-wedge: floods degrade service with honest
   refusals instead of wedging the node's inbox.

2. **Batched client authentication.** Each tick's fair-dequeued writes
   go through ``ReqAuthenticator.submit_batch`` / ``collect_batch``
   (node/client_authn.py) as ONE device dispatch — client-auth cost
   amortizes across the admitted batch exactly like commit-sig cost
   already does on the ordering path. The dispatch is pipelined (one in
   flight; the plane keeps admitting while the device computes), and
   verified requests enter the node through ``Node.submit_preverified``,
   which skips the node's own re-dispatch.

3. **Closed-loop admission.** An AIMD controller (controller.py) steers
   the dequeue budget and the effective shed watermark from queue-wait
   p95 toward ``INGRESS_SLO_P95``.

Reads and observer registrations pass straight through to the node: the
read plane already batches per-tick query sets, and at scale reads go to
OBSERVERS (ingress/observer_reads.py), not through this plane at all.
"""
from __future__ import annotations

from collections import OrderedDict, deque
from typing import Optional

from plenum_tpu.common import tracing
from plenum_tpu.common.metrics import MetricsName
from plenum_tpu.common.node_messages import LoadShed, RequestNack
from plenum_tpu.common.request import Request
from plenum_tpu.common.timer import RepeatingTimer
from plenum_tpu.execution.exceptions import InvalidClientRequest

SHED_OVERLOAD = "ingress overloaded: queue watermark reached"
SHED_CLIENT_CAP = "ingress: per-client queue full"


class IngressPlane:
    MAX_AUTH_POLLS = 50

    def __init__(self, node, config=None, tracer=None, metrics=None,
                 send=None, tick: bool = True, sink=None):
        self.node = node
        self.config = config or node.config
        self.timer = node.timer
        self.tracer = tracer if tracer is not None else node.tracer
        self.metrics = metrics if metrics is not None else node.metrics
        self._send = send or node._client_send
        # where verified writes go. Default: this node's own pipeline
        # (submit_preverified, resolved late so the node attribute stays
        # swappable). A sharded deployment hands a ShardRouter route
        # here instead — admission + the batched auth dispatch happen
        # ONCE at this front door, then the write is fanned to whichever
        # sub-pool owns its key (shards/router.py)
        self._sink = sink if sink is not None else (
            lambda req, frm: self.node.submit_preverified(req, frm))

        # client -> deque[(Request, frm, enqueue_ts)]; rotation holds each
        # ACTIVE client once, weights grant >1 dequeues per rotation pass
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._rotation: deque = deque()
        self._weights: dict[str, int] = {}
        self._total = 0
        self._shedding = False          # watermark hysteresis latch
        self._forced_watermark = None   # autopilot ladder clamp
        self._inflight = None           # (token, entries, polls)

        from .controller import make_ingress_controller
        self.controller = make_ingress_controller(
            self.config, self.timer, tracer=self.tracer,
            metrics=self.metrics)

        self.stats = {"submitted": 0, "admitted": 0, "shed": 0,
                      "shed_overload": 0, "shed_client_cap": 0,
                      "auth_batches": 0, "auth_items": 0, "auth_fail": 0,
                      "nacked": 0, "passthrough": 0, "queue_depth_max": 0,
                      # ingress-SLO ledger for the telemetry plane's
                      # burn-rate tracking: one check per dequeued write,
                      # a violation when its queue wait exceeded
                      # INGRESS_SLO_P95 (cumulative; the snapshot source
                      # takes deltas)
                      "slo_checks": 0, "slo_violations": 0}
        # register as a telemetry source: the front door's queue depth,
        # shed state, and SLO ledger are fleet-health signals
        # (observability/snapshot.py); one guarded attribute check when
        # telemetry is disabled
        telemetry = getattr(node, "telemetry", None)
        if telemetry is not None and telemetry.enabled:
            from plenum_tpu.observability import CumulativeDelta
            self._telemetry_deltas = CumulativeDelta()
            # distinct clients hitting their per-client cap this
            # snapshot interval — the breadth rule's input
            self._capped_clients: set = set()
            telemetry.add_source("ingress", self._telemetry_state)

        self._tick_timer = None
        if tick:
            self._tick_timer = RepeatingTimer(
                self.timer, self.config.INGRESS_TICK_INTERVAL, self.service)

    def stop(self) -> None:
        if self._tick_timer is not None:
            self._tick_timer.stop()

    # --- knobs ------------------------------------------------------------

    def set_weight(self, client: str, weight: int) -> None:
        """Dequeues granted to `client` per fair-rotation pass (default 1)."""
        self._weights[client] = max(1, int(weight))

    def force_shed_watermark(self, value) -> None:
        """Orchestrated degradation (the autopilot ladder's shed-harder
        step): clamp the effective shed watermark to `value`, overriding
        both the static config mark and the AIMD controller's steering.
        None releases the clamp (back to controller/config)."""
        self._forced_watermark = None if value is None \
            else max(1, int(value))

    @property
    def shed_watermark(self) -> int:
        if self._forced_watermark is not None:
            return self._forced_watermark
        if self.controller is not None:
            return self.controller.shed_watermark
        return self.config.INGRESS_HIGH_WATERMARK

    @property
    def admit_budget(self) -> int:
        if self.controller is not None:
            return self.controller.admit_max
        return self.config.INGRESS_ADMIT_MAX

    @property
    def queue_depth(self) -> int:
        return self._total

    # --- ingress ----------------------------------------------------------

    def submit(self, msg: dict, frm: str) -> None:
        """One client message at the front door. Reads, actions on the
        pass-through path, and anything the plane cannot classify go
        straight to the node (its pipeline validates them); writes pay
        admission control and queue for the batched verifier."""
        self.stats["submitted"] += 1
        if not isinstance(msg, dict) or msg.get("op") == "OBSERVER_REGISTER":
            self.node.handle_client_message(msg, frm)
            self.stats["passthrough"] += 1
            return
        try:
            request = Request.from_dict(msg)
        except Exception:
            self._send(RequestNack(identifier=str(msg.get("identifier")),
                                   req_id=msg.get("reqId") or 0,
                                   reason="malformed request"), frm)
            self.stats["nacked"] += 1
            return
        if self.node.c.read_manager.is_query_type(request.txn_type):
            # the node's read plane batches the tick's query set already;
            # at scale reads belong on observers and never reach here
            self.node.handle_client_message(msg, frm)
            self.stats["passthrough"] += 1
            return
        is_action = (self.node.action_manager is not None
                     and self.node.action_manager.is_action_type(
                         request.txn_type))
        if not is_action:
            if not self.node.c.write_manager.is_write_type(request.txn_type):
                self._send(RequestNack(
                    identifier=request.identifier, req_id=request.req_id,
                    reason=f"unknown txn type {request.txn_type!r}"), frm)
                self.stats["nacked"] += 1
                return
            try:
                # static validation BEFORE the queue: garbage must not
                # occupy admission capacity or a device-batch slot
                self.node.c.write_manager.static_validation(request)
            except InvalidClientRequest as e:
                self._send(RequestNack(identifier=request.identifier,
                                       req_id=request.req_id,
                                       reason=e.reason), frm)
                self.stats["nacked"] += 1
                return
        self._admit(request, frm)

    def _admit(self, request: Request, frm: str) -> None:
        q = self._queues.get(frm)
        if q is not None and len(q) >= self.config.INGRESS_CLIENT_QUEUE_CAP:
            self._shed(request, frm, SHED_CLIENT_CAP, "shed_client_cap")
            return
        watermark = self.shed_watermark
        if self._shedding:
            if self._total > self.config.INGRESS_LOW_WATERMARK:
                self._shed(request, frm, SHED_OVERLOAD, "shed_overload")
                return
            self._shedding = False      # drained below the low mark
        elif self._total >= watermark:
            self._shedding = True
            self._shed(request, frm, SHED_OVERLOAD, "shed_overload")
            return
        if q is None:
            q = self._queues[frm] = deque()
        if not q:                       # newly active client joins rotation
            self._rotation.append(frm)
        q.append((request, frm, self.timer.get_current_time()))
        self._total += 1
        self.stats["queue_depth_max"] = max(self.stats["queue_depth_max"],
                                            self._total)
        self.stats["admitted"] += 1
        self.metrics.add_event(MetricsName.INGRESS_ADMITTED)
        if self.tracer.enabled:
            self.tracer.emit(tracing.ING_ADMIT, request.digest, {"frm": frm})

    def _shed(self, request: Request, frm: str, reason: str,
              stat: str) -> None:
        self.stats["shed"] += 1
        self.stats[stat] += 1
        # an OVERLOAD shed spends ingress error budget: the pool refused
        # work it should have absorbed. A per-client-cap shed goes into
        # the ledger only via the BREADTH rule at snapshot time (many
        # distinct clients capped in one interval = overload; one
        # abusive client being fairness-limited must not page the pool
        # SLO alert while every well-behaved client is served in bounds)
        if stat == "shed_overload":
            self.stats["slo_checks"] += 1
            self.stats["slo_violations"] += 1
        elif stat == "shed_client_cap" and hasattr(self,
                                                   "_capped_clients"):
            self._capped_clients.add(frm)
        self.metrics.add_event(MetricsName.INGRESS_SHED)
        self._send(LoadShed(identifier=request.identifier,
                            req_id=request.req_id, reason=reason,
                            retry_after=self.config.INGRESS_TICK_INTERVAL),
                   frm)
        if self.tracer.enabled:
            self.tracer.emit(tracing.ING_SHED, request.digest,
                             {"frm": frm, "reason": reason})

    # --- the service tick -------------------------------------------------

    def service(self) -> int:
        """One tick: finish the in-flight auth dispatch, then fair-dequeue
        up to the admission budget into one new dispatch. Returns the
        number of requests whose verdicts landed this tick."""
        done = self._poll_inflight()
        if self._inflight is not None:
            return done                 # device still computing: keep
            # admitting (queues fill toward the watermark — that IS the
            # backpressure), dispatch again next tick
        self.metrics.add_event(MetricsName.INGRESS_QUEUE_DEPTH, self._total)
        if not self._total:
            return done
        batch = self._fair_dequeue(self.admit_budget)
        if not batch:
            return done
        # within-batch dedup: one device verify per digest; every copy of
        # that digest settles on the shared verdict (the signature is part
        # of the digest, so same digest = same signed bytes)
        entries: "OrderedDict[str, list]" = OrderedDict()
        for req, frm, t_enq in batch:
            entries.setdefault(req.digest, []).append((req, frm))
        uniques = [group[0][0] for group in entries.values()]
        token = self.node.c.authenticator.submit_batch(uniques)
        n_items = self.node.c.authenticator.token_item_count(token)
        self.stats["auth_batches"] += 1
        self.stats["auth_items"] += n_items
        self.metrics.add_event(MetricsName.INGRESS_AUTH_BATCH, n_items)
        if self.tracer.enabled:
            self.tracer.emit(tracing.ING_AUTH, "",
                             {"n": len(uniques), "sigs": n_items})
        verdicts = self.node.c.authenticator.collect_batch(token, wait=False)
        if verdicts is None:
            self._inflight = (token, entries, 0)
            return done
        self._finish(entries, verdicts)
        return done + sum(len(g) for g in entries.values())

    def _poll_inflight(self) -> int:
        if self._inflight is None:
            return 0
        token, entries, polls = self._inflight
        verdicts = self.node.c.authenticator.collect_batch(
            token, wait=polls >= self.MAX_AUTH_POLLS)
        if verdicts is None:
            self._inflight = (token, entries, polls + 1)
            return 0
        self._inflight = None
        self._finish(entries, verdicts)
        return sum(len(g) for g in entries.values())

    def _fair_dequeue(self, budget: int) -> list:
        """Deficit-round-robin drain: each rotation pass grants every
        active client `weight` dequeues, so under backlog the budget
        splits max-min fairly across clients instead of FIFO-rewarding
        whoever flooded first. Queue-wait samples feed the controller."""
        out: list = []
        now = self.timer.get_current_time()
        fairness: dict[str, int] = {}
        while len(out) < budget and self._rotation:
            client = self._rotation[0]
            q = self._queues.get(client)
            if not q:
                self._rotation.popleft()
                self._queues.pop(client, None)
                continue
            grant = min(self._weights.get(client, 1), len(q),
                        budget - len(out))
            for _ in range(grant):
                req, frm, t_enq = q.popleft()
                self._total -= 1
                wait = now - t_enq
                self.metrics.add_event(MetricsName.INGRESS_QUEUE_WAIT, wait)
                self.stats["slo_checks"] += 1
                if wait > self.config.INGRESS_SLO_P95:
                    self.stats["slo_violations"] += 1
                if self.controller is not None:
                    self.controller.note_admitted(wait)
                out.append((req, frm, t_enq))
                fairness[client] = fairness.get(client, 0) + 1
            self._rotation.rotate(-1)
            if not q:
                # drained: drop from rotation. After rotate(-1) the
                # client sits at the BACK — pop() is O(1) where a
                # remove() scan would make a 10k-client drain quadratic
                if self._rotation and self._rotation[-1] == client:
                    self._rotation.pop()
                self._queues.pop(client, None)
        if len(fairness) > 1:
            counts = list(fairness.values())
            self.metrics.add_event(
                MetricsName.INGRESS_FAIRNESS_SPREAD,
                max(counts) / (sum(counts) / len(counts)))
        self.metrics.add_event(MetricsName.INGRESS_CLIENTS, len(self._queues))
        return out

    def _finish(self, entries, verdicts) -> None:
        ok_n = fail_n = 0
        for (digest, group), ok in zip(entries.items(), verdicts):
            for req, frm in group:
                if ok:
                    ok_n += 1
                    self._sink(req, frm)
                else:
                    fail_n += 1
                    self.stats["auth_fail"] += 1
                    self.metrics.add_event(MetricsName.INGRESS_AUTH_FAIL)
                    self._send(RequestNack(
                        identifier=req.identifier, req_id=req.req_id,
                        reason="signature verification failed"), frm)
        if self.tracer.enabled:
            self.tracer.emit(tracing.ING_VERDICT, "",
                             {"ok": ok_n, "fail": fail_n})

    # --- reporting --------------------------------------------------------

    def _telemetry_state(self) -> dict:
        """Front-door section of the node's telemetry snapshot: live
        queue depth, the shed latch, per-interval shed volume, and the
        ingress-SLO ledger deltas the burn-rate tracker consumes."""
        out = {
            "queue_depth": self._total,
            "active_clients": len(self._queues),
            "shedding": self._shedding,
            "watermark": self.shed_watermark,
        }
        take = self._telemetry_deltas.take
        d_shed = take("shed", self.stats["shed"])
        if d_shed:
            out["shed"] = d_shed
        d_v = take("slo_v", self.stats["slo_violations"])
        d_n = take("slo_n", self.stats["slo_checks"])
        # the BREADTH rule: per-client-cap sheds count against the pool
        # SLO only when MANY distinct clients were capped this interval
        # (aggregate demand outran the pool = overload); below the
        # breadth floor it is the fairness mechanism doing its job on a
        # few abusers and must not burn the pool's error budget
        d_cap = take("cap_shed", self.stats["shed_client_cap"])
        breadth = len(self._capped_clients)
        self._capped_clients.clear()
        if d_cap and breadth >= getattr(self.config,
                                        "INGRESS_SLO_CAP_BREADTH", 3):
            d_v += d_cap
            d_n += d_cap
        if d_n > 0:
            out["slo"] = [d_v, d_n]
        return out

    def summary(self) -> dict:
        out = dict(self.stats)
        out["queue_depth"] = self._total
        out["active_clients"] = len(self._queues)
        out["watermark"] = self.shed_watermark
        out["admit_budget"] = self.admit_budget
        if self.stats["auth_batches"]:
            out["auth_batch_mean"] = round(
                self.stats["auth_items"] / self.stats["auth_batches"], 2)
        if self.controller is not None:
            out["controller"] = self.controller.trajectory()
        return out
