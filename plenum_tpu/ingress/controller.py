"""AIMD admission controller for the ingress plane.

Same control pattern as the ordering loop's batch controller
(consensus/batch_controller.py): timer-stamped samples fold into a
rolling queue-wait p95, and decisions fire on SAMPLE ARRIVALS past the
interval deadline — never on a free-running repeating timer — so a
MockTimer-driven pool adapts identically on every replay.

Two knobs, steered toward ``INGRESS_SLO_P95`` (queue-wait p95):

  * **admit_max** — the per-tick weighted-fair dequeue budget into the
    batched verifier. Queue wait over the SLO means requests sit queued
    longer than the target: grow the budget multiplicatively (bigger
    auth batches also amortize BETTER on the device — draining harder is
    free twice). Under the SLO it decays additively back toward the
    configured default so a burst-grown budget does not pin the device
    shape large forever.
  * **shed_watermark** — the effective global queue bound. Sustained SLO
    violation even at full drain budget means arrivals genuinely exceed
    service capacity: cut the watermark multiplicatively so the plane
    sheds EARLIER (clients get an explicit LoadShed now instead of a
    timeout later — shed-before-wedge). Headroom recovers it additively
    toward the configured high watermark.
"""
from __future__ import annotations

from collections import deque
from typing import Optional

from plenum_tpu.common import tracing
from plenum_tpu.common.metrics import MetricsName, percentile
from plenum_tpu.common.timer import TimerService
from plenum_tpu.config import Config

_WINDOW = 512


class IngressController:
    def __init__(self, config: Config, timer: TimerService,
                 tracer=None, metrics=None):
        self._config = config
        self._timer = timer
        self._tracer = tracer if tracer is not None else tracing.NULL_TRACER
        self._metrics = metrics

        self._admit_default = max(config.INGRESS_ADMIT_MIN,
                                  min(128, config.INGRESS_ADMIT_MAX))
        self.admit_max = self._admit_default
        self.shed_watermark = config.INGRESS_HIGH_WATERMARK
        # floor strictly ABOVE the latch-release mark: a fully-shrunk
        # watermark that equals INGRESS_LOW_WATERMARK would collapse the
        # hysteresis band to zero width and flap admit/shed per arrival
        self._watermark_floor = min(
            config.INGRESS_HIGH_WATERMARK,
            max(2 * config.INGRESS_LOW_WATERMARK,
                config.INGRESS_HIGH_WATERMARK // 8))
        self._watermark_step = max(
            1, config.INGRESS_HIGH_WATERMARK // 16)

        self._waits: deque = deque(maxlen=_WINDOW)
        self._fresh = 0
        self.decisions = 0
        self.last_decision: dict = {}
        self._next_decision = (timer.get_current_time()
                               + config.INGRESS_CONTROL_INTERVAL)

    # --- observations ----------------------------------------------------

    def note_admitted(self, queue_wait: float) -> None:
        """One request left its client queue for the auth batch; how long
        it waited (timer-stamped)."""
        self._waits.append(max(0.0, queue_wait))
        self._fresh += 1
        now = self._timer.get_current_time()
        if now >= self._next_decision:
            self._next_decision = now + self._config.INGRESS_CONTROL_INTERVAL
            self.tick()

    # --- the control loop ------------------------------------------------

    def wait_p95(self) -> float:
        return percentile(self._waits, 0.95) if self._waits else 0.0

    def tick(self) -> None:
        if not self._fresh:
            return                      # idle front door: hold the knobs
        self._fresh = 0
        p95 = self.wait_p95()
        p50 = percentile(self._waits, 0.5) if self._waits else 0.0
        slo = self._config.INGRESS_SLO_P95
        cfg = self._config
        if p95 > slo:
            if self.admit_max < cfg.INGRESS_ADMIT_MAX:
                # drain harder first: a larger fair-dequeue budget both
                # cuts the wait and grows the amortized auth batch
                verdict = "grow:drain"
                self.admit_max = min(cfg.INGRESS_ADMIT_MAX,
                                     self.admit_max * 2)
            else:
                # already draining at the cap and still over SLO:
                # arrivals exceed capacity — shed earlier
                verdict = "shrink:watermark"
                self.shed_watermark = max(self._watermark_floor,
                                          int(self.shed_watermark * 0.7))
        else:
            verdict = "recover:headroom"
            if self.shed_watermark < cfg.INGRESS_HIGH_WATERMARK:
                self.shed_watermark = min(cfg.INGRESS_HIGH_WATERMARK,
                                          self.shed_watermark
                                          + self._watermark_step)
            if p95 < 0.5 * slo and self.admit_max > self._admit_default:
                self.admit_max = max(self._admit_default,
                                     self.admit_max // 2)
        self.decisions += 1
        self._waits.clear()             # judge each interval on its own
        self.last_decision = {
            "verdict": verdict,
            "admit_max": self.admit_max,
            "watermark": self.shed_watermark,
            "wait_p50_ms": round(p50 * 1000, 3),
            "wait_p95_ms": round(p95 * 1000, 3),
            "slo_ms": round(slo * 1000, 3),
        }
        if self._tracer.enabled:
            self._tracer.emit(tracing.ING_CONTROLLER, "", self.last_decision)
        if self._metrics is not None:
            self._metrics.add_event(MetricsName.INGRESS_CTL_ADMIT,
                                    self.admit_max)
            self._metrics.add_event(MetricsName.INGRESS_CTL_WATERMARK,
                                    self.shed_watermark)
            self._metrics.add_event(MetricsName.INGRESS_CTL_DECISIONS,
                                    self.decisions)

    def trajectory(self) -> dict:
        return {
            "decisions": self.decisions,
            "admit_max": self.admit_max,
            "watermark": self.shed_watermark,
            "slo_ms": round(self._config.INGRESS_SLO_P95 * 1000, 3),
            **({"last": self.last_decision} if self.last_decision else {}),
        }


def make_ingress_controller(config: Config, timer: TimerService,
                            tracer=None, metrics=None
                            ) -> Optional[IngressController]:
    """Config-gated seam: INGRESS_CONTROLLER=False -> None, and the plane
    runs the static INGRESS_ADMIT_MAX / INGRESS_HIGH_WATERMARK knobs."""
    if not getattr(config, "INGRESS_CONTROLLER", True):
        return None
    return IngressController(config, timer, tracer=tracer, metrics=metrics)
