"""Million-client ingress plane: the pool's front door.

``IngressPlane`` multiplexes huge client populations onto a node with
per-client bounded queues, weighted-fair dequeue, watermark-based
shedding (explicit ``LoadShed`` replies — shed-before-wedge) and batched
client authentication through the node's ``ReqAuthenticator`` seam;
``IngressController`` closes the admission loop toward a queue-wait SLO;
``ObserverReadGate`` / ``SimObserver`` serve PR 4 verified-read
envelopes from replicated observer state so reads scale horizontally
without touching consensus quorums. See docs/ingress.md.
"""
from .controller import IngressController, make_ingress_controller
from .observer_reads import ObserverFleet, ObserverReadGate, SimObserver
from .plane import SHED_CLIENT_CAP, SHED_OVERLOAD, IngressPlane

__all__ = ["IngressPlane", "IngressController", "make_ingress_controller",
           "ObserverFleet", "ObserverReadGate", "SimObserver",
           "SHED_OVERLOAD", "SHED_CLIENT_CAP"]
