"""Observer-served verified reads: horizontal read fan-out.

PR 4's read plane made one VALIDATOR's answer trustworthy: the proof is
anchored to a BLS multi-signed root, so trust rides the signature, not
the server. That property is exactly what lets reads leave the pool
entirely — ANY replica holding the multi-signed root can serve millions
of verified reads without touching a consensus quorum (ROADMAP item 3).

``ObserverReadGate`` is the read-serving core an observer wires over its
replicated components:

  * **Anchor adoption is verification-gated.** Validators attach their
    newest ``MultiSignature`` to every ``BatchCommitted`` push
    (Node._reply_batch); the gate verifies it against the pool BLS keys
    (distinct participants, n-f quorum, pairing —
    ``MultiSignature.verify``) BEFORE handing it to the ReadPlane. A
    Byzantine pusher can therefore stall an observer's anchor but never
    move it to an unsigned root. Verification is memoized per signature,
    so steady traffic pays one pairing per anchor advance.
  * **Anchor lag escalates, never serves stale.** When the newest
    verified anchor is older than ``OBSERVER_ANCHOR_LAG_MAX`` (an
    observer cut off from pushes keeps its last root forever), replies
    ship PROOFLESS — the verifying client fails over to a validator —
    instead of shipping a proof the client's freshness bound would
    reject anyway (and that a lenient client might wrongly trust).

``SimObserver`` composes the gate with ``NodeObserver`` (f+1
content-quorum push application) into a full in-process observer node
for SimNetwork pools — the unit the 10k-client bench config and the
observer read tests drive. The TCP twin lives in
``node/observer_node.py`` (ObserverNode with a client listener).
"""
from __future__ import annotations

from typing import Callable, Mapping, Optional, Sequence

from plenum_tpu.common.metrics import MetricsCollector, MetricsName
from plenum_tpu.common.node_messages import (BatchCommitted,
                                             DOMAIN_LEDGER_ID, Reply,
                                             RequestNack)
from plenum_tpu.common.request import Request
from plenum_tpu.crypto.multi_signature import MultiSignature
from plenum_tpu.reads import READ_PROOF, ReadPlane


# default sentinel: resolve the lag bound from Config at construction
# (None is a MEANINGFUL value — "never suppress" — so it can't be the
# marker for "not given")
FROM_CONFIG = object()


def _resolve_lag(anchor_lag_max) -> Optional[float]:
    if anchor_lag_max is FROM_CONFIG:
        from plenum_tpu.config import Config
        return Config().OBSERVER_ANCHOR_LAG_MAX
    return anchor_lag_max


class ObserverReadGate:
    """Read plane + verified anchor intake for one observer replica."""

    def __init__(self, components, bls_keys: Mapping[str, str],
                 n_nodes: int, now: Callable[[], float],
                 anchor_lag_max=FROM_CONFIG,
                 metrics: Optional[MetricsCollector] = None,
                 tracer=None):
        anchor_lag_max = _resolve_lag(anchor_lag_max)
        self.c = components
        self.bls_keys = dict(bls_keys)
        self.n_nodes = n_nodes
        self.now = now
        self.anchor_lag_max = anchor_lag_max
        self.metrics = metrics or MetricsCollector()
        domain = components.db.get_ledger(DOMAIN_LEDGER_ID)
        self.read_plane = ReadPlane(
            components.db, components.read_manager, metrics=self.metrics,
            hasher=domain.hasher if domain is not None else None,
            tracer=tracer)
        # (signature, participants, value) -> verdict: one pairing per
        # distinct multi-sig, not one per push (n validators push the
        # same anchor epoch)
        self._ms_memo: dict = {}
        self.stats = {"pushes": 0, "ms_adopted": 0, "ms_rejected": 0,
                      "stale_suppressed": 0}

    # --- anchor intake (push path) ---------------------------------------

    def on_push(self, batch: BatchCommitted, applied: bool) -> None:
        """Every push lands here; `applied` = NodeObserver committed it.
        Applied batches record their txn root's tree size and invalidate
        the ledger's read cache; any push's multi-sig (applied or not —
        the f redundant quorum copies still carry fresh anchors) is
        adopted once it VERIFIES against the pool keys."""
        self.stats["pushes"] += 1
        self.metrics.add_event(MetricsName.OBSERVER_PUSHES)
        if applied:
            self.read_plane.on_batch_committed(
                batch.ledger_id, batch.state_root, batch.txn_root)
        if batch.multi_sig:
            ms = self._verified_ms(batch.multi_sig)
            if ms is not None:
                self.read_plane.on_multi_sig(ms)

    def _verified_ms(self, raw) -> Optional[MultiSignature]:
        try:
            ms = MultiSignature.from_list(list(raw))
        except Exception:
            self.stats["ms_rejected"] += 1
            self.metrics.add_event(MetricsName.OBSERVER_MS_REJECTED)
            return None
        key = (ms.signature, ms.participants, ms.value)
        verdict = self._ms_memo.get(key)
        if verdict is None:
            verdict = ms.verify(self.bls_keys, n=self.n_nodes)
            if len(self._ms_memo) >= 1024:
                self._ms_memo.clear()
            self._ms_memo[key] = verdict
            if verdict:
                self.stats["ms_adopted"] += 1
                self.metrics.add_event(MetricsName.OBSERVER_MS_ADOPTED)
            else:
                self.stats["ms_rejected"] += 1
                self.metrics.add_event(MetricsName.OBSERVER_MS_REJECTED)
        return ms if verdict else None

    # --- read serving -----------------------------------------------------

    def serve(self, msg: dict):
        """One raw client message dict -> the reply message (Reply or
        RequestNack). THE one serving path both observer fronts share —
        the TCP listener (ObserverNode._serve_client) and the in-process
        twin (SimObserver) must never diverge on nack reasons or
        escalation semantics."""
        try:
            request = Request.from_dict(msg)
        except Exception:
            return RequestNack(identifier=str(msg.get("identifier")),
                               req_id=msg.get("reqId") or 0,
                               reason="malformed request")
        if not self.c.read_manager.is_query_type(request.txn_type):
            # an observer holds no pool connection to forward writes;
            # a client that wants consensus dials the pool
            return RequestNack(identifier=request.identifier,
                               req_id=request.req_id,
                               reason="observers serve reads only")
        out = self.answer_batch([request])[0]
        if isinstance(out, Exception):
            return RequestNack(identifier=request.identifier,
                               req_id=request.req_id,
                               reason=getattr(out, "reason",
                                              "malformed query"))
        return Reply(result=out)

    def answer_batch(self, requests: Sequence[Request]) -> list:
        """ReadPlane.answer_batch + the anchor-lag escalation: envelopes
        anchored beyond the lag bound are STRIPPED so the client fails
        over to a validator instead of receiving a stale proof."""
        outcomes = self.read_plane.answer_batch(requests)
        if self.anchor_lag_max is None:
            return outcomes
        now = self.now()
        for out in outcomes:
            if not isinstance(out, dict):
                continue
            env = out.get(READ_PROOF)
            if not isinstance(env, dict):
                continue
            try:
                # the one layout authority — never index the wire shape
                ts = MultiSignature.from_list(
                    list(env["multi_signature"])).value.timestamp
            except Exception:
                ts = None
            if ts is None or now - ts > self.anchor_lag_max:
                out.pop(READ_PROOF, None)
                self.stats["stale_suppressed"] += 1
                self.metrics.add_event(
                    MetricsName.OBSERVER_STALE_SUPPRESSED)
        return outcomes


class SimObserver:
    """In-process observer node for SimNetwork pools.

    Register with every validator over the client plane
    (OBSERVER_REGISTER), feed the resulting BatchCommitted pushes through
    ``deliver_push`` (f+1 content-identical quorum via NodeObserver — the
    multi-sig field is excluded from the quorum content), and serve
    verified reads through the node-shaped ``handle_client_message``.
    Build BEFORE traffic flows: pushes only cover live batches, and the
    in-process twin has no GET_TXN gap-fill transport of its own.
    """

    def __init__(self, name: str, genesis: dict, validator_names,
                 bls_keys: Mapping[str, str],
                 now: Callable[[], float], f: int = 1,
                 anchor_lag_max=FROM_CONFIG,
                 send: Optional[Callable] = None,
                 metrics: Optional[MetricsCollector] = None,
                 tracer=None, state_commitment: str = "mpt",
                 state_commitment_per_ledger: Optional[dict] = None,
                 verkle_width: Optional[int] = None):
        from plenum_tpu.node.bootstrap import NodeBootstrap
        from plenum_tpu.node.observer import NodeObserver
        self.name = name
        self.client_id = f"obs:{name}"
        self.validator_names = list(validator_names)
        # the observer's replicated state MUST use the validators' scheme
        # — its roots have to land on the multi-signed anchors, or every
        # read it serves degrades to proofless escalation
        components = NodeBootstrap(
            name, genesis_txns=genesis,
            state_commitment=state_commitment,
            state_commitment_per_ledger=state_commitment_per_ledger,
            verkle_width=verkle_width).build()
        self.c = components
        self.observer = NodeObserver(components, f=f)
        self.gate = ObserverReadGate(
            components, bls_keys, n_nodes=len(self.validator_names),
            now=now, anchor_lag_max=anchor_lag_max, metrics=metrics,
            tracer=tracer)
        self.sent: list = []            # (msg, client) when no send given
        self._send = send or (lambda msg, client: self.sent.append(
            (msg, client)))
        self.batches_applied = 0

    # --- replication ------------------------------------------------------

    def register(self, submit: Callable[[str, dict], None]) -> None:
        """submit(validator_name, msg_dict): subscribe this observer's
        client id to BatchCommitted pushes on every validator."""
        for v in self.validator_names:
            submit(v, {"op": "OBSERVER_REGISTER"})

    def deliver_push(self, batch, frm: str) -> bool:
        """One validator's push (BatchCommitted or its dict); -> applied."""
        if isinstance(batch, dict):
            try:
                batch = BatchCommitted.from_dict(batch)
            except Exception:
                return False
        if not isinstance(batch, BatchCommitted):
            return False
        applied = self.observer.process_batch(batch, frm=frm)
        if applied:
            self.batches_applied += 1
        self.gate.on_push(batch, applied)
        return applied

    # --- read serving (node-shaped client API) ----------------------------

    def handle_client_message(self, msg: dict, frm: str) -> None:
        self._send(self.gate.serve(msg), frm)


class ObserverFleet:
    """Region-scoped observer read fan-out with a SPAWN/RETIRE seam.

    Observers were statically placed (build once, before traffic); the
    fleet makes placement an actuator: ``spawn(region)`` boots a fresh
    ``SimObserver`` over one shard's validator set mid-run and registers
    it for pushes, ``retire(region)`` deregisters the newest one. The
    autopilot (control/autopilot.py) drives both from read-latency burn.

    The capacity model is deliberately explicit: each observer serves
    ``capacity`` reads per telemetry interval; reads beyond the region's
    pooled capacity count as read-SLO violations. ``service()`` (called
    from the fabric's prod loop) drains the validators' push outboxes
    into the member observers and rolls each region's (violations, total)
    ledger into the aggregator's ``("reads", region)`` burn tracker — so
    regional read burn rides the SAME multi-window burn-rate rule as the
    ingress/batch SLOs and is visible to ``sustained()``.
    """

    def __init__(self, fabric, regions=("r0",), sid: int = 0,
                 per_region: int = 1, capacity: float = 64.0, f: int = 1):
        self.fabric = fabric
        self.sid = sid
        self.capacity = float(capacity)
        self.f = f
        self.regions: dict[str, list[SimObserver]] = \
            {r: [] for r in regions}
        self._interval = getattr(fabric.config, "TELEMETRY_INTERVAL", 1.0)
        self._window_start = fabric.timer.get_current_time()
        self._served = {r: 0 for r in regions}
        self._viol = {r: 0 for r in regions}
        self._last_served = {r: 0 for r in regions}
        self._rr = {r: 0 for r in regions}
        self._retired_ids: set = set()
        self._n = 0
        self.stats = {"spawned": 0, "retired": 0, "reads": 0,
                      "violations": 0}
        for r in regions:
            for _ in range(per_region):
                self.spawn(r)

    def _shard(self):
        return self.fabric.shards[self.sid]

    # --- the spawn/retire seam --------------------------------------------

    def spawn(self, region: str) -> str:
        """Boot one more observer for `region` over the anchored shard's
        validators; it replicates from the NEXT committed batch on (the
        capacity model, not the replicated prefix, is what the read-burn
        policy scales)."""
        from plenum_tpu.tools.local_pool import pool_bls_keys
        shard = self._shard()
        self._n += 1
        name = f"{region}-obs{self._n}"
        obs = SimObserver(
            name, shard.genesis, shard.names, pool_bls_keys(shard.names),
            now=self.fabric.timer.get_current_time, f=self.f,
            anchor_lag_max=None)
        obs.register(lambda v, msg: shard.nodes[v]
                     .handle_client_message(msg, obs.client_id))
        self.regions[region].append(obs)
        self.stats["spawned"] += 1
        return name

    def retire(self, region: str) -> Optional[str]:
        """Deregister the newest observer of `region` (LIFO — the
        longest-lived replica keeps serving); never below one."""
        group = self.regions[region]
        if len(group) <= 1:
            return None
        obs = group.pop()
        self._retired_ids.add(obs.client_id)
        for node in self._shard().nodes.values():
            observable = getattr(node, "observable", None)
            if observable is not None:
                observable.remove_observer(obs.client_id)
        self.stats["retired"] += 1
        return obs.name

    def count(self, region: str) -> int:
        return len(self.regions[region])

    # --- the pump ----------------------------------------------------------

    def service(self) -> None:
        """Drain validator push outboxes into member observers, drop
        retired observers' in-flight pushes, roll the read-SLO window."""
        shard = self._shard()
        by_id = {obs.client_id: obs
                 for group in self.regions.values() for obs in group}
        for v in shard.names:
            msgs = shard.client_msgs[v]
            keep = []
            for m, cid in msgs:
                obs = by_id.get(cid)
                if obs is not None:
                    if isinstance(m, BatchCommitted):
                        obs.deliver_push(m, v)
                    # non-push traffic to an observer (OBSERVER_ACK)
                    # just drains
                elif cid not in self._retired_ids:
                    keep.append((m, cid))
            shard.client_msgs[v] = keep
        self._roll_window()

    def _roll_window(self) -> None:
        now = self.fabric.timer.get_current_time()
        if now - self._window_start < self._interval:
            return
        self._window_start = now
        agg = self.fabric.aggregator
        for region in self.regions:
            n = self._served[region]
            self._last_served[region] = n
            if n:
                agg.tracker("reads", region).note(
                    now, self._viol[region], n)
            self._served[region] = 0
            self._viol[region] = 0

    # --- read serving -------------------------------------------------------

    def serve_read(self, region: str, msg: dict):
        """One client read through the region's pool: round-robin over
        members, over-capacity reads ledger an SLO violation."""
        group = self.regions[region]
        i = self._rr[region] % len(group)
        self._rr[region] = i + 1
        self._served[region] += 1
        self.stats["reads"] += 1
        if self._served[region] > self.capacity * len(group):
            self._viol[region] += 1
            self.stats["violations"] += 1
        return group[i].gate.serve(msg)

    def scale_in_safe(self, region: str, margin: float = 0.5) -> bool:
        """True when the last completed window's demand fits in the
        region MINUS one observer with 1/margin headroom — the guard
        that keeps a retire from immediately re-triggering read burn."""
        group = self.regions[region]
        if len(group) <= 1:
            return False
        return self._last_served.get(region, 0) <= \
            margin * self.capacity * (len(group) - 1)

    def summary(self) -> dict:
        return {"regions": {r: len(g) for r, g in
                            sorted(self.regions.items())},
                **self.stats}
