"""Batched Ed25519 verification on device — THE north-star kernel (v3).

Reference behavior being replaced: stp_core/crypto/nacl_wrappers.py:62,212
(libsodium Ed25519, one scalar verify per call, n× per request across the
pool — SURVEY.md §3.2 "Ed25519 HOT SPOT"). Here the expensive part of
verification — the double-scalar multiplication [S]B + [h](-A) and the
compare against R — runs for a whole batch of signatures in ONE device
dispatch.

Split of labor (see plenum_tpu/crypto/ed25519.py for the host side):
  host:   decode/decompress points (pure-Python bigint sqrt, cached per
          verkey together with [2^64k](-A) for k=1..3 — the quarter points
          of the split window ladder, kept in extended coordinates so the
          chain needs NO host inversions),
          h = SHA512(R||A||M) mod L (hashlib, C speed),
          scalars -> window digit arrays
  device: windowed multi-scalar mult over GF(2^255-19) with 20x13-bit limbs
          in int32 lanes; affine comparison against R

Kernel shape (v3; v2 was int64 10x26-bit limbs with a 2-way split):
  [S]B      via an 8-bit fixed-base comb: 32 precomputed constant tables
            T[w][d] = d*256^w*B in affine "niels" form (y+x, y-x, 2d*x*y) —
            32 mixed additions, ZERO doublings. Table selection is a
            one-hot f32 matmul (tables are batch-constant), so it rides
            the MXU instead of burning VPU cycles.
  [h](-A)   split h = h0 + 2^64*h1 + 2^128*h2 + 2^192*h3 with the quarter
            points Qk = [2^64k](-A) cached per verkey on host; four
            16-entry tables are built on device (one batched build), then
            16 iterations of (4 doublings; 4 table additions; 2 comb
            additions). The 4-way split HALVES the doubling chain of the
            classic 2-way layout (64 vs 128 doublings).
  compare   one Fermat inversion (254 squarings as fori_loop pow2k blocks)
            -> affine (x, y) -> limb compare against the raw signature R.

Design notes (TPU-first):
- Field elements are [..., 20] int32 arrays, radix 2^13, SIGNED limbs:
  TPU VPUs have no native int64, so v2's 10x26-bit int64 limbs were
  emulated; 13-bit limbs keep every product sum inside int32. Signed
  carried form ([-2, 2^13+3] per limb) makes subtraction margin-free —
  f_sub is just carry(f - g).
- Squarings (pt_double, inversion) use a symmetric schoolbook (f_sqr,
  ~half the products of f_mul).
- No data-dependent control flow: digit-driven point selection is a
  one-hot contraction, constant trip counts, static shapes. The whole
  batch advances in lockstep; the batch axis maps onto VPU lanes and
  shards cleanly across a device mesh (see plenum_tpu/parallel/).
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# --- curve constants (RFC 8032) ------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = 37095705934669439343138083508754565189542113879843219016388785533085940283555
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
BY = 46316835694926478169428394003475163141307993866256225615783033603165251855960

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1
FOLD = 19 * 32          # 2^260 = 2^5 * 2^255 ≡ 19 * 32 (mod p)

WBITS = 4               # window width for the variable point A
N_WIN = 16              # windows per 64-bit quarter of h
N_QUARTERS = 4
QUARTER_SHIFT = 64      # h = sum_k 2^(64k) * h_k
CBITS = 8               # comb digit width for the fixed base B
N_COMB = 32             # comb positions for the 256-bit S

_I32 = jnp.int32


def int_to_limbs(x: int) -> np.ndarray:
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)],
                    dtype=np.int32)


def limbs_to_int(l) -> int:
    arr = np.asarray(l)
    return sum(int(v) << (RADIX * i) for i, v in enumerate(arr))


def _margin_limbs() -> np.ndarray:
    """40p as NLIMB limbs, each with a 2^13 floor — added before strict
    normalization so transiently-negative carried limbs (and values) lift
    to nonnegative without changing the residue mod p."""
    mult = 40
    k = [int((mult * P) >> (RADIX * i)) & MASK for i in range(NLIMB + 1)]
    k[NLIMB - 1] += k[NLIMB] << RADIX
    for i in range(NLIMB - 1):
        k[i] += 1 << RADIX
        k[i + 1] -= 1
    assert sum(v << (RADIX * i) for i, v in enumerate(k[:NLIMB])) == mult * P
    assert all((1 << RADIX) <= v < (1 << 16) for v in k[:NLIMB])
    return np.array(k[:NLIMB], dtype=np.int32)


_K_MARGIN = _margin_limbs()


# --- field ops ------------------------------------------------------------
#
# Bound discipline: "carried" means signed limbs in [-2, 2^13 + 3] (the
# output of _carry). f_mul/f_sqr REQUIRE carried inputs: products are then
# < 2^26.01, and a 20-term accumulation plus the fold contributions stays
# below 2^30.6 — inside int32. Unlike v2 there is NO lazy add/sub level:
# f_add/f_sub carry their output (3 cheap vector passes) so every operand
# everywhere is carried.

def _carry(c):
    """Three vectorized carry passes with the 2^260 -> FOLD wraparound.

    Pass math: c = (c & MASK) + shift(c >> 13), the top limb's carry
    folding to limb 0 via FOLD. Arithmetic >> floors, so transiently
    negative limbs are preserved exactly. |input| < 2^30.6 -> pass1
    < 2^27 (limb 0; others < 2^17.7) -> pass2 < 2^14.6 -> pass3 in
    [-2, 2^13 + 3] ("carried" form).
    """
    for _ in range(3):
        lo = c & MASK
        hi = c >> RADIX
        c = lo + jnp.concatenate(
            [hi[..., NLIMB - 1:] * FOLD, hi[..., :NLIMB - 1]], axis=-1)
    return c


def f_add(f, g):
    return _carry(f + g)


def f_sub(f, g):
    return _carry(f - g)


def _fold_coeffs(c: list):
    """Schoolbook coefficient list [2*NLIMB-1] -> NLIMB limbs via the
    2^260 ≡ FOLD wrap, splitting each high coefficient into 13-bit halves
    so the x608 products stay inside int32."""
    for k in range(2 * NLIMB - 2, NLIMB - 1, -1):
        lo = c[k] & MASK
        hi = c[k] >> RADIX
        c[k - NLIMB] = c[k - NLIMB] + lo * FOLD
        c[k - NLIMB + 1] = c[k - NLIMB + 1] + hi * FOLD
    return _carry(jnp.stack(c[:NLIMB], axis=-1))


def f_mul(f, g):
    # schoolbook convolution: 39 coefficients, 400 int32 products
    c = [jnp.zeros(jnp.broadcast_shapes(f.shape[:-1], g.shape[:-1]), _I32)
         for _ in range(2 * NLIMB - 1)]
    for i in range(NLIMB):
        fi = f[..., i]
        for j in range(NLIMB):
            c[i + j] = c[i + j] + fi * g[..., j]
    return _fold_coeffs(c)


def f_sqr(f):
    """Squaring: symmetric schoolbook, 210 products (~0.55x f_mul)."""
    f2 = f + f                      # limbs < 2^14.01, products < 2^27.02
    c = [jnp.zeros(f.shape[:-1], _I32) for _ in range(2 * NLIMB - 1)]
    for i in range(NLIMB):
        fi = f[..., i]
        c[2 * i] = c[2 * i] + fi * fi
        f2i = f2[..., i]
        for j in range(i + 1, NLIMB):
            c[i + j] = c[i + j] + f2i * f[..., j]
    return _fold_coeffs(c)


def _pow2k(z, k: int):
    """z^(2^k) as a k-iteration squaring loop."""
    return jax.lax.fori_loop(0, k, lambda i, v: f_sqr(v), z)


def _chain_250(z):
    """Shared prefix of the curve25519 exponentiation chains:
    -> (z^(2^250 - 1), z^11)."""
    z2 = f_sqr(z)                                     # 2
    z9 = f_mul(_pow2k(z2, 2), z)                      # 9
    z11 = f_mul(z9, z2)                               # 11
    z_5 = f_mul(f_sqr(z11), z9)                       # 2^5 - 1
    z_10 = f_mul(_pow2k(z_5, 5), z_5)                 # 2^10 - 1
    z_20 = f_mul(_pow2k(z_10, 10), z_10)              # 2^20 - 1
    z_40 = f_mul(_pow2k(z_20, 20), z_20)              # 2^40 - 1
    z_50 = f_mul(_pow2k(z_40, 10), z_10)              # 2^50 - 1
    z_100 = f_mul(_pow2k(z_50, 50), z_50)             # 2^100 - 1
    z_200 = f_mul(_pow2k(z_100, 100), z_100)          # 2^200 - 1
    return f_mul(_pow2k(z_200, 50), z_50), z11        # 2^250 - 1


def f_inv(z):
    """z^(p-2) (Fermat inversion) via the standard curve25519 addition
    chain: 254 squarings (grouped into pow2k fori_loops so the compiled
    graph stays small) + 11 multiplies.

    Needed to compress the recomputed R' on device (affine y = Y/Z), which
    is what lets verification compare raw signature bytes instead of paying
    a pure-Python modular sqrt per signature on host to decompress R.
    """
    z_250, z11 = _chain_250(z)
    return f_mul(_pow2k(z_250, 5), z11)               # 2^255 - 21 = p - 2


def f_pow_p58(z):
    """z^((p-5)/8) = z^(2^252 - 3) — the sqrt-candidate exponent of the
    RFC 8032 §5.1.3 decompression for p = 5 mod 8. (2^250-1)*4 + 1."""
    z_250, _ = _chain_250(z)
    return f_mul(_pow2k(z_250, 2), z)


def _carry_strict(c):
    """Fully normalized limbs in [0, 2^13) via _carry + two sequential
    signed borrow passes (arithmetic >> floors, so borrows propagate).
    Only used on the cold path (f_canon)."""
    c = _carry(c)
    for _ in range(2):
        out = []
        carry = 0
        for i in range(NLIMB):
            v = c[..., i] + carry
            carry = v >> RADIX
            out.append(v & MASK)
        c = jnp.stack(out, axis=-1).at[..., 0].add(carry * FOLD)
    return c


_TOP_BITS = 255 - (NLIMB - 1) * RADIX    # bits of limb 19 below 2^255 (= 8)


def f_canon(f):
    """Canonical form in [0, p).

    Carried limb form encodes values up to ~2^260 ≈ 32p (and transiently
    negative ones), so conditional subtraction alone is NOT enough: add a
    40p margin (limb floors restore positivity), fold the bits at and
    above 2^255 down with weight 19, then subtract p up to two times.
    """
    f = _carry_strict(f + jnp.asarray(_K_MARGIN))
    top = f[..., NLIMB - 1] >> _I32(_TOP_BITS)
    f = f.at[..., NLIMB - 1].set(
        f[..., NLIMB - 1] & _I32((1 << _TOP_BITS) - 1))
    f = f.at[..., 0].add(top * 19)
    f = _carry_strict(f)
    p_limbs = jnp.asarray(int_to_limbs(P))
    for _ in range(2):
        # compare f >= p lexicographically from the top limb
        ge = jnp.ones(f.shape[:-1], dtype=bool)
        gt = jnp.zeros(f.shape[:-1], dtype=bool)
        for i in range(NLIMB - 1, -1, -1):
            gt = gt | (ge & (f[..., i] > p_limbs[i]))
            ge = ge & (f[..., i] >= p_limbs[i])
        take = (gt | ge)
        f = _carry_strict(f - jnp.where(take[..., None], p_limbs, 0))
    return f


# --- point ops: extended twisted Edwards (X:Y:Z:T), a = -1 ----------------
# Identity is (0, 1, 1, 0). Every coordinate in and out is CARRIED.

def pt_add(p1, p2):
    """Unified addition (add-2008-hwcd-3): complete, handles identity & P+P."""
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = f_mul(f_sub(y1, x1), f_sub(y2, x2))
    b = f_mul(f_add(y1, x1), f_add(y2, x2))
    c = f_mul(f_mul(t1, t2), jnp.asarray(int_to_limbs(D2)))
    zz = f_mul(z1, z2)
    d = f_add(zz, zz)
    e = f_sub(b, a)
    f_ = f_sub(d, c)
    g = f_add(d, c)
    h = f_add(b, a)
    return (f_mul(e, f_), f_mul(g, h), f_mul(f_, g), f_mul(e, h))


def pt_add_t2d(p1, q):
    """Addition where the second operand carries a precomputed 2d*T
    coordinate: q = (X2, Y2, Z2, T2D2) — saves the d2 multiply."""
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2d2 = q
    a = f_mul(f_sub(y1, x1), f_sub(y2, x2))
    b = f_mul(f_add(y1, x1), f_add(y2, x2))
    c = f_mul(t1, t2d2)
    zz = f_mul(z1, z2)
    d = f_add(zz, zz)
    e = f_sub(b, a)
    f_ = f_sub(d, c)
    g = f_add(d, c)
    h = f_add(b, a)
    return (f_mul(e, f_), f_mul(g, h), f_mul(f_, g), f_mul(e, h))


def pt_madd(p1, ypx, ymx, t2d):
    """Mixed addition with an affine niels point (y+x, y-x, 2d*x*y),
    Z = 1 implied — the fixed-base comb form (7 multiplies).
    The niels identity is (1, 1, 0)."""
    x1, y1, z1, t1 = p1
    a = f_mul(f_sub(y1, x1), ymx)
    b = f_mul(f_add(y1, x1), ypx)
    c = f_mul(t1, t2d)
    d = f_add(z1, z1)
    e = f_sub(b, a)
    f_ = f_sub(d, c)
    g = f_add(d, c)
    h = f_add(b, a)
    return (f_mul(e, f_), f_mul(g, h), f_mul(f_, g), f_mul(e, h))


def pt_double(p1):
    """dbl-2008-hwcd for a = -1 (ref10 sign convention): 4 squarings +
    4 multiplies."""
    x1, y1, z1, _ = p1
    a = f_sqr(x1)
    b = f_sqr(y1)
    zz = f_sqr(z1)
    c = f_add(zz, zz)
    h = f_add(a, b)
    e = f_sub(h, f_sqr(f_add(x1, y1)))
    g = f_sub(a, b)
    f_ = f_add(c, g)
    return (f_mul(e, f_), f_mul(g, h), f_mul(f_, g), f_mul(e, h))


# --- host-side extended-coordinate helpers (Python ints) ------------------

def _ext_add_int(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = D2 * t1 * t2 % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _ext_dbl_int(p):
    x1, y1, z1, _ = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = a + b
    e = h - (x1 + y1) * (x1 + y1)
    g = a - b
    f = c + g
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def ext_quarters(pt: tuple[int, int]) -> np.ndarray:
    """Affine host point -> int32[4, 4, NLIMB]: the four quarter points
    [2^(64k)]pt for k = 0..3 in extended coordinates (X:Y:Z:T). The chain
    is 192 extended doublings with NO modular inversions (T is tracked
    through _ext_dbl_int), which keeps the per-new-verkey host cost low."""
    x, y = pt
    p = (x, y, 1, x * y % P)
    out = np.zeros((N_QUARTERS, 4, NLIMB), np.int32)
    for k in range(N_QUARTERS):
        for c in range(4):
            out[k, c] = int_to_limbs(p[c])
        if k != N_QUARTERS - 1:
            for _ in range(QUARTER_SHIFT):
                p = _ext_dbl_int(p)
    return out


# --- fixed-base comb table (host-built, one batch inversion) --------------

_B_COMB: np.ndarray | None = None   # float32[N_COMB, 256, 3*NLIMB]


def b_comb_table() -> np.ndarray:
    """32 position tables for the fixed base B: T[w][d] = d*256^w*B as
    affine niels rows (y+x, y-x, 2d*x*y), entry 0 the niels identity
    (1, 1, 0). Stored as float32 so selection is ONE one-hot matmul per
    position (values < 2^13 are exact in f32) riding the MXU."""
    global _B_COMB
    if _B_COMB is not None:
        return _B_COMB
    base = (BX, BY, 1, BX * BY % P)
    ext: list[list[tuple]] = []
    for w in range(N_COMB):
        row = [base]
        for _ in range(2, 256):
            row.append(_ext_add_int(row[-1], base))
        ext.append(row)
        if w != N_COMB - 1:
            for _ in range(CBITS):
                base = _ext_dbl_int(base)
    zs = [p[2] for row in ext for p in row]
    prefix = [1]
    for z in zs:
        prefix.append(prefix[-1] * z % P)
    inv_all = pow(prefix[-1], P - 2, P)
    zinv = [0] * len(zs)
    for i in range(len(zs) - 1, -1, -1):
        zinv[i] = prefix[i] * inv_all % P
        inv_all = inv_all * zs[i] % P
    tab = np.zeros((N_COMB, 256, 3, NLIMB), np.float32)
    for w in range(N_COMB):
        tab[w, 0, 0] = int_to_limbs(1)      # identity niels: (1, 1, 0)
        tab[w, 0, 1] = int_to_limbs(1)
        for d in range(1, 256):
            x, y, _, _ = ext[w][d - 1]
            zi = zinv[w * 255 + d - 1]
            xa, ya = x * zi % P, y * zi % P
            tab[w, d, 0] = int_to_limbs((ya + xa) % P)
            tab[w, d, 1] = int_to_limbs((ya - xa) % P)
            tab[w, d, 2] = int_to_limbs(D2 * xa * ya % P)
    _B_COMB = tab.reshape(N_COMB, 256, 3 * NLIMB)
    return _B_COMB


# --- the kernel -----------------------------------------------------------

def _build_a_tables(qx, qy, qz, qt):
    """16-entry window tables for all four quarters in one batched build.

    q* are [4*n, NLIMB] int32: the stacked quarter points (extended,
    PROJECTIVE — Z need not be 1, which is what lets the host skip
    inversions). Returns 4 arrays [16, 4*n, NLIMB] (x, y, z, t2d) —
    entry d = [d]q, entry 0 = identity.

    Built as a 7-step fori_loop (tab[2k] = dbl(tab[k]);
    tab[2k+1] = tab[2k] + q) so the compiled graph stays small.
    """
    m = qx.shape[0]
    ones = jnp.broadcast_to(jnp.asarray(int_to_limbs(1)), (m, NLIMB))
    tx = jnp.zeros((16, m, NLIMB), _I32).at[1].set(qx)
    ty = jnp.zeros((16, m, NLIMB), _I32).at[0].set(ones).at[1].set(qy)
    tz = jnp.zeros((16, m, NLIMB), _I32).at[0].set(ones).at[1].set(qz)
    tt = jnp.zeros((16, m, NLIMB), _I32).at[1].set(qt)
    q = (qx, qy, qz, qt)

    def body(k, tabs):
        pk = tuple(t[k] for t in tabs)
        dbl = pt_double(pk)
        odd = pt_add(dbl, q)
        k2 = 2 * k
        out = []
        for t, dv, ov in zip(tabs, dbl, odd):
            t = jax.lax.dynamic_update_index_in_dim(t, dv, k2, axis=0)
            t = jax.lax.dynamic_update_index_in_dim(t, ov, k2 + 1, axis=0)
            out.append(t)
        return tuple(out)

    tx, ty, tz, tt = jax.lax.fori_loop(1, 8, body, (tx, ty, tz, tt))
    t2d = f_mul(tt, jnp.asarray(int_to_limbs(D2)))     # one stacked multiply
    return tx, ty, tz, t2d


def stage_on(device, *arrays):
    """Commit staged host arrays to ONE chip of a multi-device pipeline.

    jax.jit executes where its (committed) inputs live, so pinning the
    staged payload is the whole per-lane sharding entry point: lane k's
    verifier stages onto devices[k] and the SAME compiled kernel runs
    there, one executable per device. `device=None` keeps today's
    uncommitted behavior (backend default device)."""
    import jax.numpy as jnp
    if device is None:
        return tuple(jnp.asarray(a) for a in arrays)
    return tuple(jax.device_put(a, device) for a in arrays)


@jax.jit
def verify_kernel_indexed(s_digits, h_digits, aq_unique, idx, ry, r_sign):
    """verify_kernel with the verkey-derived quarter-point rows DEDUPED:
    aq_unique is int32[U, 4, 4, NLIMB] (one row per distinct verkey in
    the batch) and idx int32[N] maps each signature to its row. The
    gather runs on device, so the host->device payload shrinks from
    640 B/signature to 640 B/distinct key + 4 B/signature — measured to
    matter because ~80% of a tunneled dispatch is link transfer and aq
    was 73% of the bytes (probes/tunnel_decomposition_r04.json)."""
    aq = jnp.take(aq_unique, idx, axis=0)
    return verify_kernel(s_digits, h_digits, aq, ry, r_sign)


# --- device-side verkey decompression (the compressed dispatch path) ------

_P_LIMBS = int_to_limbs(P)


def _bytes_to_bits(u8):
    """uint8[..., 32] -> int32[..., 256] little-endian bits."""
    b = u8.astype(_I32)
    bits = (b[..., :, None] >> jnp.arange(8, dtype=_I32)) & _I32(1)
    return bits.reshape(*u8.shape[:-1], 256)


def _bits_to_limbs(bits):
    """int32[..., 256] bits -> int32[..., NLIMB] limbs of the low 255 bits.
    One f32 matmul against the bit->limb weight matrix (weights < 2^13 and
    each limb sums <= 13 bits -> exact in f32); bit 255 has zero weight."""
    w = jnp.asarray(_BIT_TO_LIMB, jnp.float32)
    return jnp.matmul(bits.astype(jnp.float32), w,
                      precision=jax.lax.Precision.HIGHEST).astype(_I32)


def _ge_p(y):
    """Lexicographic y >= p over canonical-limbed y (non-canonical point
    encodings must be REJECTED, matching host _precheck / RFC 8032)."""
    p_limbs = jnp.asarray(_P_LIMBS)
    gt = jnp.zeros(y.shape[:-1], bool)
    eq = jnp.ones(y.shape[:-1], bool)
    for i in range(NLIMB - 1, -1, -1):
        gt = gt | (eq & (y[..., i] > p_limbs[i]))
        eq = eq & (y[..., i] == p_limbs[i])
    return gt | eq


@jax.jit
def decompress_kernel(keys_u8):
    """Batched on-device verkey decompression -> quarter points of -A.

    keys_u8: uint8[U, 32] raw compressed verkeys (32 B each — what the
    host actually has; replaces the 1280 B/key limb rows of the indexed
    dispatch, a 40x transfer cut where ~80% of a tunneled dispatch is
    link time). Returns ((qx, qy, qz, qt) each int32[4, U, NLIMB] — the
    quarter points [2^64k](-A) stacked quarter-major — plus valid bool[U]).

    Math is RFC 8032 §5.1.3 (p = 5 mod 8): x = uv^3 (uv^7)^((p-5)/8),
    corrected by sqrt(-1) when v x^2 = -u; rejects y >= p, off-curve
    points, and x = 0 with the sign bit set — exactly the host-side
    `decompress` (kept as the differential-test twin). The 192-doubling
    quarter chain that the host used to pay in pure-Python bigints per
    NEW verkey runs here too, batched over the deduped key table.
    """
    bits = _bytes_to_bits(keys_u8)                       # [U, 256]
    sign = bits[..., 255]
    y = _bits_to_limbs(bits)                             # [U, NLIMB]
    noncanon = _ge_p(y)
    u_ = keys_u8.shape[0]
    one = jnp.broadcast_to(jnp.asarray(int_to_limbs(1)), (u_, NLIMB))
    y2 = f_sqr(y)
    u = f_sub(y2, one)
    v = f_add(f_mul(y2, jnp.asarray(int_to_limbs(D))), one)
    v3 = f_mul(f_sqr(v), v)
    v7 = f_mul(f_sqr(v3), v)
    x = f_mul(f_mul(u, v3), f_pow_p58(f_mul(u, v7)))
    vxx = f_mul(v, f_sqr(x))
    ok1 = jnp.all(f_canon(f_sub(vxx, u)) == 0, axis=-1)   # v x^2 =  u
    ok2 = jnp.all(f_canon(f_add(vxx, u)) == 0, axis=-1)   # v x^2 = -u
    x = jnp.where(ok1[..., None], x,
                  f_mul(x, jnp.asarray(int_to_limbs(SQRT_M1))))
    on_curve = ok1 | ok2
    xc = f_canon(x)
    x_zero = jnp.all(xc == 0, axis=-1)
    neg_xc = f_canon(f_sub(jnp.asarray(_P_LIMBS), xc))
    flip = (xc[..., 0] & _I32(1)) != sign
    # A = (x flipped to the sign bit, y); the kernel wants -A = (-x, y)
    negx = jnp.where(flip[..., None], xc, neg_xc)
    valid = on_curve & ~noncanon & ~(x_zero & (sign == 1))
    p0 = (negx, y, one, f_mul(negx, y))

    def _dbl64(p):
        return jax.lax.fori_loop(
            0, QUARTER_SHIFT, lambda i, a: pt_double(a), p)

    p1 = _dbl64(p0)
    p2 = _dbl64(p1)
    p3 = _dbl64(p2)
    qx, qy, qz, qt = (jnp.stack([p0[c], p1[c], p2[c], p3[c]])
                      for c in range(4))
    return (qx, qy, qz, qt), valid


def unpack_scalars_kernel(s_u8, h_u8, r_u8):
    """Raw per-signature byte payloads -> the kernel's digit/limb arrays.

    s_u8: uint8[N, 32] little-endian S (host-checked < L) -> the 8-bit
          comb digits ARE the bytes.
    h_u8: uint8[N, 32] little-endian h = SHA512(R||A||M) mod L; bytes
          8q..8q+7 are quarter q, split into 16 nibble windows each.
    r_u8: uint8[N, 32] raw R encoding -> (y limbs, sign bit).
    Replaces 468 B/signature of host-staged int32 digit arrays with
    100 B (s + h + R + idx) and moves the unpacking onto the device.
    """
    n = s_u8.shape[0]
    s_digits = s_u8.astype(_I32).T                       # [32, N]
    hb = h_u8.astype(_I32).reshape(n, N_QUARTERS, 8)
    nib = jnp.stack([hb & _I32(0xF), hb >> _I32(4)], axis=-1)
    h_digits = jnp.transpose(nib.reshape(n, N_QUARTERS, N_WIN),
                             (2, 1, 0))                  # [16, 4, N]
    rbits = _bytes_to_bits(r_u8)
    ry = _bits_to_limbs(rbits)
    return s_digits, h_digits, ry, rbits[..., 255]


@jax.jit
def verify_kernel_bytes(s_u8, h_u8, keys_u8, idx, r_u8):
    """THE compressed dispatch: every payload in raw bytes, everything
    else computed on device.

    Host ships 32 B S + 32 B h + 32 B R + 4 B key index per signature
    and 32 B per DISTINCT verkey; the device decompresses the keys,
    builds the window tables ONCE PER KEY (the indexed path built them
    per signature: 4N rows -> 4U rows, an N/U compute cut on top of the
    transfer cut), gathers per-signature table banks, and runs the
    double-scalar ladder. Signatures under an invalid key verify False.
    """
    n = idx.shape[0]
    u_ = keys_u8.shape[0]
    s_digits, h_digits, ry, r_sign = unpack_scalars_kernel(s_u8, h_u8, r_u8)
    (qx, qy, qz, qt), valid = decompress_kernel(keys_u8)
    tx, ty, tz, t2d = _build_a_tables(
        qx.reshape(-1, NLIMB), qy.reshape(-1, NLIMB),
        qz.reshape(-1, NLIMB), qt.reshape(-1, NLIMB))
    tab = jnp.stack([tx, ty, tz, t2d])                   # [4c, 16, 4U, L]
    tab = tab.reshape(4, 16, N_QUARTERS, u_, NLIMB)
    tabf = jnp.transpose(tab, (2, 3, 1, 0, 4)).astype(jnp.float32)
    tabf = tabf.reshape(N_QUARTERS, u_, 16, 4 * NLIMB)   # [q, U, d, 4L]
    tabf = jnp.take(tabf, idx, axis=1)                   # [q, N, d, 4L]
    ok = _banks_and_ladder(s_digits, h_digits, tabf, ry, r_sign, n)
    return ok & jnp.take(valid, idx)


@jax.jit
def verify_kernel(s_digits, h_digits, aq, ry, r_sign):
    """Batched check compress([S]B + [h](-A)) == R-bytes.

    This is the ref10/OpenSSL verification shape: recompute
    R' = [S]B - [h]A, compress it, and compare against the first 32
    signature bytes — the host never decompresses R (no per-signature
    modular sqrt; non-canonical or off-curve R encodings simply fail the
    compare, the same verdict OpenSSL gives).

    s_digits: int32[N_COMB, N] little-endian 8-bit comb digits of S.
    h_digits: int32[N_WIN, N_QUARTERS, N] little-endian 4-bit windows of
              the 64-bit quarters of h.
    aq:       int32[N, 4, 4, NLIMB] extended quarter points [2^64k](-A)
              (host-prepped; projective — Z need not be 1).
    ry:       int32[N, NLIMB] limbs of the low 255 bits of the R encoding.
    r_sign:   int32[N] top bit of the R encoding (x parity).
    Returns bool[N].
    """
    if s_digits.dtype != jnp.int32:
        raise TypeError("verify_kernel v3 takes int32 inputs")
    n = aq.shape[0]
    # quarter-major stacking: row k*n + i is quarter k of signature i
    qrows = jnp.moveaxis(aq, 0, 1)                     # [4, N, 4, NLIMB]
    tx, ty, tz, t2d = _build_a_tables(
        qrows[:, :, 0].reshape(-1, NLIMB), qrows[:, :, 1].reshape(-1, NLIMB),
        qrows[:, :, 2].reshape(-1, NLIMB), qrows[:, :, 3].reshape(-1, NLIMB))
    tab = jnp.stack([tx, ty, tz, t2d])                 # [4c, 16, 4N, L]
    tab = tab.reshape(4, 16, N_QUARTERS, n, NLIMB)
    tabf = jnp.transpose(tab, (2, 3, 1, 0, 4)).astype(jnp.float32)
    tabf = tabf.reshape(N_QUARTERS, n, 16, 4 * NLIMB)  # [q, N, d, 4L]
    return _banks_and_ladder(s_digits, h_digits, tabf, ry, r_sign, n)


def _banks_and_ladder(s_digits, h_digits, tabf, ry, r_sign, n):
    """The shared back half of both kernels: select operand banks from
    per-signature window tables (tabf [q, N, 16, 4L] f32), run the
    split-window + comb ladder, compress, compare against R."""
    ones = jnp.broadcast_to(jnp.asarray(int_to_limbs(1)), (n, NLIMB))
    zeros = jnp.zeros((n, NLIMB), _I32)
    # ---- operand banks: table selections precomputed outside the loop
    # (they depend only on digits, never on the accumulator).
    # A-tables vary per signature -> f32 one-hot einsum on the VPU
    # (exact: carried limbs < 2^14 << 2^24). B comb tables are batch
    # constants -> one-hot MATMUL on the MXU.
    oh_h = (h_digits[..., None] == jnp.arange(16, dtype=_I32)
            ).astype(jnp.float32)                      # [W, q, N, 16]
    bank_a = jnp.einsum('wqnd,qndl->wqnl', oh_h, tabf,
                        precision=jax.lax.Precision.HIGHEST)
    bank_a = bank_a.astype(_I32)                       # [W, q, N, 4L]

    oh_s = (s_digits[..., None] == jnp.arange(256, dtype=_I32)
            ).astype(jnp.float32)                      # [N_COMB, N, 256]
    cb = jnp.asarray(b_comb_table())                   # [N_COMB, 256, 3L]
    bank_b = jnp.einsum('wnd,wdl->wnl', oh_s, cb,
                        precision=jax.lax.Precision.HIGHEST)
    bank_b = bank_b.astype(_I32)                       # [N_COMB, N, 3L]

    def win_body(i, acc):
        t = N_WIN - 1 - i                  # MSB-first windows
        acc = jax.lax.fori_loop(0, WBITS, lambda _, a: pt_double(a), acc)
        qsel = jax.lax.dynamic_index_in_dim(bank_a, t, 0, keepdims=False)

        def add_q(k, a):
            row = qsel[k].reshape(n, 4, NLIMB)
            return pt_add_t2d(a, (row[:, 0], row[:, 1], row[:, 2],
                                  row[:, 3]))

        return jax.lax.fori_loop(0, N_QUARTERS, add_q, acc)

    acc = jax.lax.fori_loop(0, N_WIN, win_body, (zeros, ones, ones, zeros))

    def add_comb(w, a):
        # comb entries carry ABSOLUTE scale 256^w, so they must be added
        # after the doubling ladder has finished (zero remaining doublings)
        row = jax.lax.dynamic_index_in_dim(
            bank_b, w, 0, keepdims=False).reshape(n, 3, NLIMB)
        return pt_madd(a, row[:, 0], row[:, 1], row[:, 2])

    acc = jax.lax.fori_loop(0, N_COMB, add_comb, acc)
    px, py, pz, _ = acc
    # compress on device: affine (x, y) via one shared inversion of Z
    # (complete Edwards formulas keep Z != 0 for all valid inputs)
    zinv = f_inv(pz)
    x_aff = f_canon(f_mul(px, zinv))
    y_aff = f_canon(f_mul(py, zinv))
    ok_y = jnp.all(y_aff == ry, axis=-1)
    ok_sign = (x_aff[..., 0] & _I32(1)) == r_sign
    return ok_y & ok_sign


# --- host-side affine helpers (shared with tests & tools) -----------------

def edwards_add(p1: tuple[int, int], p2: tuple[int, int]) -> tuple[int, int]:
    """Affine Edwards addition over Python ints (host-side, no deps)."""
    x1, y1 = p1
    x2, y2 = p2
    dd = D * x1 * x2 * y1 * y2 % P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + dd, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - dd + P, P - 2, P) % P
    return (x3, y3)


def edwards_mul(k: int, pt: tuple[int, int]) -> tuple[int, int]:
    acc = None
    add = pt
    while k:
        if k & 1:
            acc = add if acc is None else edwards_add(acc, add)
        add = edwards_add(add, add)
        k >>= 1
    return acc if acc is not None else (0, 1)


def compress(pt: tuple[int, int]) -> bytes:
    x, y = pt
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


_EXT_IDENTITY = (0, 1, 1, 0)


def ext_scalar_mul(k: int, pt: tuple[int, int]) -> tuple[int, int]:
    """[k]pt over Python ints in extended coordinates (one inversion at
    the end, vs one PER ADD in edwards_mul — ~30x faster; this is the
    ladder behind the no-deps sign/verify fallback)."""
    acc = _EXT_IDENTITY
    add = (pt[0], pt[1], 1, pt[0] * pt[1] % P)
    while k:
        if k & 1:
            acc = _ext_add_int(acc, add)
        add = _ext_dbl_int(add)
        k >>= 1
    return ext_to_affine(acc)


def ext_to_affine(p) -> tuple[int, int]:
    x, y, z, _t = p
    zi = pow(z, P - 2, P)
    return (x * zi % P, y * zi % P)


def ext_double_scalar_mul(s: int, p1: tuple[int, int],
                          h: int, p2: tuple[int, int]) -> tuple[int, int]:
    """[s]p1 + [h]p2 (Shamir interleave, MSB first) -> affine."""
    e1 = (p1[0], p1[1], 1, p1[0] * p1[1] % P)
    e2 = (p2[0], p2[1], 1, p2[0] * p2[1] % P)
    e12 = _ext_add_int(e1, e2)
    acc = _EXT_IDENTITY
    for i in range(max(s.bit_length(), h.bit_length()) - 1, -1, -1):
        acc = _ext_dbl_int(acc)
        b1, b2 = (s >> i) & 1, (h >> i) & 1
        if b1 and b2:
            acc = _ext_add_int(acc, e12)
        elif b1:
            acc = _ext_add_int(acc, e1)
        elif b2:
            acc = _ext_add_int(acc, e2)
    return ext_to_affine(acc)


def pure_python_verify(msg: bytes, sig: bytes, vk: bytes) -> bool:
    """RFC 8032 verification without external deps (ref10 semantics: the
    recomputed R' = [s]B - [h]A must BYTE-match the signature's R, no
    cofactor multiplication) — the cpu-backend fallback in environments
    without `cryptography`. Strict: rejects S >= L and non-canonical A."""
    import hashlib
    try:
        msg, sig, vk = bytes(msg), bytes(sig), bytes(vk)
    except Exception:
        return False
    if len(sig) != 64 or len(vk) != 32:
        return False
    A = decompress(vk)
    if A is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    h = int.from_bytes(hashlib.sha512(sig[:32] + vk + msg).digest(),
                       "little") % L
    neg_a = ((P - A[0]) % P, A[1])
    return compress(ext_double_scalar_mul(s, (BX, BY), h, neg_a)) == sig[:32]


def pure_python_sign(seed: bytes, msg: bytes) -> tuple[bytes, bytes]:
    """RFC 8032 signing without external deps -> (signature, verkey).
    For tools/tests/the graft entry in environments without `cryptography`."""
    import hashlib
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    prefix = h[32:]
    A = ext_scalar_mul(a, (BX, BY))
    vk = compress(A)
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    R = ext_scalar_mul(r, (BX, BY))
    r_enc = compress(R)
    k = int.from_bytes(hashlib.sha512(r_enc + vk + msg).digest(),
                       "little") % L
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little"), vk


def decompress(comp: bytes):
    """Verkey/R bytes -> affine point, or None if not on curve."""
    if len(comp) != 32:
        return None
    y = int.from_bytes(comp, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= P:
        return None
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # sqrt(u/v) for p = 5 mod 8 (RFC 8032 §5.1.3)
    x = u * pow(v, 3, P) % P * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    vxx = v * x * x % P
    if vxx == u % P:
        pass
    elif vxx == (P - u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y)


def scalar_windows(values: list[int], n_windows: int,
                   bits: int = WBITS) -> np.ndarray:
    """[n_windows, N] little-endian `bits`-wide digits (int32).

    Vectorized: one to_bytes per value (C speed), then numpy byte/nibble
    splitting — this runs on the per-dispatch host hot path."""
    nbytes = (n_windows * bits + 7) // 8
    raw = np.frombuffer(
        b"".join(v.to_bytes(nbytes, "little") for v in values),
        dtype=np.uint8).reshape(len(values), nbytes)
    if bits == 8:
        out = raw[:, :n_windows].astype(np.int32)
    elif bits == 4:
        nib = np.empty((len(values), 2 * nbytes), np.uint8)
        nib[:, 0::2] = raw & 0x0F
        nib[:, 1::2] = raw >> 4
        out = nib[:, :n_windows].astype(np.int32)
    else:
        raise ValueError(f"unsupported window width {bits}")
    return np.ascontiguousarray(out.T)


# bit b of a 255-bit little-endian value belongs to limb b//13, weight
# 2^(b%13); bit 255 is the sign bit (excluded)
_BIT_TO_LIMB = np.zeros((256, NLIMB), np.int32)
for _b in range(255):
    _BIT_TO_LIMB[_b, _b // RADIX] = 1 << (_b % RADIX)


def r_bytes_to_limbs(r_encodings: Sequence[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """Raw 32-byte R encodings -> (y limbs int32[N, NLIMB], sign int32[N]).
    Vectorized: unpack bits little-endian, matmul against the bit->limb
    weight matrix (per-dispatch host hot path)."""
    raw = np.frombuffer(b"".join(bytes(e) for e in r_encodings),
                        dtype=np.uint8).reshape(len(r_encodings), 32)
    bits = np.unpackbits(raw, axis=1, bitorder="little")   # [N, 256]
    ry = bits.astype(np.int32) @ _BIT_TO_LIMB
    return ry, bits[:, 255].astype(np.int32)


def points_to_limbs(points: list[tuple[int, int]]) -> tuple[np.ndarray, ...]:
    """Affine points -> (x, y, z=1, t=x*y) limb arrays int32[N, NLIMB]."""
    n = len(points)
    arrs = tuple(np.zeros((n, NLIMB), np.int32) for _ in range(4))
    for i, (x, y) in enumerate(points):
        arrs[0][i] = int_to_limbs(x)
        arrs[1][i] = int_to_limbs(y)
        arrs[2][i] = int_to_limbs(1)
        arrs[3][i] = int_to_limbs(x * y % P)
    return arrs
