"""Batched Ed25519 verification on device — THE north-star kernel.

Reference behavior being replaced: stp_core/crypto/nacl_wrappers.py:62,212
(libsodium Ed25519, one scalar verify per call, n× per request across the
pool — SURVEY.md §3.2 "Ed25519 HOT SPOT"). Here the expensive part of
verification — the double-scalar multiplication [S]B + [h](-A) and the compare
against R — runs for a whole batch of signatures in ONE device dispatch.

Split of labor (see plenum_tpu/crypto/ed25519.py for the host side):
  host:   decode/decompress points (pure-Python bigint sqrt, cached per verkey),
          h = SHA512(R||A||M) mod L (hashlib, C speed),
          scalars -> little-endian bit arrays
  device: Shamir double-scalar mult over GF(2^255-19) with 10x26-bit limbs in
          int64 lanes; 254 fori_loop iterations of (double; table-select; add);
          affine comparison against R

Design notes (TPU-first):
- Field elements are [..., 10] int64 arrays, radix 2^26, lazily carried.
  Products stay < 2^63: limbs enter mul below 2^28.5, the 19x fold multiplier
  for the 2^260 overflow is 608 = 19*2^5 applied to 26-bit splits.
- No data-dependent control flow: bit-driven point selection is an arithmetic
  blend (multiply by 0/1 masks), constant trip counts, static shapes.
- The whole batch advances in lockstep; the batch axis maps onto VPU lanes and
  shards cleanly across a device mesh (see plenum_tpu/parallel/).
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# The limb arithmetic REQUIRES 64-bit integers; without x64 JAX silently
# truncates to int32 and every verdict is garbage. This is a deliberate
# framework-wide setting (import side effect): all plenum_tpu kernels are
# explicit about dtypes, and a guard in verify_kernel rejects int32 inputs in
# case another library flips the flag back.
jax.config.update("jax_enable_x64", True)

# --- curve constants (RFC 8032) ------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = 37095705934669439343138083508754565189542113879843219016388785533085940283555
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
BY = 46316835694926478169428394003475163141307993866256225615783033603165251855960

NLIMB = 10
RADIX = 26
MASK = (1 << RADIX) - 1
FOLD = 19 * 32          # 2^260 = 2^5 * 2^255 ≡ 19 * 32 (mod p)
NBITS = 254             # scalars are < L < 2^253; one spare bit


def int_to_limbs(x: int) -> np.ndarray:
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)],
                    dtype=np.int64)


def limbs_to_int(l) -> int:
    l = np.asarray(l)
    return sum(int(l[i]) << (RADIX * i) for i in range(NLIMB)) % P


# K = 40p decomposed with every limb in [2^26, 2^27) so (f - g + K) is
# non-negative limbwise for carried f, g. (40p because the top limb must keep
# its 2^26 floor after borrowing: 40p >> 234 = 40*2^21 > 2^26.)
def _margin_limbs() -> np.ndarray:
    mult = 40
    k = [int((mult * P) >> (RADIX * i)) & MASK for i in range(11)]
    k[9] += k[10] << RADIX
    # borrow so limbs 0..8 get a +2^26 floor
    for i in range(9):
        k[i] += 1 << RADIX
        k[i + 1] -= 1
    assert sum(v << (RADIX * i) for i, v in enumerate(k[:10])) == mult * P
    assert all((1 << RADIX) <= v < (1 << 27) for v in k[:10])
    return np.array(k[:10], dtype=np.int64)


_K_SUB = _margin_limbs()


# --- field ops (all return carried form: limbs < 2^26 + eps) --------------

def _carry(c):
    """Two carry passes with the 2^260 -> FOLD wraparound."""
    for _ in range(2):
        out = []
        carry = 0
        for i in range(NLIMB):
            v = c[..., i] + carry
            carry = v >> RADIX
            out.append(v & MASK)
        c = jnp.stack(out, axis=-1)
        c = c.at[..., 0].add(carry * FOLD)
    # final top carry is tiny; one more cheap pass on limb 0->1
    v = c[..., 0]
    c = c.at[..., 0].set(v & MASK).at[..., 1].add(v >> RADIX)
    return c


def f_add(f, g):
    return _carry(f + g)


def f_sub(f, g):
    return _carry(f - g + jnp.asarray(_K_SUB))


def f_mul(f, g):
    # schoolbook convolution: 19 coefficients
    c = [jnp.zeros(f.shape[:-1], jnp.int64) for _ in range(2 * NLIMB - 1)]
    for i in range(NLIMB):
        fi = f[..., i]
        for j in range(NLIMB):
            c[i + j] = c[i + j] + fi * g[..., j]
    # fold coefficients 10..18 down with weight 2^260 ≡ FOLD, splitting into
    # 26-bit halves so the x608 products stay far below 2^63
    for k in range(2 * NLIMB - 2, NLIMB - 1, -1):
        lo = c[k] & MASK
        hi = c[k] >> RADIX
        c[k - NLIMB] = c[k - NLIMB] + lo * FOLD
        c[k - NLIMB + 1] = c[k - NLIMB + 1] + hi * FOLD
    return _carry(jnp.stack(c[:NLIMB], axis=-1))


# p-2 bits MSB-first; the exponent is fixed so the bit table is a constant
_P2_BITS = np.array([(P - 2) >> i & 1 for i in range(254, -1, -1)],
                    dtype=np.int64)


def f_inv(z):
    """z^(p-2) (Fermat inversion) as ONE square-and-multiply fori_loop.

    Needed to compress the recomputed R' on device (affine y = Y/Z), which is
    what lets verification compare raw signature bytes instead of paying a
    pure-Python modular sqrt per signature on host to decompress R.

    Deliberately a single 254-iteration loop with an arithmetic blend rather
    than the classic unrolled addition chain: the chain's ~265 inline f_mul
    calls made XLA:TPU compilation take minutes, while this shape (same as the
    main double-scalar loop) compiles fast and costs only ~25% more multiplies.
    """
    bits = jnp.asarray(_P2_BITS)

    def body(i, acc):
        sq = f_mul(acc, acc)
        mul = f_mul(sq, z)
        b = bits[i]
        return b * mul + (1 - b) * sq

    return jax.lax.fori_loop(1, 255, body, z)   # MSB handled by acc=z


def f_canon(f):
    """Canonical form in [0, p).

    Carried limb form encodes values up to 2^260 ≈ 32p, so conditional
    subtraction alone is NOT enough: first fold the bits at and above 2^255
    (limb 9 bits >= 21) down with weight 19, bringing the value below
    2^255 + 19*32 < 2p; then subtract p up to two times.
    """
    f = _carry(f)
    top = f[..., 9] >> jnp.int64(255 - 9 * RADIX)
    f = f.at[..., 9].set(f[..., 9] & jnp.int64((1 << (255 - 9 * RADIX)) - 1))
    f = f.at[..., 0].add(top * 19)
    f = _carry(f)
    p_limbs = jnp.asarray(int_to_limbs(P))
    for _ in range(2):
        # compare f >= p lexicographically from the top limb
        ge = jnp.ones(f.shape[:-1], dtype=bool)
        gt = jnp.zeros(f.shape[:-1], dtype=bool)
        for i in range(NLIMB - 1, -1, -1):
            gt = gt | (ge & (f[..., i] > p_limbs[i]))
            ge = ge & (f[..., i] >= p_limbs[i])
        take = (gt | ge)
        f = _carry(f - jnp.where(take[..., None], p_limbs, 0))
    return f


# --- point ops: extended twisted Edwards (X:Y:Z:T), a = -1 ----------------
# Identity is (0, 1, 1, 0).

def pt_add(p1, p2):
    """Unified addition (add-2008-hwcd-3): complete, handles identity & P+P."""
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = f_mul(f_sub(y1, x1), f_sub(y2, x2))
    b = f_mul(f_add(y1, x1), f_add(y2, x2))
    c = f_mul(f_mul(t1, t2), jnp.asarray(int_to_limbs(D2)))
    zz = f_mul(z1, z2)
    d = f_add(zz, zz)
    e = f_sub(b, a)
    f_ = f_sub(d, c)
    g = f_add(d, c)
    h = f_add(b, a)
    return (f_mul(e, f_), f_mul(g, h), f_mul(f_, g), f_mul(e, h))


def pt_double(p1):
    """dbl-2008-hwcd for a = -1 (ref10 sign convention)."""
    x1, y1, z1, _ = p1
    a = f_mul(x1, x1)
    b = f_mul(y1, y1)
    zz = f_mul(z1, z1)
    c = f_add(zz, zz)
    h = f_add(a, b)
    xy = f_add(x1, y1)
    e = f_sub(h, f_mul(xy, xy))
    g = f_sub(a, b)
    f_ = f_add(c, g)
    return (f_mul(e, f_), f_mul(g, h), f_mul(f_, g), f_mul(e, h))


def _blend(bit, p_true, p_false):
    """Per-lane select between two points; bit is int64[...] of 0/1."""
    m = bit[..., None]
    return tuple(m * t + (1 - m) * f for t, f in zip(p_true, p_false))


@jax.jit
def verify_kernel(s_bits, h_bits, ax, ay, az, at, ry, r_sign):
    """Batched check compress([S]B + [h]A') == R-bytes (A' = -A, host-prepped).

    This is the ref10/OpenSSL verification shape: recompute R' = [S]B - [h]A,
    compress it, and compare against the first 32 signature bytes — so the
    host never decompresses R (no per-signature modular sqrt; non-canonical
    or off-curve R encodings simply fail the compare, same verdict OpenSSL
    gives).

    s_bits/h_bits: int64[NBITS, N] little-endian scalar bits.
    ax..at: int64[N, 10] extended coords of A' (Z=1 from host, so T=X*Y).
    ry: int64[N, 10] limbs of the low 255 bits of the R encoding.
    r_sign: int64[N] top bit of the R encoding (x parity).
    Returns bool[N].
    """
    if s_bits.dtype != jnp.int64:
        raise TypeError("verify_kernel needs int64 inputs — jax x64 mode is off")
    n = ax.shape[0]
    ones = jnp.broadcast_to(jnp.asarray(int_to_limbs(1)), (n, NLIMB))
    zeros = jnp.zeros((n, NLIMB), jnp.int64)

    b_pt = tuple(jnp.broadcast_to(jnp.asarray(int_to_limbs(v)), (n, NLIMB))
                 for v in (BX, BY, 1, BX * BY % P))
    a_pt = (ax, ay, az, at)
    ba_pt = pt_add(b_pt, a_pt)
    o_pt = (zeros, ones, ones, zeros)

    def body(i, acc):
        t = NBITS - 1 - i
        bs = jax.lax.dynamic_index_in_dim(s_bits, t, axis=0, keepdims=False)
        bh = jax.lax.dynamic_index_in_dim(h_bits, t, axis=0, keepdims=False)
        acc = pt_double(acc)
        # select O / B / A' / B+A' by (bs, bh)
        q = _blend(bs * bh, ba_pt,
                   _blend(bs * (1 - bh), b_pt,
                          _blend((1 - bs) * bh, a_pt, o_pt)))
        return pt_add(acc, q)

    acc = jax.lax.fori_loop(0, NBITS, body, o_pt)
    px, py, pz, _ = acc
    # compress on device: affine (x, y) via one shared inversion of Z
    # (complete Edwards formulas keep Z != 0 for all valid inputs)
    zinv = f_inv(pz)
    x_aff = f_canon(f_mul(px, zinv))
    y_aff = f_canon(f_mul(py, zinv))
    ok_y = jnp.all(y_aff == ry, axis=-1)
    ok_sign = (x_aff[..., 0] & jnp.int64(1)) == r_sign
    return ok_y & ok_sign


# --- host-side helpers ----------------------------------------------------

def edwards_add(p1: tuple[int, int], p2: tuple[int, int]) -> tuple[int, int]:
    """Affine Edwards addition over Python ints (host-side, no deps)."""
    x1, y1 = p1
    x2, y2 = p2
    dd = D * x1 * x2 * y1 * y2 % P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + dd, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - dd + P, P - 2, P) % P
    return (x3, y3)


def edwards_mul(k: int, pt: tuple[int, int]) -> tuple[int, int]:
    acc = (0, 1)
    while k:
        if k & 1:
            acc = edwards_add(acc, pt)
        pt = edwards_add(pt, pt)
        k >>= 1
    return acc


def compress(pt: tuple[int, int]) -> bytes:
    x, y = pt
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def pure_python_sign(seed: bytes, msg: bytes) -> tuple[bytes, bytes]:
    """RFC 8032 signing with no external deps -> (sig64, verkey32).

    Slow (pure-int scalar mults); for benches/examples where the
    `cryptography` package may be absent, NOT for production signing.
    """
    import hashlib as _hl
    hd = _hl.sha512(seed).digest()
    a = int.from_bytes(hd[:32], "little")
    a = (a & ((1 << 254) - 8)) | (1 << 254)
    B = (BX, BY)
    vk = compress(edwards_mul(a, B))
    r = int.from_bytes(_hl.sha512(hd[32:] + msg).digest(), "little") % L
    r_c = compress(edwards_mul(r, B))
    h = int.from_bytes(_hl.sha512(r_c + vk + msg).digest(), "little") % L
    s = (r + h * a) % L
    return r_c + s.to_bytes(32, "little"), vk


def decompress(comp: bytes):
    """32-byte compressed Edwards point -> (x, y) ints, or None if invalid."""
    if len(comp) != 32:
        return None
    y = int.from_bytes(comp, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= P:
        return None
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # x = u/v ^ ((p+3)/8) candidate (RFC 8032 §5.1.3)
    x = (u * pow(v, 3, P)) * pow(u * pow(v, 7, P), (P - 5) // 8, P) % P
    if (v * x * x - u) % P != 0:
        x = x * SQRT_M1 % P
        if (v * x * x - u) % P != 0:
            return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y)


def scalar_bits(values: list[int]) -> np.ndarray:
    """[N] ints -> int64[NBITS, N] little-endian bits."""
    raw = b"".join(v.to_bytes(32, "little") for v in values)
    arr = np.frombuffer(raw, dtype=np.uint8).reshape(len(values), 32)
    bits = np.unpackbits(arr, axis=1, bitorder="little")
    return bits[:, :NBITS].T.astype(np.int64)


def r_bytes_to_limbs(r_encodings: Sequence[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """[N] 32-byte R encodings -> (ry int64[N, 10], sign int64[N]).

    Pure bit repacking (vectorized numpy) — no field math, no sqrt.
    """
    n = len(r_encodings)
    arr = np.frombuffer(b"".join(r_encodings), dtype=np.uint8).reshape(n, 32)
    bits = np.unpackbits(arr, axis=1, bitorder="little")        # [N, 256]
    sign = bits[:, 255].astype(np.int64)
    padded = np.concatenate(
        [bits[:, :255], np.zeros((n, NLIMB * RADIX - 255), np.uint8)], axis=1)
    weights = (1 << np.arange(RADIX, dtype=np.int64))
    ry = padded.reshape(n, NLIMB, RADIX).astype(np.int64) @ weights
    return ry, sign


def points_to_limbs(points: list[tuple[int, int]]) -> tuple[np.ndarray, ...]:
    """Affine points -> (X, Y, Z=1, T=XY) limb arrays [N, 10]."""
    n = len(points)
    xs = np.zeros((n, NLIMB), np.int64)
    ys = np.zeros((n, NLIMB), np.int64)
    ts = np.zeros((n, NLIMB), np.int64)
    for i, (x, y) in enumerate(points):
        xs[i] = int_to_limbs(x)
        ys[i] = int_to_limbs(y)
        ts[i] = int_to_limbs(x * y % P)
    ones = np.tile(int_to_limbs(1), (n, 1))
    return xs, ys, ones, ts
