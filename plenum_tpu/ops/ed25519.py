"""Batched Ed25519 verification on device — THE north-star kernel.

Reference behavior being replaced: stp_core/crypto/nacl_wrappers.py:62,212
(libsodium Ed25519, one scalar verify per call, n× per request across the
pool — SURVEY.md §3.2 "Ed25519 HOT SPOT"). Here the expensive part of
verification — the double-scalar multiplication [S]B + [h](-A) and the compare
against R — runs for a whole batch of signatures in ONE device dispatch.

Split of labor (see plenum_tpu/crypto/ed25519.py for the host side):
  host:   decode/decompress points (pure-Python bigint sqrt, cached per
          verkey, together with [2^128](-A) for the split window ladder),
          h = SHA512(R||A||M) mod L (hashlib, C speed),
          scalars -> 4-bit window digit arrays
  device: windowed multi-scalar mult over GF(2^255-19) with 10x26-bit limbs
          in int64 lanes; affine comparison against R

Kernel shape (v2 — windowed; the v1 shape was a 254-round 1-bit Shamir
ladder, ~2.5x more serial field multiplies):
  [S]B      via a 4-bit fixed-base comb: 64 precomputed constant tables
            T[w][d] = d*16^w*B in affine "niels" form (y+x, y-x, 2d*x*y) —
            contributes 64 mixed additions and ZERO doublings.
  [h](-A)   split h = h0 + 2^128*h1 with A2 = [2^128](-A) cached per verkey
            on host; two 16-entry tables are built on device (one batched
            build for both halves), then 32 iterations of
            (4 doublings; 2 table additions; 2 comb additions).
  compare   one Fermat inversion (straight-line 254-squaring addition chain,
            pow2k blocks as fori_loops) -> affine (x, y) -> byte compare
            against the raw signature R.

Design notes (TPU-first):
- Field elements are [..., 10] int64 arrays, radix 2^26, LAZILY carried:
  add/sub do not carry at all (sub adds a 40p margin to stay non-negative);
  only f_mul carries its output. Products stay < 2^63: limbs enter mul below
  2^28.5, the 19x fold multiplier for the 2^260 overflow is 608 = 19*2^5
  applied to 26-bit splits.
- No data-dependent control flow: digit-driven point selection is a one-hot
  contraction (einsum with a 0/1 mask), constant trip counts, static shapes.
- The whole batch advances in lockstep; the batch axis maps onto VPU lanes and
  shards cleanly across a device mesh (see plenum_tpu/parallel/).
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# The limb arithmetic REQUIRES 64-bit integers; without x64 JAX silently
# truncates to int32 and every verdict is garbage. This is a deliberate
# framework-wide setting (import side effect): all plenum_tpu kernels are
# explicit about dtypes, and a guard in verify_kernel rejects int32 inputs in
# case another library flips the flag back.
jax.config.update("jax_enable_x64", True)

# --- curve constants (RFC 8032) ------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = 37095705934669439343138083508754565189542113879843219016388785533085940283555
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)
BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
BY = 46316835694926478169428394003475163141307993866256225615783033603165251855960

NLIMB = 10
RADIX = 26
MASK = (1 << RADIX) - 1
FOLD = 19 * 32          # 2^260 = 2^5 * 2^255 ≡ 19 * 32 (mod p)

WBITS = 4               # window/comb digit width
N_COMB = 64             # comb positions for the 256-bit S
N_WIN = 32              # windows per 128-bit half of h
HALF_SHIFT = 128        # h = h0 + 2^HALF_SHIFT * h1


def int_to_limbs(x: int) -> np.ndarray:
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)],
                    dtype=np.int64)


def limbs_to_int(l) -> int:
    l = np.asarray(l)
    return sum(int(l[i]) << (RADIX * i) for i in range(NLIMB)) % P


# K = 40p decomposed with every limb in [2^26, 2^27) so (f - g + K) is
# non-negative limbwise for carried f, g. (40p because the top limb must keep
# its 2^26 floor after borrowing: 40p >> 234 = 40*2^21 > 2^26.)
def _margin_limbs() -> np.ndarray:
    mult = 40
    k = [int((mult * P) >> (RADIX * i)) & MASK for i in range(11)]
    k[9] += k[10] << RADIX
    # borrow so limbs 0..8 get a +2^26 floor
    for i in range(9):
        k[i] += 1 << RADIX
        k[i + 1] -= 1
    assert sum(v << (RADIX * i) for i, v in enumerate(k[:10])) == mult * P
    assert all((1 << RADIX) <= v < (1 << 27) for v in k[:10])
    return np.array(k[:10], dtype=np.int64)


_K_SUB = _margin_limbs()


# --- field ops ------------------------------------------------------------
#
# Bound discipline: "carried" means limbs < 2^26 + 1 (the output of _carry);
# add_nc/sub_nc outputs are < 2^28.3 limbwise when their inputs obey the
# rules in the point formulas below, which keeps every f_mul product sum
# under 2^60 — far inside int64.

def _carry(c):
    """Three vectorized carry passes with the 2^260 -> FOLD wraparound.

    Each pass is whole-limb-axis arithmetic (mask/shift/roll) — no per-limb
    Python loop, so a pass is ~6 XLA ops instead of ~30 and the serial
    dependency depth is 3, not 20. Pass math: c = (c & MASK) + shift(c >> 26)
    with the top limb's carry folding to limb 0 via FOLD. Handles transiently
    negative limbs (arithmetic >> floors, so value is preserved exactly).

    Bounds: |input limbs| < 2^60 -> pass1 < 2^43.4 -> pass2 < 2^27.4 ->
    pass3 in [-2, 2^26 + 2] ("carried" form; the stray +-2 is absorbed by
    the 40p margin in sub_nc and by f_canon's margin pre-add).
    """
    for _ in range(3):
        lo = c & MASK
        hi = c >> RADIX
        c = lo + jnp.concatenate(
            [hi[..., NLIMB - 1:] * FOLD, hi[..., :NLIMB - 1]], axis=-1)
    return c


def add_nc(f, g):
    """Lazy addition: no carry. Inputs must keep the sum below 2^28.3."""
    return f + g


def sub_nc(f, g):
    """Lazy subtraction: f - g + 40p, no carry. g must be CARRIED (the 40p
    margin limbs floor at 2^26, which dominates carried limbs only)."""
    return f - g + jnp.asarray(_K_SUB)


def f_add(f, g):
    return _carry(f + g)


def f_sub(f, g):
    return _carry(f - g + jnp.asarray(_K_SUB))


def f_mul(f, g):
    # schoolbook convolution: 19 coefficients
    c = [jnp.zeros(jnp.broadcast_shapes(f.shape[:-1], g.shape[:-1]), jnp.int64)
         for _ in range(2 * NLIMB - 1)]
    for i in range(NLIMB):
        fi = f[..., i]
        for j in range(NLIMB):
            c[i + j] = c[i + j] + fi * g[..., j]
    # fold coefficients 10..18 down with weight 2^260 ≡ FOLD, splitting into
    # 26-bit halves so the x608 products stay far below 2^63
    for k in range(2 * NLIMB - 2, NLIMB - 1, -1):
        lo = c[k] & MASK
        hi = c[k] >> RADIX
        c[k - NLIMB] = c[k - NLIMB] + lo * FOLD
        c[k - NLIMB + 1] = c[k - NLIMB + 1] + hi * FOLD
    return _carry(jnp.stack(c[:NLIMB], axis=-1))


def _pow2k(z, k: int):
    """z^(2^k) as a k-iteration squaring loop."""
    return jax.lax.fori_loop(0, k, lambda i, v: f_mul(v, v), z)


def f_inv(z):
    """z^(p-2) (Fermat inversion) via the standard curve25519 addition chain:
    254 squarings (grouped into pow2k fori_loops so the compiled graph stays
    small) + 11 multiplies — half the multiplies of a square-and-multiply
    ladder.

    Needed to compress the recomputed R' on device (affine y = Y/Z), which is
    what lets verification compare raw signature bytes instead of paying a
    pure-Python modular sqrt per signature on host to decompress R.
    """
    z2 = f_mul(z, z)                                  # 2
    z9 = f_mul(_pow2k(z2, 2), z)                      # 9
    z11 = f_mul(z9, z2)                               # 11
    z_5 = f_mul(f_mul(z11, z11), z9)                  # 2^5 - 1
    z_10 = f_mul(_pow2k(z_5, 5), z_5)                 # 2^10 - 1
    z_20 = f_mul(_pow2k(z_10, 10), z_10)              # 2^20 - 1
    z_40 = f_mul(_pow2k(z_20, 20), z_20)              # 2^40 - 1
    z_50 = f_mul(_pow2k(z_40, 10), z_10)              # 2^50 - 1
    z_100 = f_mul(_pow2k(z_50, 50), z_50)             # 2^100 - 1
    z_200 = f_mul(_pow2k(z_100, 100), z_100)          # 2^200 - 1
    z_250 = f_mul(_pow2k(z_200, 50), z_50)            # 2^250 - 1
    return f_mul(_pow2k(z_250, 5), z11)               # 2^255 - 21 = p - 2


def _carry_strict(c):
    """Fully normalized limbs in [0, 2^26) via _carry + two sequential
    signed borrow passes (arithmetic >> floors, so borrows propagate).
    Only used on the cold path (f_canon) — the sequential pass is 10 deep."""
    c = _carry(c)
    for _ in range(2):
        out = []
        carry = 0
        for i in range(NLIMB):
            v = c[..., i] + carry
            carry = v >> RADIX
            out.append(v & MASK)
        c = jnp.stack(out, axis=-1).at[..., 0].add(carry * FOLD)
    return c


def f_canon(f):
    """Canonical form in [0, p).

    Carried limb form encodes values up to 2^260 ≈ 32p, so conditional
    subtraction alone is NOT enough: first fold the bits at and above 2^255
    (limb 9 bits >= 21) down with weight 19, bringing the value below
    2^255 + 19*32 < 2p; then subtract p up to two times. The 40p margin
    added up front restores limbwise positivity (carried limbs can dip to
    -2) and is folded away with the other >= 2^255 content.
    """
    f = _carry_strict(f + jnp.asarray(_K_SUB))
    top = f[..., 9] >> jnp.int64(255 - 9 * RADIX)
    f = f.at[..., 9].set(f[..., 9] & jnp.int64((1 << (255 - 9 * RADIX)) - 1))
    f = f.at[..., 0].add(top * 19)
    f = _carry_strict(f)
    p_limbs = jnp.asarray(int_to_limbs(P))
    for _ in range(2):
        # compare f >= p lexicographically from the top limb
        ge = jnp.ones(f.shape[:-1], dtype=bool)
        gt = jnp.zeros(f.shape[:-1], dtype=bool)
        for i in range(NLIMB - 1, -1, -1):
            gt = gt | (ge & (f[..., i] > p_limbs[i]))
            ge = ge & (f[..., i] >= p_limbs[i])
        take = (gt | ge)
        f = _carry_strict(f - jnp.where(take[..., None], p_limbs, 0))
    return f


# --- point ops: extended twisted Edwards (X:Y:Z:T), a = -1 ----------------
# Identity is (0, 1, 1, 0).
#
# All formulas below take CARRIED coordinates (every coordinate a caller can
# pass is an f_mul output or a canonical host constant) and produce CARRIED
# coordinates; the lazy add_nc/sub_nc intermediates never feed another
# add/sub, only f_mul.

def pt_add(p1, p2):
    """Unified addition (add-2008-hwcd-3): complete, handles identity & P+P."""
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = f_mul(sub_nc(y1, x1), sub_nc(y2, x2))
    b = f_mul(add_nc(y1, x1), add_nc(y2, x2))
    c = f_mul(f_mul(t1, t2), jnp.asarray(int_to_limbs(D2)))
    zz = f_mul(z1, z2)
    d = add_nc(zz, zz)
    e = sub_nc(b, a)
    f_ = sub_nc(d, c)
    g = add_nc(d, c)
    h = add_nc(b, a)
    return (f_mul(e, f_), f_mul(g, h), f_mul(f_, g), f_mul(e, h))


def pt_add_t2d(p1, q):
    """Addition where the second operand carries a precomputed 2d*T
    coordinate: q = (X2, Y2, Z2, T2D2) — saves the d2 multiply (8M)."""
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2d2 = q
    a = f_mul(sub_nc(y1, x1), sub_nc(y2, x2))
    b = f_mul(add_nc(y1, x1), add_nc(y2, x2))
    c = f_mul(t1, t2d2)
    zz = f_mul(z1, z2)
    d = add_nc(zz, zz)
    e = sub_nc(b, a)
    f_ = sub_nc(d, c)
    g = add_nc(d, c)
    h = add_nc(b, a)
    return (f_mul(e, f_), f_mul(g, h), f_mul(f_, g), f_mul(e, h))


def pt_double(p1):
    """dbl-2008-hwcd for a = -1 (ref10 sign convention)."""
    x1, y1, z1, _ = p1
    a = f_mul(x1, x1)
    b = f_mul(y1, y1)
    zz = f_mul(z1, z1)
    c = add_nc(zz, zz)
    h = add_nc(a, b)
    xy = add_nc(x1, y1)
    e = sub_nc(h, f_mul(xy, xy))
    g = sub_nc(a, b)
    f_ = add_nc(c, g)
    return (f_mul(e, f_), f_mul(g, h), f_mul(f_, g), f_mul(e, h))


# --- fixed-base comb table (host-built, Python ints, one batch inversion) --

def _ext_add_int(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = D2 * t1 * t2 % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _ext_dbl_int(p):
    x1, y1, z1, _ = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = a + b
    e = h - (x1 + y1) * (x1 + y1)
    g = a - b
    f = c + g
    return (e * f % P, g * h % P, f * g % P, e * h % P)


_B_COMB: tuple | None = None     # (x, y, t2d) each np.int64[2, 16, NLIMB]


def b_comb_table() -> tuple:
    """Two 16-entry window tables for the fixed base:
    T[0][d] = d*B and T[1][d] = d*[2^128]B, as affine (x, y, 2d*x*y) rows
    (Z = 1 implied; entry 0 is the identity (0, 1, 0)).

    S is split like h: S = s_lo + 2^128*s_hi. At main-loop iteration i
    (processing window t = N_WIN-1-i) an added point gets scaled by the
    remaining doublings, i.e. by 16^t — so adding T[0][digit_t(s_lo)] and
    T[1][digit_t(s_hi)] contributes digit*16^t*B resp. digit*16^t*2^128*B,
    exactly the windowed decomposition of [S]B, with zero extra doublings.
    """
    global _B_COMB
    if _B_COMB is not None:
        return _B_COMB
    bases = [(BX, BY, 1, BX * BY % P)]
    b2 = bases[0]
    for _ in range(HALF_SHIFT):
        b2 = _ext_dbl_int(b2)
    bases.append(b2)
    ext: list[list[tuple]] = []
    for base in bases:
        row = [base]
        for _ in range(2, 16):
            row.append(_ext_add_int(row[-1], base))
        ext.append(row)
    # batch-invert all Z's (Montgomery's trick: one modular inversion total)
    zs = [p[2] for row in ext for p in row]
    prefix = [1]
    for z in zs:
        prefix.append(prefix[-1] * z % P)
    inv_all = pow(prefix[-1], P - 2, P)
    zinv = [0] * len(zs)
    for i in range(len(zs) - 1, -1, -1):
        zinv[i] = prefix[i] * inv_all % P
        inv_all = inv_all * zs[i] % P
    tx = np.zeros((2, 16, NLIMB), np.int64)
    ty = np.zeros((2, 16, NLIMB), np.int64)
    t2d = np.zeros((2, 16, NLIMB), np.int64)
    for w in range(2):
        ty[w, 0] = int_to_limbs(1)             # digit 0: identity (0, 1, 0)
        for d in range(1, 16):
            x, y, _, _ = ext[w][d - 1]
            zi = zinv[w * 15 + d - 1]
            xa, ya = x * zi % P, y * zi % P
            tx[w, d] = int_to_limbs(xa)
            ty[w, d] = int_to_limbs(ya)
            t2d[w, d] = int_to_limbs(D2 * xa * ya % P)
    _B_COMB = (tx, ty, t2d)
    return _B_COMB


def mul_pow2_affine(pt: tuple[int, int], k: int) -> tuple[int, int]:
    """[2^k] * pt for an affine host point — extended-coordinate doublings
    (no per-step inversion) + one final inversion. Used to cache
    A2 = [2^128](-A) per verkey."""
    x, y = pt
    p = (x, y, 1, x * y % P)
    for _ in range(k):
        p = _ext_dbl_int(p)
    zi = pow(p[2], P - 2, P)
    return (p[0] * zi % P, p[1] * zi % P)


# --- the kernel -----------------------------------------------------------

def _onehot(digits):
    """int64[..., T] digit array -> int64[..., T, 16] one-hot mask."""
    return (digits[..., None] == jnp.arange(16, dtype=digits.dtype)
            ).astype(jnp.int64)


def _build_a_tables(qx, qy, qt, n_half: int):
    """16-entry window tables for BOTH halves in one batched build.

    q* are [2*n_half, NLIMB]: rows [:n_half] = -A, rows [n_half:] = [2^128](-A)
    (affine, Z = 1, T = X*Y). Returns 4 arrays [16, 2*n_half, NLIMB]
    (x, y, z, t2d) — entry d = [d]q, entry 0 = identity.

    Built as a 7-step fori_loop (tab[2k] = dbl(tab[k]);
    tab[2k+1] = tab[2k] + q) so the compiled graph stays small.
    """
    m = qx.shape[0]
    ones = jnp.broadcast_to(jnp.asarray(int_to_limbs(1)), (m, NLIMB))
    zeros = jnp.zeros((m, NLIMB), jnp.int64)
    tx = jnp.zeros((16, m, NLIMB), jnp.int64).at[1].set(qx)
    ty = jnp.zeros((16, m, NLIMB), jnp.int64).at[0].set(ones).at[1].set(qy)
    tz = jnp.zeros((16, m, NLIMB), jnp.int64).at[0].set(ones).at[1].set(ones)
    tt = jnp.zeros((16, m, NLIMB), jnp.int64).at[1].set(qt)
    q = (qx, qy, ones, qt)

    def body(k, tabs):
        tx, ty, tz, tt = tabs
        pk = tuple(t[k] for t in tabs)
        dbl = pt_double(pk)
        odd = pt_add(dbl, q)
        k2 = 2 * k
        out = []
        for t, dv, ov in zip(tabs, dbl, odd):
            t = jax.lax.dynamic_update_index_in_dim(t, dv, k2, axis=0)
            t = jax.lax.dynamic_update_index_in_dim(t, ov, k2 + 1, axis=0)
            out.append(t)
        return tuple(out)

    tx, ty, tz, tt = jax.lax.fori_loop(1, 8, body, (tx, ty, tz, tt))
    t2d = f_mul(tt, jnp.asarray(int_to_limbs(D2)))     # one stacked multiply
    return tx, ty, tz, t2d


@jax.jit
def verify_kernel(s_digits, h0_digits, h1_digits,
                  a0x, a0y, a0t, a1x, a1y, a1t, ry, r_sign):
    """Batched check compress([S]B + [h0]A' + [h1]A2') == R-bytes.

    A' = -A and A2' = [2^128](-A) are host-prepped affine points (Z = 1,
    T = X*Y); h = h0 + 2^128*h1. This is the ref10/OpenSSL verification
    shape: recompute R' = [S]B - [h]A, compress it, and compare against the
    first 32 signature bytes — so the host never decompresses R (no
    per-signature modular sqrt; non-canonical or off-curve R encodings simply
    fail the compare, same verdict OpenSSL gives).

    s_digits:  int64[N_COMB, N] little-endian 4-bit comb digits of S.
    h0/h1_digits: int64[N_WIN, N] little-endian 4-bit windows of the halves.
    a0*/a1*:   int64[N, 10] affine limbs of A' resp. A2'.
    ry:        int64[N, 10] limbs of the low 255 bits of the R encoding.
    r_sign:    int64[N] top bit of the R encoding (x parity).
    Returns bool[N].
    """
    if s_digits.dtype != jnp.int64:
        raise TypeError("verify_kernel needs int64 inputs — jax x64 mode is off")
    n = a0x.shape[0]
    ones = jnp.broadcast_to(jnp.asarray(int_to_limbs(1)), (n, NLIMB))
    zeros = jnp.zeros((n, NLIMB), jnp.int64)

    tx, ty, tz, t2d = _build_a_tables(
        jnp.concatenate([a0x, a1x]), jnp.concatenate([a0y, a1y]),
        jnp.concatenate([a0t, a1t]), n)

    # ---- operand banks: ALL table selections precomputed outside the loop
    # (selections depend only on digits, never on the accumulator). This
    # keeps the fori_loop body tiny — compile time on the TPU backend is
    # dominated by loop-body HLO size, and int64 lowering multiplies it.
    # Selection is masked multiply + reduce (NOT einsum/dot_general: the TPU
    # X64 rewriter has no int64 dot_general lowering).

    def sel_a(tab, oh):
        """[16, N, 10] table x one-hot [W, N, 16] -> [W, N, 10]."""
        return jnp.sum(oh[:, :, :, None] * jnp.transpose(tab, (1, 0, 2))[None],
                       axis=2)

    def sel_b(cb, oh):
        """[16, 10] const table x one-hot [W, N, 16] -> [W, N, 10]."""
        return jnp.sum(oh[:, :, :, None] * cb[None, None], axis=2)

    oh_h0 = _onehot(h0_digits)             # [N_WIN, N, 16]
    oh_h1 = _onehot(h1_digits)
    oh_s0 = _onehot(s_digits[:N_WIN])      # low half of S's 64 digits
    oh_s1 = _onehot(s_digits[N_WIN:])
    cb_x, cb_y, cb_t2d = (jnp.asarray(t) for t in b_comb_table())

    ta0 = tuple(t[:, :n] for t in (tx, ty, tz, t2d))
    ta1 = tuple(t[:, n:] for t in (tx, ty, tz, t2d))
    ones_w = jnp.broadcast_to(jnp.asarray(int_to_limbs(1)),
                              (N_WIN, n, NLIMB))
    # per-window add operands, stacked [N_WIN, 4, N, 10] per coordinate:
    # j=0: [h0]win of A', j=1: [h1]win of A2', j=2/3: fixed-base windows
    # (S = s_lo + 2^128*s_hi; window t of each half aligns with the
    # remaining-doubling scale 16^t — see b_comb_table)
    bank = []
    for coord, a_idx, cb in ((0, 0, cb_x), (1, 1, cb_y), (2, 2, None),
                             (3, 3, cb_t2d)):
        j0 = sel_a(ta0[a_idx], oh_h0)
        j1 = sel_a(ta1[a_idx], oh_h1)
        if cb is None:                     # B entries are affine: Z = 1
            j2 = j3 = ones_w
        else:
            j2 = sel_b(cb[0], oh_s0)
            j3 = sel_b(cb[1], oh_s1)
        bank.append(jnp.stack([j0, j1, j2, j3], axis=1))
    ox, oy, oz, ot = bank                  # each [N_WIN, 4, N, 10]

    def win_body(i, acc):
        t = N_WIN - 1 - i                  # MSB-first windows
        acc = jax.lax.fori_loop(0, WBITS, lambda _, a: pt_double(a), acc)
        qx = jax.lax.dynamic_index_in_dim(ox, t, 0, keepdims=False)
        qy = jax.lax.dynamic_index_in_dim(oy, t, 0, keepdims=False)
        qz = jax.lax.dynamic_index_in_dim(oz, t, 0, keepdims=False)
        qt = jax.lax.dynamic_index_in_dim(ot, t, 0, keepdims=False)
        return jax.lax.fori_loop(
            0, 4, lambda j, a: pt_add_t2d(a, (qx[j], qy[j], qz[j], qt[j])),
            acc)

    acc = jax.lax.fori_loop(0, N_WIN, win_body, (zeros, ones, ones, zeros))
    px, py, pz, _ = acc
    # compress on device: affine (x, y) via one shared inversion of Z
    # (complete Edwards formulas keep Z != 0 for all valid inputs)
    zinv = f_inv(pz)
    x_aff = f_canon(f_mul(px, zinv))
    y_aff = f_canon(f_mul(py, zinv))
    ok_y = jnp.all(y_aff == ry, axis=-1)
    ok_sign = (x_aff[..., 0] & jnp.int64(1)) == r_sign
    return ok_y & ok_sign


# --- host-side helpers ----------------------------------------------------

def edwards_add(p1: tuple[int, int], p2: tuple[int, int]) -> tuple[int, int]:
    """Affine Edwards addition over Python ints (host-side, no deps)."""
    x1, y1 = p1
    x2, y2 = p2
    dd = D * x1 * x2 * y1 * y2 % P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + dd, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - dd + P, P - 2, P) % P
    return (x3, y3)


def edwards_mul(k: int, pt: tuple[int, int]) -> tuple[int, int]:
    acc = (0, 1)
    while k:
        if k & 1:
            acc = edwards_add(acc, pt)
        pt = edwards_add(pt, pt)
        k >>= 1
    return acc


def compress(pt: tuple[int, int]) -> bytes:
    x, y = pt
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def pure_python_sign(seed: bytes, msg: bytes) -> tuple[bytes, bytes]:
    """RFC 8032 signing with no external deps -> (sig64, verkey32).

    Slow (pure-int scalar mults); for benches/examples where the
    `cryptography` package may be absent, NOT for production signing.
    """
    import hashlib as _hl
    hd = _hl.sha512(seed).digest()
    a = int.from_bytes(hd[:32], "little")
    a = (a & ((1 << 254) - 8)) | (1 << 254)
    B = (BX, BY)
    vk = compress(edwards_mul(a, B))
    r = int.from_bytes(_hl.sha512(hd[32:] + msg).digest(), "little") % L
    r_c = compress(edwards_mul(r, B))
    h = int.from_bytes(_hl.sha512(r_c + vk + msg).digest(), "little") % L
    s = (r + h * a) % L
    return r_c + s.to_bytes(32, "little"), vk


def decompress(comp: bytes):
    """32-byte compressed Edwards point -> (x, y) ints, or None if invalid."""
    if len(comp) != 32:
        return None
    y = int.from_bytes(comp, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= P:
        return None
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # x = u/v ^ ((p+3)/8) candidate (RFC 8032 §5.1.3)
    x = (u * pow(v, 3, P)) * pow(u * pow(v, 7, P), (P - 5) // 8, P) % P
    if (v * x * x - u) % P != 0:
        x = x * SQRT_M1 % P
        if (v * x * x - u) % P != 0:
            return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y)


def scalar_windows(values: list[int], n_windows: int) -> np.ndarray:
    """[N] ints -> int64[n_windows, N] little-endian 4-bit digits."""
    nbytes = (n_windows * WBITS + 7) // 8
    raw = b"".join(v.to_bytes(nbytes, "little") for v in values)
    arr = np.frombuffer(raw, dtype=np.uint8).reshape(len(values), nbytes)
    bits = np.unpackbits(arr, axis=1, bitorder="little")
    weights = (1 << np.arange(WBITS, dtype=np.int64))
    digits = bits[:, :n_windows * WBITS].reshape(
        len(values), n_windows, WBITS).astype(np.int64) @ weights
    return digits.T.copy()


def r_bytes_to_limbs(r_encodings: Sequence[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """[N] 32-byte R encodings -> (ry int64[N, 10], sign int64[N]).

    Pure bit repacking (vectorized numpy) — no field math, no sqrt.
    """
    n = len(r_encodings)
    arr = np.frombuffer(b"".join(r_encodings), dtype=np.uint8).reshape(n, 32)
    bits = np.unpackbits(arr, axis=1, bitorder="little")        # [N, 256]
    sign = bits[:, 255].astype(np.int64)
    padded = np.concatenate(
        [bits[:, :255], np.zeros((n, NLIMB * RADIX - 255), np.uint8)], axis=1)
    weights = (1 << np.arange(RADIX, dtype=np.int64))
    ry = padded.reshape(n, NLIMB, RADIX).astype(np.int64) @ weights
    return ry, sign


def points_to_limbs(points: list[tuple[int, int]]) -> tuple[np.ndarray, ...]:
    """Affine points -> (X, Y, Z=1, T=XY) limb arrays [N, 10]."""
    n = len(points)
    xs = np.zeros((n, NLIMB), np.int64)
    ys = np.zeros((n, NLIMB), np.int64)
    ts = np.zeros((n, NLIMB), np.int64)
    for i, (x, y) in enumerate(points):
        xs[i] = int_to_limbs(x)
        ys[i] = int_to_limbs(y)
        ts[i] = int_to_limbs(x * y % P)
    ones = np.tile(int_to_limbs(1), (n, 1))
    return xs, ys, ones, ts
