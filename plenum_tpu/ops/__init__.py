"""Device kernels (the TPU plane) — shared JAX runtime configuration.

Importing any kernel module routes through here, which enables the JAX
persistent compilation cache: the framework's device programs are a handful
of FIXED shapes (one Ed25519 verify bucket per node, one SHA-256 Merkle
bucket, the sharded crypto plane), and on a tunneled TPU a single XLA
compile costs minutes. With the cache, only the first process ever pays it;
every later node/bench/test process deserializes the compiled executable in
seconds. Cache location override: PLENUM_TPU_JAX_CACHE (useful for CI).

The cache directory is scoped by a HOST FINGERPRINT (platform + CPU
feature flags): XLA:CPU cache entries are ahead-of-time compiled for the
build machine's exact feature set, and loading one on a different host
is at best a `cpu_aot_loader` machine-feature-mismatch warning and at
worst a SIGILL mid-verify (the MULTICHIP_r02..r05 failure — a cache
written on the fleet's AVX-512-richer build host crept into this
container). Scoping the path means a foreign host's entries are simply
never SEEN: the first run on a new machine pays a fresh JIT compile
instead of trusting an incompatible AOT blob. `aot_preflight()` is the
explicit check harnesses run to report which case they're in.
"""
from __future__ import annotations

import hashlib
import os
import platform

import jax


def host_fingerprint() -> str:
    """Stable per-machine fingerprint of the ISA surface XLA:CPU compiles
    against: platform tag + the sorted CPU feature flags. Two hosts with
    the same flags can safely share AOT cache entries; any flag drift
    (the SIGILL risk) changes the fingerprint and isolates the caches."""
    h = hashlib.sha256()
    h.update(platform.machine().encode())
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("flags", "Features")):
                    h.update(" ".join(sorted(line.split(":", 1)[1].split()))
                             .encode())
                    break
    except OSError:
        h.update(platform.processor().encode())
    return h.hexdigest()[:12]


_cache_root = os.environ.get(
    "PLENUM_TPU_JAX_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "plenum_tpu", "jax"))
_cache_dir = os.path.join(_cache_root, f"host-{host_fingerprint()}")


def aot_preflight() -> dict:
    """Report the persistent-cache compatibility story for this host:
    whether a foreign host's AOT entries exist alongside (the stale
    state that used to crash the MULTICHIP harness) and whether THIS
    host's scoped cache is already warm. Never raises; harnesses fold
    the dict into their provenance row."""
    out = {"fingerprint": host_fingerprint(), "cache_dir": _cache_dir,
           "warm_entries": 0, "foreign_hosts": 0, "legacy_entries": 0}
    try:
        if os.path.isdir(_cache_dir):
            out["warm_entries"] = sum(
                1 for f in os.listdir(_cache_dir) if f.endswith("-cache"))
        if os.path.isdir(_cache_root):
            for entry in os.listdir(_cache_root):
                path = os.path.join(_cache_root, entry)
                if entry.startswith("host-"):
                    if path != _cache_dir:
                        out["foreign_hosts"] += 1
                elif entry.endswith("-cache"):
                    # pre-scoping flat entries: provenance unknown, so
                    # they are never loaded (the scoped dir shadows them)
                    out["legacy_entries"] += 1
    except OSError:
        pass
    return out


try:  # pragma: no cover - depends on jax version/platform
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    # cache every program (default threshold skips small/fast compiles, but
    # on the tunneled backend even "fast" compiles cost seconds)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception:
    pass
