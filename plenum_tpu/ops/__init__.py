"""Device kernels (the TPU plane) — shared JAX runtime configuration.

Importing any kernel module routes through here, which enables the JAX
persistent compilation cache: the framework's device programs are a handful
of FIXED shapes (one Ed25519 verify bucket per node, one SHA-256 Merkle
bucket, the sharded crypto plane), and on a tunneled TPU a single XLA
compile costs minutes. With the cache, only the first process ever pays it;
every later node/bench/test process deserializes the compiled executable in
seconds. Cache location override: PLENUM_TPU_JAX_CACHE (useful for CI).
"""
from __future__ import annotations

import os

import jax

_cache_dir = os.environ.get(
    "PLENUM_TPU_JAX_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "plenum_tpu", "jax"))
try:  # pragma: no cover - depends on jax version/platform
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    # cache every program (default threshold skips small/fast compiles, but
    # on the tunneled backend even "fast" compiles cost seconds)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception:
    pass
