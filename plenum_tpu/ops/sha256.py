"""Vectorized SHA-256 on device (JAX/XLA), the TPU path for Merkle hashing.

Reference behavior being replaced: ledger/tree_hasher.py:4 — RFC-6962-style
hashing (leaf = SHA256(0x00 || data), interior = SHA256(0x01 || l || r)) done
one scalar hashlib call at a time. Here whole batches of messages are hashed in
one device dispatch: state lives as uint32 lanes of shape [N] so the 64-round
compression runs element-wise across the batch on the VPU (8x128 lanes), with
zero data-dependent control flow — the round structure is fully unrolled at
trace time.

All functions are shape-polymorphic in the batch axis N but static in block
count B; callers bucket variable-length messages by padded block count so XLA
compiles one program per bucket (SURVEY.md §7 "constant-shape padding").
"""
from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# --- constants (FIPS 180-4) ----------------------------------------------

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

_H0 = np.array([0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19], dtype=np.uint32)


def _rotr(x, n):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress(state, block):
    """One SHA-256 compression. state: uint32[N, 8]; block: uint32[N, 16].

    Schedule expansion and the 64 rounds run as fori_loops so the traced graph
    stays small (fast compiles); all lanes of the batch advance together, which
    is exactly the VPU-friendly layout.
    """
    n = block.shape[0]
    k_arr = jnp.asarray(_K)

    w_init = jnp.concatenate([block, jnp.zeros((n, 48), jnp.uint32)], axis=1)

    def sched(t, w):
        wt15 = jax.lax.dynamic_slice_in_dim(w, t - 15, 1, axis=1)[:, 0]
        wt2 = jax.lax.dynamic_slice_in_dim(w, t - 2, 1, axis=1)[:, 0]
        wt16 = jax.lax.dynamic_slice_in_dim(w, t - 16, 1, axis=1)[:, 0]
        wt7 = jax.lax.dynamic_slice_in_dim(w, t - 7, 1, axis=1)[:, 0]
        s0 = _rotr(wt15, 7) ^ _rotr(wt15, 18) ^ (wt15 >> jnp.uint32(3))
        s1 = _rotr(wt2, 17) ^ _rotr(wt2, 19) ^ (wt2 >> jnp.uint32(10))
        new = wt16 + s0 + wt7 + s1
        return jax.lax.dynamic_update_slice_in_dim(w, new[:, None], t, axis=1)

    w = jax.lax.fori_loop(16, 64, sched, w_init)

    def rounds(t, s):
        a, b, c, d, e, f, g, h = s
        wt = jax.lax.dynamic_slice_in_dim(w, t, 1, axis=1)[:, 0]
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + k_arr[t] + wt
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + S0 + maj, a, b, c, d + t1, e, f, g)

    s0 = tuple(state[:, i] for i in range(8))
    sN = jax.lax.fori_loop(0, 64, rounds, s0)
    return state + jnp.stack(sN, axis=1)


@jax.jit
def sha256_words(msgs: jax.Array) -> jax.Array:
    """SHA-256 over pre-padded messages.

    msgs: uint32[N, 16*B] — big-endian words of B already-padded 64-byte blocks.
    Returns uint32[N, 8] digests. NOTE: padding is part of the hash input, so B
    must be the standard (minimal) block count for each message.
    """
    n_words = msgs.shape[-1]
    assert n_words % 16 == 0, "messages must be padded to whole 64-byte blocks"
    state = jnp.broadcast_to(jnp.asarray(_H0), msgs.shape[:-1] + (8,))
    for blk in range(n_words // 16):
        state = _compress(state, msgs[..., blk * 16:(blk + 1) * 16])
    return state


def _interior_words(left: jax.Array, right: jax.Array) -> jax.Array:
    """Pack RFC-6962 interior-node messages entirely on device.

    left/right: uint32[N, 8] child digests. The message is
    0x01 || left(32B) || right(32B) || 0x80-pad || bitlen(520) = 2 blocks.
    The 1-byte prefix shifts every word by 8 bits, done with u32 shifts.
    """
    cat = jnp.concatenate([left, right], axis=-1)          # [N, 16]
    lo8 = (cat & jnp.uint32(0xFF)) << jnp.uint32(24)       # carry byte to next word
    hi24 = cat >> jnp.uint32(8)
    prev = jnp.concatenate(
        [jnp.full(cat.shape[:-1] + (1,), 0x01000000, jnp.uint32),
         lo8[..., :-1]], axis=-1)
    words = prev | hi24                                     # words 0..15
    w16 = lo8[..., -1:] | jnp.uint32(0x00800000)            # last byte + 0x80 pad
    zeros = jnp.zeros(cat.shape[:-1] + (14,), jnp.uint32)
    bitlen = jnp.full(cat.shape[:-1] + (1,), 65 * 8, jnp.uint32)
    return jnp.concatenate([words, w16, zeros, bitlen], axis=-1)  # [N, 32]


@jax.jit
def hash_interior(left: jax.Array, right: jax.Array) -> jax.Array:
    """Batched interior-node hash: uint32[N,8] x uint32[N,8] -> uint32[N,8]."""
    return sha256_words(_interior_words(left, right))


@jax.jit
def merkle_reduce_pow2(leaf_digests: jax.Array) -> jax.Array:
    """Root of a complete (power-of-two) subtree, fully on device.

    leaf_digests: uint32[N, 8] with N a power of two. log2(N) rounds of the
    batched interior hash; each round halves the batch.
    """
    h = leaf_digests
    while h.shape[0] > 1:
        h = hash_interior(h[0::2], h[1::2])
    return h[0]


@jax.jit
def merkle_wave(new0: jax.Array, bounds: jax.Array,
                offs: jax.Array) -> tuple:
    """ALL interior levels of one append wave in ONE device program —
    the MTU-style fused tree path (PAPERS.md "MTU: The Multifunction Tree
    Unit"): no host hop between levels, the level-l parents feed level
    l+1 inside the same XLA program.

    new0:   uint32[N, 8]  — the wave's new level-0 digests, N a power of
            two (host pads; lanes past the real count compute garbage the
            host discards — valid lanes never read padded ones, because
            the pairing is element-wise on a contiguous valid prefix).
    bounds: uint32[L, 8]  — per level, the OLD left-boundary node the
            wave's first new node pairs with when the level's first new
            index is odd (an append wave is a contiguous suffix, so at
            most ONE old node joins the pairing per level). L = log2(N).
    offs:   int32[L]      — 1 when that level uses its boundary, else 0.
            Traced VALUES, not shapes: one compiled program per N serves
            every base alignment (a per-parity shape would recompile on
            every append offset).

    Returns a tuple of uint32[N/2, 8], uint32[N/4, 8], ... uint32[1, 8]:
    each level's parent digests; the host slices each level's valid
    prefix (it knows the real counts) and stores them.
    """
    outs = []
    cur = new0
    level = 0
    while cur.shape[0] >= 2:
        cap = cur.shape[0]
        inp = jnp.concatenate([bounds[level][None, :], cur], axis=0)
        # off=1: pairing starts AT the boundary (slot 0); off=0: skip it.
        start = (1 - offs[level]).astype(jnp.int32)
        shifted = jax.lax.dynamic_slice(inp, (start, jnp.int32(0)),
                                        (cap, 8))
        parents = hash_interior(shifted[0::2], shifted[1::2])
        outs.append(parents)
        cur = parents
        level += 1
    return tuple(outs)


# --- host-side packing helpers -------------------------------------------

def pad_to_words(data: bytes) -> np.ndarray:
    """Standard SHA-256 padding; returns uint32 big-endian words (1-D)."""
    length = len(data)
    padded = bytearray(data)
    padded.append(0x80)
    while len(padded) % 64 != 56:
        padded.append(0)
    padded += (length * 8).to_bytes(8, "big")
    return np.frombuffer(bytes(padded), dtype=">u4").astype(np.uint32)


def n_blocks_for(length: int) -> int:
    """Standard (minimal) SHA-256 block count for a message of `length` bytes."""
    return (length + 9 + 63) // 64


def digests_to_bytes(digests) -> list[bytes]:
    """uint32[N, 8] -> list of 32-byte digests."""
    arr = np.asarray(digests).astype(">u4")
    return [arr[i].tobytes() for i in range(arr.shape[0])]


def bytes_to_digests(hashes: Sequence[bytes]) -> np.ndarray:
    """list of 32-byte digests -> uint32[N, 8]."""
    return np.frombuffer(b"".join(hashes), dtype=">u4").astype(np.uint32).reshape(len(hashes), 8)


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def sha256_batch(msgs: Sequence[bytes], prefix: bytes = b"",
                 device=None) -> list[bytes]:
    """Hash a batch of byte strings on device.

    Messages are bucketed by their standard block count (padding is part of the
    hash, so block count can't be fudged); within a bucket the batch axis is
    padded to a power of two so XLA compiles O(log N) programs per bucket size,
    not one per batch size.

    `device` commits the staged words to one chip (the multi-device
    pipeline's per-lane sharding entry point — jit executes where its
    committed inputs live); None keeps the backend default.
    """
    if not msgs:
        return []
    from plenum_tpu.ops.ed25519 import stage_on
    buckets: dict[int, list[int]] = {}
    for i, m in enumerate(msgs):
        buckets.setdefault(n_blocks_for(len(prefix) + len(m)), []).append(i)
    out: list[bytes] = [b""] * len(msgs)
    for nb, idxs in buckets.items():
        n_pad = _pow2_at_least(len(idxs))
        words = np.zeros((n_pad, nb * 16), dtype=np.uint32)
        for j, i in enumerate(idxs):
            words[j] = pad_to_words(prefix + msgs[i])
        dig = digests_to_bytes(sha256_words(*stage_on(device, words)))
        for j, i in enumerate(idxs):
            out[i] = dig[j]
    return out
