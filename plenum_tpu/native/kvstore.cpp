// Native log-structured KV engine — the framework's LevelDB/RocksDB slot.
//
// Reference behavior being replaced: storage/kv_store_leveldb.py:14 /
// kv_store_rocksdb.py:15 (durable KV backends behind the KeyValueStorage
// ABC). Design is bitcask-shaped rather than an LSM: one append-only data
// file, an in-memory index of key -> (offset, length) built by replaying
// the log at open, CRC-checked records, torn-tail tolerance, and offline
// compaction that rewrites only live records. That matches this
// framework's access pattern (ledger logs and caches: point lookups,
// ordered scans of modest key sets, append-heavy writes) without the
// read-amplification machinery an LSM needs.
//
// Record format (little-endian):
//   u32 crc32(payload) | u8 op | u32 klen | u32 vlen | key | value
// op: 0 = PUT, 1 = DEL. A record with a bad CRC or truncated payload ends
// the replay (torn tail: everything before it stays durable).
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <unistd.h>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <map>
#include <string>
#include <vector>

namespace {

uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
    if (crc_init_done) return;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[i] = c;
    }
    crc_init_done = true;
}

uint32_t crc32(const uint8_t* data, size_t n, uint32_t seed = 0) {
    crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
    for (size_t i = 0; i < n; i++)
        c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

struct Entry {
    uint64_t offset;   // of the value bytes inside the data file
    uint32_t vlen;
};

struct Store {
    std::string path;
    FILE* fh = nullptr;     // append handle
    FILE* rf = nullptr;     // persistent read handle (reopened on compact)
    // std::map: ordered iteration comes free, which the Python ABC's
    // (start, end) iterator contract needs
    std::map<std::string, Entry> index;
    uint64_t live_bytes = 0;    // payload bytes reachable from the index
    uint64_t total_bytes = 0;   // file size (garbage ratio = 1 - live/total)
    bool batching = false;      // kvn_begin_batch: defer fflush to batch end
    bool dirty = false;         // unflushed appends pending
};

constexpr size_t HDR = 4 + 1 + 4 + 4;

bool read_exact(FILE* f, uint8_t* buf, size_t n) {
    return fread(buf, 1, n, f) == n;
}

uint32_t rd32(const uint8_t* p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

void wr32(uint8_t* p, uint32_t v) {
    p[0] = v & 0xFF; p[1] = (v >> 8) & 0xFF;
    p[2] = (v >> 16) & 0xFF; p[3] = (v >> 24) & 0xFF;
}

// Replays the log; returns false only on I/O errors opening the file.
bool replay(Store* s) {
    FILE* f = fopen(s->path.c_str(), "rb");
    if (!f) return true;                 // fresh store
    std::vector<uint8_t> payload;
    uint8_t hdr[HDR];
    uint64_t off = 0;
    while (true) {
        if (!read_exact(f, hdr, HDR)) break;            // clean EOF / torn
        uint32_t crc = rd32(hdr);
        uint8_t op = hdr[4];
        uint32_t klen = rd32(hdr + 5), vlen = rd32(hdr + 9);
        if (op > 1 || klen > (1u << 28) || vlen > (1u << 30)) break;
        payload.resize((size_t)klen + vlen);
        if (!read_exact(f, payload.data(), payload.size())) break;  // torn
        uint32_t want = crc32(hdr + 4, HDR - 4);
        want = crc32(payload.data(), payload.size(), want);
        if (want != crc) break;                          // corrupt: stop
        std::string key((const char*)payload.data(), klen);
        if (op == 0) {
            auto it = s->index.find(key);
            if (it != s->index.end())
                s->live_bytes -= it->second.vlen;
            s->index[key] = Entry{off + HDR + klen, vlen};
            s->live_bytes += vlen;
        } else {
            auto it = s->index.find(key);
            if (it != s->index.end()) {
                s->live_bytes -= it->second.vlen;
                s->index.erase(it);
            }
        }
        off += HDR + klen + vlen;
    }
    fclose(f);
    s->total_bytes = off;
    // truncate any torn tail so future appends start at a clean boundary
    FILE* t = fopen(s->path.c_str(), "rb+");
    if (t) {
        fseek(t, 0, SEEK_END);
        if ((uint64_t)ftell(t) > off) {
            fflush(t);
            if (ftruncate(fileno(t), (off_t)off) != 0) { /* keep going */ }
        }
        fclose(t);
    }
    return true;
}

int append_record(Store* s, uint8_t op, const uint8_t* key, uint32_t klen,
                  const uint8_t* val, uint32_t vlen) {
    uint8_t hdr[HDR];
    hdr[4] = op;
    wr32(hdr + 5, klen);
    wr32(hdr + 9, vlen);
    uint32_t crc = crc32(hdr + 4, HDR - 4);
    crc = crc32(key, klen, crc);
    if (vlen) crc = crc32(val, vlen, crc);
    wr32(hdr, crc);
    if (fwrite(hdr, 1, HDR, s->fh) != HDR) return -1;
    if (fwrite(key, 1, klen, s->fh) != klen) return -1;
    if (vlen && fwrite(val, 1, vlen, s->fh) != vlen) return -1;
    if (s->batching) {
        s->dirty = true;        // ONE fflush at kvn_end_batch
    } else if (fflush(s->fh) != 0) {
        return -1;
    }
    s->total_bytes += HDR + klen + vlen;
    return 0;
}

}  // namespace

extern "C" {

void* kvn_open(const char* path) {
    Store* s = new Store();
    s->path = path;
    if (!replay(s)) { delete s; return nullptr; }
    s->fh = fopen(path, "ab");
    if (!s->fh) { delete s; return nullptr; }
    s->rf = fopen(path, "rb");   // may be null for a fresh file; lazily opened
    return s;
}

int kvn_put(void* h, const uint8_t* key, uint32_t klen,
            const uint8_t* val, uint32_t vlen) {
    Store* s = (Store*)h;
    uint64_t voff = s->total_bytes + HDR + klen;
    if (append_record(s, 0, key, klen, val, vlen) != 0) return -1;
    std::string k((const char*)key, klen);
    auto it = s->index.find(k);
    if (it != s->index.end()) s->live_bytes -= it->second.vlen;
    s->index[k] = Entry{voff, vlen};
    s->live_bytes += vlen;
    return 0;
}

long kvn_get(void* h, const uint8_t* key, uint32_t klen,
             uint8_t* buf, uint32_t buflen) {
    Store* s = (Store*)h;
    auto it = s->index.find(std::string((const char*)key, klen));
    if (it == s->index.end()) return -1;
    if (it->second.vlen > buflen) return (long)it->second.vlen;  // need more
    if (!s->rf) s->rf = fopen(s->path.c_str(), "rb");
    if (!s->rf) return -2;
    // reads go through the persistent handle; appends fflush, so the
    // separate read FD always sees committed records. Inside a batch the
    // flush is deferred — a read of a just-batched key forces it, keeping
    // read-your-writes exact (commit batches are write-mostly, so this
    // rarely fires).
    if (s->dirty && fflush(s->fh) == 0) s->dirty = false;
    // (a failed lazy flush keeps dirty set so kvn_end_batch retries and
    // surfaces the error; the fread below then short-reads and returns -2)
    fseek(s->rf, (long)it->second.offset, SEEK_SET);
    size_t got = fread(buf, 1, it->second.vlen, s->rf);
    return got == it->second.vlen ? (long)it->second.vlen : -2;
}

long kvn_get_len(void* h, const uint8_t* key, uint32_t klen) {
    Store* s = (Store*)h;
    auto it = s->index.find(std::string((const char*)key, klen));
    return it == s->index.end() ? -1 : (long)it->second.vlen;
}

int kvn_del(void* h, const uint8_t* key, uint32_t klen) {
    Store* s = (Store*)h;
    std::string k((const char*)key, klen);
    auto it = s->index.find(k);
    if (it == s->index.end()) return 0;
    if (append_record(s, 1, key, klen, nullptr, 0) != 0) return -1;
    s->live_bytes -= it->second.vlen;
    s->index.erase(it);
    return 0;
}

long kvn_count(void* h) {
    return (long)((Store*)h)->index.size();
}

// Group-commit mode: appends between begin/end skip the per-record fflush;
// end issues ONE flush for the whole batch. Records keep their individual
// CRC framing, so a crash mid-batch replays a valid prefix (torn-tail
// tolerance unchanged) — the grouping is a durability-latency win, not an
// atomicity guarantee (the pure-python log's _BATCH record provides that).
int kvn_begin_batch(void* h) {
    ((Store*)h)->batching = true;
    return 0;
}

int kvn_end_batch(void* h) {
    Store* s = (Store*)h;
    s->batching = false;
    if (s->dirty) {
        s->dirty = false;
        if (fflush(s->fh) != 0) return -1;
    }
    return 0;
}

// Sorted keys in [start, end) serialized as repeated (u32 klen | key).
// start/end may be empty (slen/elen 0) for open bounds. Caller frees with
// kvn_free. *out_n gets the total byte length.
uint8_t* kvn_iter_keys(void* h, const uint8_t* start, uint32_t slen,
                       const uint8_t* end, uint32_t elen, uint64_t* out_n) {
    Store* s = (Store*)h;
    std::string lo((const char*)start, slen), hi((const char*)end, elen);
    size_t total = 0;
    auto it = slen ? s->index.lower_bound(lo) : s->index.begin();
    for (auto j = it; j != s->index.end(); ++j) {
        if (elen && j->first > hi) break;   // inclusive end: KvMemory semantics
        total += 4 + j->first.size();
    }
    uint8_t* out = (uint8_t*)malloc(total ? total : 1);
    if (!out) { *out_n = 0; return nullptr; }
    uint8_t* p = out;
    for (auto j = it; j != s->index.end(); ++j) {
        if (elen && j->first > hi) break;   // inclusive end: KvMemory semantics
        wr32(p, (uint32_t)j->first.size());
        p += 4;
        memcpy(p, j->first.data(), j->first.size());
        p += j->first.size();
    }
    *out_n = total;
    return out;
}

void kvn_free(uint8_t* p) { free(p); }

// Rewrite only live records; returns 0 on success. Safe crash-wise: writes
// to path.compact then renames over the original.
int kvn_compact(void* h) {
    Store* s = (Store*)h;
    std::string tmp = s->path + ".compact";
    FILE* out = fopen(tmp.c_str(), "wb");
    if (!out) return -1;
    FILE* in = fopen(s->path.c_str(), "rb");
    if (!in) { fclose(out); return -1; }
    Store fresh;
    fresh.path = tmp;
    fresh.fh = out;
    std::vector<uint8_t> val;
    for (auto& kv : s->index) {
        val.resize(kv.second.vlen);
        fseek(in, (long)kv.second.offset, SEEK_SET);
        if (!read_exact(in, val.data(), val.size())) {
            fclose(in); fclose(out); remove(tmp.c_str()); return -2;
        }
        if (append_record(&fresh, 0, (const uint8_t*)kv.first.data(),
                          (uint32_t)kv.first.size(), val.data(),
                          (uint32_t)val.size()) != 0) {
            fclose(in); fclose(out); remove(tmp.c_str()); return -3;
        }
    }
    fclose(in);
    fclose(out);
    fclose(s->fh);
    s->fh = nullptr;
    if (s->rf) { fclose(s->rf); s->rf = nullptr; }
    if (rename(tmp.c_str(), s->path.c_str()) != 0) {
        // failed rename: the original file is intact — restore the append
        // handle so the store stays usable (a null fh would segfault puts)
        s->fh = fopen(s->path.c_str(), "ab");
        return s->fh ? -4 : -5;
    }
    // reopen + rebuild offsets (cheap: sizes known, but replay is simplest)
    s->index.clear();
    s->live_bytes = s->total_bytes = 0;
    replay(s);
    s->fh = fopen(s->path.c_str(), "ab");
    s->rf = fopen(s->path.c_str(), "rb");
    return s->fh ? 0 : -5;
}

double kvn_garbage_ratio(void* h) {
    Store* s = (Store*)h;
    if (s->total_bytes == 0) return 0.0;
    return 1.0 - (double)s->live_bytes / (double)s->total_bytes;
}

void kvn_close(void* h) {
    Store* s = (Store*)h;
    if (s->fh) fclose(s->fh);
    if (s->rf) fclose(s->rf);
    delete s;
}

}  // extern "C"
