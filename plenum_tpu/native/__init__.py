"""Native (C++) acceleration for the crypto hot paths.

The reference leans on native libraries for exactly these ops (Rust Ursa for
BLS BN254, libsodium for Ed25519 — SURVEY.md §2.1); here the equivalents are
in-tree C++ compiled on first use with the system toolchain and loaded via
ctypes (no pybind11 in this environment). Everything degrades gracefully: if
the toolchain is missing or the build fails, callers fall back to the pure-
Python twins (which stay authoritative for differential testing).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))


def _cache_dir() -> str:
    """User-owned 0700 build cache — NEVER the world-writable temp dir: the
    source is public and the artifact name predictable, so a shared /tmp path
    would let any local user pre-plant a malicious .so for us to dlopen."""
    base = os.environ.get("XDG_CACHE_HOME") or \
        os.path.join(os.path.expanduser("~"), ".cache")
    path = os.path.join(base, "plenum_tpu")
    os.makedirs(path, mode=0o700, exist_ok=True)
    os.chmod(path, 0o700)
    return path


def _build(src_name: str, tag: str) -> Optional[ctypes.CDLL]:
    src = os.path.join(_DIR, src_name)
    try:
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        so_path = os.path.join(_cache_dir(), f"{tag}_{digest}.so")
        if not os.path.exists(so_path):
            tmp = so_path + f".build-{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                 "-std=c++17", "-o", tmp, src],
                check=True, capture_output=True, timeout=300)
            os.replace(tmp, so_path)      # atomic: concurrent builds race safely
        return ctypes.CDLL(so_path)
    except Exception:
        return None


_bn254 = _build("bn254.cpp", "bn254")

if _bn254 is not None:
    _u8p = ctypes.POINTER(ctypes.c_uint8)
    _bn254.pc_pairing_check.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                        ctypes.c_int]
    _bn254.pc_pairing_check.restype = ctypes.c_int
    for fn in (_bn254.pc_g1_mul, _bn254.pc_g2_mul,
               _bn254.pc_g1_add, _bn254.pc_g2_add):
        fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
        fn.restype = ctypes.c_int
    _bn254.pc_g2_in_subgroup.argtypes = [ctypes.c_char_p]
    _bn254.pc_g2_in_subgroup.restype = ctypes.c_int
    # differential-test surface
    _bn254.pc_miller.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                 ctypes.c_char_p]
    _bn254.pc_miller.restype = ctypes.c_int
    _bn254.pc_final_exp.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    _bn254.pc_final_exp.restype = ctypes.c_int

bn254_lib: Optional[ctypes.CDLL] = _bn254


def have_native_bn254() -> bool:
    return bn254_lib is not None
