// BN254 (alt_bn128) pairing arithmetic in C++ — the native-speed twin of
// plenum_tpu/crypto/bn254.py (same tower layout, same wire encodings), built
// because a BLS pairing check sits on the 3PC hot path: one aggregate check
// per ordered batch per node. Pure-Python bigint pairing costs ~74 ms; this
// library does it in single-digit milliseconds. Plays the role the Rust Ursa
// native library plays for the reference
// (crypto/bls/indy_crypto/bls_crypto_indy_crypto.py:6-10).
//
// Field arithmetic: 4x64-bit Montgomery (CIOS). Tower: Fq2 = Fq[i]/(i^2+1),
// Fq6 = Fq2[v]/(v^3 - (9+i)), Fq12 = Fq6[w]/(w^2 - v). Groups affine with
// Fermat inversion. Optimal-Ate Miller loop; easy+hard final exponentiation
// (plain square-and-multiply over (p^4-p^2+1)/r, matching the Python twin so
// the two implementations are differential-testable bit for bit).
//
// Exposed via a C ABI for ctypes (no pybind11 in this environment). All point
// encodings are big-endian bytes: Fp = 32B, G1 = x||y (64B, all-zero =
// infinity), G2 = x0||x1||y0||y1 (128B, all-zero = infinity) — identical to
// the Python g1_to_bytes / g2_to_bytes layout.

#include <cstdint>
#include <cstring>
#include <mutex>

typedef uint64_t u64;
typedef __uint128_t u128;

// ---------------------------------------------------------------- base field

struct Fp { u64 v[4]; };

static const u64 PL[4] = {0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                          0xb85045b68181585dULL, 0x30644e72e131a029ULL};
static const u64 NP = 0x87d20782e4866389ULL;          // -P^-1 mod 2^64
static const Fp R2 = {{0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                       0x47ab1eff0a417ff6ULL, 0x06d89f71cab8351fULL}};
static const Fp FP_ONE_M = {{0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                             0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL}};
static const Fp FP_ZERO = {{0, 0, 0, 0}};
// group order r (for scalar reduction / subgroup checks), NOT a field element
static const u64 RL[4] = {0x43e1f593f0000001ULL, 0x2833e84879b97091ULL,
                          0xb85045b68181585dULL, 0x30644e72e131a029ULL};

static inline bool fp_is_zero(const Fp &a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static inline bool fp_eq(const Fp &a, const Fp &b) {
    return a.v[0] == b.v[0] && a.v[1] == b.v[1] &&
           a.v[2] == b.v[2] && a.v[3] == b.v[3];
}

static inline int cmp4(const u64 *a, const u64 *b) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static inline void sub4(u64 *r, const u64 *a, const u64 *b) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a[i] - b[i] - (u64)borrow;
        r[i] = (u64)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static inline void fp_add(Fp &r, const Fp &a, const Fp &b) {
    u128 carry = 0;
    u64 t[4];
    for (int i = 0; i < 4; i++) {
        u128 s = (u128)a.v[i] + b.v[i] + (u64)carry;
        t[i] = (u64)s;
        carry = s >> 64;
    }
    if (carry || cmp4(t, PL) >= 0) sub4(r.v, t, PL);
    else memcpy(r.v, t, sizeof t);
}

static inline void fp_sub(Fp &r, const Fp &a, const Fp &b) {
    if (cmp4(a.v, b.v) >= 0) { sub4(r.v, a.v, b.v); return; }
    u64 t[4];
    sub4(t, b.v, a.v);          // b - a
    sub4(r.v, PL, t);           // P - (b - a)
}

static inline void fp_neg(Fp &r, const Fp &a) {
    if (fp_is_zero(a)) { r = a; return; }
    sub4(r.v, PL, a.v);
}

// Montgomery CIOS multiply: r = a*b*R^-1 mod P
static void fp_mul(Fp &r, const Fp &a, const Fp &b) {
    u64 t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {
        u128 c = 0;
        for (int j = 0; j < 4; j++) {
            u128 s = (u128)t[j] + (u128)a.v[i] * b.v[j] + (u64)c;
            t[j] = (u64)s;
            c = s >> 64;
        }
        u128 s = (u128)t[4] + (u64)c;
        t[4] = (u64)s;
        t[5] = (u64)(s >> 64);

        u64 m = t[0] * NP;
        c = ((u128)m * PL[0] + t[0]) >> 64;
        for (int j = 1; j < 4; j++) {
            u128 s2 = (u128)t[j] + (u128)m * PL[j] + (u64)c;
            t[j - 1] = (u64)s2;
            c = s2 >> 64;
        }
        u128 s3 = (u128)t[4] + (u64)c;
        t[3] = (u64)s3;
        t[4] = t[5] + (u64)(s3 >> 64);
    }
    if (t[4] || cmp4(t, PL) >= 0) sub4(r.v, t, PL);
    else memcpy(r.v, t, 4 * sizeof(u64));
}

static inline void fp_sqr(Fp &r, const Fp &a) { fp_mul(r, a, a); }

static void fp_pow(Fp &r, const Fp &a, const u64 *e, int nlimbs) {
    Fp out = FP_ONE_M, base = a;
    for (int i = 0; i < nlimbs; i++) {
        u64 w = e[i];
        for (int bit = 0; bit < 64; bit++) {
            if (w & 1) fp_mul(out, out, base);
            fp_sqr(base, base);
            w >>= 1;
        }
    }
    r = out;
}

static inline bool is_zero4(const u64 *a) {
    return (a[0] | a[1] | a[2] | a[3]) == 0;
}

static inline bool is_one4(const u64 *a) {
    return a[0] == 1 && (a[1] | a[2] | a[3]) == 0;
}

static inline void shr1_4(u64 *a) {
    a[0] = (a[0] >> 1) | (a[1] << 63);
    a[1] = (a[1] >> 1) | (a[2] << 63);
    a[2] = (a[2] >> 1) | (a[3] << 63);
    a[3] >>= 1;
}

// halve x mod p: x/2 if even, else (x+p)/2 (tracking the 257th bit)
static inline void half_mod(u64 *x) {
    if (x[0] & 1) {
        u128 carry = 0;
        for (int i = 0; i < 4; i++) {
            u128 s = (u128)x[i] + PL[i] + (u64)carry;
            x[i] = (u64)s;
            carry = s >> 64;
        }
        shr1_4(x);
        if (carry) x[3] |= 0x8000000000000000ULL;
    } else {
        shr1_4(x);
    }
}

static inline void sub_mod(u64 *r, const u64 *a, const u64 *b) {
    if (cmp4(a, b) >= 0) { sub4(r, a, b); return; }
    u64 t[4];
    sub4(t, b, a);
    sub4(r, PL, t);
}

// Binary extended GCD inversion — ~15x cheaper than Fermat and it sits under
// every affine group-law step and line evaluation.
static void fp_inv(Fp &r, const Fp &a) {
    if (fp_is_zero(a)) { r = a; return; }
    u64 u[4], v[4], x1[4], x2[4];
    memcpy(u, a.v, sizeof u);       // value of a_mont = a*R; inverted directly,
    memcpy(v, PL, sizeof v);        // then re-scaled by R2 twice below
    x1[0] = 1; x1[1] = x1[2] = x1[3] = 0;
    memset(x2, 0, sizeof x2);
    while (!is_one4(u) && !is_one4(v)) {
        while (!(u[0] & 1)) { shr1_4(u); half_mod(x1); }
        while (!(v[0] & 1)) { shr1_4(v); half_mod(x2); }
        if (cmp4(u, v) >= 0) {
            sub4(u, u, v);
            sub_mod(x1, x1, x2);
        } else {
            sub4(v, v, u);
            sub_mod(x2, x2, x1);
        }
    }
    Fp x;
    memcpy(x.v, is_one4(u) ? x1 : x2, sizeof x.v);
    // x = (aR)^-1; output must be a^-1 * R = x * R^2 = (x (*) R2) (*) R2
    fp_mul(x, x, R2);
    fp_mul(r, x, R2);
}

static void to_mont(Fp &r, const Fp &a) { fp_mul(r, a, R2); }
static void from_mont(Fp &r, const Fp &a) {
    Fp one = {{1, 0, 0, 0}};
    fp_mul(r, a, one);
}

// ------------------------------------------------------------------- Fq2

struct Fp2 { Fp c0, c1; };

static const Fp2 F2_ZERO = {FP_ZERO, FP_ZERO};

static inline void f2_add(Fp2 &r, const Fp2 &a, const Fp2 &b) {
    fp_add(r.c0, a.c0, b.c0);
    fp_add(r.c1, a.c1, b.c1);
}

static inline void f2_sub(Fp2 &r, const Fp2 &a, const Fp2 &b) {
    fp_sub(r.c0, a.c0, b.c0);
    fp_sub(r.c1, a.c1, b.c1);
}

static inline void f2_neg(Fp2 &r, const Fp2 &a) {
    fp_neg(r.c0, a.c0);
    fp_neg(r.c1, a.c1);
}

static void f2_mul(Fp2 &r, const Fp2 &a, const Fp2 &b) {
    Fp t0, t1, t2, sa, sb;
    fp_mul(t0, a.c0, b.c0);
    fp_mul(t1, a.c1, b.c1);
    fp_add(sa, a.c0, a.c1);
    fp_add(sb, b.c0, b.c1);
    fp_mul(t2, sa, sb);
    fp_sub(r.c0, t0, t1);
    fp_sub(t2, t2, t0);
    fp_sub(r.c1, t2, t1);
}

static void f2_sqr(Fp2 &r, const Fp2 &a) {
    Fp t, s, d;
    fp_mul(t, a.c0, a.c1);
    fp_add(s, a.c0, a.c1);
    fp_sub(d, a.c0, a.c1);
    fp_mul(r.c0, s, d);
    fp_add(r.c1, t, t);
}

static inline void f2_conj(Fp2 &r, const Fp2 &a) {
    r.c0 = a.c0;
    fp_neg(r.c1, a.c1);
}

static void f2_inv(Fp2 &r, const Fp2 &a) {
    Fp t0, t1, d;
    fp_sqr(t0, a.c0);
    fp_sqr(t1, a.c1);
    fp_add(t0, t0, t1);
    fp_inv(d, t0);
    fp_mul(r.c0, a.c0, d);
    fp_mul(t1, a.c1, d);
    fp_neg(r.c1, t1);
}

static inline void f2_dbl(Fp2 &r, const Fp2 &a) { f2_add(r, a, a); }

static void f2_mul_small(Fp2 &r, const Fp2 &a, int k) {  // k in {2,3,9}
    Fp2 acc = a;
    for (int i = 1; i < k; i++) f2_add(acc, acc, a);
    r = acc;
}

// multiply by xi = 9 + i
static void f2_mul_xi(Fp2 &r, const Fp2 &a) {
    Fp2 nine;
    f2_mul_small(nine, a, 9);
    Fp t0, t1;
    fp_sub(t0, nine.c0, a.c1);       // 9 a0 - a1
    fp_add(t1, a.c0, nine.c1);       // a0 + 9 a1
    r.c0 = t0;
    r.c1 = t1;
}

static inline bool f2_is_zero(const Fp2 &a) {
    return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}

static inline bool f2_eq(const Fp2 &a, const Fp2 &b) {
    return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}

// ------------------------------------------------------------------- Fq6

struct Fp6 { Fp2 c0, c1, c2; };

static const Fp6 F6_ZERO = {F2_ZERO, F2_ZERO, F2_ZERO};

static inline void f6_add(Fp6 &r, const Fp6 &a, const Fp6 &b) {
    f2_add(r.c0, a.c0, b.c0);
    f2_add(r.c1, a.c1, b.c1);
    f2_add(r.c2, a.c2, b.c2);
}

static inline void f6_sub(Fp6 &r, const Fp6 &a, const Fp6 &b) {
    f2_sub(r.c0, a.c0, b.c0);
    f2_sub(r.c1, a.c1, b.c1);
    f2_sub(r.c2, a.c2, b.c2);
}

static inline void f6_neg(Fp6 &r, const Fp6 &a) {
    f2_neg(r.c0, a.c0);
    f2_neg(r.c1, a.c1);
    f2_neg(r.c2, a.c2);
}

static void f6_mul(Fp6 &r, const Fp6 &a, const Fp6 &b) {
    Fp2 t0, t1, t2, s0, s1, u;
    f2_mul(t0, a.c0, b.c0);
    f2_mul(t1, a.c1, b.c1);
    f2_mul(t2, a.c2, b.c2);

    Fp2 c0, c1, c2;
    // c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
    f2_add(s0, a.c1, a.c2);
    f2_add(s1, b.c1, b.c2);
    f2_mul(u, s0, s1);
    f2_sub(u, u, t1);
    f2_sub(u, u, t2);
    f2_mul_xi(u, u);
    f2_add(c0, t0, u);
    // c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
    f2_add(s0, a.c0, a.c1);
    f2_add(s1, b.c0, b.c1);
    f2_mul(u, s0, s1);
    f2_sub(u, u, t0);
    f2_sub(u, u, t1);
    Fp2 xt2;
    f2_mul_xi(xt2, t2);
    f2_add(c1, u, xt2);
    // c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    f2_add(s0, a.c0, a.c2);
    f2_add(s1, b.c0, b.c2);
    f2_mul(u, s0, s1);
    f2_sub(u, u, t0);
    f2_sub(u, u, t2);
    f2_add(c2, u, t1);
    r.c0 = c0; r.c1 = c1; r.c2 = c2;
}

static inline void f6_sqr(Fp6 &r, const Fp6 &a) { f6_mul(r, a, a); }

static void f6_mul_v(Fp6 &r, const Fp6 &a) {    // (c0,c1,c2) -> (xi*c2, c0, c1)
    Fp2 t;
    f2_mul_xi(t, a.c2);
    Fp2 old0 = a.c0, old1 = a.c1;
    r.c0 = t;
    r.c1 = old0;
    r.c2 = old1;
}

static void f6_inv(Fp6 &r, const Fp6 &a) {
    Fp2 c0, c1, c2, t, u;
    f2_sqr(t, a.c0);
    f2_mul(u, a.c1, a.c2);
    f2_mul_xi(u, u);
    f2_sub(c0, t, u);
    f2_sqr(t, a.c2);
    f2_mul_xi(t, t);
    f2_mul(u, a.c0, a.c1);
    f2_sub(c1, t, u);
    f2_sqr(t, a.c1);
    f2_mul(u, a.c0, a.c2);
    f2_sub(c2, t, u);

    Fp2 d, tmp;
    f2_mul(d, a.c0, c0);
    f2_mul(tmp, a.c2, c1);
    f2_mul_xi(tmp, tmp);
    f2_add(d, d, tmp);
    f2_mul(tmp, a.c1, c2);
    f2_mul_xi(tmp, tmp);
    f2_add(d, d, tmp);
    f2_inv(d, d);
    f2_mul(r.c0, c0, d);
    f2_mul(r.c1, c1, d);
    f2_mul(r.c2, c2, d);
}

// ------------------------------------------------------------------- Fq12

struct Fp12 { Fp6 c0, c1; };

static void f12_mul(Fp12 &r, const Fp12 &a, const Fp12 &b) {
    Fp6 t0, t1, s0, s1, u;
    f6_mul(t0, a.c0, b.c0);
    f6_mul(t1, a.c1, b.c1);
    Fp6 vt1;
    f6_mul_v(vt1, t1);
    Fp6 c0;
    f6_add(c0, t0, vt1);
    f6_add(s0, a.c0, a.c1);
    f6_add(s1, b.c0, b.c1);
    f6_mul(u, s0, s1);
    f6_sub(u, u, t0);
    f6_sub(u, u, t1);
    r.c0 = c0;
    r.c1 = u;
}

static void f12_sqr(Fp12 &r, const Fp12 &a) {
    Fp6 t, s0, s1, u;
    f6_mul(t, a.c0, a.c1);
    f6_add(s0, a.c0, a.c1);
    Fp6 va1;
    f6_mul_v(va1, a.c1);
    f6_add(s1, a.c0, va1);
    f6_mul(u, s0, s1);
    Fp6 vt;
    f6_mul_v(vt, t);
    f6_sub(u, u, t);
    f6_sub(u, u, vt);
    r.c0 = u;
    f6_add(r.c1, t, t);
}

static void f12_inv(Fp12 &r, const Fp12 &a) {
    Fp6 t0, t1;
    f6_sqr(t0, a.c0);
    f6_sqr(t1, a.c1);
    f6_mul_v(t1, t1);
    f6_sub(t0, t0, t1);
    f6_inv(t0, t0);
    f6_mul(r.c0, a.c0, t0);
    Fp6 t2;
    f6_mul(t2, a.c1, t0);
    f6_neg(r.c1, t2);
}

static inline void f12_conj(Fp12 &r, const Fp12 &a) {
    r.c0 = a.c0;
    f6_neg(r.c1, a.c1);
}

static bool f12_is_one(const Fp12 &a) {
    if (!fp_eq(a.c0.c0.c0, FP_ONE_M)) return false;
    if (!fp_is_zero(a.c0.c0.c1)) return false;
    return f2_is_zero(a.c0.c1) && f2_is_zero(a.c0.c2) &&
           f2_is_zero(a.c1.c0) && f2_is_zero(a.c1.c1) && f2_is_zero(a.c1.c2);
}

// Frobenius coefficient tables (normal form; converted to Montgomery at init).
// gamma1[j] = xi^(j(p-1)/6), gamma2 = norm(gamma1), gamma3 = conj(g2)*g1.
static const u64 G1C_RAW[6][2][4] = {
    {{1, 0, 0, 0}, {0, 0, 0, 0}},
    {{0xd60b35dadcc9e470ULL, 0x5c521e08292f2176ULL, 0xe8b99fdd76e68b60ULL, 0x1284b71c2865a7dfULL},
     {0xca5cf05f80f362acULL, 0x747992778eeec7e5ULL, 0xa6327cfe12150b8eULL, 0x246996f3b4fae7e6ULL}},
    {{0x99e39557176f553dULL, 0xb78cc310c2c3330cULL, 0x4c0bec3cf559b143ULL, 0x2fb347984f7911f7ULL},
     {0x1665d51c640fcba2ULL, 0x32ae2a1d0b7c9dceULL, 0x4ba4cc8bd75a0794ULL, 0x16c9e55061ebae20ULL}},
    {{0xdc54014671a0135aULL, 0xdbaae0eda9c95998ULL, 0xdc5ec698b6e2f9b9ULL, 0x063cf305489af5dcULL},
     {0x82d37f632623b0e3ULL, 0x21807dc98fa25bd2ULL, 0x0704b5a7ec796f2bULL, 0x07c03cbcac41049aULL}},
    {{0x848a1f55921ea762ULL, 0xd33365f7be94ec72ULL, 0x80f3c0b75a181e84ULL, 0x05b54f5e64eea801ULL},
     {0xc13b4711cd2b8126ULL, 0x3685d2ea1bdec763ULL, 0x9f3a80b03b0b1c92ULL, 0x2c145edbe7fd8aeeULL}},
    {{0x2ea2c810eab7692fULL, 0x425c459b55aa1bd3ULL, 0xe93a3661a4353ff4ULL, 0x0183c1e74f798649ULL},
     {0x24c6b8ee6e0c2c4bULL, 0xb080cb99678e2ac0ULL, 0xa27fb246c7729f7dULL, 0x12acf2ca76fd0675ULL}},
};
static const u64 G2C_RAW[6][4] = {
    {1, 0, 0, 0},
    {0xe4bd44e5607cfd49ULL, 0xc28f069fbb966e3dULL, 0x5e6dd9e7e0acccb0ULL, 0x30644e72e131a029ULL},
    {0xe4bd44e5607cfd48ULL, 0xc28f069fbb966e3dULL, 0x5e6dd9e7e0acccb0ULL, 0x30644e72e131a029ULL},
    {0x3c208c16d87cfd46ULL, 0x97816a916871ca8dULL, 0xb85045b68181585dULL, 0x30644e72e131a029ULL},
    {0x5763473177fffffeULL, 0xd4f263f1acdb5c4fULL, 0x59e26bcea0d48bacULL, 0x0000000000000000ULL},
    {0x5763473177ffffffULL, 0xd4f263f1acdb5c4fULL, 0x59e26bcea0d48bacULL, 0x0000000000000000ULL},
};
static const u64 G3C_RAW[6][2][4] = {
    {{1, 0, 0, 0}, {0, 0, 0, 0}},
    {{0xe86f7d391ed4a67fULL, 0x894cb38dbe55d24aULL, 0xefe9608cd0acaa90ULL, 0x19dc81cfcc82e4bbULL},
     {0x7694aa2bf4c0c101ULL, 0x7f03a5e397d439ecULL, 0x06cbeee33576139dULL, 0x00abf8b60be77d73ULL}},
    {{0x7b746ee87bdcfb6dULL, 0x805ffd3d5d6942d3ULL, 0xbaff1c77959f25acULL, 0x0856e078b755ef0aULL},
     {0x380cab2baaa586deULL, 0x0fdf31bf98ff2631ULL, 0xa9f30e6dec26094fULL, 0x04f1de41b3d1766fULL}},
    {{0x5fcc8ad066dce9edULL, 0xbbd689a3bea870f4ULL, 0xdbf17f1dca9e5ea3ULL, 0x2a275b6d9896aa4cULL},
     {0xb94d0cb3b2594c64ULL, 0x7600ecc7d8cf6ebaULL, 0xb14b900e9507e932ULL, 0x28a411b634f09b8fULL}},
    {{0x0e1a92bc3ccbf066ULL, 0xe633094575b06bcbULL, 0x19bee0f7b5b2444eULL, 0x0bc58c6611c08dabULL},
     {0x5fe3ed9d730c239fULL, 0xa44a9e08737f96e5ULL, 0xfeb0f6ef0cd21d04ULL, 0x23d5e999e1910a12ULL}},
    {{0xebde847076261b43ULL, 0x2ed68098967c84a5ULL, 0x711699fa3b4d3f69ULL, 0x13c49044952c0905ULL},
     {0x1f25041384282499ULL, 0x3e2ddaea20028021ULL, 0x9fb1b2282a48633dULL, 0x16db366a59b1dd0bULL}},
};
static const u64 FROBX_RAW[2][4] = {
    {0x99e39557176f553dULL, 0xb78cc310c2c3330cULL, 0x4c0bec3cf559b143ULL, 0x2fb347984f7911f7ULL},
    {0x1665d51c640fcba2ULL, 0x32ae2a1d0b7c9dceULL, 0x4ba4cc8bd75a0794ULL, 0x16c9e55061ebae20ULL},
};
static const u64 FROBY_RAW[2][4] = {
    {0xdc54014671a0135aULL, 0xdbaae0eda9c95998ULL, 0xdc5ec698b6e2f9b9ULL, 0x063cf305489af5dcULL},
    {0x82d37f632623b0e3ULL, 0x21807dc98fa25bd2ULL, 0x0704b5a7ec796f2bULL, 0x07c03cbcac41049aULL},
};
// hard exponent (p^4 - p^2 + 1)/r, 761 bits, little-endian limbs
static const u64 HARD[12] = {
    0xe81bb482ccdf42b1ULL, 0x5abf5cc4f49c36d4ULL, 0xf1154e7e1da014fdULL,
    0xdcc7b44c87cdbacfULL, 0xaaa441e3954bcf8aULL, 0x6b887d56d5095f23ULL,
    0x79581e16f3fd90c6ULL, 0x3b1b1355d189227dULL, 0x4e529a5861876f6bULL,
    0x6c0eb522d5b12278ULL, 0x331ec15183177fafULL, 0x01baaa710b0759adULL,
};
static const u64 ATE_LOOP = 0x9d797039be763ba8ULL;   // low 64 bits
static const int ATE_TOP_BIT = 64;                   // bit 64 is set (value 0x1...)

static Fp2 G1C_M[6], G3C_M[6], FROBX_M, FROBY_M;
static Fp G2C_M[6];
static Fp2 G2_GEN_X, G2_GEN_Y;
static bool INITED = false;

static void load_fp2(Fp2 &out, const u64 raw[2][4]) {
    Fp a, b;
    memcpy(a.v, raw[0], sizeof a.v);
    memcpy(b.v, raw[1], sizeof b.v);
    to_mont(out.c0, a);
    to_mont(out.c1, b);
}

static void init_constants() {
    if (INITED) return;
    for (int j = 0; j < 6; j++) {
        load_fp2(G1C_M[j], G1C_RAW[j]);
        load_fp2(G3C_M[j], G3C_RAW[j]);
        Fp t;
        memcpy(t.v, G2C_RAW[j], sizeof t.v);
        to_mont(G2C_M[j], t);
    }
    load_fp2(FROBX_M, FROBX_RAW);
    load_fp2(FROBY_M, FROBY_RAW);
    INITED = true;
}

// a^(p^power) for power in {1,2,3}; layout identical to the Python twin.
static void f12_frobenius(Fp12 &r, const Fp12 &a, int power) {
    const Fp2 *cs[6] = {&a.c0.c0, &a.c1.c0, &a.c0.c1,
                        &a.c1.c1, &a.c0.c2, &a.c1.c2};
    Fp2 out[6];
    bool conj = (power % 2) == 1;
    for (int j = 0; j < 6; j++) {
        Fp2 c = *cs[j];
        if (conj) f2_conj(c, c);
        if (j) {
            if (power == 2) {
                fp_mul(c.c0, c.c0, G2C_M[j]);
                fp_mul(c.c1, c.c1, G2C_M[j]);
            } else {
                const Fp2 &co = (power == 1) ? G1C_M[j] : G3C_M[j];
                f2_mul(c, c, co);
            }
        }
        out[j] = c;
    }
    r.c0.c0 = out[0]; r.c0.c1 = out[2]; r.c0.c2 = out[4];
    r.c1.c0 = out[1]; r.c1.c1 = out[3]; r.c1.c2 = out[5];
}

static void f12_one(Fp12 &r) {
    memset(&r, 0, sizeof r);
    r.c0.c0.c0 = FP_ONE_M;
}

static const u64 BN_U = 4965661367192848881ULL;    // the BN parameter u

// a^u for UNITARY a (all final-exp intermediates are unitary after the easy
// part, so this is only ever called on unitary elements)
static void f12_pow_u(Fp12 &r, const Fp12 &a) {
    Fp12 out;
    f12_one(out);
    Fp12 base = a;
    u64 w = BN_U;
    while (w) {
        if (w & 1) f12_mul(out, out, base);
        f12_sqr(base, base);
        w >>= 1;
    }
    r = out;
}

static void f12_pow_small(Fp12 &r, const Fp12 &a, unsigned e) {
    Fp12 out;
    f12_one(out);
    Fp12 base = a;
    while (e) {
        if (e & 1) f12_mul(out, out, base);
        f12_sqr(base, base);
        e >>= 1;
    }
    r = out;
}

// Hard part f^((p^4-p^2+1)/r) via the base-p decomposition
//   lambda = l0 + l1*p + l2*p^2 + p^3,
//   l0 = -(36u^3 + 30u^2 + 18u + 2),  l1 = 1 - (36u^3 + 18u^2 + 12u),
//   l2 = 6u^2 + 1
// (derived symbolically from p(u), r(u) and verified numerically against the
// 761-bit plain exponent — see the Python twin's _HARD_EXP). Inverses are
// conjugates because the input is unitary. ~200 squarings instead of ~760.
static void f12_pow_hard(Fp12 &r, const Fp12 &f) {
    Fp12 y1, y2, y3;
    f12_pow_u(y1, f);                   // f^u
    f12_pow_u(y2, y1);                  // f^(u^2)
    f12_pow_u(y3, y2);                  // f^(u^3)

    Fp12 y3_36, y2_6, y2_12, y2_18, y2_30, y1_3, y1_12, y1_18, f2;
    Fp12 t;
    f12_pow_small(y3_36, y3, 36);
    f12_pow_small(y2_6, y2, 6);
    f12_sqr(y2_12, y2_6);
    f12_mul(y2_18, y2_12, y2_6);
    f12_mul(y2_30, y2_18, y2_12);
    f12_sqr(t, y1);
    f12_mul(y1_3, t, y1);
    f12_pow_small(y1_12, y1_3, 4);
    f12_mul(y1_18, y1_12, t);          // y1^12 * y1^2 * ... wait: 12+2=14
    f12_mul(y1_18, y1_18, t);          // +2 -> 16
    f12_mul(y1_18, y1_18, t);          // +2 -> 18
    f12_sqr(f2, f);

    Fp12 fl2, fl1, fl0, acc;
    // f^{l2} = y2^6 * f
    f12_mul(fl2, y2_6, f);
    // f^{l1} = conj(y3^36 * y2^18 * y1^12) * f
    f12_mul(t, y3_36, y2_18);
    f12_mul(t, t, y1_12);
    f12_conj(t, t);
    f12_mul(fl1, t, f);
    // f^{l0} = conj(y3^36 * y2^30 * y1^18 * f^2)
    f12_mul(t, y3_36, y2_30);
    f12_mul(t, t, y1_18);
    f12_mul(t, t, f2);
    f12_conj(fl0, t);

    Fp12 u1, u2, u3;
    f12_frobenius(u1, fl1, 1);
    f12_frobenius(u2, fl2, 2);
    f12_frobenius(u3, f, 3);
    f12_mul(acc, fl0, u1);
    f12_mul(acc, acc, u2);
    f12_mul(r, acc, u3);
}

// ------------------------------------------------------------------- groups

struct G1 { Fp x, y; bool inf; };
struct G2 { Fp2 x, y; bool inf; };

static void g1_add_pt(G1 &r, const G1 &a, const G1 &b) {
    if (a.inf) { r = b; return; }
    if (b.inf) { r = a; return; }
    Fp lam;
    if (fp_eq(a.x, b.x)) {
        Fp s;
        fp_add(s, a.y, b.y);
        if (fp_is_zero(s)) { r.inf = true; return; }
        Fp num, den, x2;
        fp_sqr(x2, a.x);
        fp_add(num, x2, x2);
        fp_add(num, num, x2);          // 3x^2
        fp_add(den, a.y, a.y);
        fp_inv(den, den);
        fp_mul(lam, num, den);
    } else {
        Fp num, den;
        fp_sub(num, b.y, a.y);
        fp_sub(den, b.x, a.x);
        fp_inv(den, den);
        fp_mul(lam, num, den);
    }
    Fp x3, t;
    fp_sqr(x3, lam);
    fp_sub(x3, x3, a.x);
    fp_sub(x3, x3, b.x);
    fp_sub(t, a.x, x3);
    fp_mul(t, lam, t);
    fp_sub(t, t, a.y);
    r.x = x3; r.y = t; r.inf = false;
}

// Jacobian coordinates for the scalar-mul ladders: the affine group law
// above pays one field inversion (~50x a mul, even with binary EGCD) per
// step, so a 256-bit ladder costs ~380 inversions. Jacobian double/add are
// inversion-free (dbl-2009-l / add-2007-bl, a=0 curve); one inversion at
// the end converts back. Measured: g1_mul 1.9 ms -> ~0.1 ms.

struct G1J { Fp X, Y, Z; };           // inf <=> Z == 0

static void g1j_from_affine(G1J &r, const G1 &a) {
    if (a.inf) { r.X = FP_ONE_M; r.Y = FP_ONE_M; r.Z = FP_ZERO; return; }
    r.X = a.x; r.Y = a.y; r.Z = FP_ONE_M;
}

static void g1j_to_affine(G1 &r, const G1J &a) {
    if (fp_is_zero(a.Z)) { r.inf = true; return; }
    Fp zi, zi2, zi3;
    fp_inv(zi, a.Z);
    fp_sqr(zi2, zi);
    fp_mul(zi3, zi2, zi);
    fp_mul(r.x, a.X, zi2);
    fp_mul(r.y, a.Y, zi3);
    r.inf = false;
}

static void g1j_dbl(G1J &r, const G1J &p) {
    if (fp_is_zero(p.Z)) { r = p; return; }
    Fp A, B, C, D, E, F, t;
    fp_sqr(A, p.X);                    // A = X^2
    fp_sqr(B, p.Y);                    // B = Y^2
    fp_sqr(C, B);                      // C = B^2
    fp_add(D, p.X, B);
    fp_sqr(D, D);
    fp_sub(D, D, A);
    fp_sub(D, D, C);
    fp_add(D, D, D);                   // D = 2((X+B)^2 - A - C)
    fp_add(E, A, A);
    fp_add(E, E, A);                   // E = 3A
    fp_sqr(F, E);                      // F = E^2
    fp_sub(r.X, F, D);
    fp_sub(r.X, r.X, D);               // X3 = F - 2D
    fp_sub(t, D, r.X);
    fp_mul(t, E, t);
    Fp c8;
    fp_add(c8, C, C);
    fp_add(c8, c8, c8);
    fp_add(c8, c8, c8);                // 8C
    fp_mul(r.Z, p.Y, p.Z);
    fp_add(r.Z, r.Z, r.Z);             // Z3 = 2YZ  (before Y3 clobbers Y)
    fp_sub(r.Y, t, c8);                // Y3 = E(D - X3) - 8C
}

// mixed addition: q is affine (Z2 = 1)
static void g1j_add_affine(G1J &r, const G1J &p, const G1 &q) {
    if (q.inf) { r = p; return; }
    if (fp_is_zero(p.Z)) { g1j_from_affine(r, q); return; }
    Fp Z1Z1, U2, S2, H, HH, I, J, rr, V, t;
    fp_sqr(Z1Z1, p.Z);
    fp_mul(U2, q.x, Z1Z1);
    fp_mul(S2, q.y, p.Z);
    fp_mul(S2, S2, Z1Z1);
    fp_sub(H, U2, p.X);
    fp_sub(rr, S2, p.Y);
    if (fp_is_zero(H)) {
        if (fp_is_zero(rr)) { g1j_dbl(r, p); return; }
        r.X = FP_ONE_M; r.Y = FP_ONE_M; r.Z = FP_ZERO;  // P + (-P)
        return;
    }
    fp_add(rr, rr, rr);                // r = 2(S2 - Y1)
    fp_sqr(HH, H);
    fp_add(I, HH, HH);
    fp_add(I, I, I);                   // I = 4HH
    fp_mul(J, H, I);
    fp_mul(V, p.X, I);
    fp_sqr(r.X, rr);
    fp_sub(r.X, r.X, J);
    fp_sub(r.X, r.X, V);
    fp_sub(r.X, r.X, V);               // X3 = r^2 - J - 2V
    fp_sub(t, V, r.X);
    fp_mul(t, rr, t);
    Fp YJ;
    fp_mul(YJ, p.Y, J);
    fp_add(YJ, YJ, YJ);
    Fp Z3;
    fp_mul(Z3, p.Z, H);
    fp_add(r.Z, Z3, Z3);               // Z3 = 2 Z1 H
    fp_sub(r.Y, t, YJ);                // Y3 = r(V - X3) - 2 Y1 J
}

static void g1_mul_pt(G1 &r, const G1 &a, const u64 *k) {
    G1J out;
    out.X = FP_ONE_M; out.Y = FP_ONE_M; out.Z = FP_ZERO;
    if (a.inf) { r = a; return; }
    int top = 3;
    while (top >= 0 && k[top] == 0) top--;
    if (top < 0) { r.inf = true; return; }
    int bit = 63;
    while (bit >= 0 && !((k[top] >> bit) & 1)) bit--;
    for (int i = top; i >= 0; i--) {
        for (int b = (i == top ? bit : 63); b >= 0; b--) {
            g1j_dbl(out, out);
            if ((k[i] >> b) & 1) g1j_add_affine(out, out, a);
        }
    }
    g1j_to_affine(r, out);
}

static void g2_add_pt(G2 &r, const G2 &a, const G2 &b) {
    if (a.inf) { r = b; return; }
    if (b.inf) { r = a; return; }
    Fp2 lam;
    if (f2_eq(a.x, b.x)) {
        Fp2 s;
        f2_add(s, a.y, b.y);
        if (f2_is_zero(s)) { r.inf = true; return; }
        Fp2 num, den, x2;
        f2_sqr(x2, a.x);
        f2_mul_small(num, x2, 3);
        f2_dbl(den, a.y);
        f2_inv(den, den);
        f2_mul(lam, num, den);
    } else {
        Fp2 num, den;
        f2_sub(num, b.y, a.y);
        f2_sub(den, b.x, a.x);
        f2_inv(den, den);
        f2_mul(lam, num, den);
    }
    Fp2 x3, t;
    f2_sqr(x3, lam);
    f2_sub(x3, x3, a.x);
    f2_sub(x3, x3, b.x);
    f2_sub(t, a.x, x3);
    f2_mul(t, lam, t);
    f2_sub(t, t, a.y);
    r.x = x3; r.y = t; r.inf = false;
}

// Jacobian ladder over Fp2 — same dbl-2009-l / add-2007-bl shapes as G1J.

struct G2J { Fp2 X, Y, Z; };          // inf <=> Z == 0

static const Fp2 F2_ONE_M = {FP_ONE_M, FP_ZERO};

static void g2j_from_affine(G2J &r, const G2 &a) {
    if (a.inf) { r.X = F2_ONE_M; r.Y = F2_ONE_M; r.Z = F2_ZERO; return; }
    r.X = a.x; r.Y = a.y; r.Z = F2_ONE_M;
}

static void g2j_to_affine(G2 &r, const G2J &a) {
    if (f2_is_zero(a.Z)) { r.inf = true; return; }
    Fp2 zi, zi2, zi3;
    f2_inv(zi, a.Z);
    f2_sqr(zi2, zi);
    f2_mul(zi3, zi2, zi);
    f2_mul(r.x, a.X, zi2);
    f2_mul(r.y, a.Y, zi3);
    r.inf = false;
}

static void g2j_dbl(G2J &r, const G2J &p) {
    if (f2_is_zero(p.Z)) { r = p; return; }
    Fp2 A, B, C, D, E, F, t;
    f2_sqr(A, p.X);
    f2_sqr(B, p.Y);
    f2_sqr(C, B);
    f2_add(D, p.X, B);
    f2_sqr(D, D);
    f2_sub(D, D, A);
    f2_sub(D, D, C);
    f2_add(D, D, D);
    f2_add(E, A, A);
    f2_add(E, E, A);
    f2_sqr(F, E);
    f2_sub(r.X, F, D);
    f2_sub(r.X, r.X, D);
    f2_sub(t, D, r.X);
    f2_mul(t, E, t);
    Fp2 c8;
    f2_add(c8, C, C);
    f2_add(c8, c8, c8);
    f2_add(c8, c8, c8);
    f2_mul(r.Z, p.Y, p.Z);
    f2_add(r.Z, r.Z, r.Z);
    f2_sub(r.Y, t, c8);
}

static void g2j_add_affine(G2J &r, const G2J &p, const G2 &q) {
    if (q.inf) { r = p; return; }
    if (f2_is_zero(p.Z)) { g2j_from_affine(r, q); return; }
    Fp2 Z1Z1, U2, S2, H, HH, I, J, rr, V, t;
    f2_sqr(Z1Z1, p.Z);
    f2_mul(U2, q.x, Z1Z1);
    f2_mul(S2, q.y, p.Z);
    f2_mul(S2, S2, Z1Z1);
    f2_sub(H, U2, p.X);
    f2_sub(rr, S2, p.Y);
    if (f2_is_zero(H)) {
        if (f2_is_zero(rr)) { g2j_dbl(r, p); return; }
        r.X = F2_ONE_M; r.Y = F2_ONE_M; r.Z = F2_ZERO;
        return;
    }
    f2_add(rr, rr, rr);
    f2_sqr(HH, H);
    f2_add(I, HH, HH);
    f2_add(I, I, I);
    f2_mul(J, H, I);
    f2_mul(V, p.X, I);
    f2_sqr(r.X, rr);
    f2_sub(r.X, r.X, J);
    f2_sub(r.X, r.X, V);
    f2_sub(r.X, r.X, V);
    f2_sub(t, V, r.X);
    f2_mul(t, rr, t);
    Fp2 YJ;
    f2_mul(YJ, p.Y, J);
    f2_add(YJ, YJ, YJ);
    Fp2 Z3;
    f2_mul(Z3, p.Z, H);
    f2_add(r.Z, Z3, Z3);
    f2_sub(r.Y, t, YJ);
}

static void g2_mul_pt(G2 &r, const G2 &a, const u64 *k) {
    if (a.inf) { r = a; return; }
    int top = 3;
    while (top >= 0 && k[top] == 0) top--;
    if (top < 0) { r.inf = true; return; }
    G2J out;
    out.X = F2_ONE_M; out.Y = F2_ONE_M; out.Z = F2_ZERO;
    int bit = 63;
    while (bit >= 0 && !((k[top] >> bit) & 1)) bit--;
    for (int i = top; i >= 0; i--) {
        for (int b = (i == top ? bit : 63); b >= 0; b--) {
            g2j_dbl(out, out);
            if ((k[i] >> b) & 1) g2j_add_affine(out, out, a);
        }
    }
    g2j_to_affine(r, out);
}

static void g2_neg_pt(G2 &r, const G2 &a) {
    r = a;
    if (!a.inf) f2_neg(r.y, a.y);
}

static void g2_frob_pt(G2 &r, const G2 &a) {
    if (a.inf) { r = a; return; }
    Fp2 cx, cy;
    f2_conj(cx, a.x);
    f2_conj(cy, a.y);
    f2_mul(r.x, cx, FROBX_M);
    f2_mul(r.y, cy, FROBY_M);
    r.inf = false;
}

// ------------------------------------------------------------------- pairing

// Multiply f by the sparse line value  A + B*w + C*w^3  (A,B,C in Fq2),
// i.e. l = ((A,0,0),(B,C,0)) in the (c0,c1) Fq6 layout. ~15 Fq2 muls vs 18
// for a generic f12_mul — and no memset/copy of a mostly-zero Fp12.
static void f12_mul_line(Fp12 &f, const Fp2 &A, const Fp2 &B, const Fp2 &C) {
    // t0 = f.c0 * (A,0,0): coefficient-wise scale by A
    Fp6 t0;
    f2_mul(t0.c0, f.c0.c0, A);
    f2_mul(t0.c1, f.c0.c1, A);
    f2_mul(t0.c2, f.c0.c2, A);
    // t1 = f.c1 * (B,C,0)
    Fp6 t1;
    {
        Fp2 a0b0, a1b1, u;
        f2_mul(a0b0, f.c1.c0, B);
        f2_mul(a1b1, f.c1.c1, C);
        f2_mul(u, f.c1.c2, C);
        f2_mul_xi(u, u);
        f2_add(t1.c0, a0b0, u);                    // a0B + xi*a2C
        Fp2 a0b1, a1b0;
        f2_mul(a0b1, f.c1.c0, C);
        f2_mul(a1b0, f.c1.c1, B);
        f2_add(t1.c1, a0b1, a1b0);                 // a0C + a1B
        Fp2 a2b0;
        f2_mul(a2b0, f.c1.c2, B);
        f2_add(t1.c2, a1b1, a2b0);                 // a1C + a2B
    }
    // (f0+f1) * (A+B, C, 0)
    Fp6 s, m;
    f6_add(s, f.c0, f.c1);
    Fp2 AB;
    f2_add(AB, A, B);
    {
        Fp2 a0b0, a1b1, u;
        f2_mul(a0b0, s.c0, AB);
        f2_mul(a1b1, s.c1, C);
        f2_mul(u, s.c2, C);
        f2_mul_xi(u, u);
        f2_add(m.c0, a0b0, u);
        Fp2 a0b1, a1b0;
        f2_mul(a0b1, s.c0, C);
        f2_mul(a1b0, s.c1, AB);
        f2_add(m.c1, a0b1, a1b0);
        Fp2 a2b0;
        f2_mul(a2b0, s.c2, AB);
        f2_add(m.c2, a1b1, a2b0);
    }
    Fp6 vt1;
    f6_mul_v(vt1, t1);
    f6_add(f.c0, t0, vt1);
    f6_sub(m, m, t0);
    f6_sub(f.c1, m, t1);
}

// P-independent half of a doubling step: the tangent's (lambda, C) at T
// — which depend ONLY on T — plus the T <- 2T advance. The expensive
// part (one Fp2 inversion, ~a Fermat exponentiation) lives here, which
// is what makes precomputing these per distinct Q worthwhile.
static void dbl_coeff(Fp2 &lam, Fp2 &C, G2 &t) {
    Fp2 num, den, x2;
    f2_sqr(x2, t.x);
    f2_mul_small(num, x2, 3);
    f2_dbl(den, t.y);
    f2_inv(den, den);
    f2_mul(lam, num, den);
    Fp2 lx;
    f2_mul(lx, lam, t.x);
    f2_sub(C, t.y, lx);
    Fp2 x3, yy;
    f2_sqr(x3, lam);
    f2_sub(x3, x3, t.x);
    f2_sub(x3, x3, t.x);
    f2_sub(yy, t.x, x3);
    f2_mul(yy, lam, yy);
    f2_sub(yy, yy, t.y);
    t.x = x3;
    t.y = yy;
}

// P-dependent half: scale the line to the G1 point (2 fp_mul, no inversion).
static inline void line_eval(Fp2 &A, Fp2 &B, const Fp2 &lam, const Fp &xp,
                             const Fp &yp) {
    A.c0 = FP_ZERO; A.c1 = FP_ZERO;
    fp_neg(A.c0, yp);
    fp_mul(B.c0, lam.c0, xp);
    fp_mul(B.c1, lam.c1, xp);
}

// Doubling step: computes the tangent line at T evaluated at P AND advances
// T <- 2T, sharing one lambda (and thus one field inversion) between them.
static void dbl_step(Fp2 &A, Fp2 &B, Fp2 &C, G2 &t, const Fp &xp,
                     const Fp &yp) {
    Fp2 lam;
    dbl_coeff(lam, C, t);
    line_eval(A, B, lam, xp, yp);
}

// Addition step: chord line through T and Q at P; T <- T+Q; shares lambda.
// Returns false for the degenerate vertical case (T = -Q), where the line is
// xP - xT*w^2 and T becomes infinity — callers fall back to a generic mul.
static bool add_coeff(Fp2 &lam, Fp2 &C, G2 &t, const G2 &q) {
    if (f2_eq(t.x, q.x)) return false;
    Fp2 num, den;
    f2_sub(num, q.y, t.y);
    f2_sub(den, q.x, t.x);
    f2_inv(den, den);
    f2_mul(lam, num, den);
    Fp2 lx;
    f2_mul(lx, lam, t.x);
    f2_sub(C, t.y, lx);
    Fp2 x3, yy;
    f2_sqr(x3, lam);
    f2_sub(x3, x3, t.x);
    f2_sub(x3, x3, q.x);
    f2_sub(yy, t.x, x3);
    f2_mul(yy, lam, yy);
    f2_sub(yy, yy, t.y);
    t.x = x3;
    t.y = yy;
    return true;
}

static bool add_step(Fp2 &A, Fp2 &B, Fp2 &C, G2 &t, const G2 &q,
                     const Fp &xp, const Fp &yp) {
    Fp2 lam;
    if (!add_coeff(lam, C, t, q)) return false;
    line_eval(A, B, lam, xp, yp);
    return true;
}

static void mul_vertical(Fp12 &f, const G2 &t, const Fp &xp) {
    // l = xP - xT*w^2: generic fallback for the (vanishingly rare) T = -Q
    Fp12 l;
    memset(&l, 0, sizeof l);
    l.c0.c0.c0 = xp;
    f2_neg(l.c0.c1, t.x);
    f12_mul(f, f, l);
}

static void miller_loop(Fp12 &f, const G2 &q, const G1 &p) {
    f12_one(f);
    if (q.inf || p.inf) return;
    G2 t = q;
    Fp2 A, B, C;
    for (int i = ATE_TOP_BIT - 1; i >= 0; i--) {
        f12_sqr(f, f);
        if (!t.inf) {
            dbl_step(A, B, C, t, p.x, p.y);
            f12_mul_line(f, A, B, C);
        }
        bool bit = (i < 64) ? ((ATE_LOOP >> i) & 1) : true;
        if (bit && !t.inf) {
            if (add_step(A, B, C, t, q, p.x, p.y)) {
                f12_mul_line(f, A, B, C);
            } else {
                // T = -Q (unreachable for subgroup inputs; guarded anyway)
                mul_vertical(f, t, p.x);
                g2_add_pt(t, t, q);
            }
        }
    }
    G2 q1, q2, nq2;
    g2_frob_pt(q1, q);
    g2_frob_pt(q2, q1);
    g2_neg_pt(nq2, q2);
    if (!t.inf) {
        if (add_step(A, B, C, t, q1, p.x, p.y)) f12_mul_line(f, A, B, C);
        else { mul_vertical(f, t, p.x); g2_add_pt(t, t, q1); }
    }
    if (!t.inf) {
        if (add_step(A, B, C, t, nq2, p.x, p.y)) f12_mul_line(f, A, B, C);
        else mul_vertical(f, t, p.x);
    }
}

// ------------------------------------------------- prepared pairings
//
// Every dbl/add step above pays an Fp2 inversion (a Fermat
// exponentiation — by far the step's dominant cost), and the (lam, C)
// coefficients those inversions produce depend ONLY on the G2 argument.
// A BLS verification pairs (G2 generator, -sig) and (aggregated pool
// key, H(m)): the generator is fixed forever and the aggregate repeats
// per participant set, so both Miller loops run inversion-free once
// their coefficient sequences are cached (keyed by the raw 128-byte G2
// encoding; a small mutex-guarded table — ctypes callers release the
// GIL, so concurrent pairing checks are real).

#define PREP_MAX_STEPS 136        // 64 dbl + <=65 add + 2 frobenius adds
struct PreparedG2 {
    uint8_t key[128];
    int n_steps;
    bool used;
    Fp2 lam[PREP_MAX_STEPS];
    Fp2 c[PREP_MAX_STEPS];
};

static bool prepare_g2(PreparedG2 &pre, const G2 &q0) {
    pre.n_steps = 0;
    if (q0.inf) return false;
    G2 t = q0;
    int s = 0;
    for (int i = ATE_TOP_BIT - 1; i >= 0; i--) {
        if (t.inf || s + 2 > PREP_MAX_STEPS) return false;
        dbl_coeff(pre.lam[s], pre.c[s], t);
        s++;
        bool bit = (i < 64) ? ((ATE_LOOP >> i) & 1) : true;
        if (bit) {
            if (t.inf) return false;
            if (!add_coeff(pre.lam[s], pre.c[s], t, q0)) return false;
            s++;
        }
    }
    G2 q1, q2, nq2;
    g2_frob_pt(q1, q0);
    g2_frob_pt(q2, q1);
    g2_neg_pt(nq2, q2);
    if (t.inf || s + 2 > PREP_MAX_STEPS) return false;
    if (!add_coeff(pre.lam[s], pre.c[s], t, q1)) return false;
    s++;
    if (t.inf) return false;
    if (!add_coeff(pre.lam[s], pre.c[s], t, nq2)) return false;
    s++;
    pre.n_steps = s;
    return true;
}

// Same loop structure as miller_loop, consuming cached coefficients:
// zero inversions, two fp_mul per line.
static void miller_loop_prepared(Fp12 &f, const PreparedG2 &pre,
                                 const G1 &p) {
    f12_one(f);
    if (p.inf) return;
    Fp2 A, B;
    int s = 0;
    for (int i = ATE_TOP_BIT - 1; i >= 0; i--) {
        f12_sqr(f, f);
        line_eval(A, B, pre.lam[s], p.x, p.y);
        f12_mul_line(f, A, B, pre.c[s]);
        s++;
        bool bit = (i < 64) ? ((ATE_LOOP >> i) & 1) : true;
        if (bit) {
            line_eval(A, B, pre.lam[s], p.x, p.y);
            f12_mul_line(f, A, B, pre.c[s]);
            s++;
        }
    }
    line_eval(A, B, pre.lam[s], p.x, p.y);
    f12_mul_line(f, A, B, pre.c[s]);
    s++;
    line_eval(A, B, pre.lam[s], p.x, p.y);
    f12_mul_line(f, A, B, pre.c[s]);
}

#define PREP_CACHE_SLOTS 8
static PreparedG2 g_prep_cache[PREP_CACHE_SLOTS];
static uint64_t g_prep_last_hit[PREP_CACHE_SLOTS];
static uint64_t g_prep_tick = 0;
static std::mutex g_prep_mu;

// Copy only the LIVE coefficients (n_steps of PREP_MAX_STEPS) so the
// critical section stays short for concurrent pairing callers.
static void prep_copy(PreparedG2 &dst, const PreparedG2 &src) {
    memcpy(dst.key, src.key, sizeof src.key);
    dst.n_steps = src.n_steps;
    dst.used = src.used;
    memcpy(dst.lam, src.lam, sizeof(Fp2) * src.n_steps);
    memcpy(dst.c, src.c, sizeof(Fp2) * src.n_steps);
}

static bool prep_cache_get(const uint8_t *key, PreparedG2 &out) {
    std::lock_guard<std::mutex> lock(g_prep_mu);
    for (int i = 0; i < PREP_CACHE_SLOTS; i++) {
        if (g_prep_cache[i].used &&
                memcmp(g_prep_cache[i].key, key, 128) == 0) {
            prep_copy(out, g_prep_cache[i]);
            g_prep_last_hit[i] = ++g_prep_tick;   // LRU: hits keep the
            return true;                          // generator resident
        }
    }
    return false;
}

static void prep_cache_put(const uint8_t *key, const PreparedG2 &pre) {
    std::lock_guard<std::mutex> lock(g_prep_mu);
    int slot = 0;
    for (int i = 1; i < PREP_CACHE_SLOTS; i++) {
        if (!g_prep_cache[i].used) { slot = i; break; }
        if (g_prep_last_hit[i] < g_prep_last_hit[slot]) slot = i;
    }
    prep_copy(g_prep_cache[slot], pre);
    memcpy(g_prep_cache[slot].key, key, 128);
    g_prep_cache[slot].used = true;
    g_prep_last_hit[slot] = ++g_prep_tick;
}

static void final_exp(Fp12 &r, const Fp12 &f) {
    Fp12 inv, t, u;
    f12_inv(inv, f);
    f12_conj(t, f);
    f12_mul(t, t, inv);                 // f^(p^6 - 1), now unitary
    f12_frobenius(u, t, 2);
    f12_mul(t, u, t);                   // ^(p^2 + 1)
    f12_pow_hard(r, t);
}

// ------------------------------------------------------------------- I/O

static bool fp_from_be(Fp &out, const uint8_t *in) {
    Fp raw;
    for (int i = 0; i < 4; i++) {
        u64 w = 0;
        for (int j = 0; j < 8; j++)
            w = (w << 8) | in[(3 - i) * 8 + j];
        raw.v[i] = w;
    }
    if (cmp4(raw.v, PL) >= 0) return false;
    to_mont(out, raw);
    return true;
}

static void fp_to_be(uint8_t *out, const Fp &a) {
    Fp n;
    from_mont(n, a);
    for (int i = 0; i < 4; i++) {
        u64 w = n.v[3 - i];
        for (int j = 0; j < 8; j++)
            out[i * 8 + j] = (uint8_t)(w >> (8 * (7 - j)));
    }
}

static bool is_zero_bytes(const uint8_t *b, int n) {
    for (int i = 0; i < n; i++)
        if (b[i]) return false;
    return true;
}

static bool g1_on_curve(const G1 &p) {
    if (p.inf) return true;
    Fp y2, x3, three;
    fp_sqr(y2, p.y);
    fp_sqr(x3, p.x);
    fp_mul(x3, x3, p.x);
    Fp b3 = {{3, 0, 0, 0}};
    to_mont(three, b3);
    fp_add(x3, x3, three);
    return fp_eq(y2, x3);
}

static bool g2_on_curve(const G2 &p) {
    if (p.inf) return true;
    // y^2 == x^3 + 3/xi
    Fp2 y2, x3, b2, three, xi;
    f2_sqr(y2, p.y);
    f2_sqr(x3, p.x);
    f2_mul(x3, x3, p.x);
    Fp t3 = {{3, 0, 0, 0}}, t9 = {{9, 0, 0, 0}}, t1 = {{1, 0, 0, 0}};
    to_mont(three.c0, t3);
    three.c1 = FP_ZERO;
    to_mont(xi.c0, t9);
    to_mont(xi.c1, t1);
    f2_inv(b2, xi);
    f2_mul(b2, b2, three);
    f2_add(x3, x3, b2);
    return f2_eq(y2, x3);
}

static bool decode_g1(G1 &out, const uint8_t *in) {
    if (is_zero_bytes(in, 64)) { out.inf = true; return true; }
    out.inf = false;
    if (!fp_from_be(out.x, in) || !fp_from_be(out.y, in + 32)) return false;
    return g1_on_curve(out);
}

static bool decode_g2(G2 &out, const uint8_t *in) {
    if (is_zero_bytes(in, 128)) { out.inf = true; return true; }
    out.inf = false;
    if (!fp_from_be(out.x.c0, in) || !fp_from_be(out.x.c1, in + 32) ||
        !fp_from_be(out.y.c0, in + 64) || !fp_from_be(out.y.c1, in + 96))
        return false;
    return g2_on_curve(out);
}

static void encode_g1(uint8_t *out, const G1 &p) {
    if (p.inf) { memset(out, 0, 64); return; }
    fp_to_be(out, p.x);
    fp_to_be(out + 32, p.y);
}

static void encode_g2(uint8_t *out, const G2 &p) {
    if (p.inf) { memset(out, 0, 128); return; }
    fp_to_be(out, p.x.c0);
    fp_to_be(out + 32, p.x.c1);
    fp_to_be(out + 64, p.y.c0);
    fp_to_be(out + 96, p.y.c1);
}

static void scalar_from_be(u64 *out, const uint8_t *in) {
    for (int i = 0; i < 4; i++) {
        u64 w = 0;
        for (int j = 0; j < 8; j++)
            w = (w << 8) | in[(3 - i) * 8 + j];
        out[i] = w;
    }
}

// ------------------------------------------------------------------- C ABI

extern "C" {

// prod of e(Q_i, P_i) == 1 ? 1 : 0; -1 on malformed input.
int pc_pairing_check(const uint8_t *g2s, const uint8_t *g1s, int n) {
    init_constants();
    Fp12 acc;
    memset(&acc, 0, sizeof acc);
    acc.c0.c0.c0 = FP_ONE_M;
    for (int i = 0; i < n; i++) {
        G2 q;
        G1 p;
        if (!decode_g2(q, g2s + 128 * i)) return -1;
        if (!decode_g1(p, g1s + 64 * i)) return -1;
        Fp12 f;
        // prepared path: reuse (or build) the coefficient sequence for
        // this G2 — inversion-free Miller loop on every cache hit. A
        // degenerate structure (infinity/vertical mid-ladder; impossible
        // for valid subgroup points) falls back to the generic loop.
        PreparedG2 pre;
        if (prep_cache_get(g2s + 128 * i, pre)) {
            miller_loop_prepared(f, pre, p);
        } else if (prepare_g2(pre, q)) {
            prep_cache_put(g2s + 128 * i, pre);
            miller_loop_prepared(f, pre, p);
        } else {
            miller_loop(f, q, p);
        }
        f12_mul(acc, acc, f);
    }
    Fp12 res;
    final_exp(res, acc);
    return f12_is_one(res) ? 1 : 0;
}

int pc_g1_mul(const uint8_t *in, const uint8_t *scalar, uint8_t *out) {
    init_constants();
    G1 p;
    if (!decode_g1(p, in)) return -1;
    u64 k[4];
    scalar_from_be(k, scalar);
    G1 r;
    g1_mul_pt(r, p, k);
    encode_g1(out, r);
    return 0;
}

int pc_g2_mul(const uint8_t *in, const uint8_t *scalar, uint8_t *out) {
    init_constants();
    G2 p;
    if (!decode_g2(p, in)) return -1;
    u64 k[4];
    scalar_from_be(k, scalar);
    G2 r;
    g2_mul_pt(r, p, k);
    encode_g2(out, r);
    return 0;
}

int pc_g1_add(const uint8_t *a, const uint8_t *b, uint8_t *out) {
    init_constants();
    G1 pa, pb, r;
    if (!decode_g1(pa, a) || !decode_g1(pb, b)) return -1;
    g1_add_pt(r, pa, pb);
    encode_g1(out, r);
    return 0;
}

int pc_g2_add(const uint8_t *a, const uint8_t *b, uint8_t *out) {
    init_constants();
    G2 pa, pb, r;
    if (!decode_g2(pa, a) || !decode_g2(pb, b)) return -1;
    g2_add_pt(r, pa, pb);
    encode_g2(out, r);
    return 0;
}

int pc_g2_in_subgroup(const uint8_t *in) {
    init_constants();
    G2 p;
    if (!decode_g2(p, in)) return 0;
    G2 r;
    g2_mul_pt(r, p, RL);
    return r.inf ? 1 : 0;
}

// --- differential-test surface (Fq12 laid out as 12 BE 32-byte coeffs in
// the order c0.c0.c0, c0.c0.c1, c0.c1.c0, ..., c1.c2.c1) ------------------

static void f12_to_be(uint8_t *out, const Fp12 &a) {
    const Fp *cs[12] = {&a.c0.c0.c0, &a.c0.c0.c1, &a.c0.c1.c0, &a.c0.c1.c1,
                        &a.c0.c2.c0, &a.c0.c2.c1, &a.c1.c0.c0, &a.c1.c0.c1,
                        &a.c1.c1.c0, &a.c1.c1.c1, &a.c1.c2.c0, &a.c1.c2.c1};
    for (int i = 0; i < 12; i++) fp_to_be(out + 32 * i, *cs[i]);
}

extern "C" int pc_miller(const uint8_t *g2, const uint8_t *g1, uint8_t *out) {
    init_constants();
    G2 q;
    G1 p;
    if (!decode_g2(q, g2) || !decode_g1(p, g1)) return -1;
    Fp12 f;
    miller_loop(f, q, p);
    f12_to_be(out, f);
    return 0;
}

extern "C" int pc_final_exp(const uint8_t *in, uint8_t *out) {
    init_constants();
    Fp12 f;
    Fp *cs[12] = {&f.c0.c0.c0, &f.c0.c0.c1, &f.c0.c1.c0, &f.c0.c1.c1,
                  &f.c0.c2.c0, &f.c0.c2.c1, &f.c1.c0.c0, &f.c1.c0.c1,
                  &f.c1.c1.c0, &f.c1.c1.c1, &f.c1.c2.c0, &f.c1.c2.c1};
    for (int i = 0; i < 12; i++)
        if (!fp_from_be(*cs[i], in + 32 * i)) return -1;
    Fp12 r;
    final_exp(r, f);
    f12_to_be(out, r);
    return 0;
}

}  // extern "C"
