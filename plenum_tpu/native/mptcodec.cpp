// MPT node codec: flat-list RLP encode + SHA3-256, one call.
//
// Reference behavior being replaced: the per-node `rlp.encode` +
// `hashlib.sha3_256` pair on every trie store/commit
// (state/trie/pruning_trie.py in the reference; plenum_tpu/state/trie.py
// and state/rlp.py here). Trie nodes are lists of byte strings; nodes
// with EMBEDDED (nested-list) children stay on the pure-Python twin —
// the Python caller checks flatness before dispatching here.
//
// SHA3-256 is FIPS 202 (padding 0x06), matching hashlib.sha3_256 —
// implemented in-tree so the .so needs no OpenSSL linkage.
//
// C ABI (ctypes):
//   mptc_encode_hash(n_items, lens[], concat, out_rlp, out_cap, out_hash32)
//       -> rlp length, or -1 if out_cap is too small
//   mptc_sha3_256(data, len, out32)           (differential-test surface)
//   mptc_rlp_encode(...)  encode without hashing (same args minus hash)

#include <cstdint>
#include <cstring>

// the absorb loop XORs input bytes straight into the uint64 lane array —
// correct only when lane byte order is little-endian (as Keccak specifies
// for its state serialization)
#if !defined(__BYTE_ORDER__) || __BYTE_ORDER__ != __ORDER_LITTLE_ENDIAN__
#error "mptcodec.cpp assumes a little-endian host"
#endif

namespace {

// ---------------------------------------------------------------- keccak
const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

inline uint64_t rotl(uint64_t x, int n) {
    return (x << n) | (x >> (64 - n));
}

void keccak_f(uint64_t st[25]) {
    for (int round = 0; round < 24; ++round) {
        // theta
        uint64_t bc[5];
        for (int i = 0; i < 5; ++i)
            bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
        for (int i = 0; i < 5; ++i) {
            uint64_t t = bc[(i + 4) % 5] ^ rotl(bc[(i + 1) % 5], 1);
            for (int j = 0; j < 25; j += 5) st[j + i] ^= t;
        }
        // rho + pi
        uint64_t t = st[1];
        static const int piln[24] = {10, 7,  11, 17, 18, 3,  5,  16,
                                     8,  21, 24, 4,  15, 23, 19, 13,
                                     12, 2,  20, 14, 22, 9,  6,  1};
        static const int rotc[24] = {1,  3,  6,  10, 15, 21, 28, 36,
                                     45, 55, 2,  14, 27, 41, 56, 8,
                                     25, 43, 62, 18, 39, 61, 20, 44};
        for (int i = 0; i < 24; ++i) {
            int j = piln[i];
            uint64_t tmp = st[j];
            st[j] = rotl(t, rotc[i]);
            t = tmp;
        }
        // chi
        for (int j = 0; j < 25; j += 5) {
            uint64_t b[5];
            for (int i = 0; i < 5; ++i) b[i] = st[j + i];
            for (int i = 0; i < 5; ++i)
                st[j + i] = b[i] ^ ((~b[(i + 1) % 5]) & b[(i + 2) % 5]);
        }
        st[0] ^= RC[round];
    }
}

void sha3_256(const uint8_t* data, size_t len, uint8_t out[32]) {
    const size_t rate = 136;  // 1088-bit rate for SHA3-256
    uint64_t st[25];
    std::memset(st, 0, sizeof(st));
    uint8_t* bytes = reinterpret_cast<uint8_t*>(st);
    // absorb
    while (len >= rate) {
        for (size_t i = 0; i < rate; ++i) bytes[i] ^= data[i];
        keccak_f(st);
        data += rate;
        len -= rate;
    }
    for (size_t i = 0; i < len; ++i) bytes[i] ^= data[i];
    bytes[len] ^= 0x06;        // FIPS 202 SHA3 domain padding
    bytes[rate - 1] ^= 0x80;
    keccak_f(st);
    std::memcpy(out, bytes, 32);
}

// ------------------------------------------------------------------- rlp
// length prefix into out; returns bytes written
size_t len_prefix(size_t length, uint8_t offset, uint8_t* out) {
    if (length < 56) {
        out[0] = offset + static_cast<uint8_t>(length);
        return 1;
    }
    uint8_t tmp[8];
    size_t n = 0;
    size_t v = length;
    while (v) {
        tmp[n++] = static_cast<uint8_t>(v & 0xff);
        v >>= 8;
    }
    out[0] = offset + 55 + static_cast<uint8_t>(n);
    for (size_t i = 0; i < n; ++i) out[1 + i] = tmp[n - 1 - i];
    return 1 + n;
}

// flat list of byte strings -> RLP; returns length or -1 if cap too small
long rlp_flat(int32_t n_items, const uint32_t* lens, const uint8_t* concat,
              uint8_t* out, size_t cap) {
    // worst case per item: 9-byte prefix + payload; header: 9
    if (cap < 18) return -1;   // room for header staging even when empty
    uint8_t hdr_buf[16];
    // encode items into out after a max header gap, then move
    size_t payload = 0;
    {
        size_t off = 0;
        size_t pos = 9;  // leave room for the largest possible list header
        for (int32_t i = 0; i < n_items; ++i) {
            const uint8_t* item = concat + off;
            size_t il = lens[i];
            size_t need = pos + 9 + il;
            if (need > cap) return -1;
            if (il == 1 && item[0] < 0x80) {
                out[pos++] = item[0];
            } else {
                pos += len_prefix(il, 0x80, out + pos);
                std::memcpy(out + pos, item, il);
                pos += il;
            }
            off += il;
        }
        payload = pos - 9;
    }
    size_t hl = len_prefix(payload, 0xc0, hdr_buf);
    std::memmove(out + hl, out + 9, payload);
    std::memcpy(out, hdr_buf, hl);
    return static_cast<long>(hl + payload);
}

}  // namespace

extern "C" {

void mptc_sha3_256(const uint8_t* data, uint64_t len, uint8_t* out32) {
    sha3_256(data, static_cast<size_t>(len), out32);
}

long mptc_rlp_encode(int32_t n_items, const uint32_t* lens,
                     const uint8_t* concat, uint8_t* out, uint64_t cap) {
    return rlp_flat(n_items, lens, concat, out, static_cast<size_t>(cap));
}

long mptc_encode_hash(int32_t n_items, const uint32_t* lens,
                      const uint8_t* concat, uint8_t* out, uint64_t cap,
                      uint8_t* out_hash32) {
    long n = rlp_flat(n_items, lens, concat, out, static_cast<size_t>(cap));
    if (n < 0) return n;
    sha3_256(out, static_cast<size_t>(n), out_hash32);
    return n;
}

// Batch encode+hash with BACKREFS: the trie's whole dirty set for a 3PC
// batch in ONE call (per-node ctypes dispatch measured ~2x slower than
// Python; the batch amortizes it). Nodes arrive in post-order (children
// before parents). Item tags:
//   -1  literal byte string (RLP string-encode; next chunk of concat)
//   -2  raw RLP splice (pre-encoded inline child; next chunk of concat)
//   j>=0 backref to node j: splice node j's RLP raw when it is <32 bytes
//        (an inline child, per the MPT ref rule), else string-encode its
//        32-byte SHA3 from out_hashes
// `lens` has one entry PER CHUNK (tag<0 items in order), not per item —
// the Python caller builds it with a single map(len, chunks).
// Node RLPs are written contiguously into out; out_lens[i] and
// out_hashes[32*i..] are filled for EVERY node. Returns total bytes,
// -1 on cap overflow, -2 on a forward backref.
long mptc_encode_hash_batch(int32_t n_nodes, const int32_t* item_counts,
                            const int32_t* tags, const uint32_t* lens,
                            const uint8_t* concat, uint8_t* out,
                            uint64_t cap64, uint32_t* out_lens,
                            uint8_t* out_hashes) {
    const size_t cap = static_cast<size_t>(cap64);
    uint64_t* offs = new uint64_t[n_nodes > 0 ? n_nodes : 1];
    size_t cursor = 0;     // next write position in out
    size_t item_idx = 0;
    size_t chunk_idx = 0;
    size_t data_off = 0;
    for (int32_t ni = 0; ni < n_nodes; ++ni) {
        const size_t node_off = cursor;
        size_t pos = node_off + 9;    // gap for the largest list header
        if (pos + 9 > cap) { delete[] offs; return -1; }
        for (int32_t k = 0; k < item_counts[ni]; ++k, ++item_idx) {
            const int32_t tag = tags[item_idx];
            if (tag == -1) {
                const size_t il = lens[chunk_idx++];
                if (pos + 9 + il > cap) { delete[] offs; return -1; }
                const uint8_t* item = concat + data_off;
                if (il == 1 && item[0] < 0x80) {
                    out[pos++] = item[0];
                } else {
                    pos += len_prefix(il, 0x80, out + pos);
                    std::memcpy(out + pos, item, il);
                    pos += il;
                }
                data_off += il;
            } else if (tag == -2) {
                const size_t il = lens[chunk_idx++];
                if (pos + il > cap) { delete[] offs; return -1; }
                std::memcpy(out + pos, concat + data_off, il);
                pos += il;
                data_off += il;
            } else {
                if (tag >= ni) { delete[] offs; return -2; }
                const uint32_t cl = out_lens[tag];
                if (cl < 32) {    // inline child: splice its RLP raw
                    if (pos + cl > cap) { delete[] offs; return -1; }
                    std::memcpy(out + pos, out + offs[tag], cl);
                    pos += cl;
                } else {          // hashed child: 0xa0 + 32-byte digest
                    if (pos + 33 > cap) { delete[] offs; return -1; }
                    out[pos++] = 0x80 + 32;
                    std::memcpy(out + pos,
                                out_hashes + 32 * static_cast<size_t>(tag),
                                32);
                    pos += 32;
                }
            }
        }
        const size_t payload = pos - (node_off + 9);
        uint8_t hdr[16];
        const size_t hl = len_prefix(payload, 0xc0, hdr);
        std::memmove(out + node_off + hl, out + node_off + 9, payload);
        std::memcpy(out + node_off, hdr, hl);
        const size_t total = hl + payload;
        out_lens[ni] = static_cast<uint32_t>(total);
        offs[ni] = node_off;
        sha3_256(out + node_off, total,
                 out_hashes + 32 * static_cast<size_t>(ni));
        cursor = node_off + total;
    }
    delete[] offs;
    return static_cast<long>(cursor);
}

}  // extern "C"
