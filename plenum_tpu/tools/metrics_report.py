"""Operator-facing analyzer for flushed node metrics.

Reference behavior: scripts/process_logs + scripts/log_stats — turn a
node's on-disk metrics history into per-metric statistics and a derived
health summary an operator can read. Here the source is the msgpack rows
a KvMetricsCollector flushes (common/metrics.py), one store per node at
<base-dir>/<name>/metrics (written by tools.start_node).

    python -m plenum_tpu.tools.metrics_report <base-dir> [--node Node1]
        [--last 300] [--json]

With no --node, every `<base-dir>/*/metrics` store found is reported
(and the derived pool summary aggregates across them).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def read_store(path: str) -> list[tuple[float, str, dict]]:
    """metrics dir -> [(ts, name, fold)] sorted by ts. GENUINELY
    read-only: never truncates a torn tail or compacts, so it is safe to
    run against a store a live node is appending to."""
    from plenum_tpu.common.metrics import rows_from_kv_items
    from plenum_tpu.storage.kv_file import read_log_readonly
    return rows_from_kv_items(read_log_readonly(path))


def fold_rows(rows: list[tuple[float, str, dict]]) -> dict[str, dict]:
    """Merge per-flush folds into one per-metric fold over the window.

    Each stored fold is {count, sum, min, max} (Accumulator.to_dict).
    `last` keeps the most recent flush's mean — the right reading for
    gauges sampled at flush time (queue depths, RSS).
    """
    out: dict[str, dict] = {}
    for ts, name, fold in rows:
        agg = out.setdefault(name, {
            "count": 0, "sum": 0.0, "min": None, "max": None,
            "first_ts": ts, "last_ts": ts, "last": None, "flushes": 0})
        agg["count"] += fold.get("count", 0)
        agg["sum"] += fold.get("sum", 0.0)
        for k, pick in (("min", min), ("max", max)):
            v = fold.get(k)
            if v is not None:
                agg[k] = v if agg[k] is None else pick(agg[k], v)
        agg["last_ts"] = ts
        agg["flushes"] += 1
        if fold.get("count"):
            agg["last"] = fold["sum"] / fold["count"]
        # commit-path stage rows carry bounded raw samples (metrics.py
        # SAMPLED_NAMES) so the report can print honest p50/p95
        if fold.get("samples"):
            agg.setdefault("samples", []).extend(fold["samples"][:4096])
    for agg in out.values():
        agg["mean"] = agg["sum"] / agg["count"] if agg["count"] else None
    return out


def merge_node_folds(per_node: dict[str, dict[str, dict]]
                     ) -> dict[str, dict]:
    """{node: folds} -> ONE pool-wide folds dict.

    Counts/sums add, min/max fold, and — the part that matters for
    percentiles — the nodes' sampled reservoirs are MERGED (concatenated)
    so pool p50/p95 is computed over the union of samples. Averaging
    per-node percentiles is wrong whenever node distributions differ
    (mean(p95_a, p95_b) is not p95(a ∪ b): two nodes at 1 ms and 100 ms
    "average" to a 50 ms pool p50 that no request ever saw); each node's
    reservoir is an unbiased sample of its own stream, so their union is
    an unbiased sample of the pool stream when streams are comparable in
    size — and honest about modality either way. Pinned by
    tests/test_telemetry.py with deliberately diverging nodes."""
    out: dict[str, dict] = {}
    for _node, folds in sorted(per_node.items()):
        for name, agg in folds.items():
            tgt = out.setdefault(name, {
                "count": 0, "sum": 0.0, "min": None, "max": None,
                "first_ts": agg.get("first_ts"),
                "last_ts": agg.get("last_ts"),
                "last": None, "flushes": 0})
            tgt["count"] += agg.get("count", 0)
            tgt["sum"] += agg.get("sum", 0.0)
            tgt["flushes"] += agg.get("flushes", 0)
            for k, pick in (("min", min), ("max", max),
                            ("first_ts", min), ("last_ts", max)):
                v = agg.get(k)
                if v is not None:
                    tgt[k] = v if tgt[k] is None else pick(tgt[k], v)
            # "last" keeps the newest node's flush-gauge reading
            if agg.get("last") is not None and (
                    tgt["last"] is None
                    or (agg.get("last_ts") or 0) >= (tgt.get("_last_at")
                                                     or float("-inf"))):
                tgt["last"] = agg["last"]
                tgt["_last_at"] = agg.get("last_ts") or 0
            if agg.get("samples"):
                tgt.setdefault("samples", []).extend(agg["samples"])
    for tgt in out.values():
        tgt.pop("_last_at", None)
        tgt["mean"] = tgt["sum"] / tgt["count"] if tgt["count"] else None
    return out


def pool_summary(per_node: dict[str, dict[str, dict]]) -> dict:
    """Pool-wide derived summary over MERGED folds (see merge_node_folds
    — pool percentiles come from the union of the nodes' reservoirs,
    never from averaging per-node percentiles).

    Two classes of figures need more than the merge:

    * the ordered stream is REPLICATED — every node orders the same
      txns, so merged ordered counts are n_nodes x the pool's real
      stream; txns_ordered/tps are de-replicated here;
    * cumulative host gauges (transport bytes, dropped frames) total
      per NODE — the fleet figure is the SUM of per-node run totals,
      and per-host gauges (RSS, GC pause) are reported as the WORST
      node, never as a pool single."""
    merged = merge_node_folds(per_node)
    firsts = [f.get("first_ts") for fs in per_node.values()
              for f in fs.values() if f.get("first_ts") is not None]
    lasts = [f.get("last_ts") for fs in per_node.values()
             for f in fs.values() if f.get("last_ts") is not None]
    span = (max(lasts) - min(firsts)) if firsts and lasts else 0.0
    out = derive_summary(merged, span)
    n = len(per_node)
    out["nodes"] = n

    if n > 1:
        out["txns_ordered"] = int(out["txns_ordered"] / n)
        if out.get("tps"):
            out["tps"] = round(out["tps"] / n, 1)
        # the division assumes ONE replicated stream across all node
        # dirs; a base dir spanning shards (different streams per
        # sub-pool) needs per-shard runs — flag the assumption so the
        # figure can't be read as shard-aware
        out["ordered_dedup"] = "assumes one replicated stream " \
                               "(run per shard for sharded base dirs)"

    def node_cums(name):            # per-node run totals (max = total)
        vals = [fs.get(name, {}).get("max") for fs in per_node.values()]
        return [v for v in vals if v is not None]

    for direction in ("tx", "rx"):
        totals = node_cums(f"transport.{direction}_bytes")
        if totals:
            out[f"transport_{direction}_bytes"] = int(sum(totals))
            if out["txns_ordered"]:
                out[f"transport_{direction}_bytes_per_txn"] = round(
                    sum(totals) / out["txns_ordered"])
    for key, name in (("transport_dropped_frames",
                       "transport.dropped_frames"),
                      ("transport_dropped_sessions",
                       "transport.dropped_sessions")):
        if key in out:
            out[key] = int(sum(node_cums(name)))
    if "propagate_tx_bytes_per_txn" in out and out["txns_ordered"]:
        prop = sum(node_cums("transport.tx.PROPAGATE")) \
            + sum(node_cums("transport.tx.PROPAGATE_BATCH"))
        out["propagate_tx_bytes_per_txn"] = round(
            prop / out["txns_ordered"])
    # per-host gauges: one pool figure is meaningless — name the worst
    for drop, worst_key, vals in (
            ("rss_mb_last", "rss_mb_max_node",
             [v / 1e6 for v in node_cums("process.rss_bytes")]),
            ("gc_pause_s", "gc_pause_s_max_node",
             node_cums("process.gc_pause_time"))):
        out.pop(drop, None)
        if vals:
            out[worst_key] = round(max(vals), 2)
    out.pop("gc_pause_pct", None)
    return out


def derive_summary(folds: dict[str, dict], span_s: float,
                   windowed: bool = False) -> dict:
    """Pool-health figures an operator actually asks for."""
    def s(name):            # total over window
        return folds.get(name, {}).get("sum") or 0.0

    def mean(name):
        return folds.get(name, {}).get("mean")

    def last(name):
        return folds.get(name, {}).get("last")

    txns = s("node.ordered_batch_size")
    # gc_pause_time is a CUMULATIVE counter sampled at each flush. Full
    # run: the latest value (max) IS the run's total, since the timer
    # starts at 0 with the process. Trailing window: the delta across
    # the window's flushes.
    gp = folds.get("process.gc_pause_time", {})
    if windowed and gp.get("flushes", 0) > 1:
        gc_pause = (gp.get("max") or 0.0) - (gp.get("min") or 0.0)
    else:
        gc_pause = gp.get("max") or 0.0
    out = {
        "window_s": round(span_s, 1),
        "txns_ordered": int(txns),
        "tps": round(txns / span_s, 1) if span_s > 0 else None,
        "mean_batch_size": mean("node.ordered_batch_size"),
        "prepare_phase_ms": _ms(mean("consensus.prepare_phase_time")),
        "commit_phase_ms": _ms(mean("consensus.commit_phase_time")),
        "ordering_ms": _ms(mean("consensus.ordering_time")),
        "view_changes": int(s("consensus.view_changes")),
        "suspicions": int(s("consensus.suspicions")),
        "catchups": int(s("consensus.catchups")),
        "client_inbox_depth_max": folds.get("node.client_inbox_depth",
                                            {}).get("max"),
        "propagate_inbox_depth_max": folds.get("node.propagate_inbox_depth",
                                               {}).get("max"),
        "request_queue_depth_max": folds.get("consensus.request_queue_depth",
                                             {}).get("max"),
        "request_queue_depth_mean": mean("consensus.request_queue_depth"),
        "gc_pause_s": round(gc_pause, 2),
        "gc_pause_pct": round(100 * gc_pause / span_s, 2) if span_s else None,
        "rss_mb_last": (last("process.rss_bytes") or 0) / 1e6 or None,
    }

    def cum(name):          # cumulative gauge: latest value = max
        return folds.get(name, {}).get("max")

    # transport silent-loss + byte totals (cumulative TcpStack gauges);
    # dropped counters are reported even at 0 ONCE the stack emits them —
    # "no drops recorded" and "drops metric absent" must read differently
    if "transport.dropped_frames" in folds:
        out["transport_dropped_frames"] = int(cum("transport.dropped_frames"))
        out["transport_dropped_sessions"] = int(
            cum("transport.dropped_sessions") or 0)
    for direction in ("tx", "rx"):
        total = cum(f"transport.{direction}_bytes")
        if total is not None:
            out[f"transport_{direction}_bytes"] = int(total)
            if txns:
                out[f"transport_{direction}_bytes_per_txn"] = round(
                    total / txns)
    propagate_tx = cum("transport.tx.PROPAGATE")
    batch_tx = cum("transport.tx.PROPAGATE_BATCH")
    if (propagate_tx is not None or batch_tx is not None) and txns:
        out["propagate_tx_bytes_per_txn"] = round(
            ((propagate_tx or 0) + (batch_tx or 0)) / txns)

    # post-ordering critical path: per-stage p50/p95 from the raw samples
    # the commit-path timers flush (bls-verify / apply / durable / reply) —
    # a latency regression must localize to a stage, not hide in a mean
    from plenum_tpu.common.metrics import percentile
    for stage in ("bls_verify", "apply", "commit_wave", "durable", "reply"):
        f = folds.get(f"commit_path.{stage}_time", {})
        samples = f.get("samples")
        if samples:
            out[f"{stage}_ms_p50"] = _ms(percentile(samples, 0.5))
            out[f"{stage}_ms_p95"] = _ms(percentile(samples, 0.95))
        elif f.get("mean") is not None:
            out[f"{stage}_ms_mean"] = _ms(f["mean"])
    # batched-BLS acceptance counter: Miller loops per ordered batch
    # (amortized O(1) target: ~2 for a same-message commit set)
    ppb = folds.get("crypto.pairings_per_batch", {})
    if ppb.get("mean") is not None:
        out["pairings_per_batch"] = round(ppb["mean"], 2)
    if "crypto.pairing_checks" in folds:
        out["pairing_checks_total"] = int(cum("crypto.pairing_checks") or 0)
        out["pairings_total"] = int(cum("crypto.pairings") or 0)
    # group-commit coalescing: ordered batches riding one durable flush
    gcb = folds.get("node.group_commit_batches", {})
    if gcb.get("mean") is not None:
        out["group_commit_batches_mean"] = round(gcb["mean"], 2)
    # device-plane observability: dispatch counter (sharded plane) +
    # coalescing-verifier batch stats, which existed as attributes/events
    # but never reached this report
    if "crypto.plane_dispatches" in folds:
        out["plane_dispatches"] = int(cum("crypto.plane_dispatches") or 0)
    sbs = folds.get("crypto.sig_batch_size", {})
    if sbs.get("mean") is not None:
        out["sig_batch_size_mean"] = round(sbs["mean"], 1)
        out["sig_batches_dispatched"] = int(sbs.get("count") or 0)
    if mean("crypto.sig_dispatch_time") is not None:
        out["sig_dispatch_ms_mean"] = _ms(mean("crypto.sig_dispatch_time"))
    if mean("crypto.sig_batch_fill_time") is not None:
        out["sig_batch_fill_ms_mean"] = _ms(
            mean("crypto.sig_batch_fill_time"))
    # plane supervisor: the degraded-mode story an operator actually
    # checks — breaker state (latest gauge), fallback volume, hedge wins,
    # deadline misses, and the dispatch-budget distribution p50/p95
    # (docs/robustness.md "Degraded modes of the crypto plane")
    bs = folds.get("crypto.breaker_state", {})
    if bs.get("last") is not None:
        out["crypto_breaker_state"] = {0: "closed", 1: "half_open",
                                       2: "open"}.get(int(bs["last"]),
                                                      "unknown")
        out["crypto_breaker_opens"] = int(cum("crypto.breaker_opens") or 0)
        out["crypto_fallback_batches"] = int(
            cum("crypto.fallback_batches") or 0)
        out["crypto_fallback_items"] = int(
            cum("crypto.fallback_items") or 0)
        out["crypto_hedge_wins"] = int(cum("crypto.hedge_wins") or 0)
        out["crypto_deadline_misses"] = int(
            cum("crypto.deadline_misses") or 0)
    budget = folds.get("crypto.dispatch_budget", {})
    if budget.get("samples"):
        out["deadline_ms_p50"] = _ms(percentile(budget["samples"], 0.5))
        out["deadline_ms_p95"] = _ms(percentile(budget["samples"], 0.95))
    if "crypto.bls_batch_fallbacks" in folds:
        out["bls_batch_fallbacks"] = int(
            cum("crypto.bls_batch_fallbacks") or 0)
    if "crypto.bls_local_fallbacks" in folds:
        out["bls_local_fallbacks"] = int(
            cum("crypto.bls_local_fallbacks") or 0)
    # fused crypto pipeline (docs/performance.md "Fused device-resident
    # crypto pipeline"): dispatch volume, coalesced items per dispatch
    # (the cross-stage amortization figure), the ring's dedup ratio, pad
    # waste, bucket hit rate, and the steering knobs' latest positions.
    # A rising compiled_shapes after warmup is the recompile-guard alarm.
    pd = folds.get("pipeline.dispatches", {})
    if pd.get("max") is not None:
        section = {
            "dispatches": int(cum("pipeline.dispatches") or 0),
            "dedup_ratio": folds.get("pipeline.dedup_ratio",
                                     {}).get("last"),
            "bucket_hit_rate": folds.get("pipeline.bucket_hit_rate",
                                         {}).get("last"),
            "compiled_shapes": int(
                cum("pipeline.compiled_shapes") or 0),
        }
        ipd = folds.get("pipeline.items_per_dispatch", {})
        if ipd.get("mean") is not None:
            section["items_per_dispatch_mean"] = round(ipd["mean"], 1)
        pw = folds.get("pipeline.pad_waste", {})
        if pw.get("mean") is not None:
            section["pad_waste_mean"] = round(pw["mean"], 3)
        occ = folds.get("pipeline.occupancy", {})
        if occ.get("mean") is not None:
            section["occupancy_mean"] = round(occ["mean"], 1)
            section["occupancy_max"] = occ.get("max")
        pctl = folds.get("pipeline_ctl.flush_wait", {})
        if pctl.get("last") is not None:
            section["controller"] = {
                "flush_wait_ms": _ms(pctl["last"]),
                "bucket_floor": int(folds.get(
                    "pipeline_ctl.bucket_floor", {}).get("last") or 0),
                "decisions": int(cum("pipeline_ctl.decisions") or 0),
            }
        # multi-device ring (docs/performance.md "Multi-device crypto
        # pipeline"): lane count, how many chip breakers are open RIGHT
        # NOW, worst lane backlog, and the dispatch spread (max/mean
        # per-lane dispatches — 1.0 = perfectly even placement; a
        # rising spread means traffic is queueing on one chip)
        lanes = folds.get("pipeline_dev.lanes", {})
        if lanes.get("last"):
            section["devices"] = {
                "lanes": int(lanes["last"]),
                "breakers_open": int(folds.get(
                    "pipeline_dev.breakers_open", {}).get("last") or 0),
                "occupancy_max": folds.get(
                    "pipeline_dev.occupancy_max", {}).get("max"),
                "dispatch_spread": folds.get(
                    "pipeline_dev.dispatch_spread", {}).get("last"),
            }
        # commit-wave (cmt) lane (docs/performance.md "Device-resident
        # ordering"): fused triple-root recommit waves, items and tree
        # levels per run, and how many waves degraded to host recommit —
        # a rising host_fallbacks is the commit-path breaker alarm
        cw = folds.get("pipeline_cmt.waves", {})
        if cw.get("max"):
            section["commit_wave"] = {
                "waves": int(cum("pipeline_cmt.waves") or 0),
                "items": int(cum("pipeline_cmt.items") or 0),
                "levels": int(cum("pipeline_cmt.levels") or 0),
                "host_fallbacks": int(
                    cum("pipeline_cmt.host_fallbacks") or 0),
            }
        # cross-host federation (docs/performance.md "Cross-host crypto
        # federation"): rented remote-host lanes, how much work migrated
        # between backlogged lanes, open remote breakers RIGHT NOW, and
        # the remote dispatch->verdict ship latency — a rising
        # remote_breakers_open means rented capacity is dark and the
        # ring is running host-local
        fl = folds.get("pipeline_fed.remote_lanes", {})
        if fl.get("last"):
            section["federation"] = {
                "remote_lanes": int(fl["last"]),
                "steals": int(folds.get(
                    "pipeline_fed.steals", {}).get("last") or 0),
                "stolen_items": int(folds.get(
                    "pipeline_fed.stolen_items", {}).get("last") or 0),
                "remote_breakers_open": int(folds.get(
                    "pipeline_fed.remote_breakers_open",
                    {}).get("last") or 0),
                "ship_ms_p95": folds.get(
                    "pipeline_fed.ship_ms_p95", {}).get("last"),
            }
        out["crypto_pipeline"] = {k: v for k, v in section.items()
                                  if v is not None}
    # closed-loop batch controller (docs/performance.md "Pipelined
    # ordering"): where the steered knobs sit (latest gauge) and how many
    # decisions the loop has made — a flat decision count under load
    # means the loop is not seeing samples (wrong node, or disabled)
    ctl_size = folds.get("batch_ctl.size", {})
    if ctl_size.get("last") is not None:
        out["batch_controller"] = {
            "batch_size": int(ctl_size["last"]),
            "wait_ms": _ms(folds.get("batch_ctl.wait", {}).get("last")),
            "depth": int(folds.get("batch_ctl.depth", {}).get("last") or 0),
            "coalesce": int(
                folds.get("batch_ctl.coalesce", {}).get("last") or 0),
            "decisions": int(cum("batch_ctl.decisions") or 0),
        }
    # verified read plane (docs/reads.md): volume, cache effectiveness,
    # proof mix, and the proof-generation stage p50/p95 — a read-latency
    # regression must localize to proof gen vs everything else, and a
    # rising proofless share is the operator's signal that clients are
    # paying the f+1 broadcast fallback
    rq = folds.get("read_plane.queries", {})
    if rq.get("count"):
        queries = rq.get("sum") or 0.0
        hits = cum("read_plane.cache_hits") or 0
        section = {
            "queries": int(queries),
            "reads_per_s": round(queries / span_s, 1) if span_s > 0
            else None,
            "cache_hits": int(hits),
            "cache_hit_rate": round(hits / queries, 3) if queries
            else None,
            "proofs_state": int(cum("read_plane.proofs_state") or 0),
            "proofs_merkle": int(cum("read_plane.proofs_merkle") or 0),
            "proofs_verkle": int(cum("read_plane.proofs_verkle") or 0),
            "proofless": int(cum("read_plane.proofless") or 0),
            "anchor_updates": int(
                cum("read_plane.anchor_updates") or 0),
            # one event per tick batch carries len(batch): the mean IS
            # the mean queries-per-tick batch size
            "batch_size_mean": rq.get("mean"),
        }
        gen = folds.get("read_plane.proof_gen_time", {})
        if gen.get("samples"):
            section["proof_gen_ms_p50"] = _ms(
                percentile(gen["samples"], 0.5))
            section["proof_gen_ms_p95"] = _ms(
                percentile(gen["samples"], 0.95))
        elif gen.get("mean") is not None:
            section["proof_gen_ms_mean"] = _ms(gen["mean"])
        # per-kind envelope bytes: what a verified read costs the client
        # to download — the bytes-per-read A/B (config13) reads THESE
        for kind in ("state", "state_multi", "merkle", "verkle",
                     "verkle_multi"):
            pb = folds.get(f"read_plane.proof_bytes_{kind}", {})
            if pb.get("samples"):
                section[f"proof_bytes_{kind}_p50"] = int(
                    percentile(pb["samples"], 0.5))
                section[f"proof_bytes_{kind}_p95"] = int(
                    percentile(pb["samples"], 0.95))
            elif pb.get("mean") is not None:
                section[f"proof_bytes_{kind}_mean"] = int(pb["mean"])
        out["read_plane"] = {k: v for k, v in section.items()
                             if v is not None}
    # ingress plane (docs/ingress.md): admission vs shed volume, the
    # queue-depth and queue-wait distributions an overloaded front door
    # shows first, the auth batch-size histogram the amortization claim
    # rides on, per-client fairness spread, and where the admission
    # controller's knobs ended up
    adm = folds.get("ingress.admitted", {})
    if adm.get("count") or folds.get("ingress.shed", {}).get("count"):
        section = {
            "admitted": int(s("ingress.admitted")),
            "shed": int(s("ingress.shed")),
            "auth_failed": int(s("ingress.auth_fail")),
            "active_clients_last": last("ingress.clients"),
        }
        for metric, label, scale in (
                ("ingress.queue_depth", "queue_depth", 1.0),
                ("ingress.queue_wait", "queue_wait_ms", 1000.0),
                ("ingress.auth_batch", "auth_batch", 1.0)):
            f = folds.get(metric, {})
            samples = f.get("samples")
            if samples:
                section[f"{label}_p50"] = round(
                    percentile(samples, 0.5) * scale, 2)
                section[f"{label}_p95"] = round(
                    percentile(samples, 0.95) * scale, 2)
            elif f.get("mean") is not None:
                section[f"{label}_mean"] = round(f["mean"] * scale, 2)
        ab = folds.get("ingress.auth_batch", {})
        if ab.get("count"):
            section["auth_batches"] = int(ab["count"])
            section["auth_batch_mean"] = round(ab["mean"], 1)
        fs = folds.get("ingress.fairness_spread", {})
        if fs.get("mean") is not None:
            # 1.0 = perfectly even per-batch split across active clients
            section["fairness_spread_mean"] = round(fs["mean"], 2)
            section["fairness_spread_max"] = round(fs.get("max") or 0, 2)
        ctl = folds.get("ingress_ctl.admit_max", {})
        if ctl.get("last") is not None:
            section["controller"] = {
                "admit_max": int(ctl["last"]),
                "watermark": int(
                    folds.get("ingress_ctl.watermark", {}).get("last")
                    or 0),
                "decisions": int(cum("ingress_ctl.decisions") or 0),
            }
        out["ingress"] = {k: v for k, v in section.items()
                          if v is not None}
    # sharding plane (docs/sharding.md): routing volume + per-shard
    # ordering, the cross-shard read ledger (attempts, verified OKs,
    # mapping-proof failures — a rising failure count is the operator's
    # forged/stale-map alarm), and the client-side composed-verification
    # p50/p95 (mapping inclusion + directory pairing + shard anchor)
    sr = folds.get("shards.routed", {})
    if sr.get("count") or folds.get("shards.cross_reads", {}).get("count"):
        section = {
            "routed": int(s("shards.routed")),
            "unroutable": int(s("shards.unroutable")),
            "cross_shard_reads": int(s("shards.cross_reads")),
            "cross_shard_reads_ok": int(s("shards.cross_reads_ok")),
            "map_proof_failures": int(s("shards.map_proof_failures")),
        }
        ob = folds.get("shards.ordered_batches", {})
        if ob.get("count"):
            # one event per shard per snapshot, value = that shard's
            # newly ordered txns since the previous snapshot: sum is
            # the exact total ordered, mean the mean per-shard
            # increment, max the busiest shard's single-poll burst
            section["ordered_total"] = int(ob.get("sum") or 0)
            section["ordered_per_shard_mean"] = round(ob["mean"], 1)
            section["ordered_per_shard_max"] = ob.get("max")
        cv = folds.get("shards.cross_verify_time", {})
        if cv.get("samples"):
            section["cross_verify_ms_p50"] = _ms(
                percentile(cv["samples"], 0.5))
            section["cross_verify_ms_p95"] = _ms(
                percentile(cv["samples"], 0.95))
        elif cv.get("mean") is not None:
            section["cross_verify_ms_mean"] = _ms(cv["mean"])
        # elastic resharding + cross-shard write 2PC (shards/reshard.py,
        # shards/cross_write.py): migration volume, the copy cursor's
        # replays, handoff forwards, fail-closed stale NACKs, the front
        # door's dead-shard fast-NACKs, and the 2PC outcome ledger —
        # zero half-commits is the invariant, so aborts are a first-
        # class figure, not a failure smell
        for key, name in (("reshard_migrations", "shards.reshard_migrations"),
                          ("reshard_copied", "shards.reshard_copied"),
                          ("reshard_forwarded", "shards.reshard_forwarded"),
                          ("reshard_stale_nacks",
                           "shards.reshard_stale_nacks"),
                          ("fast_nacked", "shards.fast_nacks"),
                          ("cross_writes", "shards.xsw_begun"),
                          ("cross_write_commits", "shards.xsw_commits"),
                          ("cross_write_aborts", "shards.xsw_aborts")):
            if folds.get(name, {}).get("count"):
                section[key] = int(s(name))
        out["shards"] = {k: v for k, v in section.items()
                         if v is not None}
    # observer read fan-out: push intake + anchor verification verdicts
    # and the stale-suppression count (proofless escalations to the pool)
    if folds.get("observer.pushes", {}).get("count"):
        out["observer_reads"] = {
            "pushes": int(s("observer.pushes")),
            "ms_adopted": int(s("observer.ms_adopted")),
            "ms_rejected": int(s("observer.ms_rejected")),
            "stale_suppressed": int(s("observer.stale_suppressed")),
        }
    # view-change robustness (docs/robustness.md "Degraded WAN and
    # membership churn"): whole-episode durations p50/p95 + the phase
    # decomposition — a churn regression must read as a p95 shift here,
    # not as an anecdote in a fuzz log
    vcd = folds.get("view_change.duration", {})
    if vcd.get("count"):
        section = {"episodes": int(vcd["count"])}
        if vcd.get("samples"):
            section["duration_s_p50"] = round(
                percentile(vcd["samples"], 0.5), 2)
            section["duration_s_p95"] = round(
                percentile(vcd["samples"], 0.95), 2)
        elif vcd.get("mean") is not None:
            section["duration_s_mean"] = round(vcd["mean"], 2)
        for phase, label in (
                ("consensus.vc_detect_to_vote", "detect_to_vote_s"),
                ("consensus.vc_vote_to_start", "vote_to_start_s"),
                ("consensus.vc_start_to_new_view", "start_to_new_view_s"),
                ("consensus.vc_new_view_to_order", "new_view_to_order_s")):
            f = folds.get(phase, {})
            if f.get("mean") is not None:
                section[label] = round(f["mean"], 2)
        out["view_change"] = section
    # catchup robustness: durations/rounds p50/p95 plus the watchdog's
    # provider switches and kicks, and the terminal degraded flag
    cd = folds.get("catchup.duration", {})
    if cd.get("count") or "catchup.watchdog_kicks" in folds:
        section = {"completed": int(cd.get("count") or 0)}
        if cd.get("samples"):
            section["duration_s_p50"] = round(
                percentile(cd["samples"], 0.5), 2)
            section["duration_s_p95"] = round(
                percentile(cd["samples"], 0.95), 2)
        elif cd.get("mean") is not None:
            section["duration_s_mean"] = round(cd["mean"], 2)
        rounds = folds.get("catchup.rounds", {})
        if rounds.get("samples"):
            section["request_rounds_p95"] = round(
                percentile(rounds["samples"], 0.95), 1)
        elif rounds.get("mean") is not None:
            section["request_rounds_mean"] = round(rounds["mean"], 1)
        section["provider_switches"] = int(
            s("catchup.provider_switches"))
        section["watchdog_kicks"] = int(s("catchup.watchdog_kicks"))
        if folds.get("catchup.degraded", {}).get("max"):
            section["read_only_degraded"] = True
        out["catchup"] = {k: v for k, v in section.items()
                          if v is not None}
    # membership churn: registry-change volume, the validator-count
    # trajectory, and BLS key rotations (each one evicts the old key
    # from the crypto planes' key tables)
    mc = folds.get("membership.pool_changes", {})
    if mc.get("count"):
        vals = folds.get("membership.validators", {})
        out["membership"] = {
            "pool_changes": int(s("membership.pool_changes")),
            "validators_last": int(vals["last"])
            if vals.get("last") is not None else None,
            "validators_min": int(vals["min"])
            if vals.get("min") is not None else None,
            "validators_max": int(vals["max"])
            if vals.get("max") is not None else None,
            "key_rotations": int(s("membership.key_rotations")),
        }
        out["membership"] = {k: v for k, v in out["membership"].items()
                             if v is not None}
    return {k: v for k, v in out.items() if v is not None}


def _ms(v):
    return round(v * 1000, 2) if v is not None else None


def report_node(path: str, last_s: float | None):
    rows = read_store(path)
    if last_s and rows:
        cutoff = rows[-1][0] - last_s
        rows = [r for r in rows if r[0] >= cutoff]
    folds = fold_rows(rows)
    span = (rows[-1][0] - rows[0][0]) if len(rows) > 1 else 0.0
    return folds, derive_summary(folds, span, windowed=last_s is not None)


def _print_table(folds: dict[str, dict]) -> None:
    hdr = f"{'metric':42} {'count':>8} {'mean':>12} {'min':>10} {'max':>10}"
    print(hdr)
    print("-" * len(hdr))
    for name in sorted(folds):
        a = folds[name]
        fmt = lambda v: f"{v:.4g}" if isinstance(v, (int, float)) else "-"
        print(f"{name:42} {a['count']:>8} {fmt(a['mean']):>12}"
              f" {fmt(a['min']):>10} {fmt(a['max']):>10}")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("base_dir")
    ap.add_argument("--node", default=None,
                    help="single node name (default: all found)")
    ap.add_argument("--last", type=float, default=None, metavar="SECONDS",
                    help="only the trailing window")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.node:
        paths = [os.path.join(args.base_dir, args.node, "metrics")]
    else:
        paths = sorted(glob.glob(os.path.join(args.base_dir, "*", "metrics")))
    paths = [p for p in paths if os.path.isdir(p)]
    if not paths:
        print(json.dumps({"error": f"no metrics stores under {args.base_dir}"}))
        return 1

    all_out = {}
    per_node_folds: dict[str, dict] = {}
    for p in paths:
        name = os.path.basename(os.path.dirname(p))
        folds, summary = report_node(p, args.last)
        per_node_folds[name] = folds
        all_out[name] = {"summary": summary,
                         "metrics": {k: {kk: vv for kk, vv in v.items()
                                         if kk in ("count", "mean", "min",
                                                   "max", "last")}
                                     for k, v in folds.items()}}
        if not args.json:
            print(f"\n=== {name} ===")
            _print_table(folds)
            print("\nderived:", json.dumps(summary, indent=2))
    if len(per_node_folds) > 1:
        # pool-wide summary over MERGED folds: counts are fleet totals
        # (sums across nodes) and percentiles come from the union of the
        # nodes' sampled reservoirs — never from averaging per-node
        # percentiles (merge_node_folds)
        pool = pool_summary(per_node_folds)
        all_out["_pool"] = {"summary": pool}
        if not args.json:
            print(f"\n=== pool ({pool['nodes']} nodes, merged) ===")
            print(json.dumps(pool, indent=2))
    if args.json:
        print(json.dumps(all_out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
