"""Run a real-time N-node pool in one process and measure write throughput.

This is the framework's equivalent of standing up the reference's 4-node local
pool under NYM load and reading the Monitor (BASELINE.md's prescription for
producing the north-star numbers). Nodes are real Node instances over
SimNetwork with microsecond latencies; time is REAL (QueueTimer over
perf_counter), so the printed TPS/latency are wall-clock measurements of the
full pipeline: client authN -> propagate quorum -> 3PC (with BLS signing and
order-time aggregate verification) -> execute -> REPLY.

Usage:  python -m plenum_tpu.tools.local_pool --nodes 4 --txns 200 \
            --backend cpu|jax [--json]
"""
from __future__ import annotations

import argparse
import json
import statistics
import time


def pool_bls_keys(names) -> dict:
    """node name -> BLS verkey under the name-seeded derivation every
    in-process genesis uses (build_genesis below, tests/test_pool.py).
    THE one copy: a verifying read client fed keys derived any other way
    would silently reject every proof and fall back to broadcast."""
    from plenum_tpu.crypto.bls import BlsCryptoSigner
    return {n: BlsCryptoSigner(seed=n.encode().ljust(32, b"\0")[:32]).pk
            for n in names}


def build_genesis(names, node_data_extra=None):
    """Pool + domain genesis txns for a named node set -> (genesis, trustee).

    node_data_extra: optional {name: dict} merged into each NODE txn's data
    (the TCP runner adds node_ip/node_port/client_ip/client_port here, the
    same fields the reference pool ledger carries)."""
    from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID,
                                                 POOL_LEDGER_ID)
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution import txn as txn_lib
    from plenum_tpu.execution.txn import NODE, NYM, TRUSTEE

    trustee = Ed25519Signer(seed=b"local-pool-trustee".ljust(32, b"\0"))
    bls_keys = pool_bls_keys(names)
    pool_txns = []
    for i, name in enumerate(names):
        data = {"alias": name, "services": ["VALIDATOR"],
                "blskey": bls_keys[name]}
        if node_data_extra and name in node_data_extra:
            data.update(node_data_extra[name])
        txn = txn_lib.new_txn(NODE, {"dest": f"{name}Dest", "data": data})
        # genesis nodes are steward-owned by the trustee so owner-only
        # NODE edits (BLS key rotation, readdressing) are exercisable
        # against a genesis pool (churn soak, membership fuzz)
        txn["txn"].setdefault("metadata", {})["from"] = trustee.identifier
        txn_lib.set_seq_no(txn, i + 1)
        pool_txns.append(txn)
    nym = txn_lib.new_txn(NYM, {"dest": trustee.identifier,
                                "verkey": trustee.verkey_b58,
                                "role": TRUSTEE})
    txn_lib.set_seq_no(nym, 1)
    return {POOL_LEDGER_ID: pool_txns, DOMAIN_LEDGER_ID: [nym]}, trustee


def build_pool(n_nodes: int, backend: str, seed: int = 1,
               trace: bool = False, config_overrides: dict = None):
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID, Reply
    from plenum_tpu.common.timer import QueueTimer
    from plenum_tpu.common.tracing import Tracer
    from plenum_tpu.config import Config
    from plenum_tpu.network import SimNetwork, SimRandom
    from plenum_tpu.node import Node, NodeBootstrap

    names = [f"Node{i + 1}" for i in range(n_nodes)]
    genesis, trustee = build_genesis(names)

    timer = QueueTimer(time.perf_counter)
    net = SimNetwork(timer, SimRandom(seed))
    net.set_latency(0.00005, 0.0002)       # LAN-ish, not the sim default 0.5s
    # 50ms partial-batch wait measured best here (fewer, fuller 3PC
    # batches amortize the per-batch BLS sign+aggregate-verify; p99
    # halves vs 5ms while p50 holds)
    config = Config(Max3PCBatchWait=0.05, crypto_backend=backend,
                    STATE_FRESHNESS_UPDATE_INTERVAL=600.0,
                    **(config_overrides or {}))
    replies: dict[str, list] = {n: [] for n in names}
    nodes = {}
    # co-hosted nodes share ONE crypto plane: the verify kernel is
    # serial-depth bound, so n_nodes small dispatches per cycle cost
    # ~n_nodes times one combined dispatch. With CRYPTO_PIPELINE (the
    # default) that plane is the fused pipeline ring — client-auth
    # Ed25519, BLS batch checks, AND Merkle hashing all coalesce/dedup
    # across the co-hosted nodes; otherwise the legacy Ed25519-only
    # CoalescingVerifier.
    plane = None
    pipeline = None
    if backend == "jax-percall":
        # A/B baseline arm (bench_configs.config8_pipeline_ab): every node
        # runs its own supervised device verifier and every call site's
        # batch dispatches ALONE — the pre-pipeline per-call behavior the
        # coalescing win is measured against
        config = config.replace(crypto_backend="jax",
                                CRYPTO_PIPELINE=False)
        backend = "jax"
    elif backend == "jax":
        from plenum_tpu.crypto.ed25519 import (CoalescingVerifier,
                                               JaxEd25519Verifier)
        # one shape covering the coalesced steady state: every node can
        # stage a full CLIENT quota and a full PROPAGATE quota in the same
        # cycle, so pad every dispatch to the next power of two covering
        # both (a second shape would mean a second multi-minute compile)
        per_node = (config.LISTENER_MESSAGE_QUOTA
                    + config.REMOTES_MESSAGE_QUOTA)
        bucket = 1
        while bucket < n_nodes * per_node:
            bucket *= 2
        # supervised: a device/tunnel wedge mid-bench degrades the pool to
        # CPU-speed verdicts (breaker + hedged fallback) instead of
        # blanking the run — the bench line then reports backend_state
        from plenum_tpu.parallel.supervisor import supervise
        if config.CRYPTO_PIPELINE:
            pipe_config = config.replace(PIPELINE_MAX_BUCKET=max(
                bucket, config.PIPELINE_MAX_BUCKET))
            if config.PIPELINE_REMOTE_HOSTS:
                # cross-host federation: rostered remote crypto hosts
                # join the ring as extra lanes with work-stealing
                # (parallel/federation.py); gated strictly on the
                # roster knob so unset keeps the arms below exact
                from plenum_tpu.parallel.federation import \
                    make_federated_pipeline
                pipeline = make_federated_pipeline(pipe_config,
                                                   min_batch=1)
            elif config.PIPELINE_DEVICES != 1:
                # multi-chip scale-out: one breakable lane per local
                # device, each with its own supervised pinned verifier
                from plenum_tpu.parallel.pipeline import \
                    make_multidevice_pipeline
                pipeline = make_multidevice_pipeline(
                    pipe_config, config.PIPELINE_DEVICES, min_batch=1)
            else:
                from plenum_tpu.parallel.pipeline import CryptoPipeline
                # the pipeline owns the shape policy: its pinned bucket
                # ladder covers the coalesced steady state
                pipeline = CryptoPipeline(
                    ed_inner=supervise(JaxEd25519Verifier(min_batch=1)),
                    config=pipe_config,
                    sha_device=True,
                    sha_min_device=config.PIPELINE_SHA_MIN_BATCH)
            plane = pipeline.verifier()
        else:
            plane = CoalescingVerifier(supervise(
                JaxEd25519Verifier(min_batch=bucket)))
    for name in names:
        bus = net.create_peer(name)
        components = NodeBootstrap(
            name, genesis_txns=genesis, crypto_backend=backend,
            verifier=None if pipeline is not None else plane,
            pipeline=pipeline,
            state_commitment=config.STATE_COMMITMENT,
            state_commitment_per_ledger=config.STATE_COMMITMENT_PER_LEDGER,
            verkle_width=config.VERKLE_WIDTH).build()
        # traced runs carry real Tracers (shared in-process clock, so
        # assembly alignment is the identity); untraced runs keep the
        # NullTracer fast path and stay the honest TPS figures
        tracer = Tracer(name, timer.get_current_time,
                        clock_domain="shared") if trace else None
        nodes[name] = Node(
            name, timer, bus, components,
            client_send=lambda msg, client, n=name: replies[n].append(
                (time.perf_counter(), msg, client)),
            config=config, tracer=tracer)
    net.connect_all()
    return (names, nodes, timer, trustee, replies, Reply, DOMAIN_LEDGER_ID,
            plane, net)


def commit_stage_stats(metrics) -> dict:
    """Post-ordering stage percentiles + pairing counters from an
    IN-PROCESS node's MetricsCollector (no flush required: the plain
    collector retains accumulators and their bounded raw samples).
    Keys match the bench line: bls_verify_ms/apply_ms/durable_ms/reply_ms
    p50+p95, pairings_per_batch, group_commit_batches."""
    from plenum_tpu.common.metrics import MetricsName, percentile
    acc = metrics.accumulators
    out = {}
    for key, label in ((MetricsName.COMMIT_BLS_VERIFY_TIME, "bls_verify_ms"),
                       (MetricsName.COMMIT_APPLY_TIME, "apply_ms"),
                       (MetricsName.COMMIT_WAVE_TIME, "commit_wave_ms"),
                       (MetricsName.COMMIT_DURABLE_TIME, "durable_ms"),
                       (MetricsName.COMMIT_REPLY_TIME, "reply_ms")):
        a = acc.get(key)
        if a is not None and a.samples:
            out[f"{label}_p50"] = round(percentile(a.samples, 0.5) * 1000, 3)
            out[f"{label}_p95"] = round(percentile(a.samples, 0.95) * 1000, 3)
    for key, label in ((MetricsName.BLS_PAIRINGS_PER_BATCH,
                        "pairings_per_batch"),
                       (MetricsName.GROUP_COMMIT_BATCHES,
                        "group_commit_batches")):
        a = acc.get(key)
        if a is not None and a.count:
            out[label] = round(a.total / a.count, 2)
    return out


def run_load(n_nodes: int = 4, n_txns: int = 200, backend: str = "cpu",
             timeout: float = 120.0, trace: bool = False,
             config_overrides: dict = None, window: int = 256) -> dict:
    """window: max requests in flight while feeding. 256 floods the
    pipeline (the headline shape); small windows trickle config7-style
    per-tick batches (the pipeline A/B's coalescing measurement)."""
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import NYM

    (names, nodes, timer, trustee,
     replies, Reply, DOMAIN_LEDGER_ID, plane, net) = build_pool(
         n_nodes, backend, trace=trace, config_overrides=config_overrides)

    # pre-sign the whole workload so client-side signing isn't measured
    requests = []
    for i in range(n_txns):
        user = Ed25519Signer(seed=(b"lpu%d" % i).ljust(32, b"\0")[:32])
        req = Request(trustee.identifier, i + 1,
                      {"type": NYM, "dest": user.identifier,
                       "verkey": user.verkey_b58})
        req.signature = trustee.sign_b58(req.signing_bytes())
        requests.append(req)

    def prod_all():
        timer.service()
        for node in nodes.values():
            node.prod()
        if plane is not None:
            # every node has staged its cycle's signatures: one dispatch
            plane.flush()

    # warmup: one txn end-to-end (compiles the single fixed-shape jax
    # program, fills the per-verkey point caches)
    warm = requests.pop()
    submit_times = {}
    for n in names:
        nodes[n].handle_client_message(warm.to_dict(), "warmup")
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        prod_all()
        if any(isinstance(m, Reply) for _, m, _ in replies[names[0]]):
            break
    for n in names:
        replies[n].clear()

    # pipeline warmup contract: compile the pad buckets steady state will
    # dispatch WHILE THE CLOCK IS NOT RUNNING, then pin — after pin() the
    # ring only selects compiled shapes (pad up / split), so the timed
    # phase can never stall on a mid-run XLA compile. The warmup txn
    # above only reaches the smallest bucket; before prewarm+pin, one
    # cold 128-bucket wave cost a 25 s retrace+compile mid-measurement
    # and collapsed this pool from 206 to 5.7 TPS.
    pipe = getattr(plane, "_pipeline", None) if plane is not None else None
    if pipe is not None:
        pipe.prewarm(pipe.buckets[:2])
        # cmt ladder for the fused commit wave: level flushes across the
        # co-hosted replicas dedup to small job counts, so a short pow-2
        # ladder covers steady state (bigger levels split at the cap)
        pipe.prewarm_cmt([1, 2, 4, 8])
        pipe.pin()

    n_txns = len(requests)
    t_start = time.perf_counter()
    next_submit = 0
    done = 0
    first_reply: dict[str, float] = {}
    deadline = time.perf_counter() + timeout
    while done < n_txns and time.perf_counter() < deadline:
        # feed in chunks so the propagate pipeline stays busy but inboxes
        # don't balloon
        while next_submit < n_txns and next_submit - done < window:
            req = requests[next_submit]
            submit_times[req.digest] = time.perf_counter()
            for n in names:
                nodes[n].handle_client_message(req.to_dict(), "bench")
            next_submit += 1
        prod_all()
        for ts, msg, _client in replies[names[0]]:
            if isinstance(msg, Reply):
                digest = msg.result.get("txn", {}).get("metadata", {}) \
                    .get("digest")
                if digest and digest not in first_reply:
                    first_reply[digest] = ts
        done = len(first_reply)
    t_total = time.perf_counter() - t_start

    latencies = sorted(first_reply[d] - submit_times[d]
                       for d in first_reply if d in submit_times)
    sizes = {nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size for n in names}
    stage = commit_stage_stats(nodes[names[0]].metrics)
    trace_summary = None
    if trace:
        # assemble the per-node rings into the bench line's waterfall
        # summary, and check stage sums against the MEASURED client e2e
        # latency (submit -> first REPLY) — both ride one process clock
        from plenum_tpu.common.metrics import percentile
        from plenum_tpu.tools.trace_report import assemble, summarize
        report = assemble([nodes[n].tracer.snapshot() for n in names])
        trace_summary = summarize(report)
        ratios = []
        for digest, per_node in report["requests"].items():
            e2e = (first_reply.get(digest, 0.0)
                   - submit_times.get(digest, 0.0))
            wf = per_node.get(names[0])
            if wf is not None and e2e > 0:
                ratios.append(wf["total"] / e2e)
        if ratios:
            trace_summary["stage_sum_vs_e2e_p50"] = round(
                percentile(ratios, 0.5), 4)
    plane_stats = None
    pipeline_summary = None
    if plane is not None:
        from plenum_tpu.parallel.supervisor import find_supervisor
        sup = find_supervisor(plane)
        if sup is not None:
            st = sup.supervisor_stats()
            plane_stats = {k: st[k] for k in
                           ("breaker_state", "breaker_opens",
                            "fallback_batches", "hedge_wins",
                            "deadline_misses", "device_batches")}
        pipe = getattr(plane, "_pipeline", None)
        if pipe is not None:
            pipeline_summary = pipe.summary()
    percall = None
    if backend == "jax-percall":
        # baseline arm: per-call dispatch accounting straight from each
        # node's supervised verifier (device_items are REAL items — the
        # inner pads after the supervisor counts)
        from plenum_tpu.parallel.supervisor import find_supervisor
        tb = ti = 0
        for n in names:
            v = getattr(nodes[n].c.authenticator.core_authenticator,
                        "verifier", None)
            sup = find_supervisor(v)
            if sup is not None:
                tb += sup.stats["device_batches"]
                ti += sup.stats["device_items"]
        percall = {"device_batches": tb, "device_items": ti,
                   "items_per_dispatch": round(ti / tb, 2) if tb else 0.0}
    # controller trajectory from the master PRIMARY (Node1 under the
    # round-robin selector): final knob positions + the rolling per-stage
    # p50/p95 vs the SLO that put them there — the bench line's view of
    # the closed loop
    ctl = getattr(nodes[names[0]], "batch_controller", None)
    return {
        **({"trace": trace_summary} if trace_summary else {}),
        **({"pipeline": pipeline_summary} if pipeline_summary else {}),
        **({"percall": percall} if percall else {}),
        **({"controller": ctl.trajectory()} if ctl is not None else {}),
        **({"commit_stage": stage} if stage else {}),
        **({"crypto_plane": plane_stats,
            "backend_state": {"closed": "ok", "half_open": "fallback",
                              "open": "open"}[plane_stats["breaker_state"]]}
           if plane_stats else {}),
        "backend": backend,
        "nodes": n_nodes,
        "txns_ordered": done,
        "txns_requested": n_txns,
        "seconds": round(t_total, 3),
        "tps": round(done / t_total, 1) if t_total > 0 else 0.0,
        "p50_latency_ms": round(
            statistics.median(latencies) * 1000, 1) if latencies else None,
        "p99_latency_ms": round(
            latencies[int(len(latencies) * 0.99)] * 1000, 1)
        if latencies else None,
        "ledger_sizes_agree": len(sizes) == 1,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--txns", type=int, default=200)
    ap.add_argument("--backend", default="cpu",
                    choices=["cpu", "jax", "jax-percall"])
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    stats = run_load(args.nodes, args.txns, args.backend)
    if args.json:
        print(json.dumps(stats))
    else:
        print(f"{stats['txns_ordered']}/{stats['txns_requested']} txns in "
              f"{stats['seconds']}s -> {stats['tps']} TPS "
              f"(p50 {stats['p50_latency_ms']} ms, "
              f"p99 {stats['p99_latency_ms']} ms, backend={stats['backend']})")


if __name__ == "__main__":
    main()
