"""Offline deterministic replay of a recorded node.

Reference behavior: plenum/recorder replay mode (STACK_COMPANION=2) — rebuild
the node from its genesis and feed the recorded ingress stream back under a
mock clock, reproducing its state evolution without any network.

    python -m plenum_tpu.tools.replay --name Node1 --base-dir /tmp/pool

Prints per-ledger sizes and roots after replay (compare against the live
node's validator info to confirm bit-identical state).
"""
from __future__ import annotations

import argparse
import json
import os


def replay_node(name: str, base_dir: str) -> dict:
    from plenum_tpu.common.event_bus import ExternalBus
    from plenum_tpu.common.timer import MockTimer
    from plenum_tpu.node import Node, NodeBootstrap
    from plenum_tpu.node.recorder import Recorder, replay
    from plenum_tpu.storage.kv_file import KvFile
    from plenum_tpu.tools.genesis import load_genesis_files
    from plenum_tpu.tools.keygen import load_keys

    keys = load_keys(base_dir, name)
    genesis = load_genesis_files(base_dir)
    rec_dir = os.path.join(base_dir, name, "recorder")
    store = KvFile(rec_dir)
    recorder = Recorder(store, now=lambda: 0.0)

    # fresh components from genesis only — replay rebuilds everything else
    components = NodeBootstrap(
        name, genesis_txns=genesis,
        bls_seed=bytes.fromhex(keys["bls_seed"])).build()
    # the live node's clock was perf_counter (arbitrary absolute values);
    # seed the mock clock with the first record's timestamp BEFORE building
    # the node, or its repeating timers spin through the whole offset
    first_ts = next((ts for ts, *_ in recorder.iter_records()), 0.0)
    timer = MockTimer(start=first_ts)
    bus = ExternalBus(send_handler=lambda msg, dst: None)   # sends -> sink
    node = Node(name, timer, bus, components)
    n = replay(recorder.iter_records(), node, timer)

    ledgers = {}
    for ledger_id, ledger in components.db.ledgers():
        ledgers[ledger_id] = {"size": ledger.size,
                              "root": ledger.root_hash.hex()}
    return {"name": name, "records_replayed": n, "ledgers": ledgers,
            "last_ordered_3pc": list(node.master_replica.last_ordered_3pc)}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--name", required=True)
    ap.add_argument("--base-dir", required=True)
    args = ap.parse_args(argv)
    print(json.dumps(replay_node(args.name, args.base_dir)))


if __name__ == "__main__":
    main()
