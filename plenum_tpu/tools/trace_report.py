"""Assemble per-node flight-recorder dumps into request latency waterfalls
and pool-level critical-path attribution.

Input: one or more JSON dumps written by `common/tracing.Tracer.dump`
(one per node — a sim pool snapshots in-process, a TCP pool's nodes write
`<base>/<name>/<name>-flight-N.json` automatically on anomalies). Each
dump is a bounded ring of `(t, stage, key, data)` span events stamped on
that node's monotonic clock, plus the clock anchors this module uses to
put every node on ONE timeline:

  * `clock_domain == "shared"` (in-process sim): all nodes read the same
    timer — alignment is the identity.
  * `clock_domain == "wall"` (TCP pool, one perf_counter epoch per
    process): the (mono_anchor, wall_anchor) pair maps each node's times
    onto the wall clock, then a CAUSALITY refinement tightens residual
    skew — a PRE-PREPARE cannot be received before the primary sent it,
    so any negative pp_sent→pp_recv gap shifts the receiver's offset.

Per-request waterfall (stages telescope: their sum equals reply−ingress):

  crypto     ingress -> signature verdict        (auth queue + dispatch)
  propagate  verdict -> f+1 propagate quorum
  queue      quorum  -> batch PRE-PREPARE        (ordering queue wait)
  ordering   PRE-PREPARE -> commit quorum        (3PC: prepare+commit)
  durable    ordered -> group-commit flush
  reply      flush   -> REPLY sent

Pool-level attribution adds `network` (pp_sent on the primary to pp_recv
on each replica, aligned) and the wall-clock `apply`/`durable` stage
durations the events carry, and prints p50/p95 per stage.

    python -m plenum_tpu.tools.trace_report DIR_OR_DUMPS... [--json]
        [--request DIGEST] [--last-n 5]
    python -m plenum_tpu.tools.trace_report --check      # self-test smoke
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional

from plenum_tpu.common import tracing
from plenum_tpu.common.metrics import percentile

# waterfall stage names, in pipeline order, with their span endpoints.
# front_door only exists for requests that entered through the ingress
# plane (ing_admit -> the node-pipeline ingress point: client queue wait
# + the batched auth dispatch); requests hitting the node directly have
# no ing_admit point and the stage folds away — totals stay exact.
_WATERFALL = (
    ("front_door", tracing.ING_ADMIT, tracing.INGRESS),
    ("crypto", tracing.INGRESS, tracing.AUTH),
    ("propagate", tracing.AUTH, tracing.PROPAGATE_QUORUM),
    ("queue", tracing.PROPAGATE_QUORUM, "pp"),
    ("ordering", "pp", tracing.ORDERED),
    ("durable", tracing.ORDERED, tracing.DURABLE),
    ("reply", tracing.DURABLE, tracing.REPLY),
)


def load_dumps(paths) -> list[dict]:
    """Dump files / directories -> the LATEST dump per node (a node that
    auto-dumped on several anomalies leaves a numbered series; the last
    one holds the freshest ring)."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*flight*.json")))
                         or sorted(glob.glob(os.path.join(p, "*.json"))))
        else:
            files.append(p)
    latest: dict[str, dict] = {}
    for f in files:
        try:
            with open(f) as fh:
                d = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(d, dict) or "events" not in d:
            continue
        prev = latest.get(d.get("node", "?"))
        if prev is None or d.get("dumped_at", 0) >= prev.get("dumped_at", 0):
            latest[d.get("node", "?")] = d
    return list(latest.values())


def align_offsets(dumps: list[dict]) -> dict[str, float]:
    """Per-node offset added to its event times for one shared timeline:
    wall anchors first, then the causality refinement (receive >= send)."""
    offsets: dict[str, float] = {}
    for d in dumps:
        if (d.get("clock_domain") == "wall"
                and d.get("wall_anchor") is not None):
            offsets[d["node"]] = d["wall_anchor"] - d["mono_anchor"]
        else:
            offsets[d["node"]] = 0.0
    # earliest aligned pp_sent per batch digest (the primary's broadcast)
    sent: dict[str, float] = {}
    for d in dumps:
        off = offsets[d["node"]]
        for t, stage, key, _data in d["events"]:
            if stage == tracing.PP_SENT:
                sent[key] = min(sent.get(key, float("inf")), t + off)
    for d in dumps:
        off = offsets[d["node"]]
        worst = 0.0
        for t, stage, key, _data in d["events"]:
            if stage == tracing.PP_RECV and key in sent:
                worst = min(worst, (t + off) - sent[key])
        if worst < 0.0:
            offsets[d["node"]] = off - worst
    return offsets


class _NodeIndex:
    """One node's events indexed for waterfall lookup (aligned times)."""

    def __init__(self, dump: dict, offset: float):
        self.node = dump["node"]
        # sharded fabrics tag each node's dump with its shard id so the
        # assembled report can attribute waterfalls and hops PER SHARD
        self.shard = (dump.get("tags") or {}).get("shard")
        # router decisions / resolved cross-shard reads seen by this
        # dump's tracer (the fabric tracer, usually)
        self.shard_routes: list[dict] = []
        self.cross_reads: list[dict] = []
        self.first: dict[tuple[str, str], float] = {}
        self.batch_of_req: dict[str, tuple[str, int]] = {}
        self.durable_by_seq: dict[int, float] = {}
        self.stage_durs: dict[str, list[float]] = {}
        self.anomalies: list[tuple[float, str, dict]] = []
        # batch-controller decisions, in order: the control trajectory
        # (knob positions + the stage p95s that moved them)
        self.control: list[tuple[float, dict]] = []
        # fused-pipeline device waves (bucket id, item count, pad waste)
        self.device_waves: list[dict] = []
        for t, stage, key, data in dump["events"]:
            at = t + offset
            self.first.setdefault((stage, key), at)
            if stage == tracing.CONTROLLER:
                self.control.append((at, data or {}))
            if stage in (tracing.PP_SENT, tracing.PP_RECV):
                for req in (data or {}).get("reqs", ()):
                    self.batch_of_req.setdefault(
                        req, (key, (data or {}).get("seq")))
            elif stage == tracing.DURABLE:
                for seq in (data or {}).get("seqs", ()):
                    self.durable_by_seq.setdefault(seq, at)
                if isinstance((data or {}).get("dur"), (int, float)):
                    self.stage_durs.setdefault("durable_wall", []).append(
                        data["dur"])
            elif stage == tracing.APPLY:
                if isinstance((data or {}).get("dur"), (int, float)):
                    self.stage_durs.setdefault("apply_wall", []).append(
                        data["dur"])
            elif stage == tracing.READ_BATCH:
                if isinstance((data or {}).get("proof_dur"), (int, float)):
                    self.stage_durs.setdefault("read_proof_wall",
                                               []).append(data["proof_dur"])
            elif stage == tracing.SHARD_ROUTE:
                self.shard_routes.append(data or {})
            elif stage == tracing.CROSS_SHARD:
                d = data or {}
                self.cross_reads.append(d)
                if isinstance(d.get("dur"), (int, float)):
                    # client-side composed verification + ladder time:
                    # the cross-shard hop as a first-class stage
                    self.stage_durs.setdefault("cross_shard",
                                               []).append(d["dur"])
            elif stage == tracing.DEVICE:
                # fused-pipeline wave: submit->pack->dispatch->collect
                # sub-spans become device_* attribution stages, and the
                # bucket/pad story is summarized per node
                d = data or {}
                for sub in ("queue", "pack", "dispatch"):
                    if isinstance(d.get(sub), (int, float)):
                        self.stage_durs.setdefault(
                            f"device_{sub}", []).append(max(0.0, d[sub]))
                self.device_waves.append(d)
            if stage.startswith(tracing.ANOMALY_PREFIX):
                self.anomalies.append(
                    (at, stage[len(tracing.ANOMALY_PREFIX):], data))

    def request_points(self, digest: str) -> dict[str, Optional[float]]:
        """Timeline points for one request on this node (None = unseen)."""
        batch = self.batch_of_req.get(digest)
        t_pp = t_ord = t_dur = None
        if batch is not None:
            bdigest, seq = batch
            t_pp = min((t for t in (self.first.get((tracing.PP_SENT, bdigest)),
                                    self.first.get((tracing.PP_RECV, bdigest)))
                        if t is not None), default=None)
            t_ord = self.first.get((tracing.ORDERED, bdigest))
            t_dur = self.durable_by_seq.get(seq)
        return {
            tracing.ING_ADMIT: self.first.get((tracing.ING_ADMIT, digest)),
            tracing.INGRESS: self.first.get((tracing.INGRESS, digest)),
            tracing.AUTH: self.first.get((tracing.AUTH, digest)),
            tracing.PROPAGATE_QUORUM:
                self.first.get((tracing.PROPAGATE_QUORUM, digest)),
            "pp": t_pp,
            tracing.ORDERED: t_ord,
            tracing.DURABLE: t_dur,
            tracing.REPLY: self.first.get((tracing.REPLY, digest)),
        }

    def waterfall(self, digest: str) -> Optional[dict]:
        """-> {"stages": {name: seconds}, "total": s, "start": t,
        "end": t} or None when this node saw too little of the request.
        Present consecutive points telescope exactly; a stage whose
        endpoints ran out of order (a replica can admit the PRE-PREPARE
        before its OWN propagate quorum completes) clamps to 0 with the
        slack folded into the surrounding stage — totals stay exact."""
        pts = self.request_points(digest)
        stages: dict[str, float] = {}
        prev_t = None
        for name, frm, to in _WATERFALL:
            t0, t1 = pts.get(frm), pts.get(to)
            if t0 is None and prev_t is not None:
                t0 = prev_t
            if t0 is None or t1 is None:
                continue
            if prev_t is not None:
                # a point earlier than the previous stage's end must not
                # re-count the overlap into this stage — start where the
                # pipeline's covered prefix ends, so stages stay disjoint
                # and the sum telescopes to max(point) - first point
                t0 = max(t0, prev_t)
            stages[name] = max(0.0, t1 - t0)
            prev_t = max(t1, t0)
        if not stages:
            return None
        seen = [t for t in pts.values() if t is not None]
        return {"stages": stages, "total": round(sum(stages.values()), 9),
                "start": min(seen), "end": max(seen)}


def assemble(dumps: list[dict]) -> dict:
    """Cross-node assembly: per-request waterfalls (every node's view of
    every request it traced end to end) + pool attribution inputs."""
    offsets = align_offsets(dumps)
    indexes = [_NodeIndex(d, offsets[d["node"]]) for d in dumps]
    requests: dict[str, dict[str, dict]] = {}
    attribution: dict[str, list[float]] = {}
    for idx in indexes:
        digests = {k for (stage, k) in idx.first
                   if stage == tracing.REPLY and k}
        for digest in digests:
            wf = idx.waterfall(digest)
            if wf is None:
                continue
            requests.setdefault(digest, {})[idx.node] = wf
            for name, dur in wf["stages"].items():
                attribution.setdefault(name, []).append(dur)
        for name, durs in idx.stage_durs.items():
            attribution.setdefault(name, []).extend(durs)
    # network: primary pp_sent -> each replica's pp_recv, aligned
    sent: dict[str, float] = {}
    for idx in indexes:
        for (stage, key), t in idx.first.items():
            if stage == tracing.PP_SENT:
                sent[key] = min(sent.get(key, float("inf")), t)
    for idx in indexes:
        for (stage, key), t in idx.first.items():
            if stage == tracing.PP_RECV and key in sent:
                attribution.setdefault("network", []).append(
                    max(0.0, t - sent[key]))
    anomalies = sorted((a for idx in indexes
                        for a in ((t, idx.node, kind, data)
                                  for t, kind, data in idx.anomalies)))
    controller = {idx.node: idx.control for idx in indexes if idx.control}
    # fused-pipeline device waves: the ring is host-shared, so the
    # last-attached node's tracer holds the full story — merge all
    device = [w for idx in indexes for w in idx.device_waves]
    # sharding plane: group nodes by their dump's shard tag and fold the
    # fabric tracer's routing/cross-read events into one story
    shards: Optional[dict] = None
    by_shard: dict = {}
    for idx in indexes:
        if idx.shard is not None:
            by_shard.setdefault(idx.shard, []).append(idx.node)
    routes = [r for idx in indexes for r in idx.shard_routes]
    cross = [c for idx in indexes for c in idx.cross_reads]
    if by_shard or routes or cross:
        per_shard_routes: dict = {}
        for r in routes:
            sid = r.get("shard")
            per_shard_routes[sid] = per_shard_routes.get(sid, 0) + 1
        shards = {"nodes_by_shard": {str(k): sorted(v)
                                     for k, v in sorted(by_shard.items())},
                  "route_decisions": len(routes),
                  "routes_per_shard": {str(k): v for k, v in
                                       sorted(per_shard_routes.items())},
                  "cross_shard_reads": len(cross),
                  "cross_shard_ok": sum(1 for c in cross if c.get("ok"))}
    return {"nodes": sorted(offsets), "offsets": offsets,
            "requests": requests, "attribution": attribution,
            "anomalies": anomalies, "controller": controller,
            "device": device,
            **({"shards": shards} if shards else {})}


def attribution_summary(report: dict) -> dict:
    """Pool-level critical path: p50/p95 (ms) per stage."""
    out = {}
    for name, durs in sorted(report["attribution"].items()):
        out[name] = {"p50_ms": round(percentile(durs, 0.5) * 1000, 3),
                     "p95_ms": round(percentile(durs, 0.95) * 1000, 3),
                     "n": len(durs)}
    return out


def summarize(report: dict, sample: int = 3) -> dict:
    """Compact summary for the bench line: stage p50/p95 + a few sampled
    waterfalls + how well stage sums cover end-to-end time."""
    attribution = attribution_summary(report)
    sampled = {}
    ratios = []
    for digest, per_node in sorted(report["requests"].items()):
        for node, wf in sorted(per_node.items()):
            span = wf["end"] - wf["start"]
            if span > 0:
                ratios.append(wf["total"] / span)
        if len(sampled) < sample:
            node, wf = sorted(per_node.items())[0]
            sampled[digest[:16]] = {
                "node": node,
                "stages_ms": {k: round(v * 1000, 3)
                              for k, v in wf["stages"].items()},
                "total_ms": round(wf["total"] * 1000, 3)}
    # control trajectory: the steering node's decision count + final knobs
    control = None
    for node, decisions in sorted(report.get("controller", {}).items(),
                                  key=lambda kv: -len(kv[1])):
        control = {"node": node, "decisions": len(decisions),
                   "final": decisions[-1][1]}
        break
    # device waves: bucket histogram + mean pad waste for the bench line
    device = None
    waves = report.get("device") or []
    if waves:
        buckets: dict = {}
        for w in waves:
            buckets[w.get("bucket")] = buckets.get(w.get("bucket"), 0) + 1
        pads = [w["pad"] / w["bucket"] for w in waves
                if w.get("bucket") and isinstance(w.get("pad"), (int, float))]
        device = {"waves": len(waves),
                  "buckets": {str(k): v for k, v in sorted(
                      buckets.items(), key=lambda kv: str(kv[0]))},
                  "pad_waste_mean": round(sum(pads) / len(pads), 3)
                  if pads else None,
                  "mean_coalesced": round(
                      sum(w.get("coalesced", 0) for w in waves)
                      / len(waves), 2)}
    return {
        "requests_traced": len(report["requests"]),
        "attribution": attribution,
        "sampled_waterfalls": sampled,
        # stage sum over observed first->last span: 1.0 = fully attributed
        "stage_sum_ratio_p50": round(percentile(ratios, 0.5), 4)
        if ratios else None,
        "anomalies": len(report["anomalies"]),
        **({"controller": control} if control else {}),
        **({"device": device} if device else {}),
        **({"shards": report["shards"]} if report.get("shards") else {}),
    }


def _print_report(report: dict, last_n: int) -> None:
    print(f"nodes: {', '.join(report['nodes'])}   "
          f"requests traced: {len(report['requests'])}   "
          f"anomalies: {len(report['anomalies'])}")
    print("\ncritical-path attribution (pool, per stage):")
    hdr = f"  {'stage':12} {'p50 ms':>10} {'p95 ms':>10} {'n':>8}"
    print(hdr + "\n  " + "-" * (len(hdr) - 2))
    for name, s in attribution_summary(report).items():
        print(f"  {name:12} {s['p50_ms']:>10} {s['p95_ms']:>10} {s['n']:>8}")
    waves = report.get("device") or []
    if waves:
        n = len(waves)
        pads = [w["pad"] / w["bucket"] for w in waves if w.get("bucket")]
        print(f"\ndevice pipeline: {n} waves, "
              f"mean coalesced {sum(w.get('coalesced', 0) for w in waves) / n:.1f}, "
              f"pad waste {sum(pads) / len(pads):.1%}" if pads else
              f"\ndevice pipeline: {n} waves")
        for w in waves[-last_n:]:
            print(f"  {w.get('kind', '?'):4} bucket={w.get('bucket')} "
                  f"n={w.get('n')} coalesced={w.get('coalesced')} "
                  f"pad={w.get('pad')} queue={1000 * w.get('queue', 0):.2f}ms "
                  f"pack={1000 * w.get('pack', 0):.2f}ms "
                  f"dispatch={1000 * w.get('dispatch', 0):.2f}ms")
    sh = report.get("shards")
    if sh:
        groups = ", ".join(f"shard {k}: {', '.join(v)}"
                           for k, v in sh["nodes_by_shard"].items())
        print(f"\nsharding: {groups or 'no shard-tagged nodes'}")
        print(f"  routes {sh['route_decisions']} "
              f"(per shard {sh['routes_per_shard']}), "
              f"cross-shard reads {sh['cross_shard_reads']} "
              f"({sh['cross_shard_ok']} verified ok)")
    for node, decisions in sorted(report.get("controller", {}).items()):
        print(f"\ncontrol trajectory @{node} ({len(decisions)} decisions):")
        for t, d in decisions[-last_n * 2:]:
            print(f"  {t:.3f} {d.get('verdict', '?'):16} "
                  f"size={d.get('batch_size')} wait={d.get('wait_ms')}ms "
                  f"depth={d.get('depth')} coalesce={d.get('coalesce')} "
                  f"e2e_p95={d.get('e2e_p95_ms')}ms slo={d.get('slo_ms')}ms")
    shown = 0
    for digest, per_node in sorted(report["requests"].items()):
        if shown >= last_n:
            break
        shown += 1
        node, wf = sorted(per_node.items())[0]
        bar = " -> ".join(f"{k} {v * 1000:.2f}ms"
                          for k, v in wf["stages"].items())
        print(f"\n  {digest[:16]}.. @{node}: {bar}"
              f"  (total {wf['total'] * 1000:.2f}ms)")
    if report["anomalies"]:
        print("\nanomaly timeline:")
        for t, node, kind, data in report["anomalies"][-last_n * 4:]:
            print(f"  {t:.3f} {node:10} {kind} {json.dumps(data, default=repr)}")


def _synthetic_dumps() -> list[dict]:
    """Two-node fixture covering every stage, with DIFFERENT wall anchors
    (so --check exercises the alignment path too)."""
    req, batch = "d" * 8, "b" * 8
    primary = {
        "node": "P", "clock_domain": "wall", "tags": {"shard": 0},
        "mono_anchor": 0.0, "wall_anchor": 100.0, "dumped_at": 1.0,
        "anomalies": 0, "events": [
            # sharding plane: a router decision and a resolved verified
            # cross-shard read (dur becomes the cross_shard stage)
            [0.005, tracing.SHARD_ROUTE, req, {"shard": 0, "frm": "cli"}],
            [0.007, tracing.CROSS_SHARD, req,
             {"shard": 1, "ok": True, "dur": 0.002}],
            [0.008, tracing.ING_ADMIT, req, {"frm": "cli"}],
            [0.010, tracing.INGRESS, req, {"frm": "cli"}],
            [0.012, tracing.AUTH, req, {"ok": True}],
            [0.015, tracing.PROPAGATE_QUORUM, req, {"votes": 2}],
            [0.020, tracing.APPLY, "", {"seq": 1, "n": 1, "dur": 0.004}],
            [0.021, tracing.PP_SENT, batch, {"seq": 1, "ledger": 1,
                                             "reqs": [req]}],
            [0.030, tracing.PREPARE_QUORUM, batch, {"seq": 1, "votes": 2}],
            [0.031, tracing.COMMIT_SENT, batch, {"seq": 1}],
            [0.040, tracing.ORDERED, batch, {"seq": 1, "votes": 2}],
            [0.045, tracing.DURABLE, "", {"seqs": [1], "dur": 0.005}],
            [0.046, tracing.REPLY, req, {"seq": 1}],
            # fused-pipeline device wave: the `device` waterfall stage
            # (submit->pack->dispatch->collect spans + bucket/pad story)
            [0.047, tracing.DEVICE, "",
             {"kind": "ed", "bucket": 64, "n": 11, "coalesced": 40,
              "pad": 53, "queue": 0.004, "pack": 0.0005,
              "dispatch": 0.009}],
            # batch-controller decisions: the control trajectory the
            # report must surface next to the waterfalls it steered
            [0.050, tracing.CONTROLLER, "",
             {"verdict": "grow:headroom", "batch_size": 1000,
              "wait_ms": 50.0, "depth": 5, "coalesce": 32,
              "p95_ms": {"queue": 3.0, "ordering": 19.0, "durable": 0.0},
              "e2e_p95_ms": 22.0, "slo_ms": 500.0, "fill": 0.06}],
            [0.055, tracing.CONTROLLER, "",
             {"verdict": "grow:fixed-cost", "batch_size": 1000,
              "wait_ms": 75.0, "depth": 5, "coalesce": 32,
              "p95_ms": {"queue": 3.0, "ordering": 600.0, "durable": 0.0},
              "e2e_p95_ms": 603.0, "slo_ms": 500.0, "fill": 0.06}],
        ]}
    # replica epoch 50s off the primary AND its wall anchor reads 10 ms
    # slow (NTP-grade skew): anchor alignment alone leaves pp_recv BEFORE
    # pp_sent, so --check passes only if the causality refinement runs
    replica = {
        "node": "R", "clock_domain": "wall",
        "mono_anchor": 0.0, "wall_anchor": 149.990, "dumped_at": 1.0,
        "anomalies": 1, "events": [
            [-49.975, tracing.INGRESS, req, {"frm": "cli"}],
            [-49.974, tracing.AUTH, req, {"ok": True}],
            [-49.973, tracing.PROPAGATE_QUORUM, req, {"votes": 2}],
            [-49.972, tracing.PP_RECV, batch, {"seq": 1, "frm": "P",
                                               "reqs": [req]}],
            [-49.960, tracing.ORDERED, batch, {"seq": 1, "votes": 2}],
            [-49.955, tracing.DURABLE, "", {"seqs": [1], "dur": 0.004}],
            [-49.954, tracing.REPLY, req, {"seq": 1}],
            [-49.950, tracing.ANOMALY_PREFIX + "suspicion",
             "", {"code": 1}],
        ]}
    return [primary, replica]


def self_check() -> int:
    """--check: assemble the synthetic fixture and assert the invariants
    the tier-1 smoke rides on. -> process exit code."""
    report = assemble(_synthetic_dumps())
    problems = []
    if set(report["nodes"]) != {"P", "R"}:
        problems.append(f"nodes {report['nodes']}")
    wf = report["requests"].get("d" * 8, {}).get("P")
    if wf is None:
        problems.append("primary waterfall missing")
    else:
        if set(wf["stages"]) != {s for s, _f, _t in _WATERFALL}:
            problems.append(f"stages {sorted(wf['stages'])}")
        span = wf["end"] - wf["start"]
        if abs(wf["total"] - span) > 1e-9:
            problems.append(f"stage sum {wf['total']} != span {span}")
    att = attribution_summary(report)
    for need in ("network", "crypto", "ordering", "durable", "reply",
                 "apply_wall", "device_queue", "device_pack",
                 "device_dispatch", "cross_shard"):
        if need not in att:
            problems.append(f"attribution missing {need}")
    sh = report.get("shards")
    if not sh or sh.get("route_decisions") != 1 \
            or sh.get("cross_shard_ok") != 1 \
            or sh.get("nodes_by_shard", {}).get("0") != ["P"]:
        problems.append(f"shard attribution wrong: {sh}")
    dev = summarize(report).get("device")
    if not dev or dev.get("waves") != 1 or "64" not in dev.get("buckets", {}):
        problems.append(f"device wave summary wrong: {dev}")
    if att.get("network", {}).get("p50_ms", -1) < 0:
        problems.append("causality alignment failed (negative network)")
    if not report["anomalies"]:
        problems.append("anomaly timeline empty")
    ctl = report.get("controller", {}).get("P")
    if not ctl or len(ctl) != 2:
        problems.append(f"controller trajectory missing/short: {ctl}")
    else:
        summary = summarize(report)
        final = summary.get("controller", {}).get("final", {})
        if final.get("verdict") != "grow:fixed-cost":
            problems.append(f"controller final decision wrong: {final}")
    print(json.dumps({"check": "ok" if not problems else "FAIL",
                      "problems": problems,
                      "attribution": att}))
    return 1 if problems else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help="dump files or directories holding *flight*.json")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--request", default=None,
                    help="print every node's waterfall for one digest")
    ap.add_argument("--last-n", type=int, default=5)
    ap.add_argument("--check", action="store_true",
                    help="run the built-in assembly self-test and exit")
    args = ap.parse_args(argv)
    if args.check:
        return self_check()
    dumps = load_dumps(args.paths)
    if not dumps:
        print(json.dumps({"error": f"no flight dumps under {args.paths}"}))
        return 1
    report = assemble(dumps)
    if args.request:
        per_node = report["requests"].get(args.request, {})
        print(json.dumps({args.request: per_node}, indent=2, default=repr))
        return 0 if per_node else 1
    if args.json:
        print(json.dumps({"summary": summarize(report),
                          "anomalies": report["anomalies"][-50:]},
                         default=repr))
    else:
        _print_report(report, args.last_n)
    return 0


if __name__ == "__main__":
    sys.exit(main())
