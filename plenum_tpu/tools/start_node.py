"""Start one validator node as an OS process.

Reference behavior: scripts/start_plenum_node — load the node's keys and the
genesis files from a base dir, stand up the real transport stacks, and run
the node until killed. A 4-node localhost pool is four of these processes
(ports from the genesis node specs) — see tests/test_tools.py for the
scripted version.

    python -m plenum_tpu.tools.start_node --name Node1 --base-dir /tmp/pool \
        [--backend cpu|jax] [--kv file|memory]
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import time
from collections import deque


class _DurableSpylog(deque):
    """The node's bounded in-memory event trace, made durable: every
    append also writes a JSONL row {"t", "event", "data"} that
    tools.log_analyzer reads back for per-view postmortem timelines."""

    def __init__(self, path: str, now=time.time, seed=()):
        super().__init__(maxlen=1000)
        self._now = now
        self._fh = open(path, "a", buffering=1)   # line-buffered
        # a crash mid-write leaves a torn line with no newline; start on
        # a fresh line so the first post-restart event stays parseable
        try:
            if os.path.getsize(path) > 0:
                with open(path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        self._fh.write("\n")
        except OSError:
            pass
        for item in seed:
            self.append(item)

    def append(self, item) -> None:
        super().append(item)
        try:
            event, data = item if isinstance(item, tuple) and \
                len(item) == 2 else (str(item), None)
            self._fh.write(json.dumps(
                {"t": self._now(), "event": event, "data": data},
                default=repr) + "\n")
        except Exception:
            pass          # a full disk must not take down consensus


def build_node(name: str, base_dir: str, backend: str = "cpu",
               kv: str = "file", record: bool = False):
    """-> (prodable, node, registry) ready for a Looper."""
    from plenum_tpu.common.node_messages import POOL_LEDGER_ID
    from plenum_tpu.common.timer import QueueTimer
    from plenum_tpu.config import load_config
    from plenum_tpu.network.tcp_stack import (ClientStack, NodeRegistry,
                                              TcpStack)
    from plenum_tpu.node import Node, NodeBootstrap
    from plenum_tpu.node.looper import Prodable
    from plenum_tpu.tools.genesis import load_genesis_files
    from plenum_tpu.tools.keygen import load_keys

    # operator overrides ride one env var of JSON (the reference layers
    # /etc + network + user config the same way, common/config_util.py);
    # unknown keys fail loudly in load_config. Merged FIRST so every
    # consumer below — data_dir, the bootstrap's crypto plane, the
    # stacks — sees ONE config, never a CLI/env split.
    overrides = json.loads(os.environ.get("PLENUM_CONFIG_JSON", "{}"))
    config = load_config({"crypto_backend": backend, "kv_backend": kv},
                         overrides)
    backend, kv = config.crypto_backend, config.kv_backend

    keys = load_keys(base_dir, name)
    genesis = load_genesis_files(base_dir)

    registry = NodeRegistry()
    my_ha = my_client_ha = None
    for txn in genesis[POOL_LEDGER_ID]:
        data = txn["txn"]["data"]["data"]
        alias = data["alias"]
        registry.set(alias, data["node_ip"], data["node_port"],
                     bytes.fromhex(data["verkey"]))
        if alias == name:
            my_ha = (data["node_ip"], data["node_port"])
            my_client_ha = (data["client_ip"], data["client_port"])
    if my_ha is None:
        raise SystemExit(f"{name} is not in the pool genesis")

    if kv not in ("file", "memory", "native", "chunked"):
        raise SystemExit(f"unknown kv backend {kv!r}")
    data_dir = os.path.join(base_dir, name, "data") if kv != "memory" \
        else None
    # "file" keeps the historical meaning "durable, best engine" (the
    # bootstrap's default picks the native store with file fallback);
    # "native"/"chunked" select those engines explicitly
    storage_backend = kv if kv in ("native", "chunked") else "native"
    components = NodeBootstrap(
        name, genesis_txns=genesis, data_dir=data_dir,
        crypto_backend=backend, storage_backend=storage_backend,
        bls_seed=bytes.fromhex(keys["bls_seed"]),
        # commitment scheme rides the ONE config (PLENUM_CONFIG_JSON
        # {"STATE_COMMITMENT": "verkle"}) — the whole pool must agree,
        # and an observer follows with start_observer --state-commitment
        state_commitment=config.STATE_COMMITMENT,
        state_commitment_per_ledger=config.STATE_COMMITMENT_PER_LEDGER,
        verkle_width=config.VERKLE_WIDTH).build()
    timer = QueueTimer(time.perf_counter)
    # durable metrics history next to the node's keys so operators can run
    # tools.metrics_report after (or during) a run — the reference flushes
    # to a RocksDB metrics store the same way (KvStoreMetricsCollector,
    # common/metrics_collector.py:428) and analyzes it with process_logs.
    # Kept even with --kv memory: the node data may be ephemeral, but the
    # performance history is what post-mortems need.
    from plenum_tpu.common.metrics import KvMetricsCollector
    from plenum_tpu.storage.kv_file import KvFile
    metrics = KvMetricsCollector(
        KvFile(os.path.join(base_dir, name, "metrics")))
    # durable text log (WARNING+ from transport/services) next to the
    # keys: the error-clustering half of tools.log_analyzer reads it
    # (the reference analyzes node logs with scripts/process_logs)
    import logging
    lh = logging.FileHandler(os.path.join(base_dir, name, "node.log"))
    lh.setLevel(logging.WARNING)
    lh.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s %(message)s"))
    logging.getLogger().addHandler(lh)
    node_stack = TcpStack(name, my_ha[0], my_ha[1], registry,
                          seed=bytes.fromhex(keys["seed"]))
    client_stack = ClientStack(name, my_client_ha[0], my_client_ha[1],
                               on_request=None,
                               max_connections=config.MAX_CONNECTED_CLIENTS,
                               idle_timeout=config.CLIENT_CONN_IDLE_TIMEOUT)
    # flight recorder: per-digest span ring + anomaly auto-dumps next to
    # the keys (<node>/<node>-flight-N.json). clock_domain="wall": each
    # OS process runs its own perf_counter epoch, so the tracer anchors
    # its monotonic timeline to time.time() once at construction and
    # tools.trace_report aligns the pool's dumps from those anchors.
    from plenum_tpu.common.tracing import make_tracer
    tracer = make_tracer(name, timer.get_current_time, config=config,
                         dump_dir=os.path.join(base_dir, name),
                         clock_domain="wall", wall=time.time)
    node = Node(name, timer, node_stack.bus, components,
                client_send=client_stack.send, config=config,
                metrics=metrics, tracer=tracer)
    # live fleet telemetry: snapshots spool next to the keys as a
    # rotating atomic window (<node>/telemetry/<node>-telemetry-N.json)
    # so tools.fleet_console can follow a live TCP pool from disk
    # without touching the process
    if node.telemetry.enabled:
        node.telemetry.spool_dir = os.path.join(base_dir, name, "telemetry")
    # durable structured event log: every spylog entry (view changes,
    # catchups, suspicions, VC stall phases) appends a JSONL row that
    # tools.log_analyzer turns into per-view timelines. Seeded with the
    # entries the constructor already traced (audit restore etc.).
    node.spylog = _DurableSpylog(
        os.path.join(base_dir, name, "events.jsonl"),
        now=time.time, seed=node.spylog)
    # late-bound: the recorder may wrap handle_client_message below, and the
    # client stack must call through the WRAPPED method
    client_stack._on_request = \
        lambda msg, frm: node.handle_client_message(msg, frm)
    # observer eviction must close the connection so the follower redials
    node.observable._close = client_stack._drop_client
    # observer pushes pack the batch once, not once per registered observer
    node.observable._send_many = client_stack.send_many

    # transport stats -> metrics history: dropped frames/sessions (silent
    # loss) and per-type tx/rx byte counters, flushed as cumulative gauges
    # that tools.metrics_report reads back (max = total)
    from plenum_tpu.common.metrics import MetricsName
    from plenum_tpu.common.timer import RepeatingTimer

    def sample_transport_stats():
        s = node_stack.stats
        metrics.add_event(MetricsName.TRANSPORT_DROPPED_FRAMES,
                          s["dropped_frames"])
        metrics.add_event(MetricsName.TRANSPORT_DROPPED_SESSIONS,
                          s["dropped_sessions"])
        for direction, table in (("tx", s["tx_msgs"]), ("rx", s["rx_msgs"])):
            total = 0
            for op, (count, nbytes) in table.items():
                total += nbytes
                metrics.add_event(f"transport.{direction}.{op}", nbytes)
                metrics.add_event(f"transport.{direction}_count.{op}", count)
            metrics.add_event(MetricsName.TRANSPORT_TX_BYTES if
                              direction == "tx" else
                              MetricsName.TRANSPORT_RX_BYTES, total)

    node._transport_stats_timer = RepeatingTimer(
        timer, config.METRICS_FLUSH_INTERVAL, sample_transport_stats)
    # the SIGTERM tail-flush must carry the FINAL totals too
    node._sample_transport_stats = sample_transport_stats

    if record:
        # the reference's STACK_COMPANION=1 mode: record every ingress +
        # prod tick durably so tools.replay can re-run this node offline
        from plenum_tpu.node.recorder import Recorder, attach_recorder
        from plenum_tpu.storage.kv_file import KvFile
        rec_dir = os.path.join(base_dir, name, "recorder")
        attach_recorder(node, Recorder(KvFile(rec_dir),
                                       now=timer.get_current_time))

    def sync_registry_from_pool():
        """Pool-ledger NODE txns drive the transport allowlist + dialing
        (ref kit_zstack connectToMissing / pool_manager reconnect)."""
        members = set(node.pool_manager.node_names)
        for alias in members:
            info = node.pool_manager.node_info(alias) or {}
            vk = info.get("verkey")
            if vk and "node_ip" in info:
                registry.set(alias, info["node_ip"], info["node_port"],
                             bytes.fromhex(vk))
        for alias in registry.names():
            if alias not in members:
                registry.remove(alias)
        node_stack.maintain_connections()

    node.on_pool_changed_callbacks.append(sync_registry_from_pool)
    return Prodable(node, node_stack, client_stack, timer), node, registry


def main(argv=None):
    from plenum_tpu.node.looper import Looper

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--name", required=True)
    ap.add_argument("--base-dir", required=True)
    ap.add_argument("--backend", default="cpu",
                    choices=["cpu", "jax", "service"])
    ap.add_argument("--kv", default="file",
                    choices=["file", "memory", "native", "chunked"])
    ap.add_argument("--record", action="store_true",
                    help="record all ingress for offline replay")
    ap.add_argument("--profile", default=None, metavar="PATH",
                    help="run under cProfile; dump pstats to PATH on SIGTERM"
                         " (feeds tools.perf_budget — the Amdahl breakdown)")
    args = ap.parse_args(argv)

    prodable, node, _ = build_node(args.name, args.base_dir, args.backend,
                                   args.kv, record=args.record)
    import signal as _signal
    profiler = None
    if args.profile:
        import cProfile
        # CPU-time timer, not wall: bench pools timeshare one core, and a
        # wall-clock profile would charge each function for time spent
        # preempted (sum across N processes then exceeds wall by ~Nx).
        # process_time counts only cycles this process actually burned.
        profiler = cProfile.Profile(time.process_time)
        profiler.enable()

    # SIGTERM only SETS a flag: the tail work (profiler dump + metrics
    # flush) runs from the event loop below, where no accumulator can be
    # mid-mutation — flushing from signal context raced add_event and
    # could silently lose the tail flush. Escalation keeps a WEDGED node
    # killable: a second SIGTERM (or the alarm if the loop never polls
    # the flag) hard-exits without the tail flush.
    term = {"requested": False}

    def _request_term(signum, frame):
        if term["requested"]:           # second SIGTERM: loop is stuck
            os._exit(143)
        term["requested"] = True
        _signal.alarm(10)               # loop dead -> SIGALRM hard-exits

    _signal.signal(_signal.SIGALRM, lambda s, f: os._exit(143))

    def _finalize_and_exit():
        # the loop is provably alive here — stand down the dead-loop
        # alarm so a >10s flush isn't hard-killed mid-append (a second
        # SIGTERM still escalates if the flush itself wedges)
        _signal.alarm(0)
        if profiler is not None:
            profiler.disable()
            profiler.dump_stats(args.profile)
        try:
            # capture the tail of the run: gauges + accumulators since the
            # last periodic flush would otherwise die with the process
            node._sample_transport_stats()
            node._flush_metrics()
        except Exception:
            pass
        try:
            # the flight-recorder ring's last seconds go to disk too, so
            # a pool torn down mid-incident still yields waterfalls
            node.tracer.dump()
        except Exception:
            pass
        # 128+SIGTERM: supervisors must see termination, not a clean exit
        os._exit(143)

    _signal.signal(_signal.SIGTERM, _request_term)
    looper = Looper()
    looper.add(prodable)

    async def forever():
        print(json.dumps({"started": args.name,
                          "node_port": prodable.node_stack.port,
                          "client_port": prodable.client_stack.port}),
              flush=True)
        last_status = time.monotonic()
        while True:
            await asyncio.sleep(0.25)
            if term["requested"]:
                _finalize_and_exit()
            if time.monotonic() - last_status >= 60:
                last_status = time.monotonic()
                info = node.validator_info()
                print(json.dumps(
                    {"uptime": round(info["uptime"], 1),
                     "last_ordered_3pc": info["last_ordered_3pc"],
                     "connected": info["connected"]}), flush=True)

    looper.run(forever())


if __name__ == "__main__":
    main()
