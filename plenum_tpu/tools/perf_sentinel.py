"""Perf-regression sentinel: the bench trajectory as a first-class ledger.

Five ``BENCH_r*.json`` files record the per-round bench results, but
nothing folds them into a TRAJECTORY — so a silent 20% TPS drop between
rounds would ship undetected, and the one real scare so far (the PR 6
config5 drop, later diagnosed as bench-host contention) had to be
triaged by hand. This tool:

* **normalizes** every ``BENCH_r*.json`` plus every appended
  ``BENCH_trajectory.jsonl`` row (bench.py writes one per run) into one
  row per round per config, provenance-tagged (``jax_source``,
  ``host_cores``, ``calib_ms``);
* **renders** the per-config trend (text sparklines, --json for tools);
* issues **variance-aware regression verdicts**: a drop only PAGES
  ("regression") when (a) it exceeds the config's observed
  interleaved-median spread and (b) the baseline round actually carried
  a spread (i.e. was a median of repeat runs). A drop past tolerance on
  a single-pass baseline stays a WARNING — the PR 6 false alarm was
  exactly a single-pass figure moving inside host noise, and a page an
  operator learns to ignore is worse than none. Headline figures are
  only compared when both rounds name the same ``headline_config``
  (the r01→r02 94% "drop" was the honest-baseline switch from
  in-process to TCP, not a regression — unnamed or changed headline
  configs are "not_comparable" by construction);
* **lints provenance**: a bench file with no ``jax_source`` cannot say
  whether its device numbers came from the live relay, the JAX-on-CPU
  pipeline, or the plain-CPU fallback — the sentinel reports it as a
  lint problem instead of silently folding it.

Tolerance: with an observed spread, tol = max(spread_frac, 0.15);
without one, 0.30 (~two single-pass host-noise bands — the measured
r05 interleaved spread alone is ~24%). Drops past tol/2 warn.

    python -m plenum_tpu.tools.perf_sentinel [--dir .] [--json]
    python -m plenum_tpu.tools.perf_sentinel --check   # tier-1 self-test

Exit: 0 clean/warnings, 2 on any "regression" verdict (--strict also
fails on provenance lint problems).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
from typing import Optional

# config label -> (value key, spread key) in a bench result dict
CONFIG_KEYS = (
    ("headline", "value", "spread"),
    ("cpu", "cpu_tps", "cpu_spread"),
    ("tcp", "tcp_tps", "tcp_spread"),
    ("tcpsvc", "tcpsvc_tps", "tcpsvc_spread"),
    ("tcpsvcjax", "tcpsvcjax_tps", None),
    ("tcp7", "tcp7_tps", None),
    ("jax", "jax_tps", None),
    ("signers", "distinct_signers_tps", None),
    ("mixed", "config2_mixed_3inst_tps", None),
    ("reads", "config3_proof_reads_per_s", None),
    ("vc_under_load", "config4_vc_under_load_tps", None),
    ("sim25", "config5_sim25_tps", None),
)

# no spread on the baseline: two independent single-pass measurements
# can sit two noise bands apart without either being wrong
NOISE_TOLERANCE = 0.30
# an interleaved-median spread tighter than this is luck, not precision
MIN_TOLERANCE = 0.15

SPARK_TICKS = "▁▂▃▄▅▆▇█"


def spread_frac(spread) -> Optional[float]:
    """(max - min) / max of an interleaved-run spread dict, or None."""
    if not isinstance(spread, dict):
        return None
    lo, hi = spread.get("min"), spread.get("max")
    if not isinstance(hi, (int, float)) or not isinstance(lo, (int, float)) \
            or hi <= 0:
        return None
    return (hi - lo) / hi


def trajectory_row(parsed: dict, label: str = "") -> dict:
    """One normalized trajectory row from a bench result dict: the
    per-config values + spreads that trend, and the provenance tags
    that make the row citable."""
    configs: dict[str, dict] = {}
    for config, value_key, spread_key in CONFIG_KEYS:
        value = parsed.get(value_key)
        if not isinstance(value, (int, float)):
            continue                # errors land as strings — not a point
        entry: dict = {"value": float(value)}
        frac = spread_frac(parsed.get(spread_key)) if spread_key else None
        if frac is not None:
            entry["spread_frac"] = round(frac, 4)
        configs[config] = entry
    row = {"label": label, "configs": configs}
    if parsed.get("headline_config"):
        row["headline_config"] = parsed["headline_config"]
    for key, src in (("jax_source", "jax_source"),
                     ("host_cores", "host_cores"),
                     ("calib_ms", "config5_calib_ms")):
        if parsed.get(src) is not None:
            row[key] = parsed[src]
    return row


def append_trajectory(parsed: dict, path: str, label: str = "") -> dict:
    """bench.py's seam: normalize `parsed` and append it to the
    append-only trajectory ledger (JSONL). Returns the row written."""
    row = trajectory_row(parsed, label=label)
    with open(path, "a") as fh:
        fh.write(json.dumps(row, sort_keys=True) + "\n")
    return row


def load_rows(bench_dir: str = ".",
              trajectory: Optional[str] = None) -> list[dict]:
    """Every BENCH_r*.json (round order) then every trajectory-ledger
    row (append order), normalized. A malformed file becomes a row with
    a `problems` list instead of being silently skipped."""
    rows: list[dict] = []
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        label = os.path.basename(path).replace("BENCH_", "") \
            .replace(".json", "")
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"label": label, "configs": {},
                         "problems": [f"unreadable: {e}"]})
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(parsed, dict):
            rows.append({"label": label, "configs": {},
                         "problems": ["no parsed bench result"]})
            continue
        rows.append(trajectory_row(parsed, label=label))
    path = trajectory or os.path.join(bench_dir, "BENCH_trajectory.jsonl")
    if os.path.exists(path):
        with open(path) as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    rows.append({"label": f"traj{i}", "configs": {},
                                 "problems": ["unreadable trajectory row"]})
                    continue
                if "configs" not in row:     # raw bench dict appended
                    row = trajectory_row(row, label=f"traj{i}")
                row.setdefault("label", f"traj{i}")
                rows.append(row)
    return rows


def lint_provenance(rows: list[dict]) -> list[str]:
    """Provenance problems, one line per offence. jax_source is the
    hard requirement: without it a device figure is uninterpretable."""
    problems: list[str] = []
    for row in rows:
        problems.extend(f"{row['label']}: {p}"
                        for p in row.get("problems", ()))
        if not row.get("configs"):
            continue
        if row.get("jax_source") is None:
            problems.append(
                f"{row['label']}: missing jax_source provenance — cannot "
                f"tell live-relay from cpu-fallback figures")
        if row.get("host_cores") is None:
            problems.append(f"{row['label']}: missing host_cores provenance")
    return problems


def _tolerance(observed_spreads: list[float]) -> float:
    if observed_spreads:
        return max(max(observed_spreads), MIN_TOLERANCE)
    return NOISE_TOLERANCE


def verdicts(rows: list[dict]) -> list[dict]:
    """Round-over-round verdicts, one per (config, consecutive pair).

    verdict ∈ ok | warn | regression | not_comparable. "regression"
    requires BOTH gates: drop > tolerance AND a spread-carrying
    (interleaved-median) baseline; a single-pass baseline caps at
    "warn" no matter how big the drop reads — the gating policy
    docs/observability.md spells out."""
    out: list[dict] = []
    configs = sorted({c for row in rows for c in row.get("configs", {})})
    for config in configs:
        series = [(row, row["configs"][config]) for row in rows
                  if config in row.get("configs", {})]
        seen_spreads: list[float] = []
        for (prev_row, prev), (cur_row, cur) in zip(series, series[1:]):
            for entry in (prev, cur):
                if entry.get("spread_frac") is not None:
                    seen_spreads.append(entry["spread_frac"])
            v = {"config": config, "from": prev_row["label"],
                 "to": cur_row["label"], "prev": prev["value"],
                 "value": cur["value"]}
            if config == "headline":
                hc0 = prev_row.get("headline_config")
                hc1 = cur_row.get("headline_config")
                if not hc0 or not hc1 or hc0 != hc1:
                    v.update({"verdict": "not_comparable",
                              "reason": f"headline config "
                                        f"{hc0 or '?'} -> {hc1 or '?'}"})
                    out.append(v)
                    continue
            if prev["value"] <= 0:
                continue
            change = (cur["value"] - prev["value"]) / prev["value"]
            tol = _tolerance(seen_spreads)
            v["change_pct"] = round(change * 100, 1)
            v["tolerance_pct"] = round(tol * 100, 1)
            drop = -change
            if drop > tol:
                if prev.get("spread_frac") is not None:
                    v["verdict"] = "regression"
                    v["reason"] = (f"drop {drop:.1%} exceeds spread-based "
                                   f"tolerance {tol:.1%} on a median "
                                   f"baseline")
                else:
                    v["verdict"] = "warn"
                    v["reason"] = (f"drop {drop:.1%} exceeds {tol:.1%} but "
                                   f"baseline is single-pass (no spread) — "
                                   f"likely host noise, re-measure with "
                                   f"interleaved repeats")
            elif drop > tol / 2:
                v["verdict"] = "warn"
                v["reason"] = f"drop {drop:.1%} within tolerance {tol:.1%}"
            else:
                v["verdict"] = "ok"
            out.append(v)
    return out


def sparkline(values: list[float], width: int = 24) -> str:
    if not values:
        return ""
    values = values[-width:]
    lo, hi = min(values), max(values)
    if hi <= lo:
        return SPARK_TICKS[0] * len(values)
    return "".join(
        SPARK_TICKS[min(len(SPARK_TICKS) - 1,
                        int((v - lo) / (hi - lo) * len(SPARK_TICKS)))]
        for v in values)


def report(bench_dir: str = ".", trajectory: Optional[str] = None) -> dict:
    rows = load_rows(bench_dir, trajectory)
    vs = verdicts(rows)
    return {
        "rows": rows,
        "verdicts": vs,
        "regressions": [v for v in vs if v["verdict"] == "regression"],
        "warnings": [v for v in vs if v["verdict"] == "warn"],
        "lint": lint_provenance(rows),
    }


def format_report(rep: dict) -> str:
    lines = [f"PERF TRAJECTORY  rounds={len(rep['rows'])}"]
    configs = sorted({c for row in rep["rows"]
                      for c in row.get("configs", {})})
    for config in configs:
        series = [(row["label"], row["configs"][config]["value"])
                  for row in rep["rows"]
                  if config in row.get("configs", {})]
        values = [v for _, v in series]
        lines.append(f"  {config:<14} {sparkline(values)}  "
                     f"{values[-1]:>10.1f}  ({series[0][0]}→"
                     f"{series[-1][0]}, n={len(values)})")
    for v in rep["verdicts"]:
        if v["verdict"] in ("regression", "warn", "not_comparable"):
            tag = {"regression": "REGRESSION", "warn": "warn",
                   "not_comparable": "n/c"}[v["verdict"]]
            lines.append(f"  [{tag}] {v['config']} {v['from']}→{v['to']}: "
                         f"{v.get('reason', '')}")
    for p in rep["lint"]:
        lines.append(f"  [lint] {p}")
    if not rep["regressions"]:
        lines.append("  no regressions")
    return "\n".join(lines)


# --- self test (tier-1) ------------------------------------------------------

def self_check() -> list[str]:
    """Synthetic-trajectory self-test of the verdict and lint rules."""
    problems: list[str] = []

    def mk(label, tps, spread=None, headline=380.0, hc="tcpsvc", **kw):
        parsed = {"value": headline, "headline_config": hc,
                  "tcpsvc_tps": tps, "jax_source": "live-relay",
                  "host_cores": 8, **kw}
        if spread:
            parsed["tcpsvc_spread"] = spread
            parsed["spread"] = spread
        return trajectory_row(parsed, label=label)

    # 1. a stable config inside its spread -> no regression, no warn
    rows = [mk("a", 400.0, spread={"min": 360.0, "max": 440.0, "n": 3}),
            mk("b", 390.0, spread={"min": 350.0, "max": 430.0, "n": 3})]
    vs = [v for v in verdicts(rows) if v["config"] == "tcpsvc"]
    if any(v["verdict"] != "ok" for v in vs):
        problems.append(f"stable series not ok: {vs}")

    # 2. a >spread drop on a median baseline -> exactly one regression
    rows = [mk("a", 400.0, spread={"min": 360.0, "max": 440.0, "n": 3}),
            mk("b", 250.0, spread={"min": 240.0, "max": 260.0, "n": 3})]
    vs = [v for v in verdicts(rows) if v["config"] == "tcpsvc"]
    if [v["verdict"] for v in vs] != ["regression"]:
        problems.append(f"median-baseline cliff not a regression: {vs}")

    # 3. the same cliff on a single-pass baseline stays a WARNING —
    #    the PR 6 host-contention rule
    rows = [mk("a", 400.0), mk("b", 250.0)]
    vs = [v for v in verdicts(rows) if v["config"] == "tcpsvc"]
    if [v["verdict"] for v in vs] != ["warn"]:
        problems.append(f"single-pass cliff should warn, got: {vs}")

    # 4. a borderline drop (between tol/2 and tol) -> warn, not page
    rows = [mk("a", 400.0, spread={"min": 360.0, "max": 440.0, "n": 3}),
            mk("b", 350.0, spread={"min": 340.0, "max": 365.0, "n": 3})]
    vs = [v for v in verdicts(rows) if v["config"] == "tcpsvc"]
    if [v["verdict"] for v in vs] != ["warn"]:
        problems.append(f"borderline drop should warn, got: {vs}")

    # 5. headline rounds with different (or missing) headline_config are
    #    not comparable — the r01→r02 honest-baseline switch
    rows = [mk("a", 400.0, headline=4800.0, hc=None),
            mk("b", 390.0, headline=380.0)]
    vs = [v for v in verdicts(rows) if v["config"] == "headline"]
    if [v["verdict"] for v in vs] != ["not_comparable"]:
        problems.append(f"headline switch should be not_comparable: {vs}")

    # 6. missing jax_source -> provenance lint problem, never a crash
    row = trajectory_row({"value": 100.0, "tcpsvc_tps": 100.0}, label="x")
    lint = lint_provenance([row])
    if not any("jax_source" in p for p in lint):
        problems.append(f"missing jax_source not linted: {lint}")

    # 7. round-trip: append_trajectory writes a row load_rows folds back
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "BENCH_trajectory.jsonl")
        append_trajectory({"value": 380.0, "headline_config": "tcpsvc",
                           "tcpsvc_tps": 380.0, "jax_source": "live-relay",
                           "host_cores": 8}, path, label="run1")
        rows = load_rows(td, trajectory=path)
        if (len(rows) != 1 or rows[0]["label"] != "run1"
                or rows[0]["configs"]["tcpsvc"]["value"] != 380.0):
            problems.append(f"trajectory round-trip failed: {rows}")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json")
    ap.add_argument("--trajectory", default=None,
                    help="trajectory ledger path "
                         "(default <dir>/BENCH_trajectory.jsonl)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on provenance lint problems")
    ap.add_argument("--check", action="store_true",
                    help="run the verdict-rule self-test and exit")
    args = ap.parse_args(argv)
    if args.check:
        problems = self_check()
        print(json.dumps({"check": "perf_sentinel",
                          "problems": problems}))
        return 0 if not problems else 1
    rep = report(args.dir, args.trajectory)
    if args.json:
        print(json.dumps(rep))
    else:
        print(format_report(rep))
    if rep["regressions"]:
        return 2
    if args.strict and rep["lint"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
