"""MULTICHIP harness: the N-device crypto-plane run that must NEVER
crash or blank a column again.

MULTICHIP_r02..r05 "ran" the 8-device dryrun against a persistent XLA
compile cache holding AOT entries compiled on a DIFFERENT machine: the
`cpu_aot_loader` machine-feature mismatch floods stderr and is one
unlucky instruction away from a SIGILL mid-verify. Two fixes compose
here:

1. **Root cause** — `plenum_tpu.ops` now scopes the persistent cache by
   a host fingerprint (platform + CPU feature flags), so a foreign
   host's AOT entries are never even seen; `aot_preflight()` reports
   the cache compatibility story this run starts from.
2. **Fail-closed harness** — the measured step runs in a SUBPROCESS.
   If it dies (or its stderr carries a mismatch marker), the scoped
   cache is purged and the step re-runs once against a FRESH cache —
   a fresh JIT compile instead of a poisoned AOT load. The emitted row
   is then tagged `jax_source: cpu-fallback`, a measured number with
   its provenance named, never a crash or a blank column.

The measured step itself drives the multi-device pipeline: one
breakable lane per forced-host CPU device (the same code path a TPU
pod runs), a correctness wave of real signatures through EVERY lane,
then a timed flood whose aggregate wave throughput and PER-DEVICE
dispatch counts are the row. Run:

    python -m plenum_tpu.tools.multichip --devices 8 --out MULTICHIP_r06.json
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

MISMATCH_MARKERS = ("cpu_aot_loader", "Target machine feature",
                    "machine type for execution", "SIGILL")

_INNER = r"""
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=%(n)d").strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", %(n)d)
except AttributeError:
    pass

from plenum_tpu.ops import aot_preflight
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.parallel.pipeline import make_multidevice_pipeline

out = {"n_devices": %(n)d, "aot": aot_preflight(),
       "devices_seen": len(jax.devices())}
cfg = Config(PIPELINE_MIN_BUCKET=%(bucket)d, PIPELINE_MAX_BUCKET=%(bucket)d,
             PIPELINE_FLUSH_WAIT=0.0)
pipe = make_multidevice_pipeline(cfg, %(n)d, min_batch=1)
t0 = time.perf_counter()
pipe.prewarm([%(bucket)d])
pipe.prewarm_cmt([4])        # the cmt ladder the commit wave below rides
pipe.pin()
out["warmup_s"] = round(time.perf_counter() - t0, 1)

# correctness wave through EVERY lane: real signatures, every verdict
# checked (the dryrun acceptance, per chip). Content is UNIQUE PER LANE
# — the ring's verdict cache is shared, so repeating one item set would
# settle lanes 1..N-1 from lane 0's cached verdicts and never test
# their chips at all
signer = Ed25519Signer(seed=b"multichip-harness".ljust(32, b"\0"))
lanes_ok = True
for lane in range(len(pipe.lanes)):
    msgs = [b"mc-l%%d-%%d" %% (lane, i) for i in range(4)]
    good = [(m, signer.sign(m), signer.verkey) for m in msgs]
    bad = [(b"forged-l%%d" %% lane, signer.sign(msgs[0]), signer.verkey)]
    disp_before = pipe.lanes[lane].stats["dispatches"]
    got = pipe.collect_verify(
        pipe.submit_verify(good + bad, lane=lane), wait=True)
    if list(got) != [True] * 4 + [False]:
        lanes_ok = False
    if pipe.lanes[lane].stats["dispatches"] <= disp_before:
        lanes_ok = False        # the wave must have HIT this chip
out["lanes_ok"] = lanes_ok

# timed flood: unique well-formed content (the kernel's work does not
# depend on verdict), ring-placed across all lanes, double-buffered
import random
rng = random.Random(7)
def junk(k):
    return [(rng.randbytes(16), rng.randbytes(63) + b"\x00",
             rng.randbytes(32)) for _ in range(k)]
deadline = time.perf_counter() + %(seconds)f
settled = 0
toks = []
while time.perf_counter() < deadline:
    toks.append(pipe.submit_verify(junk(%(bucket)d)))
    pipe.service()
    while len(toks) > 2 * len(pipe.lanes):
        tok = toks.pop(0)
        if pipe.collect_verify(tok, wait=True) is not None:
            settled += %(bucket)d
t_flood0 = time.perf_counter()
for tok in toks:
    if pipe.collect_verify(tok, wait=True) is not None:
        settled += %(bucket)d
elapsed = %(seconds)f + (time.perf_counter() - t_flood0)
out["flood_items_per_s"] = round(settled / elapsed, 1)
out["per_device_dispatches"] = {
    "lane%%d" %% d["lane"]: d["dispatches"] for d in pipe.device_state()}

# commitment lane: a two-level commit wave (hlev sha3 jobs — the MPT
# node-hash levels a state recommit stages) rides the SAME ring. Roots
# are checked against a host-computed reference, and the verdict folds
# in the pipeline_cmt.* wave stats — a run whose cmt lane went dark or
# degraded to the host engine mid-wave fails the row, not just one
# whose ed lanes misbehaved
import hashlib
from plenum_tpu.parallel.commit_wave import CommitWave
def _cmt_family(tag):
    def gen():
        msgs = tuple(b"mc-cmt-%%d-%%d" %% (tag, j) for j in range(8))
        (lvl1,) = yield [("hlev", "sha3", msgs)]
        (root,) = yield [("hlev", "sha3", (b"".join(lvl1),))]
        return root[0]
    return gen()
def _cmt_expect(tag):
    msgs = [b"mc-cmt-%%d-%%d" %% (tag, j) for j in range(8)]
    lvl1 = b"".join(hashlib.sha3_256(m).digest() for m in msgs)
    return hashlib.sha3_256(lvl1).digest()
cwave = CommitWave(pipe)
for fam in range(3):
    cwave.add("fam%%d" %% fam, _cmt_family(fam))
roots = cwave.run()
out["cmt"] = {"waves": pipe.stats["cmt_waves"],
              "levels": pipe.stats["cmt_levels"],
              "items": pipe.stats["cmt_items"],
              "host_fallbacks": pipe.stats["cmt_host_fallbacks"]}
cmt_ok = (all(roots.get("fam%%d" %% f) == _cmt_expect(f)
              for f in range(3))
          and pipe.stats["cmt_waves"] >= 1
          and pipe.stats["cmt_levels"] >= 2
          and pipe.stats["cmt_host_fallbacks"] == 0)
out["cmt_ok"] = cmt_ok

out["unpinned_shapes"] = pipe.stats["unpinned_shapes"]
out["ok"] = bool(lanes_ok and settled > 0 and cmt_ok
                 and pipe.stats["unpinned_shapes"] == 0)
pipe.close()
print(json.dumps(out))
"""


def _run_step(n_devices: int, bucket: int, seconds: float,
              timeout: float, env_extra: dict | None = None) -> dict:
    code = _INNER % {"n": n_devices, "bucket": bucket, "seconds": seconds}
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout, env=env,
                              cwd=os.path.dirname(os.path.dirname(
                                  os.path.dirname(
                                      os.path.abspath(__file__)))))
    except subprocess.TimeoutExpired:
        return {"rc": -1, "error": "measured step timed out", "tail": ""}
    row: dict = {"rc": proc.returncode,
                 "tail": (proc.stderr or "")[-2000:]}
    for line in reversed((proc.stdout or "").strip().splitlines() or [""]):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            row.update(parsed)
            return row
    row["error"] = "no measured output"
    return row


def _mismatch(row: dict) -> bool:
    tail = row.get("tail", "")
    return any(marker in tail for marker in MISMATCH_MARKERS)


def run_harness(n_devices: int = 8, bucket: int = 16,
                seconds: float = 10.0, timeout: float = 1500.0) -> dict:
    """-> the MULTICHIP row. Exit-0 contract: a stale-AOT/crashed first
    attempt re-runs against a FRESH cache (fresh JIT compiles); the row
    is then measured-but-tagged, never absent. The scoped cache is
    PURGED only on a detected AOT mismatch — a timeout must not destroy
    a legitimately warm cache (that would make every later run on this
    host start cold AND strictly slower than the attempt that timed
    out), and a plain crash retries isolated without assuming the warm
    entries are at fault."""
    from plenum_tpu.ops import _cache_dir, aot_preflight
    row = _run_step(n_devices, bucket, seconds, timeout)
    timed_out = row.get("rc") == -1
    crashed = (row.get("rc") != 0 or not row.get("ok")) and not timed_out
    stale_aot = _mismatch(row)
    if stale_aot or crashed:
        if stale_aot:
            # poisoned entries must not be loadable the second time
            try:
                shutil.rmtree(_cache_dir, ignore_errors=True)
            except Exception:
                pass
        fresh = tempfile.mkdtemp(prefix="plenum-multichip-cache-")
        try:
            retry = _run_step(n_devices, bucket, seconds, timeout,
                              env_extra={"PLENUM_TPU_JAX_CACHE": fresh})
        finally:
            shutil.rmtree(fresh, ignore_errors=True)
        retry["jax_source"] = "cpu-fallback"
        retry["first_attempt"] = {
            "rc": row.get("rc"), "ok": row.get("ok", False),
            "stale_aot_detected": stale_aot,
            "tail": row.get("tail", "")[-400:]}
        retry["cache_purged"] = stale_aot
        row = retry
    else:
        row["jax_source"] = "jax-on-cpu"
    row["skipped"] = False
    row["ok"] = bool(row.get("ok")) and row.get("rc") == 0
    row.setdefault("aot", aot_preflight())
    # the emitted tail carries only mismatch-relevant lines — the raw
    # XLA feature dump that used to swamp the r02-r05 rows stays out
    tail = row.get("tail", "")
    row["tail"] = "\n".join(
        ln for ln in tail.splitlines()
        if any(m in ln for m in MISMATCH_MARKERS))[-1500:]
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--bucket", type=int, default=16)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--timeout", type=float, default=1500.0)
    ap.add_argument("--out", default=None,
                    help="also write the row to this JSON file")
    args = ap.parse_args(argv)
    row = run_harness(args.devices, args.bucket, args.seconds,
                      args.timeout)
    print(json.dumps(row))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(row, fh, indent=2)
    # exit-0 contract: a measured row (even cpu-fallback-tagged) is a
    # SUCCESS; only a retry that ALSO failed is a harness failure
    return 0 if row.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
