"""Node log analyzer: error clustering + per-view timelines for postmortems.

Reference behavior: scripts/process_logs:1 and scripts/log_stats — the
operators' postmortem loop over node logs (cluster repeated errors, lay
protocol events on a per-view timeline). The redesign here reads TWO
durable sources a node writes next to its keys:

  <base>/<node>/events.jsonl   structured protocol events (the node's
                               spylog made durable by tools/start_node:
                               view changes, catchups, suspicions,
                               VC stall phase decompositions, ...)
  <base>/<node>/node.log       python logging text (WARNING+ from the
                               transport and services)

Structured events beat regex-mining free text for timelines — the text
log is only mined for the error-clustering half, where it is the source
of truth (unexpected exceptions land there).

CLI:  python -m plenum_tpu.tools.log_analyzer --base-dir DIR [--node N]
          [--json] [--last-s SECONDS]
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Optional

# digits, hex runs, and quoted strings collapse so one template matches
# every instance of a repeated error
_NORM_PATTERNS = [
    (re.compile(r"0x[0-9a-fA-F]+"), "0x#"),
    (re.compile(r"\b[0-9a-fA-F]{8,}\b"), "#hex#"),
    (re.compile(r"\d+"), "#"),
    (re.compile(r"'[^']*'"), "'...'"),
    (re.compile(r'"[^"]*"'), '"..."'),
]

_LOG_LINE = re.compile(
    r"^(?P<ts>[\d\-T:., ]+)?(?P<level>DEBUG|INFO|WARNING|ERROR|CRITICAL)"
    r"[: ](?P<rest>.*)$")


def normalize_message(msg: str) -> str:
    for pat, repl in _NORM_PATTERNS:
        msg = pat.sub(repl, msg)
    return msg.strip()


def cluster_log_text(path: str) -> list[dict]:
    """-> clusters of WARNING+ lines (and traceback heads), most frequent
    first: {level, template, count, first_line, example}."""
    if not os.path.exists(path):
        return []
    clusters: dict[tuple, dict] = {}
    with open(path, errors="replace") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.rstrip("\n")
            m = _LOG_LINE.match(line)
            if m and m.group("level") in ("WARNING", "ERROR", "CRITICAL"):
                level, rest = m.group("level"), m.group("rest")
            elif line.startswith("Traceback (most recent call last)"):
                level, rest = "TRACEBACK", line
            else:
                continue
            key = (level, normalize_message(rest))
            c = clusters.get(key)
            if c is None:
                clusters[key] = {"level": level, "template": key[1],
                                 "count": 1, "first_line": lineno,
                                 "example": line[:240]}
            else:
                c["count"] += 1
    return sorted(clusters.values(), key=lambda c: -c["count"])


def read_events(path: str, last_s: Optional[float] = None) -> list[dict]:
    """events.jsonl rows {"t": wall_ts, "event": str, "data": ...};
    tolerant of torn tails (a crashing node tears its last line)."""
    if not os.path.exists(path):
        return []
    rows = []
    with open(path, errors="replace") as fh:
        for line in fh:
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue   # torn line (crash mid-write): skip it alone —
                #            a restart appends more rows AFTER the tear,
                #            and a postmortem needs exactly those
    if last_s is not None and rows:
        cutoff = rows[-1].get("t", 0) - last_s
        rows = [r for r in rows if r.get("t", 0) >= cutoff]
    return rows


def read_flight_anomalies(node_dir: str,
                          last_s: Optional[float] = None) -> list[dict]:
    """Flight-recorder dumps (<node_dir>/*flight*.json, common/tracing)
    -> anomaly rows in events.jsonl shape, named `flight.<kind>` so the
    timeline distinguishes recorder-sourced rows from spylog ones.

    Times are mapped onto the wall clock when the dump carries a wall
    anchor (TCP pools); shared-clock sim dumps keep their timer times.
    Dumps overlap across a numbered series — rows are deduplicated by
    (t, kind) so a dump-per-anomaly cascade doesn't multiply counts."""
    rows: list[dict] = []
    seen: set = set()
    for path in sorted(glob.glob(os.path.join(node_dir, "*flight*.json"))):
        try:
            with open(path, errors="replace") as fh:
                dump = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        off = 0.0
        if dump.get("clock_domain") == "wall" \
                and dump.get("wall_anchor") is not None:
            off = dump["wall_anchor"] - dump["mono_anchor"]
        for ev in dump.get("events", ()):
            try:
                t, stage, _key, data = ev
            except (TypeError, ValueError):
                continue
            if not isinstance(stage, str) \
                    or not stage.startswith("anomaly."):
                continue
            kind = stage[len("anomaly."):]
            # payload is part of the identity: the frozen per-cycle clock
            # stamps two same-kind anomalies from one prod cycle with one
            # timestamp, and only the payload tells them apart — dedup
            # exists solely for the overlap across a numbered dump series
            dedup_key = (t, kind,
                         json.dumps(data, sort_keys=True, default=repr))
            if dedup_key in seen:
                continue
            seen.add(dedup_key)
            rows.append({"t": t + off, "event": f"flight.{kind}",
                         "data": data})
    rows.sort(key=lambda r: r["t"])
    if last_s is not None and rows:
        cutoff = rows[-1]["t"] - last_s
        rows = [r for r in rows if r["t"] >= cutoff]
    return rows


def view_timeline(events: list[dict]) -> list[dict]:
    """Partition events into per-view segments. A view segment opens at
    the preceding view's `view_change_complete` (view 0 opens at the
    first event) and records what happened inside it."""
    views: list[dict] = []
    cur = {"view_no": 0, "from_t": events[0]["t"] if events else None,
           "events": {}, "vc_stall": None}

    def _close(at_t):
        cur["to_t"] = at_t
        views.append(dict(cur))

    for r in events:
        ev, data = r.get("event"), r.get("data")
        if ev == "view_change_complete":
            _close(r["t"])
            cur = {"view_no": data, "from_t": r["t"], "events": {},
                   "vc_stall": None}
            continue
        cur["events"][ev] = cur["events"].get(ev, 0) + 1
        if ev == "vc_stall_phases" and isinstance(data, dict):
            # emitted just BEFORE view_change_complete, so the stall
            # record lands in the view segment the VC ended — i.e. a
            # view's vc_stall describes how that view DIED
            t0 = min(data.values())
            cur["vc_stall"] = {
                "total_s": round(max(data.values()) - t0, 3),
                "phases": {k: round(v - t0, 3)
                           for k, v in sorted(data.items(),
                                              key=lambda kv: kv[1])}}
    _close(events[-1]["t"] if events else None)
    return views


def analyze_node(node_dir: str, last_s: Optional[float] = None) -> dict:
    events = read_events(os.path.join(node_dir, "events.jsonl"), last_s)
    # flight-recorder anomalies (breaker transitions, tracer-side VC /
    # catchup / suspicion stamps) merge into the SAME per-view timeline:
    # a view segment then shows the device-plane story next to the
    # protocol one, which is exactly what a breaker-open-during-VC
    # postmortem needs in one place
    flight = read_flight_anomalies(node_dir, last_s)
    if flight:
        events = sorted(events + flight, key=lambda r: r.get("t", 0))
    counts: dict[str, int] = {}
    for r in events:
        counts[r.get("event", "?")] = counts.get(r.get("event", "?"), 0) + 1
    return {
        "node": os.path.basename(node_dir.rstrip("/")),
        "event_counts": counts,
        "flight_anomalies": len(flight),
        "views": view_timeline(events),
        "error_clusters": cluster_log_text(
            os.path.join(node_dir, "node.log")),
    }


def _print_report(rep: dict) -> None:
    print(f"== {rep['node']} ==")
    if rep["event_counts"]:
        print("  events:", ", ".join(f"{k}={v}" for k, v in
                                     sorted(rep["event_counts"].items())))
    for v in rep["views"]:
        span = ""
        if v.get("from_t") is not None and v.get("to_t") is not None:
            span = f" ({v['to_t'] - v['from_t']:.1f}s)"
        evs = ", ".join(f"{k}={n}" for k, n in sorted(v["events"].items()))
        print(f"  view {v['view_no']}{span}: {evs or '-'}")
        if v.get("vc_stall"):
            st = v["vc_stall"]
            print(f"    vc stall {st['total_s']}s: "
                  + " -> ".join(f"{k}@{t}s"
                                for k, t in st["phases"].items()))
    for c in rep["error_clusters"][:10]:
        print(f"  [{c['level']} x{c['count']}] {c['template'][:150]}")


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base-dir", required=True)
    ap.add_argument("--node", help="one node (default: every node dir)")
    ap.add_argument("--last-s", type=float, default=None)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.node:
        dirs = [os.path.join(args.base_dir, args.node)]
    else:
        dirs = sorted(d for d in glob.glob(os.path.join(args.base_dir, "*"))
                      if os.path.isdir(d)
                      and (os.path.exists(os.path.join(d, "events.jsonl"))
                           or os.path.exists(os.path.join(d, "node.log"))
                           or glob.glob(os.path.join(d, "*flight*.json"))))
    reports = [analyze_node(d, args.last_s) for d in dirs]
    if args.json:
        print(json.dumps(reports, indent=2))
    else:
        for rep in reports:
            _print_report(rep)


if __name__ == "__main__":
    main()
