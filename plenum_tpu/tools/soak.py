"""Pool soak: sustained write load with memory/GC telemetry.

Answers the question the reference's gc_trackers exist for
(common/gc_trackers.py + node.py:180,2283): does a pool under sustained
load leak? Runs the in-process 4-node pool (full authN -> propagate ->
3PC+BLS -> execute pipeline) in WAVES of NYM writes for --seconds, sampling
RSS / gc-tracked objects / gc pause time between waves via the same
sample_process_gauges the node flushes (common/metrics.py), and prints one
JSON summary: per-wave TPS + rss trajectory + first/last deltas.

Bounded-growth is judged by the shared history-plane primitive
(observability/history.py GrowthWatch): the per-wave rss/gc samples
feed a windowed linear fit per gauge, and ``growth_verdicts`` in the
summary says bounded / growing / insufficient — the same verdict rule
the fleet aggregator pages through, instead of a hand-rolled
first-vs-last delta.

    python -m plenum_tpu.tools.soak --seconds 600 [--wave 200]
"""
from __future__ import annotations

import argparse
import json
import time


def run_soak(seconds: float = 600.0, wave: int = 200,
             n_nodes: int = 4) -> dict:
    from plenum_tpu.common.metrics import (MetricsCollector, MetricsName,
                                           sample_process_gauges)
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import NYM
    from plenum_tpu.observability.history import GrowthWatch
    from plenum_tpu.tools.local_pool import build_pool

    (names, nodes, timer, trustee,
     replies, Reply, DOMAIN_LEDGER_ID, plane, net) = build_pool(n_nodes, "cpu")

    # rss/gc-tracked trends judged by the shared growth-verdict rule;
    # cumulative counters (gc pause, gen2 count) grow by design and are
    # reported, not judged
    watch = GrowthWatch(window=max(60.0, seconds), min_points=5,
                        floors={"rss_mb": 64.0, "gc_tracked": 200_000.0})
    t_start = time.perf_counter()

    def sample() -> dict:
        c = MetricsCollector()
        sample_process_gauges(c)
        s = c.summary()
        out = _fold_sample(s, MetricsName)
        t = time.perf_counter() - t_start
        for gauge in ("rss_mb", "gc_tracked"):
            if out.get(gauge) is not None:
                watch.note(gauge, t, out[gauge])
        return out

    def _fold_sample(s, MetricsName) -> dict:
        return {
            "rss_mb": round(
                s[MetricsName.PROCESS_RSS_BYTES]["max"] / 2**20, 1)
            if MetricsName.PROCESS_RSS_BYTES in s else None,
            "gc_tracked": s[MetricsName.GC_TRACKED_OBJECTS]["max"],
            "gc_pause_s": round(s[MetricsName.GC_PAUSE_TIME]["max"], 3),
            "gc_gen2": s.get(MetricsName.GC_GEN2_COLLECTIONS,
                             {"max": 0})["max"],
        }

    t_end = time.perf_counter() + seconds
    waves = []
    samples = [sample()]
    req_no = 0
    wave_no = 0
    while time.perf_counter() < t_end:
        reqs = []
        for _ in range(wave):
            req_no += 1
            user = Ed25519Signer(
                seed=(b"soak%08d" % req_no).ljust(32, b"\0")[:32])
            req = Request(trustee.identifier, req_no,
                          {"type": NYM, "dest": user.identifier,
                           "verkey": user.verkey_b58})
            req.signature = trustee.sign_b58(req.signing_bytes())
            reqs.append(req)
        t0 = time.perf_counter()
        done = set()
        i = 0
        while len(done) < len(reqs) and time.perf_counter() < t0 + 120:
            while i < len(reqs) and i - len(done) < 256:
                for n in names:
                    nodes[n].handle_client_message(reqs[i].to_dict(), "soak")
                i += 1
            timer.service()
            for node in nodes.values():
                node.prod()
            if plane is not None:
                plane.flush()
            for _, msg, _c in replies[names[0]]:
                if isinstance(msg, Reply):
                    d = msg.result.get("txn", {}).get("metadata", {}) \
                        .get("digest")
                    if d:
                        done.add(d)
            replies[names[0]].clear()
        dt = time.perf_counter() - t0
        wave_no += 1
        waves.append({"wave": wave_no, "ordered": len(done),
                      "tps": round(len(done) / dt, 1) if dt else 0.0})
        samples.append(sample())
        for n in names:
            replies[n].clear()

    first, last = samples[0], samples[-1]
    ledger_sizes = {nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
                    for n in names}
    return {
        "seconds": seconds, "waves": len(waves), "wave_size": wave,
        "txns_total": sum(w["ordered"] for w in waves),
        "tps_first_wave": waves[0]["tps"] if waves else None,
        "tps_last_wave": waves[-1]["tps"] if waves else None,
        "rss_mb_start": first["rss_mb"], "rss_mb_end": last["rss_mb"],
        "rss_mb_growth": round((last["rss_mb"] or 0) - (first["rss_mb"] or 0), 1),
        "gc_pause_s_total": last["gc_pause_s"],
        "gc_gen2_collections": last["gc_gen2"],
        "ledgers_agree": len(ledger_sizes) == 1,
        "samples": samples[:: max(1, len(samples) // 10)],
        "growth_verdicts": watch.verdicts(),
        "growth_ok": not any(v.get("verdict") == "growing"
                             for v in watch.verdicts().values()),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seconds", type=float, default=600.0)
    ap.add_argument("--wave", type=int, default=200)
    args = ap.parse_args(argv)
    print(json.dumps(run_soak(args.seconds, args.wave)))


if __name__ == "__main__":
    main()
