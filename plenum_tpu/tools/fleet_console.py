"""Live fleet console: the telemetry plane's operator surface.

Reads node telemetry spools (the rotating ``<node>-telemetry-N.json``
windows ``observability/snapshot.py`` writes next to each node's data,
atomic so a live tail never sees a torn file) plus any flight-recorder
dumps, feeds a :class:`FleetAggregator`, and renders the pool-wide view:
per-node/per-shard health, ordered rates, the shard load-imbalance
index, SLO burn rates, active alerts, and cross-node incident timelines.

    python -m plenum_tpu.tools.fleet_console BASE_DIR...
        [--json] [--watch SECONDS] [--last-n 5]
    python -m plenum_tpu.tools.fleet_console --check   # tier-1 self-test

``--watch`` re-reads and re-renders every N seconds — the "live text
dashboard"; a one-shot run renders the spool's current window once.
``--check`` drives the aggregator through synthetic healthy / overload /
crypto-fault / hot-shard streams and asserts the judgments the tier-1
smoke rides on (zero idle alerts, the ingress burn alert, health
degrade + recovery, the imbalance flag, incident clustering).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Optional


def load_spools(paths) -> list[dict]:
    """Spool files / directories -> snapshots sorted by (t, node, seq).
    Directories are searched recursively for *-telemetry-*.json."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(glob.glob(
                os.path.join(p, "**", "*-telemetry-*.json"),
                recursive=True))
        elif p.endswith(".json"):
            files.append(p)
    snaps = []
    for f in sorted(files):
        try:
            with open(f) as fh:
                d = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue                 # a rotating slot mid-replace: skip
        if isinstance(d, dict) and "counters" in d and "node" in d:
            snaps.append(d)
    snaps.sort(key=lambda s: (s.get("t", 0.0), s.get("node", ""),
                              s.get("seq", 0)))
    return snaps


def load_flight_dumps(paths) -> list[dict]:
    from plenum_tpu.tools.trace_report import load_dumps
    return load_dumps([p for p in paths if os.path.isdir(p)])


def build_view(paths, config=None):
    """-> (aggregator, incidents) from on-disk artifacts. The console's
    aggregator carries its own IN-MEMORY history ring (rebuilt from the
    spool window each refresh — writing slots from a reader would fight
    the pool's own on-disk ring), so TREND renders without extra I/O."""
    from plenum_tpu.observability import (FleetAggregator, HistoryRecorder,
                                          incident_timelines)
    agg = FleetAggregator(config=config)
    agg.attach_history(HistoryRecorder(
        max_slots=getattr(config, "HISTORY_MAX_SLOTS", 512)))
    for snap in load_spools(paths):
        agg.ingest(snap)
    dumps = load_flight_dumps(paths)
    incidents = incident_timelines(
        dumps, alerts=agg.alerts, history=agg.history) \
        if (dumps or agg.alerts) else []
    return agg, incidents


def render(agg, incidents, last_n: int = 5) -> str:
    from plenum_tpu.observability.correlate import format_incidents
    s = agg.fleet_summary()
    lines = [f"fleet @ t={s['t']:.2f}  snapshots={s['snapshots']}  "
             f"nodes={len(s['nodes'])}"]
    epochs = s.get("mapping_epochs", {})
    migrations = s.get("migrations", {})
    hdr = (f"  {'node':12} {'shard':>5} {'health':>7} {'seq':>6} "
           f"{'anchor_age':>10} {'epoch':>6} {'migration':>16}")
    lines.append(hdr)
    lines.append("  " + "-" * (len(hdr) - 2))
    for name, row in s["nodes"].items():
        h = row["health"]
        age = row["anchor_age"]
        shard = row["shard"]
        epoch = epochs.get(str(shard)) if shard is not None else None
        mig = migrations.get(str(shard)) if shard is not None else None
        mig_cell = "-"
        if mig:
            mig_cell = (f"{mig.get('role', '?')[:3]}:"
                        f"{mig.get('phase', '?')}"
                        f"@{mig.get('progress', 0.0):.0%}")
        lines.append(
            f"  {name:12} {str(shard if shard is not None else '-'):>5} "
            f"{'-' if h is None else format(h, '.2f'):>7} "
            f"{str(row['seq'] if row['seq'] is not None else '-'):>6} "
            f"{'-' if age is None else format(age, '.1f'):>10} "
            f"{str(epoch if epoch is not None else '-'):>6} "
            f"{mig_cell:>16}")
    if s["shard_health"]:
        lines.append(f"  shard health: {s['shard_health']}  "
                     f"ordered/s: {s['ordered_rates']}")
    if migrations:
        lines.append("  migrations: " + ", ".join(
            f"shard {sid}: {m.get('role')} {m.get('phase')} "
            f"{m.get('progress', 0.0):.0%}"
            for sid, m in sorted(migrations.items())))
    if s["load_imbalance"] is not None:
        hot = s["hot_shard"]
        lines.append(f"  load imbalance index: {s['load_imbalance']}"
                     + (f"  HOT SHARD: {hot}" if hot is not None else ""))
    if s.get("staleness"):
        worst = max(s["staleness"].items(), key=lambda kv: kv[1])
        lines.append(f"  anchor staleness (worst): {worst[0]}="
                     f"{worst[1]:.1f}s")
    # multi-device crypto ring: name the sick chip(s) — a lane whose
    # breaker is not closed is serving its pinned traffic on host
    # fallback while the rest of the ring keeps dispatching
    sick_lanes = []
    for name, snap in sorted(getattr(agg, "latest", {}).items()):
        pipe_state = snap.get("state", {}).get("pipeline", {})
        for dev in pipe_state.get("devices", []) or []:
            if dev.get("breaker") not in ("closed", "none"):
                sick_lanes.append(
                    f"{name}:lane{dev.get('lane')}={dev.get('breaker')}")
    if sick_lanes:
        lines.append("  SICK CHIPS: " + ", ".join(sick_lanes))
    # cross-host federation: rented remote crypto-host lanes — roster
    # size, steal traffic, ship latency, and any remote whose breaker is
    # open (that host's capacity is dark; its queue stole back local)
    remote_lines = []
    for name, snap in sorted(getattr(agg, "latest", {}).items()):
        pipe_state = snap.get("state", {}).get("pipeline", {})
        fed = pipe_state.get("federation") or {}
        remotes = [d for d in pipe_state.get("devices", []) or []
                   if d.get("remote")]
        if not fed and not remotes:
            continue
        dark = [f"{d.get('host', 'lane%s' % d.get('lane'))}="
                f"{d.get('breaker')}" for d in remotes
                if d.get("breaker") not in ("closed", "none")]
        remote_lines.append(
            f"{name}: {fed.get('remote_lanes', len(remotes))} remote, "
            f"steals={fed.get('steals', 0)}"
            f"/{fed.get('stolen_items', 0)} items, "
            f"ship_p95={fed.get('ship_ms_p95', '-')}ms"
            + (f", DARK: {', '.join(dark)}" if dark else ""))
    if remote_lines:
        lines.append("  REMOTE LANES: " + "; ".join(remote_lines))
    # autopilot control plane: what the closed loop has decided — level,
    # action/revert/hold counts, and any live lane re-pins, so an
    # operator can tell actuation from drift at a glance
    ap = getattr(agg, "autopilot", None)
    if ap:
        lines.append(
            f"  AUTOPILOT: level={ap.get('state', '?')} "
            f"decisions={ap.get('decisions', 0)} "
            f"actions={ap.get('actions', 0)} "
            f"reverts={ap.get('reverts', 0)} holds={ap.get('holds', 0)}"
            + (f" repins={ap.get('repins')}" if ap.get("repins") else ""))
    # Proof-CDN edge tier (reads/edge.py): per-region keyless-cache
    # absorption — how much read traffic never reaches the pool, and
    # at what hit rate (the autopilot's observer policy reads the same
    # number before spawning)
    ed = getattr(agg, "edge", None)
    if ed:
        cells = []
        for region, row in sorted(ed.get("regions", {}).items()):
            rate = row.get("hit_rate")
            cells.append(
                f"{region} edges={row.get('edges', 0)} "
                f"served={row.get('served', 0)} "
                f"hit={'-' if rate is None else format(rate, '.0%')}")
        lines.append(f"  EDGE: " + ", ".join(cells)
                     + f"  bytes={ed.get('bytes', 0)}")
    # fleet history plane: the TREND sparklines come from the attached
    # history ring's downsampled window; FOOTPRINT is the current
    # resource-gauge inventory with growing gauges marked — the same
    # verdicts behind the unbounded_growth alert
    hist = getattr(agg, "history", None)
    if hist is not None and getattr(hist, "rows", None):
        from plenum_tpu.tools.perf_sentinel import sparkline
        rows = hist.query(max_points=24)
        tps = [float(r.get("tps", 0.0)) for r in rows]
        hmin = [float(r["health_min"]) for r in rows
                if r.get("health_min") is not None]
        lines.append(
            f"  TREND: tps {sparkline(tps)} {tps[-1]:.1f}"
            + (f"  health_min {sparkline(hmin)} {hmin[-1]:.2f}"
               if hmin else "")
            + f"  rows={len(hist.rows)}/{hist.seq}")
    fp = s.get("footprint")
    if fp:
        from plenum_tpu.observability import GROWTH_EXEMPT_GAUGES
        growth = s.get("growth", {})
        cells = []
        for gauge in sorted(fp):
            mark = "↑!" if (gauge not in GROWTH_EXEMPT_GAUGES
                            and growth.get(gauge, {}).get("verdict")
                            == "growing") else ""
            cells.append(f"{gauge}={int(fp[gauge])}{mark}")
        lines.append("  FOOTPRINT: " + " ".join(cells))
        growing = sorted(g for g, v in growth.items()
                         if v.get("verdict") == "growing"
                         and g not in GROWTH_EXEMPT_GAUGES)
        if growing:
            lines.append("  UNBOUNDED GROWTH: " + ", ".join(
                f"{g} +{growth[g].get('slope_per_s', 0)}/s "
                f"(projected {growth[g].get('projected')} > "
                f"{growth[g].get('threshold')})" for g in growing))
    for kind, per_node in s["burn"].items():
        burning = {n: b for n, b in per_node.items()
                   if b["fast"] > 0 or b["slow"] > 0}
        if burning:
            lines.append(f"  burn[{kind}]: " + ", ".join(
                f"{n} fast={b['fast']} slow={b['slow']}"
                for n, b in sorted(burning.items())))
    active = s["active_alerts"]
    lines.append(f"  alerts: {len(active)} active / "
                 f"{len(s['alerts'])} recent")
    for a in active[-last_n:]:
        lines.append(f"    [{a['severity']}] {a['kind']} "
                     f"{a['subject']}: {json.dumps(a['detail'])}")
    if incidents:
        lines.append("  incidents:")
        for line in format_incidents(incidents, last_n):
            lines.append(f"    {line}")
    return "\n".join(lines)


# --- the --check self-test ---------------------------------------------------

def _snap(node, seq, t, state, tags=None):
    return {"v": 1, "node": node, "seq": seq, "t": t,
            **({"tags": tags} if tags else {}),
            "counters": {}, "sampled": {}, "state": state}


def self_check() -> int:
    """Synthetic streams through the real aggregator; asserts the
    judgments the acceptance criteria name. -> process exit code."""
    from plenum_tpu.config import Config
    from plenum_tpu.observability import FleetAggregator, incident_timelines

    problems = []
    config = Config(SLO_BURN_FAST_WINDOW=5.0, SLO_BURN_SLOW_WINDOW=20.0)
    nodes = ["N1", "N2", "N3", "N4"]

    def healthy(node, seq, t, ordered=0, shard=None, slo=None):
        state = {"node": {"ordered_total": ordered, "view_no": 0,
                          "vc_in_progress": False, "catchup_running": False,
                          "read_only_degraded": False, "validators": 4,
                          "anchor_age": 1.0}}
        if slo is not None:
            state["ingress"] = {"queue_depth": 0, "shedding": False,
                                "slo": slo}
        return _snap(node, seq, t, state,
                     tags={"shard": shard} if shard is not None else None)

    # 1) idle healthy pool: ZERO alerts, health 1.0 everywhere
    agg = FleetAggregator(config=config)
    for i in range(30):
        for n in nodes:
            agg.ingest(healthy(n, i, i * 1.0, ordered=i,
                               slo=[0, 5]))
    if agg.alerts:
        problems.append(f"idle pool raised alerts: "
                        f"{[a.to_dict() for a in agg.alerts]}")
    if any(agg.node_health(n) != 1.0 for n in nodes):
        problems.append(f"idle pool unhealthy: "
                        f"{ {n: agg.node_health(n) for n in nodes} }")

    # 2) sustained ingress overload: the burn-rate alert fires on both
    # windows, then CLEARS after recovery
    agg2 = FleetAggregator(config=config)
    t = 0.0
    for i in range(25):
        t = i * 1.0
        agg2.ingest(healthy("N1", i, t, slo=[4, 5] if i >= 5 else [0, 5]))
    fired = [a for a in agg2.alerts if a.kind == "slo_burn.ingress"
             and a.severity == "page"]
    if not fired:
        problems.append("sustained overload never fired the ingress "
                        "burn alert")
    for i in range(25, 60):
        t = i * 1.0
        agg2.ingest(healthy("N1", i, t, slo=[0, 5]))
    cleared = [a for a in agg2.alerts if a.kind == "slo_burn.ingress"
               and a.severity == "clear"]
    if fired and not cleared:
        problems.append("ingress burn alert never cleared after recovery")

    # 3) crypto-plane fault: breaker open + front door shedding degrade
    # the health score below the floor (warn alert), then recovery clears
    agg3 = FleetAggregator(config=config)
    sick = healthy("N1", 0, 0.0)
    sick["state"]["crypto"] = {"breaker_state": "open"}
    sick["state"]["ingress"] = {"shedding": True}
    agg3.ingest(sick)
    h_sick = agg3.node_health("N1")
    if h_sick is None or h_sick >= 0.5:
        problems.append(f"breaker-open health {h_sick} not degraded")
    if not any(a.kind == "health.node" for a in agg3.alerts):
        problems.append("degraded health raised no alert")
    agg3.ingest(healthy("N1", 1, 1.0))
    if agg3.node_health("N1") != 1.0:
        problems.append("health did not recover after the fault healed")
    if not any(a.severity == "clear" and a.kind == "health.node"
               for a in agg3.alerts):
        problems.append("health alert never cleared")

    # 3b) multi-device ring: ONE sick chip lane degrades the node
    # lightly (lane penalty, not the full plane-breaker one) and the
    # console names the chip — the operator must see WHICH lane is sick
    agg3b = FleetAggregator(config=config)
    laney = healthy("N1", 0, 0.0)
    laney["state"]["pipeline"] = {
        "occupancy": 0, "dispatches": 10, "breakers_open": 1,
        "devices": [
            {"lane": 0, "breaker": "closed", "occupancy": 0,
             "dispatches": 5},
            {"lane": 2, "breaker": "open", "occupancy": 3,
             "dispatches": 5}]}
    agg3b.ingest(laney)
    h_lane = agg3b.node_health("N1")
    if h_lane is None or not (0.5 < h_lane < 1.0):
        problems.append(f"one sick lane health {h_lane}: expected a "
                        f"light ding, not full-plane or healthy")
    text = render(agg3b, [])
    if "SICK CHIPS" not in text or "N1:lane2=open" not in text:
        problems.append("console did not name the sick chip lane")
    agg3b.ingest(healthy("N1", 1, 1.0))
    if agg3b.node_health("N1") != 1.0:
        problems.append("lane health did not recover after re-admission")

    # 3c) cross-host federation: the console shows the rented remote
    # lanes (roster, steal traffic, ship latency) and names a remote
    # host whose breaker is open — dark rented capacity must be visible
    agg3c = FleetAggregator(config=config)
    feddy = healthy("N1", 0, 0.0)
    feddy["state"]["pipeline"] = {
        "occupancy": 0, "dispatches": 20, "breakers_open": 1,
        "devices": [
            {"lane": 0, "breaker": "closed", "occupancy": 0,
             "dispatches": 12},
            {"lane": 1, "breaker": "open", "occupancy": 0,
             "dispatches": 8, "remote": True, "host": "/run/ch0.sock",
             "steals_in": 2, "steals_out": 1}],
        "federation": {"remote_lanes": 1, "steals": 3,
                       "stolen_items": 96, "remote_breakers_open": 1,
                       "ship_ms_p95": 4.2}}
    agg3c.ingest(feddy)
    text = render(agg3c, [])
    if "REMOTE LANES" not in text:
        problems.append("console did not show the federated remote lanes")
    elif "/run/ch0.sock=open" not in text or "steals=3" not in text:
        problems.append("console did not name the dark remote host "
                        "or its steal traffic")

    # 3d) autopilot seam: when the control plane published a summary,
    # the console renders the AUTOPILOT line (level + counts + repins)
    agg3d = FleetAggregator(config=config)
    agg3d.ingest(healthy("N1", 0, 0.0))
    agg3d.autopilot = {"level": 1, "state": "shed_harder",
                       "decisions": 12, "actions": 3, "reverts": 1,
                       "holds": 2, "repins": {0: {"prev": 0, "sick": 2}}}
    text = render(agg3d, [])
    if "AUTOPILOT: level=shed_harder" not in text \
            or "actions=3" not in text or "repins=" not in text:
        problems.append("console did not render the autopilot line")

    # 3e) edge tier seam: windows fed through note_edge render the EDGE
    # line (per-region fleet size + served volume + windowed hit rate),
    # and the windowed fold exposes the hit rate the autopilot reads
    agg3e = FleetAggregator(config=config)
    agg3e.ingest(healthy("N1", 0, 0.0))
    agg3e.note_edge("r0", hits=90, served=100, edges=2,
                    bytes_served=4096, now=1.0)
    agg3e.note_edge("r0", hits=98, served=100, edges=2,
                    bytes_served=4096, now=2.0)
    rate = agg3e.edge_hit_rate("r0")
    if rate is None or abs(rate - 0.94) > 1e-9:
        problems.append(f"edge hit-rate fold wrong: {rate}")
    text = render(agg3e, [])
    if "EDGE:" not in text or "r0 edges=2" not in text \
            or "hit=94%" not in text:
        problems.append("console did not render the edge line")

    # 4) hot shard: skewed ordered rates flag shard 0
    agg4 = FleetAggregator(config=config)
    for i in range(30):
        t = i * 1.0
        agg4.ingest(healthy("S0N1", i, t, ordered=i * 50, shard=0))
        agg4.ingest(healthy("S1N1", i, t, ordered=i * 2, shard=1))
    index, hot = agg4.load_imbalance()
    if hot != 0 or index is None or index < config.SHARD_IMBALANCE_THRESHOLD:
        problems.append(f"hot shard not flagged: index={index} hot={hot}")
    if not any(a.kind == "shard.imbalance" for a in agg4.alerts):
        problems.append("imbalance raised no alert")

    # 4b) reshard convergence: the per-shard mapping-epoch + migration-
    # progress columns an operator watches a live split through — the
    # laggard's epoch is what shows, and the migration column clears
    # when the handoff completes
    agg4b = FleetAggregator(config=config)

    def resharding(node, seq, t, shard, epoch, mig=None):
        snap = healthy(node, seq, t, ordered=seq, shard=shard)
        snap["state"]["shard_map"] = {"epoch": epoch,
                                      **({"migration": mig} if mig else {})}
        return snap

    agg4b.ingest(resharding("S0N2", 0, 0.0, 0, 0))    # laggard: epoch 0
    agg4b.ingest(resharding("S0N1", 0, 0.5, 0, 1,
                            mig={"role": "source", "phase": "copying",
                                 "progress": 0.4}))
    agg4b.ingest(resharding("S2N1", 0, 0.5, 2, 1,
                            mig={"role": "target", "phase": "copying",
                                 "progress": 0.4}))
    if agg4b.mapping_epochs() != {0: 0, 2: 1}:
        problems.append(f"mapping epochs wrong (laggard must show): "
                        f"{agg4b.mapping_epochs()}")
    migs = agg4b.migrations()
    if set(migs) != {0, 2} or migs[0].get("role") != "source" \
            or migs[2].get("role") != "target":
        problems.append(f"migration columns wrong: {migs}")
    txt = render(agg4b, [])
    if "sou:copying@40%" not in txt or "migrations:" not in txt:
        problems.append("console does not render migration progress")
    # the handoff completes: migration column clears, epochs converge
    agg4b.ingest(resharding("S0N1", 1, 1.0, 0, 1))
    agg4b.ingest(resharding("S0N2", 1, 1.0, 0, 1))
    agg4b.ingest(resharding("S2N1", 1, 1.0, 2, 1))
    if agg4b.migrations() or agg4b.mapping_epochs() != {0: 1, 2: 1}:
        problems.append(
            f"post-reshard view did not converge: "
            f"{agg4b.migrations()} {agg4b.mapping_epochs()}")
    # a decommissioned (merged-away) node is FORGOTTEN, not paged
    agg4b.forget_node("S2N1")
    if "S2N1" in agg4b.fleet_summary()["nodes"]:
        problems.append("forget_node left the retired node enrolled")

    # 5) incident clustering: anomalies on two nodes within the gap fold
    # into ONE incident; a distant one stands alone
    dumps = [
        {"node": "A", "clock_domain": "shared", "mono_anchor": 0.0,
         "wall_anchor": None, "dumped_at": 50.0, "anomalies": 2,
         "events": [[10.0, "anomaly.suspicion", "", {"code": 1}],
                    [10.5, "anomaly.view_change_start", "", {}]]},
        {"node": "B", "clock_domain": "shared", "mono_anchor": 0.0,
         "wall_anchor": None, "dumped_at": 50.0, "anomalies": 2,
         "events": [[11.0, "anomaly.view_change_start", "", {}],
                    [40.0, "anomaly.breaker", "", {"to": "open"}]]},
    ]
    incidents = incident_timelines(dumps, gap_s=2.0)
    if len(incidents) != 2 or incidents[0]["nodes"] != ["A", "B"] \
            or len(incidents[0]["events"]) != 3:
        problems.append(f"incident clustering wrong: {incidents}")

    # 6) the renderer survives every view above (smoke, not goldens)
    try:
        for a in (agg, agg2, agg3, agg3e, agg4, agg4b):
            render(a, incidents)
    except Exception as e:
        problems.append(f"render failed: {type(e).__name__}: {e}")

    # 7) fleet history plane: bounded footprint gauges stay quiet, an
    # injected leak raises EXACTLY ONE unbounded_growth page naming the
    # gauge, ledger-backed gauges never page, the history ring honors
    # its slot bound, query() downsamples, and the console renders the
    # TREND/FOOTPRINT rungs off the same ring
    from plenum_tpu.observability import HistoryRecorder
    agg7 = FleetAggregator(config=config)
    agg7.attach_history(HistoryRecorder(max_slots=16))
    for i in range(60):
        snap = healthy("N1", i, i * 1.0, ordered=i * 3)
        snap["state"]["footprint"] = {
            # breathing inside its working set: bounded
            "stashed_entries": 120 + (i % 5) * 8,
            # the injected leak: grows without bound
            "leaky_stash": 80 + 10 * i,
            # ledger-backed: grows by design, exempt from paging
            "kv_entries": 1000 * (i + 1),
        }
        agg7.ingest(snap)
    pages = [a for a in agg7.alerts if a.kind == "unbounded_growth"
             and a.severity == "page"]
    if len(pages) != 1 or pages[0].subject != "leaky_stash" \
            or pages[0].detail.get("gauge") != "leaky_stash":
        problems.append(
            f"leak should page exactly once naming leaky_stash: "
            f"{[a.to_dict() for a in pages]}")
    if any(a.subject in ("stashed_entries", "kv_entries")
           for a in agg7.alerts if a.kind == "unbounded_growth"):
        problems.append("bounded/exempt gauge paged unbounded_growth")
    if len(agg7.history.rows) > 16 or agg7.history.seq != 60:
        problems.append(
            f"history ring unbounded: rows={len(agg7.history.rows)} "
            f"seq={agg7.history.seq}")
    down = agg7.history.query(max_points=5)
    full = agg7.history.window()
    if len(down) != 5 or down[0] != full[0] or down[-1] != full[-1]:
        problems.append(f"query downsample wrong: {len(down)} rows")
    text = render(agg7, [])
    if "TREND:" not in text or "FOOTPRINT:" not in text \
            or "leaky_stash" not in text:
        problems.append("console did not render TREND/FOOTPRINT rungs")

    print(json.dumps({"check": "ok" if not problems else "FAIL",
                      "problems": problems}))
    return 1 if problems else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help="dirs holding *-telemetry-*.json spools "
                         "(+ optional flight dumps)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--watch", type=float, default=None, metavar="SECONDS")
    ap.add_argument("--last-n", type=int, default=5)
    ap.add_argument("--config", action="append", default=[],
                    metavar="NAME=VALUE",
                    help="Config override (repeatable), e.g. "
                         "--config SLO_BURN_THRESHOLD=1.2 — the console "
                         "must judge with the POOL's thresholds, not the "
                         "defaults, or dashboard and pool disagree")
    ap.add_argument("--check", action="store_true",
                    help="run the built-in self-test and exit")
    args = ap.parse_args(argv)
    if args.check:
        return self_check()
    if not args.paths:
        ap.error("paths required (or --check)")
    from plenum_tpu.config import Config
    overrides = {}
    for item in args.config:
        name, _, raw = item.partition("=")
        if not _:
            ap.error(f"--config wants NAME=VALUE, got {item!r}")
        try:
            overrides[name] = json.loads(raw)
        except json.JSONDecodeError:
            overrides[name] = raw
    config = Config(**overrides)
    prev_mark = None
    while True:
        agg, incidents = build_view(args.paths, config=config)
        if not agg.latest:
            print(json.dumps(
                {"error": f"no telemetry spools under {args.paths}"}))
            return 1
        # staleness is judged on the FLEET clock (newest snapshot anyone
        # sent), which needs at least one live reporter — a whole-pool
        # outage freezes it, so the console itself watches for a spool
        # that stopped advancing between refreshes
        mark = (agg.snapshots, agg.now)
        spool_idle = args.watch is not None and prev_mark == mark
        prev_mark = mark
        if args.json:
            print(json.dumps({"fleet": agg.fleet_summary(),
                              "spool_idle": spool_idle,
                              "incidents": [
                                  {k: v for k, v in inc.items()
                                   if k != "events"}
                                  for inc in incidents[-args.last_n:]]},
                             default=repr))
        else:
            if args.watch:
                print("\033[2J\033[H", end="")    # clear for the live view
            print(render(agg, incidents, args.last_n))
            if spool_idle:
                print("  WARNING: no new telemetry since the last "
                      "refresh — the whole fleet may be down (health "
                      "scores above are last-known, not live)")
        if not args.watch:
            return 0
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
