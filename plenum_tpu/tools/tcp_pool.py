"""Real-transport pool benchmark: N OS processes over TCP under write load.

The in-process benchmark (tools/local_pool.py) measures the consensus
pipeline over the deterministic sim fabric; THIS tool stands up the same
pool the way an operator would — keygen + genesis + one start_node process
per validator, authenticated-encrypted TCP between them (network/tcp_stack)
— and drives pre-signed NYM writes through the client ports with a
pipelined streaming client, reporting wall-clock TPS and commit latency.
This is the framework's analog of benchmarking the reference's
scripts/start_plenum_node x4 localhost pool.

    python -m plenum_tpu.tools.tcp_pool --nodes 4 --txns 200 [--json]
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import signal
import socket
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def setup_pool_dir(base: str, names: list[str], trustee_seed: bytes):
    """keygen + genesis files for a localhost pool -> port specs."""
    from plenum_tpu.tools import genesis as gen
    from plenum_tpu.tools import keygen

    ports = _free_ports(2 * len(names))
    specs = []
    for i, name in enumerate(names):
        keygen.save_keys(keygen.generate_keys(
            name, seed=(b"tcppool%d" % i).ljust(32, b"\0")), base)
        specs.append((name, "127.0.0.1", ports[2 * i], ports[2 * i + 1]))
    gen.build_genesis_files(base, specs, trustee_seed)
    return specs


def _wait_all_started(procs, deadline_s: float) -> None:
    """Wait (bounded!) for every child to print its "started" line — a
    wedged child must fail the bench, never hang it."""
    import selectors
    deadline = time.perf_counter() + deadline_s
    sel = selectors.DefaultSelector()
    pending = {}
    for p in procs:
        os.set_blocking(p.stdout.fileno(), False)
        sel.register(p.stdout, selectors.EVENT_READ, p)
        pending[p.stdout.fileno()] = b""
    try:
        while pending:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                raise RuntimeError(
                    f"{len(pending)} node(s) never reported 'started'")
            for key, _ in sel.select(timeout=remaining):
                fd = key.fileobj.fileno()
                chunk = key.fileobj.read() or b""
                buf = pending[fd] + chunk
                if b"started" in buf:
                    sel.unregister(key.fileobj)
                    del pending[fd]
                elif key.data.poll() is not None:
                    raise RuntimeError(
                        f"node exited before starting: {buf!r}")
                else:
                    pending[fd] = buf
    finally:
        sel.close()
        for p in procs:
            if p.poll() is None:
                os.set_blocking(p.stdout.fileno(), True)


async def drive_load(addrs, f, requests, window: int, timeout: float):
    """-> (done {key: t_done}, submit_times {key: t_sent})."""
    from plenum_tpu.client.pipelined import PipelinedPoolClient
    client = PipelinedPoolClient(addrs, f)
    return await client.drive(requests, window=window, timeout=timeout)


def run_tcp_pool(n_nodes: int = 4, n_txns: int = 200, backend: str = "cpu",
                 base_dir: str | None = None, timeout: float = 120.0,
                 profile_dir: str | None = None,
                 service_min_batch: int | None = None,
                 window: int = 100,
                 config_overrides: dict | None = None) -> dict:
    from plenum_tpu.client.wallet import Wallet
    from plenum_tpu.execution.txn import NYM

    names = [f"Node{i + 1}" for i in range(n_nodes)]
    f = (n_nodes - 1) // 3
    tmp = base_dir or tempfile.mkdtemp(prefix="plenum_tcp_pool_")
    trustee_seed = b"tcp-pool-trustee".ljust(32, b"\0")
    specs = setup_pool_dir(tmp, names, trustee_seed)

    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    # reproducibility: node config comes ONLY from the explicit param —
    # a stray PLENUM_CONFIG_JSON in the operator shell must not silently
    # reconfigure every bench node
    env.pop("PLENUM_CONFIG_JSON", None)
    if config_overrides:
        env["PLENUM_CONFIG_JSON"] = json.dumps(config_overrides)
    procs = []
    service_proc = None
    # "service:<inner>" runs the cross-process crypto plane: ONE process
    # owns the device/verifier, nodes ship batches to it (the topology a
    # single TPU chip requires — n processes initializing jax wedge on
    # device contention; parallel/crypto_service.py)
    try:
        if backend.startswith("service:"):
            inner = backend.split(":", 1)[1]
            sock_path = os.path.join(tmp, "crypto.sock")
            service_env = dict(env)
            if inner.startswith("jax"):
                # the service owns the real device; nodes keep
                # JAX_PLATFORMS=cpu
                service_env.pop("JAX_PLATFORMS", None)
            service_proc = subprocess.Popen(
                [sys.executable, "-m", "plenum_tpu.parallel.crypto_service",
                 "--socket", sock_path, "--backend", inner,
                 # device dispatches pay a fixed tunnel round-trip that
                 # dwarfs padded compute (48 ms RTT vs ~4 ms at 512), so
                 # the jax plane pads to ONE large bucket; min_batch only
                 # pads — it never waits — so latency is unaffected
                 "--min-batch", str(service_min_batch if service_min_batch
                                    else (512 if inner.startswith("jax")
                                          else 128))],
                env=service_env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            deadline = time.perf_counter() + 240.0   # jax init can compile
            while time.perf_counter() < deadline:
                if os.path.exists(sock_path):
                    break
                if service_proc.poll() is not None:
                    raise RuntimeError("crypto service died during startup")
                time.sleep(0.2)
            else:
                raise RuntimeError("crypto service never bound its socket")
            env = dict(env, PLENUM_CRYPTO_SOCKET=sock_path)
            backend = "service"
        for name in names:
            cmd = [sys.executable, "-m", "plenum_tpu.tools.start_node",
                   "--name", name, "--base-dir", tmp, "--kv", "memory",
                   "--backend", backend]
            if profile_dir:
                cmd += ["--profile",
                        os.path.join(profile_dir, f"{name}.pstats")]
            procs.append(subprocess.Popen(
                cmd, env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT))
        _wait_all_started(procs, deadline_s=60.0)

        wallet = Wallet("bench")
        trustee_did = wallet.add_identifier(seed=trustee_seed)
        requests = []
        for i in range(n_txns):
            user = wallet.add_identifier(
                seed=(b"tcpu%d" % i).ljust(32, b"\0")[:32])
            requests.append(wallet.sign_request(
                {"type": NYM, "dest": user,
                 "verkey": wallet.verkey_of(user)}, identifier=trustee_did))

        addrs = {name: ("127.0.0.1", spec[3])
                 for name, spec in zip(names, specs)}
        t0 = time.perf_counter()
        done, submit_times = asyncio.run(
            drive_load(addrs, f, requests, window=window, timeout=timeout))
        t_total = (max(done.values()) - t0) if done else 0.0
        lat = sorted(done[k] - submit_times[k] for k in done)
        service_stats = None
        if service_proc is not None:
            try:
                from plenum_tpu.parallel.crypto_service import \
                    ServiceEd25519Verifier
                service_stats = ServiceEd25519Verifier(
                    socket_path=env["PLENUM_CRYPTO_SOCKET"]).stats()
            except Exception:
                pass
        result = {
            **({"crypto_service": service_stats} if service_stats else {}),
            "transport": "tcp", "nodes": n_nodes, "backend": backend,
            "txns_ordered": len(done), "txns_requested": n_txns,
            "seconds": round(t_total, 3),
            "tps": round(len(done) / t_total, 1) if t_total > 0 else 0.0,
            "p50_latency_ms": round(
                statistics.median(lat) * 1000, 1) if lat else None,
            "p99_latency_ms": round(
                lat[int(len(lat) * 0.99)] * 1000, 1) if lat else None,
        }
        # bytes-on-wire + loss accounting from a node's flushed metrics
        # history (SIGTERM first so the tail flush carries final totals)
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        try:
            from plenum_tpu.tools.metrics_report import (derive_summary,
                                                         fold_rows,
                                                         read_store)
            folds = fold_rows(read_store(os.path.join(tmp, names[0],
                                                      "metrics")))
            # one derivation (cum-as-max, propagate op set) lives in
            # metrics_report; this just renames the keys the bench wants
            summary = derive_summary(folds, 0.0)
            for src, dst in (
                    ("transport_tx_bytes_per_txn", "tx_bytes_per_txn"),
                    ("propagate_tx_bytes_per_txn",
                     "propagate_tx_bytes_per_txn"),
                    ("transport_dropped_frames", "dropped_frames"),
                    ("propagate_inbox_depth_max",
                     "propagate_inbox_depth_max")):
                if summary.get(src) is not None:
                    result[dst] = summary[src]
            # commit-path stage percentiles + pairing/group-commit counters
            # (derive_summary computes them from the flushed raw samples)
            stage = {k: summary[k] for k in summary
                     if k.startswith(("bls_verify_ms", "apply_ms",
                                      "durable_ms", "reply_ms"))
                     or k in ("pairings_per_batch",
                              "group_commit_batches_mean",
                              "plane_dispatches", "sig_batch_size_mean")}
            if stage:
                result["commit_stage"] = stage
            # plane-supervisor health: breaker state, fallback volume,
            # hedge wins, deadline distribution (degraded-mode acceptance:
            # these must be on the bench line, not buried in a KV store).
            # Gated on the breaker gauge: only configs that actually RAN a
            # device plane report a backend_state — a pure-CPU pool must
            # not claim a healthy device it never had.
            if "crypto_breaker_state" in summary:
                plane = {k: summary[k] for k in summary
                         if k.startswith(("crypto_", "deadline_ms_",
                                          "bls_batch", "bls_local"))}
                result["crypto_plane"] = plane
                result["backend_state"] = {
                    "closed": "ok", "half_open": "fallback",
                    "open": "open"}.get(
                        plane["crypto_breaker_state"], "ok")
        except Exception:
            pass                     # byte accounting is best-effort extra
        return result
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        if service_proc is not None:
            service_proc.terminate()
            try:
                service_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                service_proc.kill()
        if base_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--txns", type=int, default=200)
    ap.add_argument("--backend", default="cpu",
                    choices=["cpu", "jax", "service:cpu", "service:jax",
                             "service:jax-sharded"])
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    stats = run_tcp_pool(args.nodes, args.txns, args.backend)
    if args.json:
        print(json.dumps(stats))
    else:
        print(f"{stats['txns_ordered']}/{stats['txns_requested']} txns in "
              f"{stats['seconds']}s over TCP -> {stats['tps']} TPS "
              f"(p50 {stats['p50_latency_ms']} ms, "
              f"p99 {stats['p99_latency_ms']} ms)")


if __name__ == "__main__":
    main()
