"""Amdahl budget: where does a write transaction's time actually go?

The north star (SURVEY.md:19) is >=10x pool throughput via TPU crypto
offload.  Whether that is reachable is a pure Amdahl question: only the
crypto fraction of per-transaction cost can be offloaded, so the implied
ceiling is 1 / (1 - offloadable_fraction).  This tool measures that
fraction on the REAL pool: it runs the TCP pool (tools/tcp_pool — four OS
processes, encrypted TCP, full 3PC + BLS pipeline) with every node under
cProfile, then folds each node's exclusive-time profile into budget
categories:

    ed25519   client-signature verification (authN hot spot,
              ref plenum/server/client_authn.py:273 / nacl_wrappers.py:62)
    bls       BN254 sign/verify/aggregate on the commit path
              (ref plenum/bls/bls_bft_replica_plenum.py)
    merkle    ledger SHA-256 tree appends + proofs (ref ledger/)
    mpt       state trie SHA3/RLP (ref state/trie/pruning_trie.py)
    serde     wire+ledger serialization, canonical JSON, msgpack
    transport TCP stack, framing, ChaCha20 channel crypto
    idle      event-loop waits (epoll/select/sleep) — NOT offloadable,
              but also not CPU cost: it bounds how much pipelining slack
              the node has at this load
    consensus 3PC bookkeeping (ordering/checkpoint/view-change services)
    node      node orchestration, propagation, execution, storage
    other     everything else (stdlib, interpreter overhead)

Builtin C functions (OpenSSL Ed25519 verify, hashlib digests, msgpack,
socket sends) carry no filename, so their exclusive time is attributed to
the category of their CALLERS, proportionally — pstats records per-caller
splits exactly for this.

Output: one JSON line with per-category exclusive seconds and per-txn
milliseconds for the busiest node, plus the offloadable fraction and the
implied Amdahl ceiling.  docs/performance.md quotes this table.

    python -m plenum_tpu.tools.perf_budget [--nodes 4] [--txns 300]
"""
from __future__ import annotations

import argparse
import json
import os
import pstats
import tempfile

# path fragment -> category; first match wins (order matters: ops/ed25519
# before ops/, crypto/bls before consensus/)
_PATH_RULES = [
    ("crypto/ed25519", "ed25519"),
    ("ops/ed25519", "ed25519"),
    ("node/client_authn", "ed25519"),
    ("crypto/bn254", "bls"),
    ("crypto/bls", "bls"),
    ("crypto/multi_signature", "bls"),
    ("consensus/bls_bft_replica", "bls"),
    ("ops/sha256", "merkle"),
    ("ledger/", "merkle"),
    ("state/", "mpt"),
    ("common/serialization", "serde"),
    ("common/request", "serde"),        # digest computation = hashing the wire form
    ("utils/base58", "serde"),
    ("network/", "transport"),
    ("consensus/", "consensus"),
    ("node/", "node"),
    ("execution/", "node"),
    ("storage/", "node"),
    ("common/", "consensus"),           # buses, stashing, timers, messages
    ("plenum_tpu/", "node"),
]

# builtin-name patterns (checked on the function name) for C calls whose
# caller attribution is ambiguous or absent
_IDLE_BUILTINS = ("epoll", "select", "poll", "kqueue", "sleep",
                  "run_until_complete", "_run_once")


def _category_of_file(filename: str) -> str | None:
    f = filename.replace("\\", "/")
    if "plenum_tpu" in f:
        tail = f.split("plenum_tpu/", 1)[-1]
        for frag, cat in _PATH_RULES:
            if frag.rstrip("/") in ("plenum_tpu",):
                continue
            if tail.startswith(frag) or ("/" + frag) in ("/" + tail):
                return cat
        return "node"
    if "/asyncio/" in f or "selectors.py" in f:
        return "transport"
    if "/json/" in f:
        return "serde"
    return None                      # stdlib/other: resolve via name or bucket


def _category_of_func(func: tuple, callers_cat: str | None) -> str:
    filename, _lineno, name = func
    if filename == "~" or filename.startswith("<"):
        # builtin: name-based idle detection first, else caller's category
        lname = name.lower()
        if any(p in lname for p in _IDLE_BUILTINS):
            return "idle"
        if "sock" in lname or "ssl" in lname:
            return "transport"
        return callers_cat or "other"
    cat = _category_of_file(filename)
    return cat or "other"


def fold_profile(path: str) -> dict[str, float]:
    """pstats file -> {category: exclusive_seconds}."""
    st = pstats.Stats(path)
    # func -> (cc, nc, tt, ct, callers)
    raw = st.stats  # type: ignore[attr-defined]

    def caller_category(callers: dict) -> str | None:
        # dominant caller's file category, weighted by per-caller time
        best_cat, best_t = None, 0.0
        for cfunc, stats in callers.items():
            t = stats[3] if len(stats) >= 4 else 0.0   # cumulative via caller
            cat = _category_of_file(cfunc[0]) if cfunc[0] not in ("~",) \
                else None
            if cat and t >= best_t:
                best_cat, best_t = cat, t
        return best_cat

    out: dict[str, float] = {}
    for func, (_cc, _nc, tt, _ct, callers) in raw.items():
        if tt <= 0.0:
            continue
        cat = _category_of_func(func, caller_category(callers))
        out[cat] = out.get(cat, 0.0) + tt
    return out


def top_functions(path: str, category: str, n: int = 8) -> list[tuple]:
    """The heaviest exclusive-time functions inside one category."""
    st = pstats.Stats(path)
    rows = []
    for func, (_cc, _nc, tt, _ct, callers) in st.stats.items():  # type: ignore
        def _cc_of(c=callers):
            best_cat, best_t = None, 0.0
            for cfunc, s in c.items():
                t = s[3] if len(s) >= 4 else 0.0
                cat = _category_of_file(cfunc[0])
                if cat and t >= best_t:
                    best_cat, best_t = cat, t
            return best_cat
        if _category_of_func(func, _cc_of()) == category:
            rows.append((tt, f"{os.path.basename(func[0])}:{func[1]}:{func[2]}"))
    rows.sort(reverse=True)
    return rows[:n]


def run_budget(n_nodes: int = 4, n_txns: int = 300,
               timeout: float = 180.0) -> dict:
    from plenum_tpu.tools.tcp_pool import run_tcp_pool

    profile_dir = tempfile.mkdtemp(prefix="plenum_budget_")
    stats = run_tcp_pool(n_nodes=n_nodes, n_txns=n_txns, timeout=timeout,
                         profile_dir=profile_dir)
    txns = stats.get("txns_ordered") or 1
    per_node = {}
    for fn in sorted(os.listdir(profile_dir)):
        if fn.endswith(".pstats"):
            per_node[fn[:-7]] = fold_profile(os.path.join(profile_dir, fn))
    if not per_node:
        return {"error": "no profiles written", "pool": stats}

    def busy(cats: dict) -> float:
        return sum(v for k, v in cats.items() if k != "idle")

    # Which aggregation bounds throughput depends on the host: on a
    # multi-core box nodes run in parallel and the BUSIEST node is the
    # bottleneck; on this 1-core benchmark host all N node processes
    # timeshare one core, so the SUM of busy time across nodes is what
    # 1/TPS must pay.  Report both; docs quote the one matching nproc.
    busiest = max(per_node, key=lambda k: busy(per_node[k]))
    total = {}
    for cats in per_node.values():
        for k, v in cats.items():
            total[k] = total.get(k, 0.0) + v

    def to_ms_per_txn(cats: dict) -> dict:
        return {k: round(v * 1000.0 / txns, 3)
                for k, v in sorted(cats.items(), key=lambda kv: -kv[1])}

    offloadable = ("ed25519", "bls", "merkle")
    busy_sum = busy(total)
    off = sum(total.get(k, 0.0) for k in offloadable)
    frac = off / busy_sum if busy_sum else 0.0
    b = per_node[busiest]
    bfrac = (sum(b.get(k, 0.0) for k in offloadable) / busy(b)) if busy(b) else 0.0
    return {
        "pool": stats,
        "profile_dir": profile_dir,
        "txns": txns,
        "ncpu": os.cpu_count(),
        "sum_ms_per_txn": to_ms_per_txn(total),
        "sum_busy_ms_per_txn": round(busy_sum * 1000.0 / txns, 3),
        "busiest_node": busiest,
        "busiest_ms_per_txn": to_ms_per_txn(b),
        "busiest_busy_ms_per_txn": round(busy(b) * 1000.0 / txns, 3),
        "wall_ms_per_txn": round(
            stats.get("seconds", 0.0) * 1000.0 / txns, 3),
        "offloadable_categories": list(offloadable),
        "offloadable_fraction_sum": round(frac, 4),
        "offloadable_fraction_busiest": round(bfrac, 4),
        "amdahl_ceiling_sum": round(1.0 / (1.0 - frac), 2) if frac < 1 else None,
        "amdahl_ceiling_busiest": round(1.0 / (1.0 - bfrac), 2)
            if bfrac < 1 else None,
    }


def run_differential(n_nodes: int = 4, lo: int = 100, hi: int = 400,
                     timeout: float = 240.0) -> dict:
    """Marginal per-txn budget: profile the pool at two load sizes and
    subtract.  Fixed costs (keygen, genesis, handshakes, initial catchup)
    appear identically in both runs and cancel; what remains is what one
    EXTRA transaction costs — the quantity 1/TPS is made of.

    Caveat recorded in the output: cProfile inflates Python-call-dense
    categories (~2x observed wall slowdown) but not time spent inside a
    single C call, so the crypto fractions below are LOWER bounds; the
    unprofiled primitive microbenches in docs/performance.md bracket them
    from the other side.
    """
    a = run_budget(n_nodes, lo, timeout)
    b = run_budget(n_nodes, hi, timeout)
    if "error" in a or "error" in b:
        return {"error": "profile run failed", "lo": a, "hi": b}
    dtxn = b["txns"] - a["txns"]
    marginal = {}
    for k in set(a["sum_ms_per_txn"]) | set(b["sum_ms_per_txn"]):
        d = (b["sum_ms_per_txn"].get(k, 0.0) * b["txns"]
             - a["sum_ms_per_txn"].get(k, 0.0) * a["txns"]) / dtxn
        marginal[k] = round(d, 3)
    marginal = dict(sorted(marginal.items(), key=lambda kv: -kv[1]))
    busy = sum(v for k, v in marginal.items() if k != "idle")
    off = sum(marginal.get(k, 0.0) for k in ("ed25519", "bls", "merkle"))
    frac = off / busy if busy else 0.0
    return {
        "mode": "differential", "nodes": n_nodes, "lo_txns": lo, "hi_txns": hi,
        "ncpu": os.cpu_count(),
        "lo_pool_tps": a["pool"].get("tps"), "hi_pool_tps": b["pool"].get("tps"),
        "marginal_sum_ms_per_txn": marginal,
        "marginal_busy_ms_per_txn": round(busy, 3),
        "offloadable_fraction": round(frac, 4),
        "amdahl_ceiling": round(1.0 / (1.0 - frac), 2) if frac < 1 else None,
        "profile_dirs": [a["profile_dir"], b["profile_dir"]],
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--txns", type=int, default=300)
    ap.add_argument("--differential", action="store_true",
                    help="two-point run (txns/4 and txns): report MARGINAL "
                         "per-txn cost with fixed startup costs cancelled")
    ap.add_argument("--top", metavar="CATEGORY",
                    help="also list the heaviest functions in CATEGORY "
                         "for the busiest node")
    args = ap.parse_args(argv)
    if args.differential:
        result = run_differential(args.nodes, max(50, args.txns // 4),
                                  args.txns)
        print(json.dumps(result, indent=2))
        return
    result = run_budget(args.nodes, args.txns)
    print(json.dumps(result, indent=2))
    if args.top and "busiest_node" in result:
        path = os.path.join(result["profile_dir"],
                            result["busiest_node"] + ".pstats")
        for tt, where in top_functions(path, args.top):
            print(f"  {tt:8.3f}s  {where}")


if __name__ == "__main__":
    main()
