"""Pool-genesis generator CLI.

Reference behavior: plenum/common/test_network_setup.py +
scripts/generate_plenum_pool_transactions — build the pool and domain
genesis transaction files for a named node set from their key files.

    python -m plenum_tpu.tools.genesis --base-dir /tmp/pool \
        --nodes Node1:127.0.0.1:9701:9702 Node2:127.0.0.1:9703:9704 ... \
        [--trustee-seed <32 chars>]

Writes <base-dir>/pool_genesis.json and <base-dir>/domain_genesis.json
(one txn per line, the reference's genesis file format family) and prints
the trustee DID. Node keys must already exist (tools.keygen).
"""
from __future__ import annotations

import argparse
import json
import os


def build_genesis_files(base_dir: str, node_specs: list[tuple[str, str, int, int]],
                        trustee_seed: bytes) -> dict:
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution import txn as txn_lib
    from plenum_tpu.execution.txn import NODE, NYM, TRUSTEE
    from plenum_tpu.tools.keygen import load_keys

    trustee = Ed25519Signer(seed=trustee_seed)
    pool_txns = []
    for i, (name, host, node_port, client_port) in enumerate(node_specs):
        keys = load_keys(base_dir, name)
        txn = txn_lib.new_txn(NODE, {
            "dest": keys["verkey_b58"],
            "data": {"alias": name, "services": ["VALIDATOR"],
                     "blskey": keys["bls_pk"],
                     "blskey_pop": keys["bls_pop"],
                     "verkey": keys["verkey"],
                     "node_ip": host, "node_port": node_port,
                     "client_ip": host, "client_port": client_port}})
        txn_lib.set_seq_no(txn, i + 1)
        pool_txns.append(txn)
    nym = txn_lib.new_txn(NYM, {"dest": trustee.identifier,
                                "verkey": trustee.verkey_b58,
                                "role": TRUSTEE})
    txn_lib.set_seq_no(nym, 1)

    os.makedirs(base_dir, exist_ok=True)
    pool_path = os.path.join(base_dir, "pool_genesis.json")
    domain_path = os.path.join(base_dir, "domain_genesis.json")
    with open(pool_path, "w") as f:
        for txn in pool_txns:
            f.write(json.dumps(txn) + "\n")
    with open(domain_path, "w") as f:
        f.write(json.dumps(nym) + "\n")
    return {"pool_genesis": pool_path, "domain_genesis": domain_path,
            "trustee_did": trustee.identifier,
            "trustee_verkey": trustee.verkey_b58}


def load_genesis_files(base_dir: str) -> dict:
    """-> {ledger_id: [txn, ...]} for NodeBootstrap."""
    from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID,
                                                 POOL_LEDGER_ID)
    out = {}
    for ledger_id, fname in ((POOL_LEDGER_ID, "pool_genesis.json"),
                             (DOMAIN_LEDGER_ID, "domain_genesis.json")):
        path = os.path.join(base_dir, fname)
        with open(path) as f:
            out[ledger_id] = [json.loads(line) for line in f if line.strip()]
    return out


def parse_node_spec(spec: str) -> tuple[str, str, int, int]:
    name, host, node_port, client_port = spec.split(":")
    return name, host, int(node_port), int(client_port)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base-dir", required=True)
    ap.add_argument("--nodes", nargs="+", required=True,
                    metavar="NAME:HOST:NODEPORT:CLIENTPORT")
    ap.add_argument("--trustee-seed", default="genesis-trustee-seed")
    args = ap.parse_args(argv)
    specs = [parse_node_spec(s) for s in args.nodes]
    seed = args.trustee_seed.encode().ljust(32, b"\0")[:32]
    print(json.dumps(build_genesis_files(args.base_dir, specs, seed)))


if __name__ == "__main__":
    main()
