"""Lint: every registered sim-fuzz kind must have a tier-1 smoke rung.

The fuzz suite's contract is that each scenario kind runs its 20+-seed
sweep under the `slow` marker AND keeps at least one always-on smoke
rung in the default (tier-1) suite. A kind that exists only in the slow
sweep is SILENT coverage loss: the default CI run would green-light a
change that breaks the scenario outright, and nobody notices until the
next manual sweep. This lint makes that gap a tier-1 test failure, the
exact discipline tools/metrics_lint.py applies to the snapshot schema.

Registered kinds are discovered from tests/test_sim_fuzz.py by AST:

* every top-level ``run_*_scenario`` function (a scenario kind), and
* every top-level ``run_*_with_*`` function (a composition runner)

must be REFERENCED from at least one top-level ``test_*`` function that
is NOT decorated ``pytest.mark.slow`` (the smoke rung; lambdas inside
the test body count — the AST walk covers them).

    python -m plenum_tpu.tools.fuzz_lint [--json] [--file PATH]
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys

DEFAULT_FUZZ_FILE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "test_sim_fuzz.py")


def _is_slow(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        # pytest.mark.slow / mark.slow / @slow — match on the tail name
        node = dec
        if isinstance(node, ast.Call):
            node = node.func
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
        if "slow" in parts:
            return True
    return False


def _referenced_names(fn: ast.FunctionDef) -> set[str]:
    return {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}


def run_lint(path: str = DEFAULT_FUZZ_FILE) -> dict:
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)

    scenarios: list[str] = []
    fast_tests: dict[str, set[str]] = {}
    slow_tests: dict[str, set[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        name = node.name
        if name.startswith("run_") and (
                name.endswith("_scenario") or "_with_" in name):
            scenarios.append(name)
        elif name.startswith("test_"):
            (slow_tests if _is_slow(node) else fast_tests)[name] = \
                _referenced_names(node)

    problems = []
    covered = {}
    for scenario in scenarios:
        smoke = sorted(t for t, refs in fast_tests.items()
                       if scenario in refs)
        sweeps = sorted(t for t, refs in slow_tests.items()
                        if scenario in refs)
        covered[scenario] = {"smoke": smoke, "sweeps": sweeps}
        if not smoke:
            problems.append(
                f"{scenario}: no tier-1 smoke rung — only "
                f"{sweeps or 'NOTHING'} runs it; a fuzz kind that lives "
                f"only in the slow sweep is silent coverage loss (add a "
                f"non-slow test_*_smoke that calls it)")
    if not scenarios:
        problems.append(f"no run_*_scenario functions found in {path} — "
                        f"the lint's discovery rule no longer matches "
                        f"the suite's naming convention")
    return {
        "check": "ok" if not problems else "FAIL",
        "file": path,
        "scenarios": len(scenarios),
        "smoke_covered": sum(1 for v in covered.values() if v["smoke"]),
        "kinds": covered,
        "problems": problems,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--file", default=DEFAULT_FUZZ_FILE)
    args = ap.parse_args(argv)
    out = run_lint(args.file)
    if args.json:
        print(json.dumps(out))
    else:
        print(f"fuzz_lint: {out['check']} — {out['scenarios']} scenario "
              f"runners, {out['smoke_covered']} with tier-1 smoke rungs")
        for p in out["problems"]:
            print(f"  {p}")
    return 0 if out["check"] == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
