"""Lint: every MetricsName must be in the telemetry snapshot schema.

The fleet view (observability/) only shows what the snapshot schema
names. A counter added to `common/metrics.MetricsName` but not to
`SNAPSHOT_SCHEMA` (or to `EXEMPT_METRICS`, with a reason) would flow
into the flushed history but silently bypass the live fleet view — the
exact post-hoc-only blind spot the telemetry plane exists to close.
This lint is wired into tier-1 (tests/test_telemetry.py), so the gap is
a test failure, not a code-review hope.

Checks:
  1. every MetricsName value is in exactly one schema section, or
     exempted with a reason;
  2. no name appears in BOTH the schema and the exemptions;
  3. the schema names no unknown metrics (a typo'd schema entry would
     otherwise "cover" nothing);
  4. no name appears in two schema sections (double-counted in the view).

    python -m plenum_tpu.tools.metrics_lint [--json]
"""
from __future__ import annotations

import argparse
import json
import sys


def run_lint() -> dict:
    from plenum_tpu.common.metrics import MetricsName
    from plenum_tpu.observability.snapshot import (EXEMPT_METRICS,
                                                   SNAPSHOT_SCHEMA)

    declared = {
        value for attr, value in vars(MetricsName).items()
        if not attr.startswith("_") and isinstance(value, str)}
    schema_names: dict[str, list[str]] = {}
    for section, names in SNAPSHOT_SCHEMA.items():
        for name in names:
            schema_names.setdefault(name, []).append(section)

    problems = []
    for name in sorted(declared):
        covered = name in schema_names
        exempt = name in EXEMPT_METRICS
        if covered and exempt:
            problems.append(f"{name}: both in schema "
                            f"({schema_names[name]}) and exempted")
        elif not covered and not exempt:
            problems.append(
                f"{name}: not in any snapshot schema section and not "
                f"exempted — add it to observability/snapshot.py "
                f"SNAPSHOT_SCHEMA (or EXEMPT_METRICS with a reason)")
    for name, sections in sorted(schema_names.items()):
        if name not in declared:
            problems.append(f"{name}: named by schema section(s) "
                            f"{sections} but not a MetricsName")
        if len(sections) > 1:
            problems.append(f"{name}: in multiple schema sections "
                            f"{sections}")
    return {
        "check": "ok" if not problems else "FAIL",
        "metrics": len(declared),
        "covered": sum(1 for n in declared if n in schema_names),
        "exempted": sum(1 for n in declared if n in EXEMPT_METRICS),
        "problems": problems,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    out = run_lint()
    if args.json:
        print(json.dumps(out))
    else:
        print(f"metrics_lint: {out['check']} — {out['metrics']} metrics, "
              f"{out['covered']} in schema, {out['exempted']} exempted")
        for p in out["problems"]:
            print(f"  {p}")
    return 0 if out["check"] == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
