"""Node key generation CLI.

Reference behavior: scripts/init_plenum_keys + init_bls_keys — derive a
node's Ed25519 transport/steward keys and BLS consensus keys from a seed and
write them under a base dir. Usage:

    python -m plenum_tpu.tools.keygen --name Node1 --base-dir /tmp/pool \
        [--seed <32 chars>] [--json]
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os


def generate_keys(name: str, seed: bytes | None = None) -> dict:
    from plenum_tpu.crypto.bls import BlsCryptoSigner
    from plenum_tpu.crypto.ed25519 import Ed25519Signer

    seed = seed or os.urandom(32)
    assert len(seed) == 32
    node_signer = Ed25519Signer(seed=seed)
    bls_seed = hashlib.sha256(b"bls" + seed).digest()
    bls_signer = BlsCryptoSigner(seed=bls_seed)
    return {
        "name": name,
        "seed": seed.hex(),
        "verkey": node_signer.verkey.hex(),
        "verkey_b58": node_signer.verkey_b58,
        "bls_seed": bls_seed.hex(),
        "bls_pk": bls_signer.pk,
        "bls_pop": bls_signer.generate_pop(),
    }


def save_keys(keys: dict, base_dir: str) -> str:
    """Write <base>/<name>/keys.json 0600; returns the path."""
    node_dir = os.path.join(base_dir, keys["name"])
    os.makedirs(node_dir, exist_ok=True)
    path = os.path.join(node_dir, "keys.json")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(keys, f, indent=2)
    return path


def load_keys(base_dir: str, name: str) -> dict:
    with open(os.path.join(base_dir, name, "keys.json")) as f:
        return json.load(f)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--name", required=True)
    ap.add_argument("--base-dir", required=True)
    ap.add_argument("--seed", help="32-char seed (default: random)")
    ap.add_argument("--json", action="store_true",
                    help="print full keys as JSON (includes SECRETS)")
    args = ap.parse_args(argv)
    seed = args.seed.encode().ljust(32, b"\0")[:32] if args.seed else None
    keys = generate_keys(args.name, seed)
    path = save_keys(keys, args.base_dir)
    if args.json:
        print(json.dumps(keys))
    else:
        public = {k: keys[k] for k in ("name", "verkey_b58", "bls_pk",
                                       "bls_pop")}
        print(json.dumps({"saved": path, **public}))


if __name__ == "__main__":
    main()
