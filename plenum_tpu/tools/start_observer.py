"""Start an observer (read follower) as an OS process.

The observer counterpart of tools/start_node: load the pool genesis from a
base dir, derive every validator's client address from the pool ledger,
and run a plenum_tpu.node.observer_node.ObserverNode until killed. Plays
the role of the reference's runnable ObserverNode
(plenum/server/observer/observer_node.py).

    python -m plenum_tpu.tools.start_observer --name obs1 --base-dir /tmp/pool \
        [--f 1] [--data-dir /var/obs1] [--kv file|native|memory]
"""
from __future__ import annotations

import argparse
import asyncio
import json


def main(argv=None):
    from plenum_tpu.common.node_messages import POOL_LEDGER_ID
    from plenum_tpu.node.observer_node import ObserverNode
    from plenum_tpu.tools.genesis import load_genesis_files

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--name", required=True)
    ap.add_argument("--base-dir", required=True,
                    help="pool dir holding the genesis files")
    ap.add_argument("--f", type=int, default=1,
                    help="push quorum is f+1 content-identical validators")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--kv", default="memory",
                    choices=["memory", "file", "native"])
    ap.add_argument("--client-port", type=int, default=None,
                    help="serve verified reads (read_proof envelopes) to "
                         "clients on this port")
    ap.add_argument("--anchor-lag-max", type=float, default=None,
                    help="serve proofless (clients escalate to a "
                         "validator) once the newest verified anchor is "
                         "older than this; default: "
                         "Config.OBSERVER_ANCHOR_LAG_MAX")
    ap.add_argument("--state-commitment", default="mpt",
                    choices=["mpt", "verkle"],
                    help="MUST match the pool's STATE_COMMITMENT: the "
                         "observer's replicated roots have to land on "
                         "the multi-signed anchors, or every read it "
                         "serves degrades to proofless escalation")
    ap.add_argument("--verkle-width", type=int, default=None,
                    help="pool's VERKLE_WIDTH (verkle pools only)")
    ap.add_argument("--state-commitment-per-ledger", default=None,
                    help='JSON {"<ledger_id>": "<backend>"} — must match '
                         "the pool's STATE_COMMITMENT_PER_LEDGER; a "
                         "diverging ledger's replicated roots never land "
                         "on the signed anchors (proofless reads)")
    args = ap.parse_args(argv)

    genesis = load_genesis_files(args.base_dir)
    addrs = {}
    for txn in genesis[POOL_LEDGER_ID]:
        data = txn["txn"]["data"]["data"]
        addrs[data["alias"]] = (data["client_ip"], data["client_port"])

    from plenum_tpu.ingress.observer_reads import FROM_CONFIG
    obs = ObserverNode(args.name, genesis, addrs, f=args.f,
                       data_dir=args.data_dir, storage_backend=args.kv,
                       client_port=args.client_port,
                       anchor_lag_max=FROM_CONFIG
                       if args.anchor_lag_max is None
                       else args.anchor_lag_max,
                       state_commitment=args.state_commitment,
                       state_commitment_per_ledger=json.loads(
                           args.state_commitment_per_ledger)
                       if args.state_commitment_per_ledger else None,
                       verkle_width=args.verkle_width)

    async def run():
        stop = asyncio.Event()
        print(json.dumps({"started": args.name,
                          "following": sorted(addrs)}), flush=True)
        await obs.run(stop)

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
