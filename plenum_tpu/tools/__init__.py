"""Operator tools: pool runners, key generation, benchmarks
(ref scripts/ — start_plenum_node, generate_plenum_pool_transactions &c)."""
