"""Cheap liveness probe for the TPU device relay.

The device tunnel in this environment fronts the TPU behind a local relay
(127.0.0.1:8082/8083).  When the relay is down, ``jax.devices()`` does not
fail — it hangs forever retrying — so any benchmark that reaches for the
device without probing first burns its whole timeout budget (25 minutes in
round 3) learning nothing.  A 3-second TCP connect distinguishes
"nothing is listening" from "relay up" in bounded time without touching
jax APIs at all (important: the tunnel is single-tenant, and a second
process touching device APIs can wedge it — see docs/performance.md).

Replaces nothing in the reference (its CUDA devices are local); this is
operational armor specific to a tunneled single-tenant accelerator.

Usage:
    python -m plenum_tpu.tools.tpu_probe          # human-readable + rc 0/1
    from plenum_tpu.tools.tpu_probe import probe_relay
"""
from __future__ import annotations

import socket
import time

RELAY_HOST = "127.0.0.1"
RELAY_PORTS = (8083, 8082)


def probe_relay(timeout: float = 3.0) -> dict:
    """TCP-connect each relay port. -> {"up": bool, "ports": {...}, "ts": iso}.

    Never raises; never imports jax.
    """
    ports = {}
    for port in RELAY_PORTS:
        t0 = time.monotonic()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        try:
            sock.connect((RELAY_HOST, port))
            ports[port] = {"state": "open",
                           "ms": round((time.monotonic() - t0) * 1e3, 1)}
        except OSError as exc:
            ports[port] = {"state": type(exc).__name__,
                           "ms": round((time.monotonic() - t0) * 1e3, 1)}
        finally:
            sock.close()
    return {
        "up": any(p["state"] == "open" for p in ports.values()),
        "ports": ports,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def main() -> int:
    result = probe_relay()
    state = "UP" if result["up"] else "DOWN"
    detail = " ".join(f"{port}={info['state']}({info['ms']}ms)"
                      for port, info in result["ports"].items())
    print(f"{result['ts']} tpu-relay {state} {detail}")
    return 0 if result["up"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
