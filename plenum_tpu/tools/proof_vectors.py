"""Golden proof vectors: canonical (keys -> root -> proof -> verify)
fixtures for every state-commitment backend.

A verifier-side encoding drift — a reordered transcript field, a
changed domain separator, a different leaf-scalar preimage — would not
fail any same-process test (prover and verifier drift together); it
would silently invalidate every proof already held by deployed clients.
These vectors pin the full byte-level contract: the committed root
anchor, a single-key proof, a multi-key page proof, and an absence
proof, for a fixed keyset, per backend. tests/test_proof_vectors.py
regenerates them in-process and compares against the checked-in file —
a drift breaks loudly in tier-1 instead of silently on clients.

    python -m plenum_tpu.tools.proof_vectors            # print JSON
    python -m plenum_tpu.tools.proof_vectors --write    # refresh file
    python -m plenum_tpu.tools.proof_vectors --check    # diff vs file
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from plenum_tpu.common.serialization import pack

VECTORS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "vectors",
    "proof_vectors.json")

# the fixed fixture keyset: enough keys to force internal structure at
# every supported width, including a missing key for absence proofs
FIXTURE_KEYS = [b"did:sov:%04d" % i for i in range(24)]
FIXTURE_VALUES = [b"record-%04d" % i for i in range(24)]
MISSING_KEY = b"did:sov:missing"
PAGE = FIXTURE_KEYS[:7] + [MISSING_KEY]

# second write-set for the RECOMMIT vectors: applied on top of the
# committed fixture state, resolved once on the host path and once
# through the fused commit wave — the two roots must be byte-identical
# to each other AND to the checked-in vector, so a kernel or staging
# change can never silently fork the state root
RECOMMIT_KEYS = [b"did:sov:wave:%04d" % i for i in range(17)]
RECOMMIT_VALUES = [b"wave-%04d" % i for i in range(17)]
RECOMMIT_TXNS = [{"seq": i, "v": v.hex()}
                 for i, v in enumerate(RECOMMIT_VALUES)]


def _hex(b: bytes) -> str:
    return b.hex()


def _build_state(backend: str):
    from plenum_tpu.state.commitment import make_state
    st = make_state(backend)
    for k, v in zip(FIXTURE_KEYS, FIXTURE_VALUES):
        st.set(k, v)
    st.commit(st.head_hash)
    return st


def _wave_root(add_family) -> bytes:
    """Resolve one staged family through a real CommitWave on a fresh
    host-engine pipeline (the same trampoline the ordered path runs)."""
    from plenum_tpu.parallel.commit_wave import CommitWave
    from plenum_tpu.parallel.pipeline import CryptoPipeline
    wave = CommitWave(CryptoPipeline())
    add_family(wave)
    return wave.run()["root"]


def recommit_roots(backend: str) -> dict:
    """{"host": hex, "fused": hex}: the second write-set's state root,
    resolved inline vs through the commit wave."""
    from plenum_tpu.state.commitment import make_state

    def build():
        st = make_state(backend)
        for k, v in zip(FIXTURE_KEYS, FIXTURE_VALUES):
            st.set(k, v)
        st.commit(st.head_hash)
        for k, v in zip(RECOMMIT_KEYS, RECOMMIT_VALUES):
            st.set(k, v)
        return st

    host = build().head_hash
    fused = _wave_root(lambda w: w.add("root", build().recommit_staged()))
    return {"host": _hex(host), "fused": _hex(fused)}


def ledger_recommit_roots() -> dict:
    """{"host": hex, "fused": hex}: the staged-ledger shadow root, leaf
    hashing inline vs deferred to the commit wave."""
    from plenum_tpu.ledger.ledger import Ledger

    def build(defer):
        lg = Ledger()
        lg.append_txns_to_uncommitted(list(RECOMMIT_TXNS),
                                      defer_hash=defer)
        return lg

    host = build(False).uncommitted_root_hash
    fused = _wave_root(
        lambda w: w.add("root", build(True).uncommitted_root_staged()))
    return {"host": _hex(host), "fused": _hex(fused)}


def generate() -> dict:
    out: dict = {"version": 1, "keys": [_hex(k) for k in FIXTURE_KEYS],
                 "values": [_hex(v) for v in FIXTURE_VALUES],
                 "page": [_hex(k) for k in PAGE],
                 "backends": {}}
    for backend in ("mpt", "verkle"):
        st = _build_state(backend)
        root = st.committed_head_hash
        single = st.generate_state_proof(FIXTURE_KEYS[0], serialize=True)
        absent = st.generate_state_proof(MISSING_KEY, serialize=True)
        page = st.batch_open(PAGE)
        rec = recommit_roots(backend)
        if rec["fused"] != rec["host"]:
            # NEVER write a forked vector: a fused/host divergence is
            # the exact drift these vectors exist to catch
            raise RuntimeError(
                f"{backend}: fused recommit root {rec['fused']} != "
                f"host root {rec['host']}")
        out["backends"][backend] = {
            "root": _hex(root),
            "single_proof": _hex(bytes(single)),
            "absence_proof": _hex(bytes(absent)),
            "page_proof": _hex(pack(page)),
            "recommit_root": rec["host"],
        }
    lrec = ledger_recommit_roots()
    if lrec["fused"] != lrec["host"]:
        raise RuntimeError(
            f"ledger: fused recommit root {lrec['fused']} != "
            f"host root {lrec['host']}")
    out["ledger_recommit_root"] = lrec["host"]
    return out


def check_vectors(doc: dict) -> list[str]:
    """Re-verify a vector document with the CURRENT verifiers (the
    client-side half of the drift check: old proofs must still verify)
    and re-generate to catch prover-side drift. -> problem list."""
    from plenum_tpu.common.serialization import unpack
    from plenum_tpu.state.commitment import PruningState, VerkleState

    problems = []
    fresh = generate()
    for backend in ("mpt", "verkle"):
        want = doc.get("backends", {}).get(backend)
        got = fresh["backends"][backend]
        if want is None:
            problems.append(f"{backend}: missing from vector file")
            continue
        for field in ("root", "single_proof", "absence_proof",
                      "page_proof", "recommit_root"):
            if want.get(field) != got[field]:
                problems.append(
                    f"{backend}.{field}: regenerated bytes differ from "
                    f"the checked-in vector — an encoding drift would "
                    f"invalidate deployed clients' proofs")
        cls = PruningState if backend == "mpt" else VerkleState
        try:
            root = bytes.fromhex(want["root"])
            if not cls.verify_state_proof(
                    root, FIXTURE_KEYS[0], FIXTURE_VALUES[0],
                    bytes.fromhex(want["single_proof"])):
                problems.append(f"{backend}: checked-in single proof no "
                                f"longer verifies")
            if not cls.verify_state_proof(
                    root, MISSING_KEY, None,
                    bytes.fromhex(want["absence_proof"])):
                problems.append(f"{backend}: checked-in absence proof no "
                                f"longer verifies")
            entries = [(k, FIXTURE_VALUES[FIXTURE_KEYS.index(k)]
                        if k in FIXTURE_KEYS else None) for k in PAGE]
            if not cls.verify_batch_proof(
                    root, entries,
                    unpack(bytes.fromhex(want["page_proof"]))):
                problems.append(f"{backend}: checked-in page proof no "
                                f"longer verifies")
        except Exception as e:
            problems.append(f"{backend}: verification raised "
                            f"{type(e).__name__}: {e}")
    if doc.get("ledger_recommit_root") != fresh["ledger_recommit_root"]:
        problems.append(
            "ledger_recommit_root: regenerated root differs from the "
            "checked-in vector — the staged ledger append no longer "
            "matches the host shadow tree")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--write", action="store_true",
                    help="refresh tests/vectors/proof_vectors.json")
    ap.add_argument("--check", action="store_true",
                    help="verify the checked-in vectors against the "
                         "current implementation")
    args = ap.parse_args(argv)
    if args.check:
        try:
            with open(VECTORS_PATH) as fh:
                doc = json.load(fh)
        except OSError as e:
            print(f"proof_vectors: FAIL — cannot read {VECTORS_PATH}: {e}")
            return 1
        problems = check_vectors(doc)
        if problems:
            print(f"proof_vectors: FAIL — {len(problems)} problem(s)")
            for p in problems:
                print(f"  {p}")
            return 1
        print("proof_vectors: ok — both backends match the checked-in "
              "golden vectors")
        return 0
    doc = generate()
    text = json.dumps(doc, indent=1, sort_keys=True)
    if args.write:
        os.makedirs(os.path.dirname(VECTORS_PATH), exist_ok=True)
        with open(VECTORS_PATH, "w") as fh:
            fh.write(text + "\n")
        print(f"wrote {VECTORS_PATH}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
