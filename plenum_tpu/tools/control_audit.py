"""Control-ledger audit: replay and lint an autopilot decision history.

The autopilot (plenum_tpu/control/autopilot.py) records every decision
as an ordered transaction on the reserved ``CONTROL_LEDGER_ID``. This
tool replays such a ledger and lints the invariants the control plane
promises — the same ones the fuzz suite pins live:

- seqs are strictly increasing from 1; timestamps never run backwards
- every actuation carries attributed evidence (an empty evidence dict
  on a non-hold record means the autopilot acted on nothing)
- every undo (``unpin``/``observer_retire``/``recover``) cites the seq
  of an EARLIER record whose action is the matching forward action
- no record lands on a (policy, subject) pair before a prior record's
  cooldown stamp expires (holds are exempt: a hold IS the ledger's
  account of a blocked intent)

    python -m plenum_tpu.tools.control_audit LEDGER.jsonl [--json]
    python -m plenum_tpu.tools.control_audit --check   # tier-1 self-test

``--check`` audits a synthetic good ledger (must lint clean) and a
corrupted variant per lint rule (each must be caught).
"""
from __future__ import annotations

import argparse
import json
import sys

from plenum_tpu.control import CONTROL_LEDGER_ID, LADDER, REVERT_OF


def audit_records(records: list[dict]) -> list[str]:
    """Lint a control ledger (list of ControlRecord dicts, ledger
    order). -> list of violation strings, [] when clean."""
    problems: list[str] = []
    by_seq: dict[int, dict] = {}
    # (policy, subject) -> latest cooldown_until stamped by a non-hold
    cooldowns: dict[tuple[str, str], float] = {}
    prev_seq, prev_t = 0, float("-inf")
    for rec in records:
        seq = rec.get("seq")
        t = rec.get("t", 0.0)
        action = rec.get("action", "?")
        policy = rec.get("policy", "?")
        subject = rec.get("subject", "?")
        tag = f"seq={seq} {policy}/{action}@{subject}"
        if rec.get("ledger_id") != CONTROL_LEDGER_ID:
            problems.append(f"{tag}: ledger_id {rec.get('ledger_id')} "
                            f"!= {CONTROL_LEDGER_ID}")
        if not isinstance(seq, int) or seq != prev_seq + 1:
            problems.append(f"{tag}: seq not contiguous after {prev_seq}")
        else:
            prev_seq = seq
        if t < prev_t:
            problems.append(f"{tag}: time ran backwards ({t} < {prev_t})")
        prev_t = max(prev_t, t)
        if action != "hold" and not rec.get("evidence"):
            problems.append(f"{tag}: actuation without evidence")
        if action in REVERT_OF:
            cited = by_seq.get(rec.get("cites"))
            if cited is None:
                problems.append(f"{tag}: undo cites no earlier record "
                                f"(cites={rec.get('cites')})")
            elif cited.get("action") != REVERT_OF[action]:
                problems.append(
                    f"{tag}: undo cites seq={rec.get('cites')} "
                    f"({cited.get('action')}), wants "
                    f"{REVERT_OF[action]}")
        if action != "hold":
            key = (policy, subject)
            until = cooldowns.get(key, float("-inf"))
            if t < until:
                problems.append(f"{tag}: fired inside cooldown "
                                f"(t={t} < {until})")
            stamp = rec.get("cooldown_until", 0.0)
            if stamp:
                cooldowns[key] = max(until, stamp)
        if isinstance(seq, int):
            by_seq[seq] = rec
    return problems


def replay(records: list[dict]) -> dict:
    """Fold the ledger into the final control state it describes."""
    state = {"level": 0, "state": LADDER[0], "pins": {},
             "observers": {}, "splits": 0, "merges": 0, "holds": 0}
    for rec in records:
        action = rec.get("action")
        subject = rec.get("subject", "?")
        if action == "hold":
            state["holds"] += 1
        elif action == "split":
            state["splits"] += 1
        elif action == "merge":
            state["merges"] += 1
        elif action == "repin":
            state["pins"][subject] = rec.get("post", {}).get("lane")
        elif action == "unpin":
            state["pins"].pop(subject, None)
        elif action in ("observer_spawn", "observer_retire"):
            state["observers"][subject] = \
                rec.get("post", {}).get("observers")
        elif action in ("degrade", "recover"):
            state["level"] = rec.get("post", {}).get("level",
                                                     state["level"])
            state["state"] = rec.get("post", {}).get("state",
                                                     state["state"])
    return state


# --- the --check self-test ---------------------------------------------------

def _rec(seq, t, policy, action, subject, evidence=None, pre=None,
         post=None, cooldown_until=0.0, cites=None):
    return {"ledger_id": CONTROL_LEDGER_ID, "seq": seq, "t": t,
            "policy": policy, "action": action, "subject": subject,
            "evidence": evidence if evidence is not None else {"e": 1},
            "pre": pre or {}, "post": post or {},
            "cooldown_until": cooldown_until, "cites": cites}


def _good_ledger() -> list[dict]:
    return [
        _rec(1, 10.0, "lane", "repin", "shard0",
             {"sick_lane": 2, "breaker": "open"},
             pre={"lane": 2}, post={"lane": 0}, cooldown_until=40.0),
        _rec(2, 12.0, "reshard", "split", "shard0",
             {"index": 0.9, "hot_shard": 0},
             pre={"shards": [0, 1]}, post={"shards": [0, 1, 2]},
             cooldown_until=42.0),
        _rec(3, 20.0, "observer", "observer_spawn", "r0",
             {"region": "r0", "fast": 2.0},
             pre={"observers": 1}, post={"observers": 2},
             cooldown_until=50.0),
        _rec(4, 30.0, "ladder", "hold", "pool",
             {"wanted": "degrade", "blocked_until": 42.0}),
        _rec(5, 45.0, "ladder", "degrade", "shed_harder",
             {"burning": [["slo_burn.ingress", "N1"]]},
             pre={"level": 0, "state": "normal"},
             post={"level": 1, "state": "shed_harder"},
             cooldown_until=75.0),
        _rec(6, 50.0, "lane", "unpin", "shard0",
             {"healed_lane": 2, "clear_streak": 5},
             pre={"lane": 0}, post={"lane": 2},
             cooldown_until=80.0, cites=1),
        _rec(7, 60.0, "observer", "observer_retire", "r0",
             {"region": "r0", "demand": 3},
             pre={"observers": 2}, post={"observers": 1},
             cooldown_until=90.0, cites=3),
        _rec(8, 80.0, "ladder", "recover", "shed_harder",
             {"clear_for": 5},
             pre={"level": 1, "state": "shed_harder"},
             post={"level": 0, "state": "normal"},
             cooldown_until=110.0, cites=5),
    ]


def self_check() -> int:
    problems = []
    good = _good_ledger()
    got = audit_records(good)
    if got:
        problems.append(f"good ledger did not lint clean: {got}")
    final = replay(good)
    if final["level"] != 0 or final["pins"] or final["splits"] != 1 \
            or final["observers"].get("r0") != 1 or final["holds"] != 1:
        problems.append(f"replay of the good ledger is wrong: {final}")

    def corrupt(mutate, expect: str):
        bad = [dict(r) for r in _good_ledger()]
        mutate(bad)
        found = audit_records(bad)
        if not any(expect in p for p in found):
            problems.append(f"corruption not caught (wanted {expect!r}): "
                            f"{found}")

    corrupt(lambda b: b[2].update(seq=9), "seq not contiguous")
    corrupt(lambda b: b[3].update(t=5.0), "time ran backwards")
    corrupt(lambda b: b[1].update(evidence={}), "without evidence")
    corrupt(lambda b: b[5].update(cites=None), "cites no earlier record")
    corrupt(lambda b: b[5].update(cites=2), "wants repin")
    # an action/undo flap inside one cooldown window — the no-flap pin
    corrupt(lambda b: b[5].update(t=15.0), "fired inside cooldown")
    corrupt(lambda b: b[0].update(ledger_id=100), "ledger_id")

    print(json.dumps({"check": "ok" if not problems else "FAIL",
                      "problems": problems}))
    return 1 if problems else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("ledger", nargs="?",
                    help="jsonl file of control records, or '-' for stdin")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--check", action="store_true",
                    help="run the built-in self-test and exit")
    args = ap.parse_args(argv)
    if args.check:
        return self_check()
    if not args.ledger:
        ap.error("ledger required (or --check)")
    fh = sys.stdin if args.ledger == "-" else open(args.ledger)
    try:
        records = [json.loads(line) for line in fh if line.strip()]
    finally:
        if fh is not sys.stdin:
            fh.close()
    problems = audit_records(records)
    final = replay(records)
    if args.json:
        print(json.dumps({"records": len(records), "problems": problems,
                          "final": final}))
    else:
        print(f"{len(records)} control records; final state: {final}")
        for p in problems:
            print(f"  VIOLATION: {p}")
        if not problems:
            print("  clean: every action evidenced, every undo cited, "
                  "no cooldown violations")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
