"""The remaining BASELINE.json benchmark configs (2-5).

BASELINE.json names five configs; tools/local_pool + tools/tcp_pool cover
config 1 (4-node NYM writes). This module measures the rest, each as one
function returning a small stats dict that bench.py folds into its extras:

  config2  4-node pool, THREE RBFT protocol instances, mixed NYM/ATTRIB
  config3  BLS state-proof reads: GET_NYM queries answered with a state
           proof + BLS multi-signature (single node serves reads)
  config4  7-node / f=2 pool over real TCP, view change UNDER LOAD (the
           master primary process is killed mid-drive)
  config5  25-node simulated pool ordering datum

Every function is wall-clock bounded and returns {"error": ...} instead of
raising — bench.py must always print its one JSON line.
"""
from __future__ import annotations

import json
import time


def _mixed_requests(trustee, n: int):
    """NYM-create for even i, ATTRIB for odd i. ATTRIBs are trustee-
    authored (a trustee may set attributes on any DID) and target a DID
    created >=128 requests earlier — or the genesis trustee itself — so
    an in-flight window never races a dest's NYM commit: a fresh DID is
    unusable until its NYM lands, exactly as for real clients."""
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import ATTRIB, NYM

    users = []
    reqs = []
    for i in range(n):
        if i % 2 == 0:
            user = Ed25519Signer(seed=(b"mix%08d" % i).ljust(32, b"\0")[:32])
            users.append(user)
            req = Request(trustee.identifier, i + 1,
                          {"type": NYM, "dest": user.identifier,
                           "verkey": user.verkey_b58})
        else:
            settled = len(users) - 64          # 64 NYMs = 128 requests ago
            dest = users[(i // 2) % settled].identifier if settled > 0 \
                else trustee.identifier
            req = Request(trustee.identifier, i + 1,
                          {"type": ATTRIB, "dest": dest,
                           "raw": json.dumps({"endpoint%d" % i: str(i)})})
        req.signature = trustee.sign_b58(req.signing_bytes())
        reqs.append(req)
    return reqs


def _drive_inprocess(names, nodes, timer, replies, Reply, plane, requests,
                     timeout: float):
    t0 = time.perf_counter()
    done: set = set()
    i = 0
    while len(done) < len(requests) and time.perf_counter() < t0 + timeout:
        while i < len(requests) and i - len(done) < 256:
            for n in names:
                nodes[n].handle_client_message(requests[i].to_dict(), "bench")
            i += 1
        timer.service()
        for node in nodes.values():
            node.prod()
        if plane is not None:
            plane.flush()
        for _, msg, _c in replies[names[0]]:
            if isinstance(msg, Reply):
                d = msg.result.get("txn", {}).get("metadata", {}).get("digest")
                if d:
                    done.add(d)
        replies[names[0]].clear()
    return len(done), time.perf_counter() - t0


def config2_three_instances_mixed(n_txns: int = 200,
                                  timeout: float = 120.0) -> dict:
    """4 nodes, 3 RBFT instances, mixed NYM/ATTRIB writes."""
    import plenum_tpu.tools.local_pool as lp
    from plenum_tpu.common.node_messages import Reply
    from plenum_tpu.common.timer import QueueTimer
    from plenum_tpu.config import Config
    from plenum_tpu.network import SimNetwork, SimRandom
    from plenum_tpu.node import Node, NodeBootstrap

    try:
        names = [f"Node{i + 1}" for i in range(4)]
        genesis, trustee = lp.build_genesis(names)
        timer = QueueTimer(time.perf_counter)
        net = SimNetwork(timer, SimRandom(7))
        net.set_latency(0.00005, 0.0002)
        config = Config(Max3PCBatchWait=0.05,
                        STATE_FRESHNESS_UPDATE_INTERVAL=600.0)
        replies = {n: [] for n in names}
        nodes = {}
        for name in names:
            bus = net.create_peer(name)
            comp = NodeBootstrap(name, genesis_txns=genesis).build()
            nodes[name] = Node(
                name, timer, bus, comp,
                client_send=lambda msg, client, n=name: replies[n].append(
                    (time.perf_counter(), msg, client)),
                config=config, instance_count=3)
        net.connect_all()
        assert all(len(nd.replicas) == 3 for nd in nodes.values())

        reqs = _mixed_requests(trustee, n_txns)
        done, dt = _drive_inprocess(names, nodes, timer, replies, Reply,
                                    None, reqs, timeout)
        # backups shadow-order slightly behind the master's replies; give
        # them a drain window before reading their progress gauge
        for _ in range(400):
            timer.service()
            for node in nodes.values():
                node.prod()
        # every backup instance must be shadow-ordering, or the "3
        # instances" claim is hollow
        inst_progress = [
            min(nodes[n].replicas[i].data.last_ordered_3pc[1]
                for n in names) for i in (0, 1, 2)]
        return {"txns_ordered": done, "txns_requested": n_txns,
                "tps": round(done / dt, 1) if dt else 0.0,
                "instances": 3,
                "min_backup_ordered": min(inst_progress[1:]),
                }
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def config3_bls_proof_reads(n_reads: int = 2000,
                            timeout: float = 120.0) -> dict:
    """GET_NYM state-proof read throughput on one node, with the BLS
    multi-signature attached (ref docs/source/main.md:24 — one node's
    reply suffices because the proof + multi-sig carry the trust)."""
    import plenum_tpu.tools.local_pool as lp
    from plenum_tpu.common.node_messages import Reply
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import GET_NYM, NYM

    try:
        (names, nodes, timer, trustee,
         replies, ReplyCls, DOMAIN, plane, net) = lp.build_pool(4, "cpu")
        # commit a handful of NYMs so the BLS store holds multi-sigs
        users = []
        reqs = []
        for i in range(20):
            user = Ed25519Signer(seed=(b"rd%08d" % i).ljust(32, b"\0")[:32])
            users.append(user)
            req = Request(trustee.identifier, i + 1,
                          {"type": NYM, "dest": user.identifier,
                           "verkey": user.verkey_b58})
            req.signature = trustee.sign_b58(req.signing_bytes())
            reqs.append(req)
        done, _ = _drive_inprocess(names, nodes, timer, replies, ReplyCls,
                                   plane, reqs, 60.0)
        if done < len(reqs):
            return {"error": f"setup ordered only {done}/{len(reqs)}"}

        node = nodes[names[0]]
        served = 0
        with_multisig = 0
        t0 = time.perf_counter()
        i = 0
        while served < n_reads and time.perf_counter() < t0 + timeout:
            q = Request("reader", i + 1,
                        {"type": GET_NYM,
                         "dest": users[i % len(users)].identifier})
            node.handle_client_message(q.to_dict(), "reader")
            i += 1
            if i % 100 == 0 or i >= n_reads:
                node.prod()
                for _, msg, _c in replies[names[0]]:
                    if isinstance(msg, ReplyCls) and \
                            msg.result.get("type") == GET_NYM:
                        served += 1
                        if msg.result.get("state_proof", {}) \
                                .get("multi_signature"):
                            with_multisig += 1
                replies[names[0]].clear()
        dt = time.perf_counter() - t0
        return {"reads_served": served,
                "reads_with_multisig": with_multisig,
                "reads_per_s": round(served / dt, 1) if dt else 0.0}
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def config4_viewchange_under_load(n_txns: int = 150,
                                  timeout: float = 150.0) -> dict:
    """7-node / f=2 TCP pool; the master primary's OS process is SIGKILLed
    mid-drive. Done = the remaining requests still finish (view change
    under load) and the figure reports effective TPS across the fault."""
    import asyncio
    import os
    import shutil
    import signal
    import subprocess
    import sys
    import tempfile

    from plenum_tpu.tools.tcp_pool import (REPO, _wait_all_started,
                                           setup_pool_dir)

    names = [f"Node{i + 1}" for i in range(7)]
    tmp = tempfile.mkdtemp(prefix="plenum_vc_pool_")
    trustee_seed = b"vc-pool-trustee!".ljust(32, b"\0")
    procs = []
    try:
        specs = setup_pool_dir(tmp, names, trustee_seed)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        for name in names:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "plenum_tpu.tools.start_node",
                 "--name", name, "--base-dir", tmp, "--kv", "memory"],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT))
        _wait_all_started(procs, deadline_s=90.0)

        from plenum_tpu.client.wallet import Wallet
        from plenum_tpu.execution.txn import NYM
        wallet = Wallet("vc-bench")
        trustee = wallet.add_identifier(seed=trustee_seed)
        requests = []
        for i in range(n_txns):
            user = wallet.add_identifier(
                seed=(b"vcu%05d" % i).ljust(32, b"\0")[:32])
            requests.append(wallet.sign_request(
                {"type": NYM, "dest": user,
                 "verkey": wallet.verkey_of(user)}, identifier=trustee))
        addrs = {name: ("127.0.0.1", spec[3])
                 for name, spec in zip(names, specs)}

        async def drive():
            from plenum_tpu.client.pipelined import PipelinedPoolClient
            client = PipelinedPoolClient(addrs, f=2)

            async def killer():
                await asyncio.sleep(1.0)         # mid-load
                procs[0].send_signal(signal.SIGKILL)   # Node1 = primary

            kill_task = asyncio.create_task(killer())
            # window matches the headline TCP-pool config (bench.py
            # window=250) so "TPS across the fault" is comparable to the
            # steady-state 7-node figure from the same bench run
            done, submit = await client.drive(requests, window=250,
                                              timeout=timeout)
            await kill_task
            return done, submit

        t0 = time.perf_counter()
        done, _submit = asyncio.run(drive())
        dt = time.perf_counter() - t0
        out = {"txns_ordered": len(done), "txns_requested": n_txns,
               "primary_killed_at_s": 1.0,
               "recovered": len(done) == n_txns,
               "tps_across_fault": round(len(done) / dt, 1) if dt else 0.0}
        # the fault's cost, separated from run length: the stall is the
        # longest gap between consecutive request completions, and the
        # steady rate is what the pool does outside that gap
        times = sorted(done.values())
        if len(times) > 2:
            gaps = [b - a for a, b in zip(times, times[1:])]
            stall = max(gaps)
            out["stall_s"] = round(stall, 2)
            span = times[-1] - times[0] - stall
            if span > 0:
                out["steady_tps_outside_stall"] = round(
                    (len(times) - 2) / span, 1)
        # per-phase stall decomposition from a SURVIVOR's flushed metrics
        # store (nodes were just SIGTERMed -> tail flush ran)
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        try:
            from plenum_tpu.tools.metrics_report import (fold_rows,
                                                         read_store)
            folds = fold_rows(read_store(
                os.path.join(tmp, names[1], "metrics")))
            for short, metric in (
                    ("detect_to_vote", "consensus.vc_detect_to_vote"),
                    ("vote_to_start", "consensus.vc_vote_to_start"),
                    ("start_to_new_view",
                     "consensus.vc_start_to_new_view"),
                    ("new_view_to_order",
                     "consensus.vc_new_view_to_order")):
                f = folds.get(metric)
                if f and f.get("count"):
                    out[f"vc_{short}_s"] = round(f["sum"] / f["count"], 3)
        except Exception:
            pass                     # decomposition is best-effort extra
        return out
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _host_calib_ms() -> float:
    """Fixed deterministic CPU spin, timed. The sim25 figure is a pure
    single-process CPU measurement, so host contention scales it directly
    — the BENCH_r04/r05 'regression' (47-52 -> 13-15 TPS) reproduced at
    ~45-53 TPS on an idle host with the very same code, while the bench
    rounds ran it last in a round that had just hammered the host with
    multi-process TCP pools. This calibration figure rides the bench line
    so a contended round is READABLE as contended (calib_ms inflates with
    the same factor) instead of masquerading as an ordering regression."""
    import hashlib
    t0 = time.perf_counter()
    block = b"\0" * 65536
    h = hashlib.sha256()
    for _ in range(200):
        h.update(block)
    return round((time.perf_counter() - t0) * 1000, 2)


def _sim25_once(n_txns: int, timeout: float, config_overrides=None,
                topology: str = None) -> dict:
    import plenum_tpu.tools.local_pool as lp
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import NYM

    (names, nodes, timer, trustee,
     replies, ReplyCls, DOMAIN, plane, net) = lp.build_pool(
         25, "cpu", config_overrides=config_overrides)
    if topology is not None:
        from plenum_tpu.network import make_topology
        net.set_topology(make_topology(topology, names))
    reqs = []
    for i in range(n_txns):
        user = Ed25519Signer(seed=(b"s25_%05d" % i).ljust(32, b"\0")[:32])
        req = Request(trustee.identifier, i + 1,
                      {"type": NYM, "dest": user.identifier,
                       "verkey": user.verkey_b58})
        req.signature = trustee.sign_b58(req.signing_bytes())
        reqs.append(req)
    done, dt = _drive_inprocess(names, nodes, timer, replies, ReplyCls,
                                plane, reqs, timeout)
    wire = net.bytes_summary()
    prop = sum(c["bytes"] for op, c in wire["by_type"].items()
               if op in ("PROPAGATE", "PROPAGATE_BATCH"))
    stage = lp.commit_stage_stats(nodes[names[0]].metrics)
    ctl = getattr(nodes[names[0]], "batch_controller", None)
    return {"nodes": 25, "txns_ordered": done, "txns_requested": n_txns,
            "tps": round(done / dt, 1) if dt else 0.0,
            "wire_bytes_per_txn": round(wire["total_bytes"] / done)
            if done else None,
            "propagate_bytes_per_txn": round(prop / done)
            if done else None,
            **({"controller": ctl.trajectory()} if ctl is not None else {}),
            **({"commit_stage": stage} if stage else {})}


def config5_sim25(n_txns: int = 60, timeout: float = 180.0) -> dict:
    """25-node simulated pool (SimNetwork fabric, one process) ordering
    datum — the scale test's shape (tests/test_scale.py) with a number.

    Runs an A/B: the default deep-pipelined + controller-steered ordering
    vs the legacy static knobs (in-flight window 4, no controller), plus a
    host-contention calibration so a loaded bench host can't masquerade as
    an ordering regression (see _host_calib_ms). Tracing note: this config
    runs the NullTracer fast path — it keeps NO tracing overhead, and the
    calib figure is the only non-pool work it pays for."""
    try:
        calib = _host_calib_ms()
        # One DISCARDED warm-up pass, then 3 runs per arm INTERLEAVED and
        # medians taken: single sim25 passes ride a ±20% host-noise band
        # (the r04/r05 lesson), and the first pool in a process runs
        # measurably cold — an A/B that always ran one arm first
        # systematically penalized it (measured: same arm 54.7 first vs
        # 67.6 fourth in one process).
        legacy_cfg = {"BATCH_CONTROLLER": False, "Max3PCBatchesInFlight": 4}
        _sim25_once(n_txns, timeout)             # warm-up, discarded
        runs, legacy_runs = [], []
        for _ in range(3):
            runs.append(_sim25_once(n_txns, timeout))
            legacy_runs.append(_sim25_once(n_txns, timeout,
                                           config_overrides=legacy_cfg))
        runs.sort(key=lambda r: r["tps"])
        legacy_runs.sort(key=lambda r: r["tps"])
        out = runs[1]
        out["tps_spread"] = {"min": runs[0]["tps"], "max": runs[-1]["tps"]}
        out["calib_ms"] = calib
        out["tracing_overhead"] = "none (NullTracer fast path)"
        out["legacy_tps"] = legacy_runs[1].get("tps")
        return out
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def config9_wan25(n_txns: int = 40, timeout: float = 240.0) -> dict:
    """25-node pool over the TOPOLOGY-AWARE fabric: the same sim25 shape,
    once per region preset (geo3 clean WAN, lossy_wan degraded). The
    orderings-still-happen number the WAN robustness work is judged by —
    and the honest cost of geography: the delta vs config5's flat-LAN
    figure is propagation+loss, not code. Real time (QueueTimer), so WAN
    delays are actually waited out; txn count kept small accordingly."""
    out: dict = {"nodes": 25, "txns_requested": n_txns}
    try:
        for preset in ("geo3", "lossy_wan"):
            run = _sim25_once(n_txns, timeout, topology=preset)
            out[preset] = {k: run.get(k) for k in
                           ("txns_ordered", "tps", "wire_bytes_per_txn")}
        return out
    except Exception as e:                       # pragma: no cover
        out["error"] = f"{type(e).__name__}: {e}"
        return out





def config6_read_plane(n_reads: int = 1800, write_every: int = 9,
                       timeout: float = 120.0) -> dict:
    """Read-heavy mix (90:10 read:write) through the VERIFIED read plane:
    every read goes to ONE node and the client checks the state proof +
    BLS multi-sig + freshness (reads/client.py). Reports reads/s, the
    measured per-read fanout (messages per read, target 2 = 1 request +
    1 reply vs the legacy 2n broadcast), client verify p50/p95, and the
    serving node's cache hit rate."""
    import plenum_tpu.tools.local_pool as lp
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import GET_NYM, NYM
    from plenum_tpu.reads import SimReadDriver

    try:
        (names, nodes, timer, trustee,
         replies, ReplyCls, DOMAIN, plane, net) = lp.build_pool(4, "cpu")
        users = []
        setup = []
        for i in range(20):
            user = Ed25519Signer(seed=(b"rp%08d" % i).ljust(32, b"\0")[:32])
            users.append(user)
            req = Request(trustee.identifier, i + 1,
                          {"type": NYM, "dest": user.identifier,
                           "verkey": user.verkey_b58})
            req.signature = trustee.sign_b58(req.signing_bytes())
            setup.append(req)
        done, _ = _drive_inprocess(names, nodes, timer, replies, ReplyCls,
                                   plane, setup, 60.0)
        if done < len(setup):
            return {"error": f"setup ordered only {done}/{len(setup)}"}

        bls_keys = lp.pool_bls_keys(names)

        def submit(name, req):
            nodes[name].handle_client_message(req.to_dict(), "rdr")

        def collect(name):
            out = [m.result for _, m, c in replies[name]
                   if isinstance(m, ReplyCls) and c == "rdr"]
            replies[name].clear()
            return out

        def pump(seconds):
            t_end = time.perf_counter() + seconds
            while time.perf_counter() < t_end:
                timer.service()
                for node in nodes.values():
                    node.prod()

        driver = SimReadDriver(submit, collect, pump, names, bls_keys,
                               freshness_s=1e9,
                               now=timer.get_current_time)
        served = 0
        writes = 0
        write_id = 1000
        t0 = time.perf_counter()
        for i in range(n_reads):
            if time.perf_counter() > t0 + timeout:
                break
            if i % write_every == write_every - 1:
                # the write share of the 90:10 mix, fire-and-forget
                user = Ed25519Signer(
                    seed=(b"rpw%07d" % i).ljust(32, b"\0")[:32])
                w = Request(trustee.identifier, write_id,
                            {"type": NYM, "dest": user.identifier,
                             "verkey": user.verkey_b58})
                w.signature = trustee.sign_b58(w.signing_bytes())
                write_id += 1
                for n in names:
                    nodes[n].handle_client_message(w.to_dict(), "bench-w")
                writes += 1
            q = Request("reader", i + 1,
                        {"type": GET_NYM,
                         "dest": users[i % len(users)].identifier})
            if driver.read(q, per_node_s=2.0, step_s=0.001) is not None:
                served += 1
        dt = time.perf_counter() - t0
        s = driver.stats.summary()
        rp = nodes[names[0]].read_plane.stats
        out = {"reads_served": served, "writes_submitted": writes,
               "reads_per_s": round(served / dt, 1) if dt else 0.0,
               "read_fanout": s.get("fanout"),
               "legacy_read_fanout": 2 * len(names),
               "single_reply_ok": s["single_reply_ok"],
               "failovers": s["failovers"], "fallbacks": s["fallbacks"],
               "verify_ms_p50": s.get("verify_ms_p50"),
               "verify_ms_p95": s.get("verify_ms_p95")}
        if rp["queries"]:
            out["server_cache_hit_rate"] = round(
                rp["cache_hits"] / rp["queries"], 3)
        return out
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def config7_ingress_10k(n_clients: int = 10_000, n_ops: int = 3000,
                        burst_clients: int = 200, burst_per_client: int = 10,
                        timeout: float = 180.0) -> dict:
    """10k-simulated-client, 95:5 read:write mix through the whole
    ingress plane (docs/ingress.md):

      * writes enter each node through an IngressPlane — admission
        control, weighted-fair dequeue, and ONE batched Ed25519 dispatch
        per tick through the ReqAuthenticator seam (the published
        auth_batch_mean must be >> 1 for the amortization claim);
      * reads are served by TWO observers replicating via BatchCommitted
        pushes (multi-sig verified before anchoring) with client-side
        proof verification (SimReadDriver, observer tier first);
      * an overload A/B floods one front door: the ingress arm holds
        queue depth at the watermark with explicit LoadShed replies and
        the pool KEEPS ordering (zero wedges), while the no-ingress arm
        swallows the whole burst into the node inbox unboundedly.
    """
    import plenum_tpu.tools.local_pool as lp
    from plenum_tpu.client.sim_clients import (SimClientPopulation,
                                               burst_writes)
    from plenum_tpu.common.node_messages import BatchCommitted
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import NYM
    from plenum_tpu.ingress import IngressPlane, SimObserver
    from plenum_tpu.reads import SimReadDriver

    try:
        (names, nodes, timer, trustee,
         replies, ReplyCls, DOMAIN, plane, net) = lp.build_pool(4, "cpu")
        bls_keys = lp.pool_bls_keys(names)

        # observers BEFORE traffic: pushes only cover live batches.
        # build_genesis is deterministic per name set, so the observers
        # bootstrap from byte-identical genesis txns
        genesis, _ = lp.build_genesis(names)
        observers = {
            f"obs{i + 1}": SimObserver(
                f"obs{i + 1}", genesis, names, bls_keys,
                now=timer.get_current_time, f=1, anchor_lag_max=None)
            for i in range(2)}
        for obs in observers.values():
            obs.register(lambda v, msg, o=obs: nodes[v]
                         .handle_client_message(msg, o.client_id))

        ingress = {n: IngressPlane(nodes[n], tick=False) for n in names}

        def route_pushes():
            """Move BatchCommitted pushes out of the validator client
            outboxes into the observers."""
            for v in names:
                keep = []
                for ts, msg, client in replies[v]:
                    obs = observers.get(
                        client[4:] if client.startswith("obs:") else "")
                    if obs is not None and isinstance(msg, BatchCommitted):
                        obs.deliver_push(msg, v)
                    else:
                        keep.append((ts, msg, client))
                replies[v][:] = keep

        def step():
            timer.service()
            for node in nodes.values():
                node.prod()
            for ing in ingress.values():
                ing.service()
            route_pushes()

        # setup: 20 read-target DIDs ordered through the INGRESS plane
        users = []
        t0 = time.perf_counter()
        for i in range(20):
            user = Ed25519Signer(seed=(b"i7%08d" % i).ljust(32, b"\0")[:32])
            users.append(user)
            req = Request(trustee.identifier, i + 1,
                          {"type": NYM, "dest": user.identifier,
                           "verkey": user.verkey_b58})
            req.signature = trustee.sign_b58(req.signing_bytes())
            for n in names:
                ingress[n].submit(req.to_dict(), "setup")
        domain = nodes[names[0]].c.db.get_ledger(DOMAIN)
        while domain.size < 21 and time.perf_counter() < t0 + 60.0:
            step()
        if domain.size < 21:
            return {"error": f"setup ordered only {domain.size - 1}/20"}
        base_size = domain.size

        # --- the 95:5 mixed drive ------------------------------------
        def submit(name, req):
            if name in observers:
                observers[name].handle_client_message(req.to_dict(), "rdr")
            else:
                nodes[name].handle_client_message(req.to_dict(), "rdr")

        def collect(name):
            if name in observers:
                out = [m.result for m, c in observers[name].sent
                       if isinstance(m, ReplyCls)]
                observers[name].sent.clear()
                return out
            out = [m for _, m, c in replies[name]
                   if isinstance(m, ReplyCls) and c == "rdr"]
            replies[name][:] = [e for e in replies[name]
                                if not (isinstance(e[1], ReplyCls)
                                        and e[2] == "rdr")]
            return [m.result for m in out]

        def pump(seconds):
            t_end = time.perf_counter() + seconds
            while time.perf_counter() < t_end:
                step()

        driver = SimReadDriver(submit, collect, pump, names, bls_keys,
                               freshness_s=1e9,
                               now=timer.get_current_time,
                               observer_names=sorted(observers))
        pop = SimClientPopulation(n_clients, trustee,
                                  [u.identifier for u in users], seed=7)
        served = writes = 0
        t0 = time.perf_counter()
        # wave-shaped drive: each wave's writes land in the ingress
        # queues FIRST and ride the tick's ONE auth dispatch together
        # (real front doors see concurrent arrivals, not one write per
        # service tick); the wave's reads then run against the observers
        ops = list(pop.ops(n_ops))
        wave_size = 100
        for w0 in range(0, len(ops), wave_size):
            if time.perf_counter() > t0 + timeout:
                break
            wave = ops[w0:w0 + wave_size]
            for client_id, kind, req in wave:
                if kind == "write":
                    for n in names:
                        ingress[n].submit(req.to_dict(), client_id)
                    writes += 1
            step()
            for client_id, kind, req in wave:
                if kind == "read":
                    if driver.read(req, per_node_s=2.0,
                                   step_s=0.001) is not None:
                        served += 1
        # drain the tail of in-flight writes
        t_drain = time.perf_counter() + 20.0
        while (domain.size - base_size) < writes and \
                time.perf_counter() < t_drain:
            step()
        dt = time.perf_counter() - t0
        # SNAPSHOT before the overload arms order their own flood writes
        writes_ordered = domain.size - base_size
        s = driver.stats.summary()
        ing_sum = ingress[names[0]].summary()

        # --- overload A/B --------------------------------------------
        # arm A: flood ONE ingress front door; queue depth stays at the
        # watermark, the surplus sheds explicitly, the pool keeps
        # ordering. Watermarks scale with the burst (a quarter of it) so
        # the A/B sheds decisively at any parameterization and still
        # drains in seconds.
        burst = burst_writes(trustee, burst_clients, burst_per_client,
                             seed=7)
        wm = max(32, len(burst) // 4)
        flood_cfg = nodes[names[0]].config.replace(
            INGRESS_HIGH_WATERMARK=wm,
            INGRESS_LOW_WATERMARK=max(8, wm // 4),
            INGRESS_CLIENT_QUEUE_CAP=max(2, burst_per_client // 2),
            INGRESS_CONTROLLER=False)
        flood_ing = IngressPlane(nodes[names[0]], config=flood_cfg,
                                 tick=False)
        size_before = domain.size
        for client, req in burst:
            flood_ing.submit(req.to_dict(), client)

        def flood_step():
            step()
            flood_ing.service()          # tick=False: serviced here

        t_flood = time.perf_counter() + 15.0
        while time.perf_counter() < t_flood and flood_ing.queue_depth:
            flood_step()
        # the queue drains into dispatches before ordering completes:
        # give the pool a bounded window to show it KEPT ordering the
        # admitted subset (the zero-wedge claim), not just shedding
        admitted = flood_ing.stats["admitted"]
        t_flood = time.perf_counter() + 20.0
        while domain.size - size_before < admitted and \
                time.perf_counter() < t_flood:
            flood_step()
        fa = flood_ing.summary()
        arm_a = {
            "burst": len(burst),
            "watermark": wm,
            "queue_depth_peak": fa["queue_depth_max"],
            "bounded": fa["queue_depth_max"] <= wm,
            "shed": fa["shed"],
            "admitted": admitted,
            "auth_batch_mean": fa.get("auth_batch_mean"),
            "ordered_after_flood": domain.size - size_before,
            "inbox_peak": max((len(nodes[n]._client_inbox)
                               for n in names), default=0),
        }
        # arm B: the same burst straight into the node inbox — nothing
        # sheds, the inbox swallows the whole flood (unbounded growth)
        for client, req in burst:
            nodes[names[0]].handle_client_message(req.to_dict(), client)
        arm_b = {"burst": len(burst),
                 "inbox_depth_after_burst":
                     len(nodes[names[0]]._client_inbox)}
        t_flood = time.perf_counter() + 30.0
        while nodes[names[0]]._client_inbox and \
                time.perf_counter() < t_flood:
            step()

        return {
            "clients": n_clients, "ops": n_ops,
            "reads_served": served, "writes_submitted": writes,
            "writes_ordered": writes_ordered,
            "reads_per_s": round(served / dt, 1) if dt else 0.0,
            "observer_served": s.get("observer_ok", 0),
            "read_fanout": s.get("fanout"),
            "verify_ms_p50": s.get("verify_ms_p50"),
            "verify_ms_p95": s.get("verify_ms_p95"),
            "auth_batch_mean": ing_sum.get("auth_batch_mean"),
            "auth_batches": ing_sum.get("auth_batches"),
            "ingress_admitted": ing_sum.get("admitted"),
            "ingress_shed": ing_sum.get("shed"),
            **({"ingress_controller": ing_sum["controller"]}
               if "controller" in ing_sum else {}),
            "overload_ab": {"ingress": arm_a, "no_ingress": arm_b},
        }
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def _sharded_arm_once(n_shards: int, nodes_per_shard: int, n_txns: int,
                      timeout: float, n_reads: int = 60,
                      cross_fraction: float = 0.5) -> dict:
    """One real-time pass over a ShardedSimFabric: route `n_txns` writes
    across the shards, then run a read mix where `cross_fraction` of the
    reads target keys owned by a NON-home shard (home = shard 0, the
    reader's local one) — every read composes mapping-ownership +
    shard-anchor verification either way; the fraction only steers which
    shard answers. n_shards=1 IS the matched-node-count baseline: the
    identical code path (router, gates, composed verification) over one
    ordering instance, so the A/B isolates the sharding, not the plumbing."""
    import time as _time

    from plenum_tpu.common.request import Request
    from plenum_tpu.common.timer import QueueTimer
    from plenum_tpu.config import Config
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import GET_NYM, NYM
    from plenum_tpu.shards import ShardedSimFabric

    fab = ShardedSimFabric(
        n_shards=n_shards, nodes_per_shard=nodes_per_shard,
        timer=QueueTimer(_time.perf_counter), seed=11,
        config=Config(Max3PCBatchWait=0.05,
                      STATE_FRESHNESS_UPDATE_INTERVAL=600.0),
        latency=(0.00005, 0.0002))

    users = []
    reqs = []
    for i in range(n_txns):
        user = Ed25519Signer(seed=(b"sh%08d" % i).ljust(32, b"\0")[:32])
        req = Request(fab.trustee.identifier, i + 1,
                      {"type": NYM, "dest": user.identifier,
                       "verkey": user.verkey_b58})
        req.signature = fab.trustee.sign_b58(req.signing_bytes())
        users.append(user)
        reqs.append(req)

    def ordered_total():
        return sum(s.ordered_count() for s in fab.shards.values())

    base = ordered_total()
    t0 = _time.perf_counter()
    i = 0
    while ordered_total() - base < n_txns and \
            _time.perf_counter() < t0 + timeout:
        while i < n_txns and i - (ordered_total() - base) < 256:
            fab.submit_write(reqs[i])
            i += 1
        fab.prod_all()
        if fab.pipeline is not None:
            fab.pipeline.flush()
    dt = _time.perf_counter() - t0
    done = ordered_total() - base
    per_shard = {sid: s.ordered_count() for sid, s in fab.shards.items()}

    # read phase: home-vs-cross mix through the composed verifier
    def pump(seconds):
        t_end = _time.perf_counter() + seconds
        while _time.perf_counter() < t_end:
            fab.prod_all()

    driver = fab.read_driver(pump=pump)
    home, away = [], []
    for u in users:
        req = Request("r", 1, {"type": GET_NYM, "dest": u.identifier})
        (home if fab.router.shard_of(req) == 0 else away).append(u)
    served = cross_served = 0
    t1 = _time.perf_counter()
    for j in range(n_reads):
        cross = (j % 10) < cross_fraction * 10 and away
        pool_u = away if cross else (home or away)
        if not pool_u:
            break
        u = pool_u[j % len(pool_u)]
        q = Request("reader", j + 1, {"type": GET_NYM, "dest": u.identifier})
        if driver.read(q, per_node_s=2.0, step_s=0.001) is not None:
            served += 1
            if cross:
                cross_served += 1
    read_dt = _time.perf_counter() - t1
    s = driver.stats.summary()
    return {
        "shards": n_shards, "nodes": n_shards * nodes_per_shard,
        "txns_ordered": done, "txns_requested": n_txns,
        "seconds": round(dt, 2),
        "aggregate_tps": round(done / dt, 1) if dt else 0.0,
        "per_shard_tps": {str(sid): round(n / dt, 1) if dt else 0.0
                          for sid, n in per_shard.items()},
        "router": fab.router.summary(),
        "reads_served": served, "cross_shard_served": cross_served,
        "reads_per_s": round(served / read_dt, 1) if read_dt else 0.0,
        "cross_verify_ms_p50": s.get("verify_ms_p50"),
        "cross_verify_ms_p95": s.get("verify_ms_p95"),
        "map_proof_failures": s.get("map_proof_failures"),
    }


def config10_shards(n_txns: int = 120, timeout: float = 240.0) -> dict:
    """Horizontal sharding A/B on the bench line (docs/sharding.md): 2-
    and 4-shard fabrics vs the SINGLE-shard pool at MATCHED total node
    count, under a 95:5-shaped load (the write drive + a cross-shard
    read mix at 50% cross fraction). Interleaved medians of 3 after one
    discarded warm-up pass, per the config5/config8 methodology (the
    first pool per process runs cold; host noise rides a ±20% band).

    The acceptance figure is speedup_2x4 = 2-shard aggregate write TPS /
    matched 8-node single-pool TPS (target >= 1.6): the per-txn ordering
    work in a 4-node shard is a fraction of an 8-node pool's (quadratic
    3PC messaging, half the commit sigs), so splitting the SAME total
    node count two ways buys super-linear aggregate throughput."""
    try:
        arms = {
            "single_8": (1, 8),
            "sharded_2x4": (2, 4),
            "sharded_4x2": (4, 2),
        }
        _sharded_arm_once(2, 4, max(20, n_txns // 4), timeout)   # warm-up
        runs: dict[str, list] = {k: [] for k in arms}
        for _ in range(3):
            for k, (ns, npn) in arms.items():        # interleaved
                runs[k].append(_sharded_arm_once(ns, npn, n_txns, timeout))

        def med(rs):
            good = sorted((r for r in rs if r.get("txns_ordered")),
                          key=lambda r: r["aggregate_tps"])
            return good[len(good) // 2] if good else {"error": "no runs"}

        out = {k: med(v) for k, v in runs.items()}
        base = out["single_8"].get("aggregate_tps") or 0.0
        two = out["sharded_2x4"].get("aggregate_tps") or 0.0
        four = out["sharded_4x2"].get("aggregate_tps") or 0.0
        if base:
            out["speedup_2x4"] = round(two / base, 2)
            out["speedup_4x2"] = round(four / base, 2)
        return out
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def _pipeline_ab_inproc(n_txns: int = 150, repeat: int = 3) -> dict:
    """The fused-pipeline A/B, run INSIDE a JAX_PLATFORMS=cpu subprocess
    (config8_pipeline_ab spawns it): the SAME 4-node write load through
    (a) the pipeline ring (cross-stage + cross-node coalescing/dedup) and
    (b) the per-call baseline — every node its own supervised device
    verifier, every call site's batch dispatched alone. WARMED and
    INTERLEAVED per the PR 6 methodology (the first pool per process pays
    the XLA compiles and runs cold; a fixed-order A/B lies), medians of
    `repeat`. The coalescing figure is mean caller-items-per-device-
    dispatch: the pipeline arm counts every caller item a wave settles
    (dedup riders included), the per-call arm counts the supervised
    verifier's real submitted items — both BEFORE padding."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from plenum_tpu.tools.local_pool import run_load

    arms = {"pipeline": "jax", "percall": "jax-percall"}
    # config7-style load shape for BOTH arms: a 32-deep trickle through
    # SMALL per-call-site batches (quota 16 — the shape ingress ticks
    # produce: many small per-tick auth batches per node) rather than the
    # headline's 256-deep flood. Per-call dispatches stay tick-sized
    # while the ring coalesces the same work across stages and co-hosted
    # nodes into RTT-sized waves — exactly the amortization the pipeline
    # exists to buy.
    overrides = {"LISTENER_MESSAGE_QUOTA": 16, "REMOTES_MESSAGE_QUOTA": 16}
    for b in arms.values():              # cold pass: compiles + warmup
        run_load(n_nodes=4, n_txns=40, backend=b, timeout=120.0,
                 config_overrides=overrides)
    runs: dict[str, list] = {k: [] for k in arms}
    for _ in range(repeat):
        for k, b in arms.items():        # interleaved
            runs[k].append(run_load(n_nodes=4, n_txns=n_txns, backend=b,
                                    timeout=120.0, window=32,
                                    config_overrides=overrides))

    def med(rs):
        good = sorted((r for r in rs if r.get("txns_ordered")),
                      key=lambda r: r["tps"])
        return good[len(good) // 2] if good else None

    pipe, percall = med(runs["pipeline"]), med(runs["percall"])
    out: dict = {"n_txns": n_txns, "repeat": repeat}
    if pipe is not None:
        out["pipeline_tps"] = pipe["tps"]
        out["pipeline_p50_ms"] = pipe.get("p50_latency_ms")
        ps = pipe.get("pipeline") or {}
        out["pipeline_items_per_dispatch"] = ps.get("items_per_dispatch")
        out["pipeline_dedup_ratio"] = ps.get("pipeline_dedup_ratio")
        out["pipeline_dispatches"] = ps.get("dispatches")
        out["pipeline_compiled_shapes"] = ps.get("compiled_shapes")
        out["pipeline_unpinned_shapes"] = ps.get("unpinned_shapes")
    if percall is not None:
        out["percall_tps"] = percall["tps"]
        out["percall_p50_ms"] = percall.get("p50_latency_ms")
        pc = percall.get("percall") or {}
        out["percall_items_per_dispatch"] = pc.get("items_per_dispatch")
        out["percall_dispatches"] = pc.get("device_batches")
    a = out.get("pipeline_items_per_dispatch")
    b = out.get("percall_items_per_dispatch")
    if a and b:
        out["coalescing_ratio"] = round(a / b, 2)
    return out


def config8_pipeline_ab(n_txns: int = 150,
                        timeout: float = 900.0) -> dict:
    """Pipelined-vs-per-call device A/B on JAX-ON-CPU, in a subprocess so
    the bench process never imports jax against a possibly-wedged tunnel.
    This figure is published UNCONDITIONALLY (relay up or down) — the
    round-5 failure mode was a blank device column; JAX-on-CPU runs the
    exact code path the TPU runs, so the A/B is never blank and its
    provenance is named (`jax_source`)."""
    import os
    import subprocess
    import sys

    code = ("import json\n"
            "from plenum_tpu.tools.bench_configs import _pipeline_ab_inproc\n"
            f"print(json.dumps(_pipeline_ab_inproc(n_txns={n_txns})))\n")
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"error": "pipeline A/B timed out"}
    for line in reversed(out.stdout.strip().splitlines() or [""]):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            parsed["jax_source"] = "jax-on-cpu"
            return parsed
    return {"error": (out.stderr or "no output").strip()[-300:]}


def _multichip_ab_inproc(seconds: float = 6.0, bucket: int = 16,
                         n_devices: int = 8, repeat: int = 3) -> dict:
    """The multi-device crypto-pipeline A/B, run INSIDE a forced-8-CPU-
    device subprocess (config14_multichip spawns it): the SAME pipelined
    crypto-wave flood (PR 8's 256-deep shape: unique well-formed content,
    double-buffered, every wave padded to the pinned bucket) through

      (a) ONE device  — the PR 8 single-ring pipeline pinned to chip 0;
      (b) N devices   — the ring sharded into per-chip lanes, one
                        breakable supervised verifier per device.

    WARMED and INTERLEAVED per the PR 6/PR 8 methodology, medians of
    `repeat`. The figure is aggregate crypto-wave throughput (caller
    items settled per second) — the thing lane scale-out buys; per-lane
    dispatch counts ride along as placement provenance. Honesty note:
    on forced-host CPU devices each lane's kernel execution runs on the
    host's shared cores, so the measured scaling is the RING's ability
    to keep N execution streams busy (dispatch concurrency + double
    buffering), the same property that scales on real chips."""
    import random
    import time as _time

    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        pass

    from plenum_tpu.config import Config
    from plenum_tpu.crypto.ed25519 import JaxEd25519Verifier
    from plenum_tpu.parallel.mesh import lane_roster
    from plenum_tpu.parallel.pipeline import (CryptoPipeline,
                                              make_multidevice_pipeline)
    from plenum_tpu.parallel.supervisor import supervise

    cfg = Config(PIPELINE_MIN_BUCKET=bucket, PIPELINE_MAX_BUCKET=bucket,
                 PIPELINE_FLUSH_WAIT=0.0)
    devs = lane_roster(n_devices)
    one = CryptoPipeline(
        ed_inner=supervise(JaxEd25519Verifier(min_batch=1,
                                              device=devs[0]),
                           label="lane0"),
        config=cfg)
    multi = make_multidevice_pipeline(cfg, n_devices, min_batch=1)
    for pipe in (one, multi):           # cold pass: compiles + warmup
        pipe.prewarm([bucket])
        pipe.pin()

    rng = random.Random(17)

    def junk(k):
        return [(rng.randbytes(16), rng.randbytes(63) + b"\x00",
                 rng.randbytes(32)) for _ in range(k)]

    def flood(pipe, lanes: int) -> float:
        settled = 0
        toks = []
        t0 = _time.perf_counter()
        deadline = t0 + seconds
        while _time.perf_counter() < deadline:
            toks.append(pipe.submit_verify(junk(bucket)))
            pipe.service()
            while len(toks) > 2 * lanes:
                if pipe.collect_verify(toks.pop(0), wait=True) is not None:
                    settled += bucket
        for tok in toks:
            if pipe.collect_verify(tok, wait=True) is not None:
                settled += bucket
        return settled / (_time.perf_counter() - t0)

    flood(one, 1)                       # warm the drive loop itself
    flood(multi, n_devices)
    ones, multis = [], []
    for _ in range(repeat):             # interleaved
        ones.append(flood(one, 1))
        multis.append(flood(multi, n_devices))
    ones.sort()
    multis.sort()
    one_med = ones[len(ones) // 2]
    multi_med = multis[len(multis) // 2]
    out = {
        "n_devices": n_devices, "bucket": bucket, "repeat": repeat,
        "one_device_items_per_s": round(one_med, 1),
        "multi_device_items_per_s": round(multi_med, 1),
        "scaling": round(multi_med / one_med, 2) if one_med else None,
        "per_device_dispatches": {
            "lane%d" % d["lane"]: d["dispatches"]
            for d in multi.device_state()},
        "one_device_dispatches": one.stats["dispatches"],
        "unpinned_shapes": (one.stats["unpinned_shapes"]
                            + multi.stats["unpinned_shapes"]),
    }
    multi.close()
    return out


def config14_multichip(seconds: float = 6.0,
                       timeout: float = 1500.0) -> dict:
    """N-device pipelined-flood A/B on JAX-ON-CPU (8 forced host
    devices), in a subprocess so the bench process never reconfigures
    its own jax backend. Published with `jax_source` provenance and the
    per-device dispatch counts — the multi-chip scale-out headline's
    measured stand-in (the TPU runs the same lane code)."""
    import os
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "flags = os.environ.get('XLA_FLAGS', '')\n"
        "if 'xla_force_host_platform_device_count' not in flags:\n"
        "    os.environ['XLA_FLAGS'] = (flags +"
        " ' --xla_force_host_platform_device_count=8').strip()\n"
        "import json\n"
        "from plenum_tpu.tools.bench_configs import _multichip_ab_inproc\n"
        f"print(json.dumps(_multichip_ab_inproc(seconds={seconds})))\n")
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"error": "multichip A/B timed out"}
    for line in reversed(out.stdout.strip().splitlines() or [""]):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            parsed["jax_source"] = "jax-on-cpu"
            return parsed
    return {"error": (out.stderr or "no output").strip()[-300:]}


def _federation_ab_inproc(seconds: float = 6.0, bucket: int = 16,
                          n_hosts: int = 1, repeat: int = 3) -> dict:
    """Cross-host crypto-federation A/B (config17_federation spawns it
    in a subprocess): the SAME pipelined crypto-wave flood through

      (a) local-only — the PR 8 single-ring pipeline on this process's
          chip 0 (the arm a node runs when PIPELINE_REMOTE_HOSTS is
          unset);
      (b) federated  — the same local lane PLUS `n_hosts` RENTED crypto
          hosts: real `crypto_service` worker subprocesses, rostered
          over the wire as extra lanes with prewarm/pin negotiated up
          front and work-stealing balancing the backlog.

    WARMED and INTERLEAVED per the PR 6/PR 8 methodology, medians of
    `repeat`. The figure is aggregate items settled per second — what
    renting a host buys; per-host dispatch counts, steal counters and
    the remote ship p95 ride along as placement provenance. Honesty
    note: the rented workers run the NATIVE-LIBRARY backend, standing
    in for a host whose engine outruns the renting node's jax-on-cpu
    lane — the reason to rent at all (a TPU-backed fleet vs a CPU
    node). On a multi-core runner they also add genuine process-level
    parallelism; `host_cores` rides the row so a single-core runner's
    figure can never masquerade as core scale-out."""
    import os
    import random
    import subprocess
    import sys
    import tempfile
    import time as _time

    import jax
    jax.config.update("jax_platforms", "cpu")

    from plenum_tpu.config import Config
    from plenum_tpu.crypto.ed25519 import JaxEd25519Verifier
    from plenum_tpu.parallel.federation import make_federated_pipeline
    from plenum_tpu.parallel.mesh import lane_roster
    from plenum_tpu.parallel.pipeline import CryptoPipeline
    from plenum_tpu.parallel.supervisor import supervise

    cfg = Config(PIPELINE_MIN_BUCKET=bucket, PIPELINE_MAX_BUCKET=bucket,
                 PIPELINE_FLUSH_WAIT=0.0,
                 PIPELINE_STEAL_THRESHOLD=bucket,
                 PIPELINE_STEAL_COOLDOWN=0.02)
    tmp = tempfile.mkdtemp(prefix="plenum-fed-bench-")
    hosts: list[str] = []
    procs: list = []
    fed = None
    try:
        for j in range(n_hosts):
            path = os.path.join(tmp, "host%d.sock" % j)
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "plenum_tpu.parallel.crypto_service",
                 "--socket", path, "--backend", "cpu",
                 "--min-batch", "1"],
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
            hosts.append(path)
        deadline = _time.monotonic() + 60.0
        for path in hosts:
            while not os.path.exists(path):
                if _time.monotonic() > deadline:
                    raise RuntimeError("crypto host %s never came up"
                                       % path)
                _time.sleep(0.05)

        local = CryptoPipeline(
            ed_inner=supervise(JaxEd25519Verifier(min_batch=1,
                                                  device=lane_roster(1)[0]),
                               label="lane0"),
            config=cfg)
        fed = make_federated_pipeline(cfg, min_batch=1, hosts=hosts,
                                      n_devices=1)
        for pipe in (local, fed):       # cold pass: compiles BOTH sides
            pipe.prewarm([bucket])      # of the wire before measuring
            pipe.pin()

        rng = random.Random(17)

        def junk(k):
            return [(rng.randbytes(16), rng.randbytes(63) + b"\x00",
                     rng.randbytes(32)) for _ in range(k)]

        def flood(pipe, lanes: int) -> float:
            # READY-ORDER drain, not FIFO: a blocking collect on the
            # oldest token would head-of-line block the fast local lane
            # behind every wire round trip, measuring the latency of
            # one remote wave instead of the throughput of the fleet
            settled = 0
            toks = []
            t0 = _time.perf_counter()
            deadline = t0 + seconds
            while _time.perf_counter() < deadline:
                toks.append(pipe.submit_verify(junk(bucket)))
                pipe.service()
                if len(toks) >= 4 * lanes:
                    still = []
                    for tok in toks:
                        if pipe.collect_verify(tok,
                                               wait=False) is not None:
                            settled += bucket
                        else:
                            still.append(tok)
                    toks = still
                while len(toks) > 6 * lanes:    # bounded backpressure
                    if pipe.collect_verify(toks.pop(0),
                                           wait=True) is not None:
                        settled += bucket
            for tok in toks:
                if pipe.collect_verify(tok, wait=True) is not None:
                    settled += bucket
            return settled / (_time.perf_counter() - t0)

        n_lanes = 1 + n_hosts
        flood(local, 1)                 # warm the drive loop itself
        flood(fed, n_lanes)
        locals_, feds = [], []
        for _ in range(repeat):         # interleaved
            locals_.append(flood(local, 1))
            feds.append(flood(fed, n_lanes))
        locals_.sort()
        feds.sort()
        local_med = locals_[len(locals_) // 2]
        fed_med = feds[len(feds) // 2]
        fed_state = fed.federation_state()
        out = {
            "n_hosts": n_hosts, "bucket": bucket, "repeat": repeat,
            "host_cores": os.cpu_count(),
            "local_items_per_s": round(local_med, 1),
            "federated_items_per_s": round(fed_med, 1),
            "scaling": (round(fed_med / local_med, 2)
                        if local_med else None),
            "per_host_dispatches": {
                (d.get("host") or "local%d" % d["lane"]): d["dispatches"]
                for d in fed.device_state()},
            "steals": fed.stats["steals"],
            "stolen_items": fed.stats["stolen_items"],
            "ship_ms_p95": fed_state["ship_ms_p95"],
            "unpinned_shapes": (local.stats["unpinned_shapes"]
                                + fed.stats["unpinned_shapes"]),
            "scaling_target": 1.7,
        }
        if (os.cpu_count() or 1) < 2:
            out["scaling_note"] = (
                "single-core runner: the local lane and the rented "
                "host share ONE core, so the A/B measures the "
                "federation machinery (latency-aware placement, "
                "stealing, wire, zero double-verifies) at capacity "
                "parity, not core scale-out; the >=1.7x target needs "
                "a multi-core runner or a real fleet")
        fed.close()
        fed = None
        return out
    finally:
        if fed is not None:
            try:
                fed.close()
            except Exception:
                pass
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)


def config17_federation(seconds: float = 6.0,
                        timeout: float = 1500.0) -> dict:
    """Local-only vs local+1-rented-crypto-host flood A/B on JAX-ON-CPU,
    in a subprocess so the bench process never reconfigures its own jax
    backend (the rented host is a further subprocess — a real separate
    interpreter reached over the crypto_service wire). Published with
    `jax_source` provenance plus per-host dispatch and steal counts —
    the cross-host federation headline's measured stand-in (a real
    fleet runs the same lane/wire code against TPU-backed hosts)."""
    import os
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import json\n"
        "from plenum_tpu.tools.bench_configs import _federation_ab_inproc\n"
        f"print(json.dumps(_federation_ab_inproc(seconds={seconds})))\n")
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"error": "federation A/B timed out"}
    for line in reversed(out.stdout.strip().splitlines() or [""]):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            parsed["jax_source"] = "jax-on-cpu"
            return parsed
    return {"error": (out.stderr or "no output").strip()[-300:]}


def _ordered_path_ab_inproc(n_txns: int = 100, repeat: int = 3,
                            n_devices: int = 4) -> dict:
    """Fused-commit-wave vs host-recommit A/B on the FULL write path
    (config16_ordered_path spawns it inside a forced-N-CPU-device
    subprocess): the SAME 4-node NYM write load through

      (a) fused — COMMIT_WAVE on: each ordered batch's triple-root
          recommit (state head + ledger append + audit append) rides
          the shared ring's cmt lane, level sweeps deduped across the
          co-hosted replicas and flushed as pinned pow-2 waves;
      (b) host  — COMMIT_WAVE off: every replica resolves every root
          inline (per-node sha3/RLP and shadow-tree loops), the
          pre-wave path.

    WARMED and INTERLEAVED per the PR 6/PR 8 methodology, medians of
    `repeat`. The figure is ordered-path TPS (client submit -> first
    REPLY), NOT crypto items/s — VaultxGPU's per-phase attribution
    point; the commit_stage percentiles (apply vs commit_wave) ride
    along so the delta localizes to the recommit stage, and the pinned
    ladder must close the run with 0 unpinned cmt shapes."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from plenum_tpu.tools.local_pool import run_load

    arms = {"fused": {"COMMIT_WAVE": True},
            "host": {"COMMIT_WAVE": False}}
    base = {"PIPELINE_DEVICES": n_devices}
    for ov in arms.values():             # cold pass: compiles + warmup
        run_load(n_nodes=4, n_txns=30, backend="jax", timeout=180.0,
                 config_overrides=dict(base, **ov))
    runs: dict[str, list] = {k: [] for k in arms}
    for _ in range(repeat):
        for k, ov in arms.items():       # interleaved
            runs[k].append(run_load(n_nodes=4, n_txns=n_txns,
                                    backend="jax", timeout=240.0,
                                    config_overrides=dict(base, **ov)))

    def med(rs):
        good = sorted((r for r in rs if r.get("txns_ordered")),
                      key=lambda r: r["tps"])
        return good[len(good) // 2] if good else None

    fused, host = med(runs["fused"]), med(runs["host"])
    out: dict = {"n_txns": n_txns, "repeat": repeat,
                 "n_devices": n_devices}
    if fused is not None:
        out["fused_tps"] = fused["tps"]
        out["fused_p50_ms"] = fused.get("p50_latency_ms")
        ps = fused.get("pipeline") or {}
        cmt = ps.get("cmt") or {}
        out["commit_waves"] = cmt.get("waves")
        out["commit_wave_levels"] = cmt.get("levels")
        out["commit_wave_host_fallbacks"] = cmt.get("host_fallbacks")
        out["fused_unpinned_shapes"] = ps.get("unpinned_shapes")
        out["per_device_dispatches"] = {
            "lane%d" % d["lane"]: d["dispatches"]
            for d in ps.get("devices", [])}
        stage = fused.get("commit_stage") or {}
        out["fused_commit_wave_ms_p50"] = stage.get("commit_wave_ms_p50")
        out["fused_apply_ms_p50"] = stage.get("apply_ms_p50")
    if host is not None:
        out["host_tps"] = host["tps"]
        out["host_p50_ms"] = host.get("p50_latency_ms")
        stage = host.get("commit_stage") or {}
        out["host_apply_ms_p50"] = stage.get("apply_ms_p50")
    if out.get("fused_tps") and out.get("host_tps"):
        out["ordered_path_speedup"] = round(
            out["fused_tps"] / out["host_tps"], 2)
    return out


def config16_ordered_path(n_txns: int = 100,
                          timeout: float = 1800.0) -> dict:
    """Ordered-path fused-vs-host recommit A/B on JAX-ON-CPU (4 forced
    host devices, the multichip harness pattern), in a subprocess so
    the bench process never reconfigures its own jax backend. Published
    with `jax_source` provenance and the per-device dispatch counts —
    the device-resident-ordering headline's measured stand-in (the TPU
    runs the same wave code)."""
    import os
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "flags = os.environ.get('XLA_FLAGS', '')\n"
        "if 'xla_force_host_platform_device_count' not in flags:\n"
        "    os.environ['XLA_FLAGS'] = (flags +"
        " ' --xla_force_host_platform_device_count=4').strip()\n"
        "import json\n"
        "from plenum_tpu.tools.bench_configs import _ordered_path_ab_inproc\n"
        f"print(json.dumps(_ordered_path_ab_inproc(n_txns={n_txns})))\n")
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"error": "ordered-path A/B timed out"}
    for line in reversed(out.stdout.strip().splitlines() or [""]):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            parsed["jax_source"] = "jax-on-cpu"
            return parsed
    return {"error": (out.stderr or "no output").strip()[-300:]}


def config1b_distinct_signers(n_txns: int = 200,
                              timeout: float = 120.0) -> dict:
    """Diverse-client honesty datum: every write signed by a DIFFERENT
    key. The headline configs sign everything with one trustee key,
    which maximally amortizes verkey parsing/decompression and the
    co-hosted verdict caches across hops (one content per request is
    still unique, but a single signer is the cache-friendliest shape).
    Here, phase 1 creates n DIDs (trustee-signed NYMs), phase 2 has
    each DID owner-sign an ATTRIB on itself — n distinct verkeys on the
    authentication hot path. Reported tps covers phase 2 only."""
    import json as _json

    import plenum_tpu.tools.local_pool as lp
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import ATTRIB, NYM

    try:
        (names, nodes, timer, trustee,
         replies, ReplyCls, DOMAIN, plane, net) = lp.build_pool(4, "cpu")
        users = [Ed25519Signer(seed=(b"ds%08d" % i).ljust(32, b"\0")[:32])
                 for i in range(n_txns)]
        nyms = []
        for i, u in enumerate(users):
            req = Request(trustee.identifier, i + 1,
                          {"type": NYM, "dest": u.identifier,
                           "verkey": u.verkey_b58})
            req.signature = trustee.sign_b58(req.signing_bytes())
            nyms.append(req)
        done, _ = _drive_inprocess(names, nodes, timer, replies, ReplyCls,
                                   plane, nyms, timeout)
        if done < n_txns:
            return {"error": f"setup incomplete: {done}/{n_txns} NYMs"}
        attribs = []
        for i, u in enumerate(users):
            req = Request(u.identifier, 1,
                          {"type": ATTRIB, "dest": u.identifier,
                           "raw": _json.dumps({"endpoint": str(i)})})
            req.signature = u.sign_b58(req.signing_bytes())
            attribs.append(req)
        done, dt = _drive_inprocess(names, nodes, timer, replies, ReplyCls,
                                    plane, attribs, timeout)
        return {"txns_ordered": done, "txns_requested": n_txns,
                "distinct_signers": n_txns,
                "tps": round(done / dt, 1) if dt else 0.0}
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def config11_telemetry(n_txns: int = 150, timeout: float = 120.0) -> dict:
    """Telemetry-plane acceptance on the bench line (docs/observability.md
    "Live fleet telemetry"):

    1. **Overhead A/B** — the SAME 4-node cpu write load with the
       telemetry plane enabled vs disabled (NULL_TELEMETRY fast path),
       WARMED and INTERLEAVED medians of 3 per the config5/config8
       methodology. The budget is the tracing plane's: <=2% (the
       disabled path is one attribute check, microbench-pinned in
       tests/test_telemetry.py; this publishes the measured end-to-end
       figure, which rides the host's single-run noise band).
    2. **Burn-rate / imbalance columns** — a sim-time 2-shard fabric
       under a zipfian-hot write mix (90% of writes key into one
       shard): the aggregator's load-imbalance index must flag the hot
       shard, and the burn/health summaries ride along.
    """
    from plenum_tpu.tools.local_pool import run_load

    try:
        arms = {"on": {"TELEMETRY": True}, "off": {"TELEMETRY": False}}
        for ov in arms.values():                 # cold pass: warmup
            run_load(n_nodes=4, n_txns=40, backend="cpu", timeout=timeout,
                     config_overrides=ov)
        # 5 interleaved repeats (vs the usual 3): the expected delta is
        # ~0 (the emitter works once per TELEMETRY_INTERVAL, not per
        # txn), so the A/B is measuring inside the host-noise band and
        # needs the tighter median
        runs: dict[str, list] = {k: [] for k in arms}
        for _ in range(5):
            for k, ov in arms.items():           # interleaved
                runs[k].append(run_load(n_nodes=4, n_txns=n_txns,
                                        backend="cpu", timeout=timeout,
                                        config_overrides=ov))

        def med(rs):
            good = sorted((r for r in rs if r.get("txns_ordered")),
                          key=lambda r: r["tps"])
            return good[len(good) // 2] if good else None

        on, off = med(runs["on"]), med(runs["off"])
        out: dict = {"n_txns": n_txns}
        if on is not None and off is not None and off.get("tps"):
            out["telemetry_on_tps"] = on["tps"]
            out["telemetry_off_tps"] = off["tps"]
            out["telemetry_overhead_pct"] = round(
                100 * (1 - on["tps"] / off["tps"]), 1)

        # hot-shard arm: sim-time fabric, zipfian-hot key mix
        out.update(_telemetry_hot_shard_arm())
        return out
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def _telemetry_hot_shard_arm(n_txns: int = 120) -> dict:
    """Deterministic sim-time 2-shard fabric under a 90:10 hot-key skew;
    -> the aggregator's imbalance/burn/health columns."""
    from plenum_tpu.common.request import Request
    from plenum_tpu.config import Config
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import NYM
    from plenum_tpu.shards import ShardedSimFabric

    fab = ShardedSimFabric(
        n_shards=2, nodes_per_shard=3, seed=17,
        config=Config(Max3PCBatchWait=0.05, TELEMETRY_INTERVAL=0.5,
                      STATE_FRESHNESS_UPDATE_INTERVAL=600.0))
    by_shard: dict[int, list] = {0: [], 1: []}
    i = 0
    while min(len(v) for v in by_shard.values()) < n_txns and i < 8 * n_txns:
        i += 1
        user = Ed25519Signer(seed=(b"tz%08d" % i).ljust(32, b"\0")[:32])
        req = Request(fab.trustee.identifier, i,
                      {"type": NYM, "dest": user.identifier,
                       "verkey": user.verkey_b58})
        req.signature = fab.trustee.sign_b58(req.signing_bytes())
        sid = fab.router.shard_of(req)
        if sid in by_shard:
            by_shard[sid].append(req)
    hot, cold = by_shard[0], by_shard[1]
    # 90:10 zipfian-shaped skew onto shard 0
    for j in range(n_txns):
        fab.submit_write(hot[j] if j % 10 else cold[j // 10])
        if j % 16 == 15:
            fab.run(1.0)
    fab.run(10.0)
    fab.ordered_counts()
    index, hot_sid = fab.aggregator.load_imbalance()
    s = fab.aggregator.fleet_summary()
    return {
        "imbalance_index": index,
        "hot_shard": hot_sid,
        "ordered_rates": s["ordered_rates"],
        "shard_health": s["shard_health"],
        "burn": {k: v for k, v in s["burn"].items()},
        "alerts": len(s["alerts"]),
    }


def config12_reshard(n_users: int = 320, phase_s: float = 20.0) -> dict:
    """Elastic-resharding acceptance on the bench line (docs/sharding.md
    "Elastic resharding"): a deterministic sim-time 2-shard fabric under
    a zipfian hot-range workload (90% of writes key into shard 0). The
    PR 11 aggregator flags the hot shard, ``maybe_split`` consumes the
    signal and live-splits the hot range onto a new sub-pool UNDER the
    same load, and the run publishes:

    * pre/post aggregate TPS (sim-time) and the recovery ratio — the
      acceptance gate is post >= 0.8 * pre within the run;
    * the load-imbalance index before (hot flagged) and after (below
      ``SHARD_IMBALANCE_THRESHOLD``);
    * the migration ledger: txns copied, handoff forwards, epoch.
    """
    from plenum_tpu.common.request import Request
    from plenum_tpu.config import Config
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import NYM
    from plenum_tpu.shards import ShardedSimFabric

    try:
        config = Config(Max3PCBatchWait=0.05, TELEMETRY_INTERVAL=0.5,
                        SLO_BURN_SLOW_WINDOW=30.0,
                        STATE_FRESHNESS_UPDATE_INTERVAL=600.0)
        fab = ShardedSimFabric(n_shards=2, nodes_per_shard=3, seed=23,
                               config=config)
        # mine the zipfian request pools: 90% hot (shard 0), 10% cold
        # the pools must outlast all three driven phases (the zipfian
        # cursor advancing past the hot pool's end would fake a post-
        # reshard skew flip)
        hot, cold = [], []
        i = 0
        while (len(hot) < n_users or len(cold) < n_users // 6) \
                and i < 12 * n_users:
            i += 1
            u = Ed25519Signer(seed=(b"rz%08d" % i).ljust(32, b"\0")[:32])
            req = Request(fab.trustee.identifier, i,
                          {"type": NYM, "dest": u.identifier,
                           "verkey": u.verkey_b58})
            req.signature = fab.trustee.sign_b58(req.signing_bytes())
            (hot if fab.router.shard_of(req) == 0 else cold).append(req)

        cursor = {"h": 0, "c": 0, "n": 0}

        def drive(seconds: float) -> float:
            """Zipfian-paced submission; -> ordered txns per SIM second."""
            t0 = fab.timer.get_current_time()
            base = sum(s.ordered_count() for s in fab.shards.values())
            steps = int(seconds / 0.25)
            for k in range(steps):
                cursor["n"] += 1
                if cursor["n"] % 10 and cursor["h"] < len(hot):
                    fab.submit_write(hot[cursor["h"]])
                    cursor["h"] += 1
                elif cursor["c"] < len(cold):
                    fab.submit_write(cold[cursor["c"]])
                    cursor["c"] += 1
                fab.run(0.25)
                fab.ordered_counts()
            dt = fab.timer.get_current_time() - t0
            done = sum(s.ordered_count()
                       for s in fab.shards.values()) - base
            return round(done / dt, 2) if dt else 0.0

        pre_tps = drive(phase_s)
        index_before, hot_sid = fab.aggregator.load_imbalance()
        m = fab.reshard.maybe_split()          # consume the PR 11 signal
        if m is None:
            return {"error": f"imbalance signal never flagged the hot "
                             f"shard (index={index_before})"}
        during_tps = drive(phase_s)            # reshard runs under load
        elapsed = 0.0
        while m.phase not in ("done", "aborted") and elapsed < 120.0:
            fab.run(0.5)
            elapsed += 0.5
        # the post phase runs 2x so the imbalance window judges a sample
        # big enough that a 72-write binomial wobble cannot re-flag a
        # healthily split range
        post_tps = drive(2 * phase_s)          # post-reshard steady state
        index_after, hot_after = fab.aggregator.load_imbalance()
        return {
            "pre_tps": pre_tps,
            "during_tps": during_tps,
            "post_tps": post_tps,
            "recovery_ratio": round(post_tps / pre_tps, 2)
            if pre_tps else None,
            "imbalance_before": index_before,
            "hot_shard_flagged": hot_sid,
            "imbalance_after": index_after,
            "hot_shard_after": hot_after,
            "imbalance_threshold": config.SHARD_IMBALANCE_THRESHOLD,
            "migration": m.to_dict(),
            "epoch": fab.mapping.epoch,
            "shards_after": len(fab.shards),
            "stale_nacks": len(fab.stale_nacks),
        }
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def config13_commitment(page_size: int = 16, n_dids: int = 48,
                        n_pages: int = 8, timeout: float = 90.0) -> dict:
    """Proof-size / verify-time A/B between the two state-commitment
    backends (docs/state_commitment.md): the SAME 4-node pool + DID set,
    once with STATE_COMMITMENT=mpt and once =verkle.

    Measures, per arm:

    * a 16-key client page as ONE envelope (`ReadPlane.page_envelope` —
      Verkle aggregates the whole page into one opening; MPT's baseline
      is the honest per-key sibling chains), bytes from the PRODUCTION
      proof-byte counters (read_plane.proof_bytes_*), client verify
      p50/p95 over `verify_page_envelope`;
    * single verified GET_NYM reads through the ordinary ladder
      (driver verify p50/p95 + per-envelope bytes);
    * the expected transfer time of one page over the ``lossy_wan``
      inter-region link profile (2.5e6 B/s, 3% loss -> x1/(1-p)
      expected retransmission bytes) — the bytes-are-the-product
      framing for WAN clients.

    Arms run INTERLEAVED with one discarded warm-up and medians of 3
    (the bench-host contention lesson from config5).
    """
    import plenum_tpu.tools.local_pool as lp
    from plenum_tpu.common.metrics import MetricsName, percentile
    from plenum_tpu.common.request import Request
    from plenum_tpu.common.serialization import pack
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import GET_NYM, NYM
    from plenum_tpu.reads import SimReadDriver
    from plenum_tpu.reads.proofs import verify_page_envelope

    LOSSY_BW = 2.5e6                 # bytes/s (lossy_wan inter-region)
    LOSSY_LOSS = 0.03

    def one_arm(backend: str) -> dict:
        (names, nodes, timer, trustee,
         replies, ReplyCls, DOMAIN, plane, net) = lp.build_pool(
             4, "cpu", config_overrides={"STATE_COMMITMENT": backend})
        users, setup = [], []
        for i in range(n_dids):
            u = Ed25519Signer(seed=(b"c13%05d" % i).ljust(32, b"\0")[:32])
            users.append(u)
            req = Request(trustee.identifier, i + 1,
                          {"type": NYM, "dest": u.identifier,
                           "verkey": u.verkey_b58})
            req.signature = trustee.sign_b58(req.signing_bytes())
            setup.append(req)
        done, _ = _drive_inprocess(names, nodes, timer, replies, ReplyCls,
                                   plane, setup, timeout)
        if done < len(setup):
            return {"error": f"{backend}: ordered {done}/{len(setup)}"}
        bls_keys = lp.pool_bls_keys(names)
        node = nodes[names[0]]

        # --- single reads through the verified ladder ---
        def submit(name, req):
            nodes[name].handle_client_message(req.to_dict(), "c13")

        def collect(name):
            out = [m.result for _, m, c in replies[name]
                   if isinstance(m, ReplyCls) and c == "c13"]
            replies[name].clear()
            return out

        def pump(seconds):
            t_end = time.perf_counter() + seconds
            while time.perf_counter() < t_end:
                timer.service()
                for nd in nodes.values():
                    nd.prod()

        driver = SimReadDriver(submit, collect, pump, names, bls_keys,
                               freshness_s=1e12,
                               now=timer.get_current_time)
        served = 0
        for i, u in enumerate(users[:page_size]):
            q = Request("c13r", i + 1,
                        {"type": GET_NYM, "dest": u.identifier})
            if driver.read(q, per_node_s=2.0, step_s=0.001) is not None:
                served += 1
        s = driver.stats.summary()

        # --- the 16-key page as ONE envelope ---
        page_keys = [u.identifier.encode() for u in users[:page_size]]
        gen_s: list[float] = []
        env = None
        for _ in range(n_pages):
            t0 = time.perf_counter()
            env = node.read_plane.page_envelope(DOMAIN, page_keys)
            gen_s.append(time.perf_counter() - t0)
        if env is None:
            return {"error": f"{backend}: page envelope unanchorable"}
        page_bytes = len(pack(env))
        ver_s: list[float] = []
        for _ in range(n_pages):
            t0 = time.perf_counter()
            ok, values, why = verify_page_envelope(
                env, page_keys, bls_keys, DOMAIN, freshness_s=1e12,
                now=timer.get_current_time)
            ver_s.append(time.perf_counter() - t0)
            if not ok:
                return {"error": f"{backend}: page verify failed ({why})"}

        # production proof-byte counters (the satellite contract: the
        # A/B reads what the node actually sampled, not a bench tally)
        metric = (MetricsName.READ_PROOF_BYTES_VERKLE_MULTI
                  if backend == "verkle"
                  else MetricsName.READ_PROOF_BYTES_STATE_MULTI)
        acc = node.metrics.accumulators.get(metric)
        counter_bytes = None
        if acc is not None and acc.samples:
            counter_bytes = {
                "p50": int(percentile(acc.samples, 0.5)),
                "p95": int(percentile(acc.samples, 0.95)),
            }
        transfer_ms = page_bytes / LOSSY_BW / (1 - LOSSY_LOSS) * 1000
        return {
            "singles_served": served,
            "single_verify_ms_p50": s.get("verify_ms_p50"),
            "single_verify_ms_p95": s.get("verify_ms_p95"),
            "page_bytes": page_bytes,
            "bytes_per_read": round(page_bytes / page_size, 1),
            "page_gen_ms_p50": round(
                percentile(gen_s, 0.5) * 1000, 2),
            "page_verify_ms_p50": round(
                percentile(ver_s, 0.5) * 1000, 2),
            "page_verify_ms_p95": round(
                percentile(ver_s, 0.95) * 1000, 2),
            "proof_bytes_counter": counter_bytes,
            "lossy_wan_page_transfer_ms": round(transfer_ms, 2),
        }

    try:
        one_arm("mpt")                           # warm-up, discarded
        runs = {"mpt": [], "verkle": []}
        for _ in range(3):                       # interleaved
            for backend in ("mpt", "verkle"):
                arm = one_arm(backend)
                if "error" in arm:
                    return arm
                runs[backend].append(arm)
        out: dict = {"page_size": page_size, "n_dids": n_dids}
        for backend in ("mpt", "verkle"):
            arms = sorted(runs[backend],
                          key=lambda a: a["page_verify_ms_p50"])
            out[backend] = arms[1]               # median by verify time
        out["bytes_reduction"] = round(
            out["mpt"]["page_bytes"] / out["verkle"]["page_bytes"], 2)
        # TS-Verkle-derived client budget (docs/state_commitment.md):
        # per-page = 2 pairings + one MSM over <= page*depth openings
        out["verify_budget_ms_p95"] = 60.0
        out["verify_within_budget"] = (
            out["verkle"]["page_verify_ms_p95"]
            <= out["verify_budget_ms_p95"])
        return out
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def config18_autopilot(n_users: int = 320, phase_s: float = 20.0) -> dict:
    """Hands-off heal of the config12 zipfian hot-range flood
    (docs/robustness.md "Autopilot"): the SAME 2-shard fabric and
    90%-hot workload, but ``AUTOPILOT=True`` and the driver never
    touches the control plane — no ``maybe_split`` call, no lane
    pokes, zero test-driven actuation. The autopilot's reshard policy
    must flag the sustained imbalance on its own cadence and live-
    split the hot range UNDER the flood (possibly already inside the
    first phase: the control plane acts as soon as the signal
    sustains, it does not wait for the driver's phase boundaries).

    * pre/post aggregate TPS and the recovery ratio — the acceptance
      gate is post >= 0.8 * pre, same bar as config12;
    * the control ledger (reserved CONTROL_LEDGER_ID txns) with the
      split decision's seq/time and its full audit
      (tools/control_audit.py) — must lint clean;
    * the migration ledger, exactly as config12 reports it.
    """
    from plenum_tpu.common.request import Request
    from plenum_tpu.config import Config
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import NYM
    from plenum_tpu.shards import ShardedSimFabric
    from plenum_tpu.tools.control_audit import audit_records

    try:
        # generous batch/ingress SLOs: this run grades the RESHARD
        # policy end-to-end; the degradation ladder has its own fuzz
        # scenario and must not park the pool read-only over sim-time
        # batching noise mid-split
        config = Config(Max3PCBatchWait=0.05, TELEMETRY_INTERVAL=0.5,
                        SLO_BURN_SLOW_WINDOW=30.0,
                        STATE_FRESHNESS_UPDATE_INTERVAL=600.0,
                        AUTOPILOT=True, AUTOPILOT_INTERVAL=0.5,
                        BATCH_SLO_P95=30.0, INGRESS_SLO_P95=30.0)
        fab = ShardedSimFabric(n_shards=2, nodes_per_shard=3, seed=23,
                               config=config)
        hot, cold = [], []
        i = 0
        while (len(hot) < n_users or len(cold) < n_users // 6) \
                and i < 12 * n_users:
            i += 1
            u = Ed25519Signer(seed=(b"rz%08d" % i).ljust(32, b"\0")[:32])
            req = Request(fab.trustee.identifier, i,
                          {"type": NYM, "dest": u.identifier,
                           "verkey": u.verkey_b58})
            req.signature = fab.trustee.sign_b58(req.signing_bytes())
            (hot if fab.router.shard_of(req) == 0 else cold).append(req)

        cursor = {"h": 0, "c": 0, "n": 0}

        def drive(seconds: float) -> float:
            t0 = fab.timer.get_current_time()
            base = sum(s.ordered_count() for s in fab.shards.values())
            steps = int(seconds / 0.25)
            for k in range(steps):
                cursor["n"] += 1
                if cursor["n"] % 10 and cursor["h"] < len(hot):
                    fab.submit_write(hot[cursor["h"]])
                    cursor["h"] += 1
                elif cursor["c"] < len(cold):
                    fab.submit_write(cold[cursor["c"]])
                    cursor["c"] += 1
                fab.run(0.25)
                fab.ordered_counts()
            dt = fab.timer.get_current_time() - t0
            done = sum(s.ordered_count()
                       for s in fab.shards.values()) - base
            return round(done / dt, 2) if dt else 0.0

        pre_tps = drive(phase_s)               # flood onset
        index_flood, hot_sid = fab.aggregator.load_imbalance()
        during_tps = drive(phase_s)            # autopilot acts in here
        elapsed = 0.0                          # run any migration out
        while fab.reshard.active is not None and elapsed < 120.0:
            fab.run(0.5)
            elapsed += 0.5
        post_tps = drive(2 * phase_s)          # post-heal steady state
        index_after, hot_after = fab.aggregator.load_imbalance()
        records = fab.autopilot.ledger.to_dicts()
        splits = [r for r in records if r["action"] == "split"]
        if not splits:
            return {"error": "the autopilot never split the hot shard "
                             f"(imbalance={index_flood}, "
                             f"records={len(records)})"}
        m = fab.reshard.history[0] if fab.reshard.history else None
        return {
            "pre_tps": pre_tps,
            "during_tps": during_tps,
            "post_tps": post_tps,
            "recovery_ratio": round(post_tps / pre_tps, 2)
            if pre_tps else None,
            "imbalance_flood": index_flood,
            "hot_shard_flagged": hot_sid,
            "imbalance_after": index_after,
            "hot_shard_after": hot_after,
            "test_driven_actuations": 0,       # by construction
            "split_seq": splits[0]["seq"],
            "split_t": splits[0]["t"],
            "split_evidence": splits[0]["evidence"],
            "control_records": len(records),
            "control_holds": sum(1 for r in records
                                 if r["action"] == "hold"),
            "audit_problems": audit_records(records),
            "migration": m.to_dict() if m is not None else None,
            "epoch": fab.mapping.epoch,
            "shards_after": len(fab.shards),
            "autopilot": fab.autopilot.summary(),
        }
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def config19_edge(n_reads: int = 1800, write_every: int = 20,
                  timeout: float = 120.0) -> dict:
    """The Proof CDN under a 95:5 read:write flood (docs/edge.md): the
    config6 pool with ONE keyless edge cache (reads/edge.py) in front —
    every read walks the edge-first ladder and verifies client-side.
    Reports the edge hit-rate and the client-facing edge service rate
    (the acceptance bar: >95% of verified reads served by edges), the
    POOL read load left behind (validator-served reads + CDN origin
    refills — what the edge tier exists to keep near zero), bytes per
    edge-served read, client verify p95, and `jax_source` provenance
    (the pool's crypto plane is the jax-on-cpu pipeline build_pool
    compiles)."""
    import plenum_tpu.tools.local_pool as lp
    from plenum_tpu.common.node_messages import BatchCommitted
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import GET_NYM, NYM
    from plenum_tpu.reads import SimEdge, SimReadDriver

    try:
        (names, nodes, timer, trustee,
         replies, ReplyCls, DOMAIN, plane, net) = lp.build_pool(4, "cpu")
        users = []
        setup = []
        for i in range(20):
            user = Ed25519Signer(seed=(b"ed%08d" % i).ljust(32, b"\0")[:32])
            users.append(user)
            req = Request(trustee.identifier, i + 1,
                          {"type": NYM, "dest": user.identifier,
                           "verkey": user.verkey_b58})
            req.signature = trustee.sign_b58(req.signing_bytes())
            setup.append(req)
        done, _ = _drive_inprocess(names, nodes, timer, replies, ReplyCls,
                                   plane, setup, 60.0)
        if done < len(setup):
            return {"error": f"setup ordered only {done}/{len(setup)}"}

        rr = {"i": 0}

        def origin(request):
            name = names[rr["i"] % len(names)]
            rr["i"] += 1
            return nodes[name].read_plane.answer(request)

        edge = SimEdge("edge1", origin, now=timer.get_current_time,
                       freshness_s=1e9)
        edge.register(lambda v, msg: nodes[v]
                      .handle_client_message(msg, edge.client_id), names)

        def route_pushes(name):
            keep = []
            for t, m, c in replies[name]:
                if c == edge.client_id:
                    if isinstance(m, BatchCommitted):
                        edge.deliver_push(m, name)
                else:
                    keep.append((t, m, c))
            replies[name][:] = keep

        def submit(name, req):
            if name == edge.name:
                edge.handle_client_message(req.to_dict(), "rdr")
            else:
                nodes[name].handle_client_message(req.to_dict(), "rdr")

        def collect(name):
            if name == edge.name:
                out = [m.result for m, _ in edge.sent
                       if isinstance(m, ReplyCls)]
                edge.sent.clear()
                return out
            route_pushes(name)
            out = [m.result for _, m, c in replies[name]
                   if isinstance(m, ReplyCls) and c == "rdr"]
            replies[name].clear()
            return out

        def pump(seconds):
            t_end = time.perf_counter() + seconds
            while time.perf_counter() < t_end:
                timer.service()
                for node in nodes.values():
                    node.prod()

        bls_keys = lp.pool_bls_keys(names)
        driver = SimReadDriver(submit, collect, pump, names, bls_keys,
                               freshness_s=1e9,
                               now=timer.get_current_time,
                               edge_names=[edge.name])
        served = 0
        writes = 0
        write_id = 1000
        t0 = time.perf_counter()
        for i in range(n_reads):
            if time.perf_counter() > t0 + timeout:
                break
            if i % write_every == write_every - 1:
                # the 5% write share: fire-and-forget, and the commit's
                # push fan-out invalidates the edge (anchor advance)
                user = Ed25519Signer(
                    seed=(b"edw%07d" % i).ljust(32, b"\0")[:32])
                w = Request(trustee.identifier, write_id,
                            {"type": NYM, "dest": user.identifier,
                             "verkey": user.verkey_b58})
                w.signature = trustee.sign_b58(w.signing_bytes())
                write_id += 1
                for n in names:
                    nodes[n].handle_client_message(w.to_dict(), "bench-w")
                writes += 1
                # let the write order: edge serving is synchronous (the
                # ladder never pumps on a cache hit), so the pool only
                # progresses when driven — and the commit's push
                # fan-out is what exercises invalidation + SWR
                pump(0.05)
            # CDN-shaped traffic: 90% of reads hammer 3 hot entries,
            # the tail rotates the cold set — hot entries amortize each
            # anchor-advance refill across many stale-while-revalidate
            # hits, the tail pays ~one refill per epoch per touched key
            hot = i % 10 < 9
            dest = users[i % 3] if hot else users[3 + i % 17]
            q = Request("reader", i + 1, {"type": GET_NYM,
                                          "dest": dest.identifier})
            if driver.read(q, per_node_s=2.0, step_s=0.001) is not None:
                served += 1
            for n in names:        # the push fan-out (anchor advances)
                route_pushes(n)
        dt = time.perf_counter() - t0
        s = driver.stats.summary()
        cs = edge.cache.stats
        # client-facing pool load (reads a VALIDATOR had to serve on the
        # ladder — the acceptance bar wants this ~0) vs CDN origin
        # refills (cold fills + revalidations: background traffic the
        # edge pays so clients don't)
        ladder_reads = s["single_reply_ok"] - s.get("edge_ok", 0)
        out = {"reads_served": served, "writes_submitted": writes,
               "reads_per_s": round(served / dt, 1) if dt else 0.0,
               "edge_served_rate": round(s.get("edge_ok", 0) / served, 4)
               if served else None,
               "edge_cache_hit_rate": round(cs["hits"] / cs["queries"], 4)
               if cs["queries"] else None,
               "edge_stale_served": cs["stale_served"],
               "edge_revalidations": cs["revalidations"],
               "edge_invalidations": cs["invalidations"],
               "pool_ladder_reads": ladder_reads,
               "origin_refills": cs["origin_fetches"],
               "origin_offload": round(
                   1.0 - cs["origin_fetches"] / cs["queries"], 4)
               if cs["queries"] else None,
               "bytes_per_edge_read": round(
                   cs["bytes_served"] / cs["hits"]) if cs["hits"] else None,
               "edge_verify_failures": s.get("edge_verify_failures", 0),
               "failovers": s["failovers"], "fallbacks": s["fallbacks"],
               "verify_ms_p50": s.get("verify_ms_p50"),
               "verify_ms_p95": s.get("verify_ms_p95"),
               "jax_source": "jax-on-cpu"}
        return out
    except Exception as e:                       # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def main():
    for name, fn in (("config1b", config1b_distinct_signers),
                     ("config2", config2_three_instances_mixed),
                     ("config3", config3_bls_proof_reads),
                     ("config4", config4_viewchange_under_load),
                     ("config5", config5_sim25),
                     ("config6", config6_read_plane),
                     ("config7", config7_ingress_10k),
                     ("config8", config8_pipeline_ab),
                     ("config10", config10_shards),
                     ("config11", config11_telemetry),
                     ("config12", config12_reshard),
                     ("config13", config13_commitment),
                     ("config16", config16_ordered_path),
                     ("config17", config17_federation),
                     ("config18", config18_autopilot),
                     ("config19", config19_edge)):
        print(name, json.dumps(fn()), flush=True)


if __name__ == "__main__":
    main()
