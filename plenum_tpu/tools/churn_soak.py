"""Churn soak: sustained writes under live membership churn, with
BOUNDED-GROWTH assertions on every in-memory structure that must not leak.

The plain soak (tools/soak.py) answers "does steady-state load leak?".
This one answers the nastier question ROADMAP item 5 asks: does the pool
leak while the WAN is degraded and the membership itself keeps changing —
demotions, re-promotions, BLS key rotations, primary demotions — for
minutes on end? Every churn event exercises exactly the structures that
have historically grown without bound (stashed future-view messages,
request state, per-view vote sets, verdict caches), so the soak samples
them between waves and FAILS if any of them trends past its cap:

* flight-recorder rings            (<= TRACE_RING_SIZE per node)
* metrics accumulators             (bounded name set, samples <= cap)
* stashing-router queues+discarded (<= router limit / 1000-deque)
* propagator request state         (TTL-swept)
* read-plane result cache          (bounded per-ledger shards)
* view-change / instance-change vote sets (retired per view)
* BLS sig/pending-order maps       (GC'd at stable checkpoints)

Runs on SIMULATED time (MockTimer + SimNetwork under the `lossy_wan`
topology preset), so "10 minutes" means 10 simulated minutes of timer
fires and churn events, wall-bounded only by host speed.

    python -m plenum_tpu.tools.churn_soak --seconds 600 [--json]

The fast tier-1 smoke (tests/test_resilience.py) runs the same loop for
a few sim-minutes; the full 10-minute run is the `soak`-marked test.
"""
from __future__ import annotations

import argparse
import json


def _stash_sizes(node) -> int:
    """Total stashed messages across every service router on the node."""
    total = 0
    for replica in node.replicas:
        for svc in (replica.ordering, replica.checkpointer,
                    replica.view_changer):
            stasher = getattr(svc, "_stasher", None)
            if stasher is not None:
                total += sum(len(q) for q in stasher._queues.values())
                total += len(stasher.discarded)
    return total


def _bounds_snapshot(pool) -> dict:
    """One sample of every bounded-growth structure, max across nodes."""
    out = {"flight_ring": 0, "metrics_accs": 0, "metrics_samples_max": 0,
           "stashed": 0, "request_state": 0, "seen_propagates": 0,
           "read_cache": 0, "vc_votes": 0, "ic_votes": 0, "bls_sigs": 0}
    for node in pool.nodes.values():
        snap = node.tracer.snapshot() if node.tracer.enabled else None
        if snap is not None:
            out["flight_ring"] = max(out["flight_ring"],
                                     len(snap["events"]))
        accs = node.metrics.accumulators
        out["metrics_accs"] = max(out["metrics_accs"], len(accs))
        out["metrics_samples_max"] = max(
            out["metrics_samples_max"],
            max((len(a.samples or ()) for a in accs.values()), default=0))
        out["stashed"] = max(out["stashed"], _stash_sizes(node))
        out["request_state"] = max(out["request_state"],
                                   len(node.propagator.requests))
        out["seen_propagates"] = max(out["seen_propagates"],
                                     len(node._seen_propagates))
        out["read_cache"] = max(
            out["read_cache"],
            sum(len(s) for s in node.read_plane._cache.values()))
        vcs = node.master_replica.view_changer
        out["vc_votes"] = max(
            out["vc_votes"],
            sum(len(d) for d in vcs._view_changes.values()))
        trigger = node.master_replica.vc_trigger
        if trigger is not None:
            out["ic_votes"] = max(
                out["ic_votes"],
                sum(len(d) for d in trigger._votes.values()))
        bls = node.master_replica.bls
        if bls is not None:
            out["bls_sigs"] = max(
                out["bls_sigs"],
                len(bls._sigs) + len(bls._pending_order))
    return out


def _check_bounds(sample: dict, config, n_validators: int) -> list[str]:
    """-> list of violated-bound descriptions (empty = healthy)."""
    caps = {
        "flight_ring": config.TRACE_RING_SIZE,
        "metrics_accs": 256,                 # the MetricsName namespace
        "metrics_samples_max": 256,          # metrics.SAMPLE_CAP
        "stashed": 8 * 1000,                 # routers' discarded deques +
        #                                      transient stash churn
        "request_state": 5000,               # TTL-swept under FAST sweeps
        "seen_propagates": 5000,
        "read_cache": 4 * 4096,
        "vc_votes": 4 * n_validators,        # <= a few views in flight
        "ic_votes": 130 * n_validators,      # MAX_FUTURE_VIEWS rows
        "bls_sigs": 2 * config.CHK_FREQ * n_validators,
    }
    return [f"{k}={sample[k]} > cap {caps[k]}"
            for k in caps if sample[k] > caps[k]]


def run_churn_soak(seconds: float = 600.0, seed: int = 11,
                   wave_s: float = 20.0) -> dict:
    """Drive a 5-node sim pool (4 validators + 1 churning member) over the
    lossy_wan topology for `seconds` of SIMULATED time: steady writes
    plus one churn event per wave, bounds sampled between waves."""
    import sys
    sys.path.insert(0, _tests_dir())
    from test_pool import Pool, signed_nym                  # noqa: E402
    from test_scale import signed_node_services             # noqa: E402

    from plenum_tpu.config import Config
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    from plenum_tpu.crypto.bls import BlsCryptoSigner
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.common.request import Request
    from plenum_tpu.execution.txn import NODE
    from plenum_tpu.network import make_topology

    names = ["Alpha", "Beta", "Gamma", "Delta", "Eps"]
    config = Config(Max3PCBatchWait=0.05,
                    PRIMARY_HEALTH_CHECK_FREQ=0.5,
                    ORDERING_PROGRESS_TIMEOUT=2.0,
                    STATE_FRESHNESS_UPDATE_INTERVAL=3.0,
                    VIEW_CHANGE_TIMEOUT=8.0, NEW_VIEW_TIMEOUT=4.0,
                    OUTDATED_REQS_CHECK_INTERVAL=5.0,
                    EXECUTED_REQ_RETENTION=10.0,
                    PROPAGATE_BODYLESS_REQ_TIMEOUT=10.0)
    pool = Pool(names=names, seed=seed, config=config)
    pool.net.set_topology(make_topology("lossy_wan", names))

    req_id = 0
    rotation_no = 0

    def write(n_writes: int) -> None:
        nonlocal req_id
        for _ in range(n_writes):
            req_id += 1
            user = Ed25519Signer(
                seed=(b"churn%08d" % req_id).ljust(32, b"\0")[:32])
            pool.submit(signed_nym(pool.trustee, user, req_id))
            pool.run(0.5)

    def churn(event_no: int) -> str:
        nonlocal req_id, rotation_no
        req_id += 1
        kind = event_no % 3
        if kind == 0:
            # demote the 5th member ... or re-promote it if demoted
            demoted = "Eps" not in pool.nodes["Alpha"].validators
            pool.submit(signed_node_services(
                pool.trustee, "Eps",
                ["VALIDATOR"] if demoted else [], req_id))
            return "promote" if demoted else "demote"
        if kind == 1:
            # rotate a non-primary validator's BLS key, then re-key the
            # node's signer (the operator restart, simulated in place)
            primary = pool.nodes["Alpha"].master_replica.data.primary_name
            victim = next(n for n in ("Beta", "Gamma", "Delta")
                          if n != primary)
            rotation_no += 1
            new_signer = BlsCryptoSigner(
                seed=(b"rot%s%04d" % (victim.encode(), rotation_no))
                .ljust(32, b"\0")[:32])
            req = Request(pool.trustee.identifier, req_id,
                          {"type": NODE, "dest": f"{victim}Dest",
                           "data": {"blskey": new_signer.pk,
                                    "blskey_pop":
                                    new_signer.generate_pop()}})
            req.signature = pool.trustee.sign_b58(req.signing_bytes())
            pool.submit(req)
            pool.run(3.0)
            if victim in pool.nodes:
                pool.nodes[victim].replicas.master.bls._signer = new_signer
            return f"rotate:{victim}"
        # demote the current primary -> forced view change; but never
        # shrink below 4 validators (f must stay >= 1 for the soak to
        # keep meaning BFT) — re-promote a demoted member instead
        validators = pool.nodes["Alpha"].validators
        demoted = [n for n in names if n not in validators]
        if demoted:
            pool.submit(signed_node_services(pool.trustee, demoted[0],
                                             ["VALIDATOR"], req_id))
            return f"repromote:{demoted[0]}"
        primary = pool.nodes["Alpha"].master_replica.data.primary_name
        pool.submit(signed_node_services(pool.trustee, primary, [],
                                         req_id))
        return f"demote_primary:{primary}"

    samples = [_bounds_snapshot(pool)]
    events: list[str] = []
    violations: list[str] = []
    elapsed = 0.0
    wave_no = 0
    while elapsed < seconds:
        write(3)
        events.append(churn(wave_no))
        pool.run(wave_s - 5.0)      # writes/churn above consumed ~5 sim-s
        elapsed += wave_s
        wave_no += 1
        sample = _bounds_snapshot(pool)
        samples.append(sample)
        bad = _check_bounds(sample, config,
                            len(pool.nodes["Alpha"].validators))
        if bad:
            violations.append(f"wave {wave_no}: " + "; ".join(bad))

    # final convergence: the surviving validator set must order one more
    # write everywhere (liveness after minutes of churn)
    req_id += 1
    user = Ed25519Signer(seed=(b"churn-final%d" % seed)
                         .ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, req_id))
    pool.run(30.0)
    validators = pool.nodes["Alpha"].validators
    sizes = {n: pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in validators if n in pool.nodes}
    converged = len(set(sizes.values())) == 1

    first, last = samples[0], samples[-1]
    return {
        "sim_seconds": elapsed, "waves": wave_no, "events": events,
        "txns_submitted": req_id,
        "converged": converged, "ledger_sizes": sizes,
        "bounds_ok": not violations, "violations": violations,
        "bounds_first": first, "bounds_last": last,
        "bounds_max": {k: max(s[k] for s in samples) for k in first},
    }


def _tests_dir() -> str:
    """The in-process Pool/signed_nym helpers live in tests/ next to the
    package — the soak reuses them instead of forking a third pool
    builder."""
    import os
    import plenum_tpu
    return os.path.join(
        os.path.dirname(os.path.dirname(plenum_tpu.__file__)), "tests")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seconds", type=float, default=600.0,
                    help="SIMULATED seconds of churn load")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    out = run_churn_soak(args.seconds, seed=args.seed)
    print(json.dumps(out if args.json else out, indent=None
                     if args.json else 2))
    return 0 if (out["bounds_ok"] and out["converged"]) else 1


if __name__ == "__main__":
    raise SystemExit(main())
