"""Churn soak: sustained writes under live membership churn, with
BOUNDED-GROWTH assertions on every in-memory structure that must not leak.

The plain soak (tools/soak.py) answers "does steady-state load leak?".
This one answers the nastier question ROADMAP item 5 asks: does the pool
leak while the WAN is degraded and the membership itself keeps changing —
demotions, re-promotions, BLS key rotations, primary demotions — for
minutes on end? Every churn event exercises exactly the structures that
have historically grown without bound (stashed future-view messages,
request state, per-view vote sets, verdict caches).

The bounded-growth verdicts come from the fleet history plane
(observability/history.py): every node's TelemetryEmitter ships its
``footprint`` section into one FleetAggregator, whose GrowthWatch fits
growth-rate trends per gauge and raises edge-triggered
``unbounded_growth`` alerts, and whose HistoryRecorder keeps a
queryable per-interval ring of the whole run. The soak FAILS if any
growth alert pages (exempt chain-growth gauges aside) — plus a
hard-cap backstop over the same ``Node.footprint()`` gauges, because a
leak that plateaus below the trend threshold but above its design cap
is still a leak:

* flight-recorder rings            (<= TRACE_RING_SIZE per node)
* metrics accumulators             (bounded name set, samples <= cap)
* stashing-router queues+discarded (<= router limit / 1000-deque)
* propagator request state / dedup map (TTL-swept)
* read-plane result cache          (bounded per-ledger shards)
* view-change + instance-change vote sets (retired per view)
* BLS sig/pending-order maps       (GC'd at stable checkpoints)

``leak_rate > 0`` injects a synthetic unbounded gauge (``leaky_stash``)
into one node's footprint source — the self-test that proves the
detector pages, and pages exactly once (edge-triggered), naming the
gauge.

Runs on SIMULATED time (MockTimer + SimNetwork under the `lossy_wan`
topology preset), so "10 minutes" means 10 simulated minutes of timer
fires and churn events, wall-bounded only by host speed.

    python -m plenum_tpu.tools.churn_soak --seconds 600 [--json]

The fast tier-1 smoke (tests/test_resilience.py) runs the same loop for
a few sim-minutes; the full 10-minute run is the `soak`-marked test.
"""
from __future__ import annotations

import argparse
import json


def _bounds_snapshot(pool) -> dict:
    """One sample of every bounded-growth structure, max across nodes.

    The per-structure walk lives in ``Node.footprint()`` now — the same
    gauges the telemetry footprint section ships — so the soak, the
    emitter, and the aggregator's growth trends all read ONE
    accounting. Only the metrics-collector internals (not footprint
    gauges: they meter the meter) stay hand-sampled here.
    """
    out = {"metrics_accs": 0, "metrics_samples_max": 0}
    for node in pool.nodes.values():
        for gauge, value in node.footprint().items():
            out[gauge] = max(out.get(gauge, 0), value)
        accs = node.metrics.accumulators
        out["metrics_accs"] = max(out["metrics_accs"], len(accs))
        out["metrics_samples_max"] = max(
            out["metrics_samples_max"],
            max((len(a.samples or ()) for a in accs.values()), default=0))
    return out


def _check_bounds(sample: dict, config, n_validators: int) -> list[str]:
    """-> list of violated-bound descriptions (empty = healthy).

    Hard caps backstop the growth verdicts: kv_* gauges (chain growth
    by design, GROWTH_EXEMPT) carry no cap.
    """
    caps = {
        "flight_ring_entries": config.TRACE_RING_SIZE,
        "metrics_accs": 256,                 # the MetricsName namespace
        "metrics_samples_max": 256,          # metrics.SAMPLE_CAP
        "stashed_entries": 8 * 1000,         # routers' discarded deques +
        #                                      transient stash churn
        "request_state_entries": 5000,       # TTL-swept under FAST sweeps
        "dedup_map_entries": 5000,
        "read_cache_entries": 4 * 4096,
        # view-change votes (a few views in flight) + instance-change
        # votes (MAX_FUTURE_VIEWS rows) land in ONE combined gauge
        "vc_vote_entries": (4 + 130) * n_validators,
        "bls_sig_entries": 2 * config.CHK_FREQ * n_validators,
        "bls_verdict_cache_entries": 16384,  # bls._BLS_VERDICTS_MAX
    }
    return [f"{k}={sample[k]} > cap {caps[k]}"
            for k in caps if sample.get(k, 0) > caps[k]]


def run_churn_soak(seconds: float = 600.0, seed: int = 11,
                   wave_s: float = 20.0, leak_rate: float = 0.0) -> dict:
    """Drive a 5-node sim pool (4 validators + 1 churning member) over the
    lossy_wan topology for `seconds` of SIMULATED time: steady writes
    plus one churn event per wave; the fleet aggregator's growth
    verdicts + history ring judge bounded growth, with the hard caps as
    backstop. `leak_rate > 0` adds a synthetic ever-growing
    ``leaky_stash`` gauge (entries per telemetry tick) to Alpha's
    footprint — the detector self-test."""
    import sys
    sys.path.insert(0, _tests_dir())
    from test_pool import Pool, signed_nym                  # noqa: E402
    from test_scale import signed_node_services             # noqa: E402

    from plenum_tpu.config import Config
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    from plenum_tpu.crypto.bls import BlsCryptoSigner
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.common.request import Request
    from plenum_tpu.execution.txn import NODE
    from plenum_tpu.network import make_topology
    from plenum_tpu.observability import (GROWTH_EXEMPT_GAUGES,
                                          FleetAggregator, HistoryRecorder)

    names = ["Alpha", "Beta", "Gamma", "Delta", "Eps"]
    config = Config(Max3PCBatchWait=0.05,
                    PRIMARY_HEALTH_CHECK_FREQ=0.5,
                    ORDERING_PROGRESS_TIMEOUT=2.0,
                    STATE_FRESHNESS_UPDATE_INTERVAL=3.0,
                    VIEW_CHANGE_TIMEOUT=8.0, NEW_VIEW_TIMEOUT=4.0,
                    OUTDATED_REQS_CHECK_INTERVAL=5.0,
                    EXECUTED_REQ_RETENTION=10.0,
                    PROPAGATE_BODYLESS_REQ_TIMEOUT=10.0)
    pool = Pool(names=names, seed=seed, config=config)
    pool.net.set_topology(make_topology("lossy_wan", names))

    # The history plane: every node ships snapshots into one aggregator;
    # growth trends + the per-interval ring come for free with ingest.
    agg = FleetAggregator(config=config)
    agg.attach_history(HistoryRecorder(
        max_slots=getattr(config, "HISTORY_MAX_SLOTS", 512)))
    for node in pool.nodes.values():
        node.telemetry.add_sink(agg.ingest)

    if leak_rate > 0:
        alpha = pool.nodes["Alpha"]
        real_footprint = alpha._telemetry_footprint_state
        ticks = {"n": 0}

        def leaky_footprint() -> dict:
            out = real_footprint()
            ticks["n"] += 1
            out["leaky_stash"] = int(64 + ticks["n"] * leak_rate)
            return out

        # re-registering under the same source name replaces the real one
        alpha.telemetry.add_source("footprint", leaky_footprint)

    req_id = 0
    rotation_no = 0

    def write(n_writes: int) -> None:
        nonlocal req_id
        for _ in range(n_writes):
            req_id += 1
            user = Ed25519Signer(
                seed=(b"churn%08d" % req_id).ljust(32, b"\0")[:32])
            pool.submit(signed_nym(pool.trustee, user, req_id))
            pool.run(0.5)

    def churn(event_no: int) -> str:
        nonlocal req_id, rotation_no
        req_id += 1
        kind = event_no % 3
        if kind == 0:
            # demote the 5th member ... or re-promote it if demoted
            demoted = "Eps" not in pool.nodes["Alpha"].validators
            pool.submit(signed_node_services(
                pool.trustee, "Eps",
                ["VALIDATOR"] if demoted else [], req_id))
            return "promote" if demoted else "demote"
        if kind == 1:
            # rotate a non-primary validator's BLS key, then re-key the
            # node's signer (the operator restart, simulated in place)
            primary = pool.nodes["Alpha"].master_replica.data.primary_name
            victim = next(n for n in ("Beta", "Gamma", "Delta")
                          if n != primary)
            rotation_no += 1
            new_signer = BlsCryptoSigner(
                seed=(b"rot%s%04d" % (victim.encode(), rotation_no))
                .ljust(32, b"\0")[:32])
            req = Request(pool.trustee.identifier, req_id,
                          {"type": NODE, "dest": f"{victim}Dest",
                           "data": {"blskey": new_signer.pk,
                                    "blskey_pop":
                                    new_signer.generate_pop()}})
            req.signature = pool.trustee.sign_b58(req.signing_bytes())
            pool.submit(req)
            pool.run(3.0)
            if victim in pool.nodes:
                pool.nodes[victim].replicas.master.bls._signer = new_signer
            return f"rotate:{victim}"
        # demote the current primary -> forced view change; but never
        # shrink below 4 validators (f must stay >= 1 for the soak to
        # keep meaning BFT) — re-promote a demoted member instead
        validators = pool.nodes["Alpha"].validators
        demoted = [n for n in names if n not in validators]
        if demoted:
            pool.submit(signed_node_services(pool.trustee, demoted[0],
                                             ["VALIDATOR"], req_id))
            return f"repromote:{demoted[0]}"
        primary = pool.nodes["Alpha"].master_replica.data.primary_name
        pool.submit(signed_node_services(pool.trustee, primary, [],
                                         req_id))
        return f"demote_primary:{primary}"

    samples = [_bounds_snapshot(pool)]
    events: list[str] = []
    violations: list[str] = []
    elapsed = 0.0
    wave_no = 0
    while elapsed < seconds:
        write(3)
        events.append(churn(wave_no))
        pool.run(wave_s - 5.0)      # writes/churn above consumed ~5 sim-s
        elapsed += wave_s
        wave_no += 1
        sample = _bounds_snapshot(pool)
        samples.append(sample)
        bad = _check_bounds(sample, config,
                            len(pool.nodes["Alpha"].validators))
        if bad:
            violations.append(f"wave {wave_no}: " + "; ".join(bad))

    # final convergence: the surviving validator set must order one more
    # write everywhere (liveness after minutes of churn)
    req_id += 1
    user = Ed25519Signer(seed=(b"churn-final%d" % seed)
                         .ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, req_id))
    pool.run(30.0)
    validators = pool.nodes["Alpha"].validators
    sizes = {n: pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in validators if n in pool.nodes}
    converged = len(set(sizes.values())) == 1

    # growth verdicts + alert audit from the history plane
    verdicts = agg.growth_verdicts()
    growth_alerts = [a.to_dict() for a in agg.alerts
                     if a.kind == "unbounded_growth"
                     and a.severity == "page"]
    unexpected = [a for a in growth_alerts
                  if not (leak_rate > 0 and a["subject"] == "leaky_stash")]
    growing = sorted(g for g, v in verdicts.items()
                     if v.get("verdict") == "growing"
                     and g not in GROWTH_EXEMPT_GAUGES
                     and not (leak_rate > 0 and g == "leaky_stash"))
    hist = agg.history

    first, last = samples[0], samples[-1]
    return {
        "sim_seconds": elapsed, "waves": wave_no, "events": events,
        "txns_submitted": req_id,
        "converged": converged, "ledger_sizes": sizes,
        "bounds_ok": not violations and not unexpected and not growing,
        "violations": violations,
        "bounds_first": first, "bounds_last": last,
        "bounds_max": {k: max(s.get(k, 0) for s in samples)
                       for k in last},
        "growth_verdicts": verdicts,
        "growth_alerts": growth_alerts,
        "growth_unexpected": [a["subject"] for a in unexpected] + growing,
        "history_rows": len(hist.rows), "history_seq": hist.seq,
        "history_tail": hist.query(max_points=12),
    }


def _tests_dir() -> str:
    """The in-process Pool/signed_nym helpers live in tests/ next to the
    package — the soak reuses them instead of forking a third pool
    builder."""
    import os
    import plenum_tpu
    return os.path.join(
        os.path.dirname(os.path.dirname(plenum_tpu.__file__)), "tests")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seconds", type=float, default=600.0,
                    help="SIMULATED seconds of churn load")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--leak-rate", type=float, default=0.0,
                    help="inject a synthetic leak of N entries per "
                         "telemetry tick (detector self-test)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    out = run_churn_soak(args.seconds, seed=args.seed,
                         leak_rate=args.leak_rate)
    print(json.dumps(out if args.json else out, indent=None
                     if args.json else 2))
    return 0 if (out["bounds_ok"] and out["converged"]) else 1


if __name__ == "__main__":
    raise SystemExit(main())
