"""Kernel microbenchmarks on the real device behind the tunnel.

Measures what docs/performance.md publishes: Ed25519 verify-kernel v3
sigs/s at the headline batch sizes (2048 warm, 128 small-dispatch), and
the batch SHA-256 Merkle leaf kernel. Replaces the hot spot the
reference spends its CPU on (/root/reference/stp_core/crypto/
nacl_wrappers.py:62,212 — scalar libsodium verify per request per node).

Run: python -m plenum_tpu.tools.tpu_microbench [--batches 2048,128]
Prints one JSON line per measurement plus a trailing summary line.
A dead relay fails in ~3 s (tpu_probe), never hangs.
"""
from __future__ import annotations

import json
import os
import sys
import time


def _now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def bench_ed25519(batch: int, reps: int = 5) -> dict:
    """sigs/s for one warm fixed-shape dispatch of `batch` signatures."""
    import numpy as np
    from plenum_tpu.crypto.ed25519 import Ed25519Signer, JaxEd25519Verifier

    rng = np.random.default_rng(7)
    signers = [Ed25519Signer(bytes(rng.integers(0, 256, 32, dtype=np.uint8)))
               for _ in range(min(batch, 64))]
    items = []
    for i in range(batch):
        s = signers[i % len(signers)]
        msg = bytes(rng.integers(0, 256, 64, dtype=np.uint8))
        items.append((msg, s.sign(msg), s.verkey))
    ver = JaxEd25519Verifier(min_batch=batch)
    # warm: compile + verkey-cache fill
    t0 = time.perf_counter()
    out = ver.verify_batch(items)
    compile_s = time.perf_counter() - t0
    if not bool(out.all()):
        return {"error": f"verdicts wrong at batch {batch}"}
    # negative control: one corrupted signature must flip exactly one verdict
    bad = list(items)
    bad[0] = (bad[0][0], bad[0][1][:32] + bytes(32), bad[0][2])
    out_bad = ver.verify_batch(bad)
    if bool(out_bad[0]) or not bool(out_bad[1:].all()):
        return {"error": f"negative control failed at batch {batch}"}
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        ver.verify_batch(items)
        times.append(time.perf_counter() - t0)
    best = min(times)
    med = sorted(times)[len(times) // 2]
    return {
        "kernel": "ed25519_verify_v3", "batch": batch,
        "compile_plus_first_s": round(compile_s, 3),
        "warm_best_s": round(best, 5), "warm_median_s": round(med, 5),
        "sigs_per_s_best": round(batch / best, 1),
        "sigs_per_s_median": round(batch / med, 1),
        "reps": reps,
    }


def bench_sha256(batch: int = 4096, reps: int = 5) -> dict:
    """Merkle leaf-hash kernel: batch SHA-256 over 64-byte blocks."""
    import numpy as np
    try:
        from plenum_tpu.ops import sha256 as s256
    except Exception as e:  # pragma: no cover
        return {"error": f"sha256 ops import: {e}"}
    rng = np.random.default_rng(3)
    leaves = [bytes(rng.integers(0, 256, 48, dtype=np.uint8))
              for _ in range(batch)]
    import hashlib
    t0 = time.perf_counter()
    out = s256.sha256_batch(leaves, prefix=b"\x00")   # RFC 6962 leaf prefix
    compile_s = time.perf_counter() - t0
    ref0 = hashlib.sha256(b"\x00" + leaves[0]).digest()
    got0 = out[0] if isinstance(out[0], bytes) else bytes(np.asarray(out)[0])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        s256.sha256_batch(leaves, prefix=b"\x00")
        times.append(time.perf_counter() - t0)
    best = min(times)
    return {
        "kernel": "sha256_leaves", "batch": batch,
        "compile_plus_first_s": round(compile_s, 3),
        "warm_best_s": round(best, 5),
        "hashes_per_s_best": round(batch / best, 1),
        "leaf0_matches_hashlib": got0 == ref0,
        "reps": reps,
    }


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="2048,128")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--skip-probe", action="store_true")
    args = ap.parse_args(argv)

    if not args.skip_probe:
        from plenum_tpu.tools.tpu_probe import probe_relay
        probe = probe_relay()
        if not probe["up"]:
            print(json.dumps({"error": "device relay down", "ts": probe["ts"],
                              "ports": {p: i["state"]
                                        for p, i in probe["ports"].items()}}))
            return 1

    import jax
    devs = jax.devices()
    header = {"ts": _now_iso(), "devices": [str(d) for d in devs],
              "platform": devs[0].platform}
    print(json.dumps(header), flush=True)

    results = []
    for b in [int(x) for x in args.batches.split(",") if x]:
        r = bench_ed25519(b, reps=args.reps)
        r["ts"] = _now_iso()
        print(json.dumps(r), flush=True)
        results.append(r)
    r = bench_sha256(reps=args.reps)
    r["ts"] = _now_iso()
    print(json.dumps(r), flush=True)
    results.append(r)

    errors = [r["error"] for r in results if "error" in r]
    summary = {"summary": True, **header, "errors": errors,
               "ed25519": {str(r["batch"]): r.get("sigs_per_s_best")
                           for r in results if r.get("kernel") == "ed25519_verify_v3"}}
    print(json.dumps(summary), flush=True)
    # rc mirrors correctness: a wrong verdict / failed negative control
    # must not look like a passed device run to log-scrapers
    return 2 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
