"""Unprofiled per-txn cost of the crypto/storage seams in a live pool.

tools/perf_budget (cProfile over the TCP pool) gives the SHAPE of the
per-transaction budget but inflates call-dense Python categories: its wall
timer also charges preemption (5 processes, 1 core) and its CPU timer pays
a syscall per call.  The categories that decide the Amdahl question —
ed25519, BLS, ledger hashing, state trie — all sit behind class-method
seams, so this tool times them EXACTLY, unprofiled: it wraps the methods
with perf_counter accumulators (~1 us per call against ~100 us+ calls,
<2% overhead), runs the real in-process 4-node pool (tools/local_pool:
full authN -> propagate -> 3PC+BLS -> execute pipeline), and reports
seconds-per-category, call counts, and the uninstrumented residual
(consensus bookkeeping + serialization + sim transport + node glue).

A reentrancy guard attributes nested calls to the OUTERMOST category
(e.g. the msgpack pack inside Ledger.commit_txns counts as ledger, not
serde), so category totals never double-count.

    python -m plenum_tpu.tools.micro_costs [--txns 300] [--nodes 4]
"""
from __future__ import annotations

import argparse
import json
import time


class _Accum:
    __slots__ = ("seconds", "calls")

    def __init__(self):
        self.seconds = 0.0
        self.calls = 0


class SeamTimer:
    """Wrap (cls, method) seams with accumulating timers, by category."""

    def __init__(self):
        self.accums: dict[str, _Accum] = {}
        self._originals: list[tuple[type, str, object]] = []
        self._active: list[str] = []      # category stack (reentrancy guard)

    def wrap(self, category: str, cls: type, method: str) -> None:
        import types
        orig = cls.__dict__.get(method)
        is_prop = isinstance(orig, property)
        target = orig.fget if is_prop else orig
        if not isinstance(target, types.FunctionType):
            return          # absent or staticmethod: skip
        acc = self.accums.setdefault(category, _Accum())
        timer = self

        def wrapper(*args, __orig=target, __acc=acc, **kwargs):
            if timer._active:              # nested: outer category owns it
                return __orig(*args, **kwargs)
            timer._active.append(category)
            t0 = time.perf_counter()
            try:
                return __orig(*args, **kwargs)
            finally:
                __acc.seconds += time.perf_counter() - t0
                __acc.calls += 1
                timer._active.pop()

        self._originals.append((cls, method, orig))
        setattr(cls, method,
                property(wrapper, orig.fset, orig.fdel) if is_prop
                else wrapper)

    def unwrap_all(self) -> None:
        for cls, method, orig in reversed(self._originals):
            setattr(cls, method, orig)
        self._originals.clear()


def install_seams(timer: SeamTimer) -> None:
    from plenum_tpu.crypto.bls import (BlsCryptoSigner, BlsCryptoVerifier,
                                       BlsSignKey)
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.ledger.ledger import Ledger
    from plenum_tpu.state.pruning_state import PruningState

    timer.wrap("ed25519", CpuEd25519Verifier, "verify_batch")
    for m in ("sign",):
        timer.wrap("bls", BlsCryptoSigner, m)
        timer.wrap("bls", BlsSignKey, m)
    for m in ("verify_sig", "verify_multi_sig", "create_multi_sig",
              "is_wellformed_sig", "verify_key_proof_of_possession"):
        timer.wrap("bls", BlsCryptoVerifier, m)
    for m in ("append", "append_batch", "append_txns_to_uncommitted",
              "commit_txns", "discard_txns", "uncommitted_root_hash",
              "merkle_info", "consistency_proof", "get_by_seq_no"):
        timer.wrap("ledger", Ledger, m)
    for m in ("set", "get", "remove", "commit", "revert_to_head",
              "head_hash", "committed_head_hash", "get_for_root",
              "generate_state_proof", "as_dict"):
        timer.wrap("state", PruningState, m)
    timer.wrap("ledger", Ledger, "uncommitted_root_hash")
    timer.wrap("ledger", Ledger, "root_hash")


def run(n_nodes: int = 4, n_txns: int = 300) -> dict:
    from plenum_tpu.tools.local_pool import run_load

    timer = SeamTimer()
    install_seams(timer)
    try:
        stats = run_load(n_nodes=n_nodes, n_txns=n_txns, backend="cpu")
    finally:
        timer.unwrap_all()

    txns = stats.get("txns_ordered") or 1
    wall_ms = 1000.0 * stats["seconds"] / txns
    cats = {
        k: {"ms_per_txn": round(a.seconds * 1000.0 / txns, 3),
            "calls_per_txn": round(a.calls / txns, 2),
            "us_per_call": round(a.seconds * 1e6 / a.calls, 1)
            if a.calls else None}
        for k, a in sorted(timer.accums.items(),
                           key=lambda kv: -kv[1].seconds)
    }
    measured = sum(v["ms_per_txn"] for v in cats.values())
    off = sum(cats.get(k, {"ms_per_txn": 0.0})["ms_per_txn"]
              for k in ("ed25519", "bls", "ledger"))
    return {
        "pool": stats,
        "txns": txns,
        "wall_ms_per_txn": round(wall_ms, 3),     # all nodes share 1 process
        "categories": cats,
        "measured_ms_per_txn": round(measured, 3),
        "residual_ms_per_txn": round(wall_ms - measured, 3),
        "offloadable_ms_per_txn": round(off, 3),  # ed25519+bls+ledger-merkle
        "offloadable_fraction_of_wall": round(off / wall_ms, 4) if wall_ms else 0,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--txns", type=int, default=300)
    args = ap.parse_args(argv)
    print(json.dumps(run(args.nodes, args.txns), indent=2))


if __name__ == "__main__":
    main()
