"""Client/catchup-side proof verification (no tree access needed).

Reference behavior: ledger/merkle_verifier.py — verify RFC-6962 inclusion
(audit) proofs and consistency proofs against advertised roots. Used by catchup
to check CatchupRep txn ranges (SURVEY.md §3.4) and by clients on REPLY.
"""
from __future__ import annotations

from typing import Sequence

from .tree_hasher import TreeHasher


class MerkleVerificationError(Exception):
    pass


class MerkleVerifier:
    def __init__(self, hasher: TreeHasher | None = None):
        self.hasher = hasher or TreeHasher()

    def calc_root_from_inclusion(self, leaf_data: bytes, m: int, n: int,
                                 path: Sequence[bytes]) -> bytes:
        """Recompute the size-n root from leaf m's data and its audit path
        (RFC 6962 §2.1.1 verification, bottom-up)."""
        if not (0 <= m < n):
            raise MerkleVerificationError(f"bad leaf index {m} for size {n}")
        h = self.hasher.hash_leaf(leaf_data)
        fn, sn = m, n - 1
        for p in path:
            if sn == 0:
                raise MerkleVerificationError("proof too long")
            if fn & 1 or fn == sn:
                h = self.hasher.hash_children(p, h)
                if not fn & 1:
                    while fn & 1 == 0 and fn != 0:
                        fn >>= 1
                        sn >>= 1
            else:
                h = self.hasher.hash_children(h, p)
            fn >>= 1
            sn >>= 1
        if sn != 0:
            raise MerkleVerificationError("proof too short")
        return h

    def verify_inclusion(self, leaf_data: bytes, m: int, n: int,
                         path: Sequence[bytes], root: bytes) -> bool:
        try:
            return self.calc_root_from_inclusion(leaf_data, m, n, path) == root
        except MerkleVerificationError:
            return False

    def verify_consistency(self, m: int, n: int, old_root: bytes,
                           new_root: bytes, proof: Sequence[bytes]) -> bool:
        """RFC 6962 §2.1.2 consistency-proof verification."""
        try:
            self._check_consistency(m, n, old_root, new_root, list(proof))
            return True
        except MerkleVerificationError:
            return False

    def _check_consistency(self, m: int, n: int, old_root: bytes,
                           new_root: bytes, proof: list[bytes]) -> None:
        if m > n:
            raise MerkleVerificationError("old size exceeds new size")
        if m == n:
            if old_root != new_root or proof:
                raise MerkleVerificationError("equal sizes but roots/proof differ")
            return
        if m == 0:
            raise MerkleVerificationError("consistency from empty tree undefined")
        # m is a power of two exactly when the old root is itself a node of
        # the new tree; then the proof does not repeat it.
        node, last = m - 1, n - 1
        while node & 1:
            node >>= 1
            last >>= 1
        p = iter(proof)
        try:
            new_hash = old_hash = next(p) if node else old_root
            while node:
                if node & 1:
                    nxt = next(p)
                    old_hash = self.hasher.hash_children(nxt, old_hash)
                    new_hash = self.hasher.hash_children(nxt, new_hash)
                elif node < last:
                    new_hash = self.hasher.hash_children(new_hash, next(p))
                node >>= 1
                last >>= 1
            while last:
                new_hash = self.hasher.hash_children(new_hash, next(p))
                last >>= 1
        except StopIteration:
            raise MerkleVerificationError("proof too short")
        if any(True for _ in p):
            raise MerkleVerificationError("proof too long")
        if old_hash != old_root:
            raise MerkleVerificationError("old root mismatch")
        if new_hash != new_root:
            raise MerkleVerificationError("new root mismatch")
