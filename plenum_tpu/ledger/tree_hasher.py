"""Merkle tree hashing seam — the first of the three crypto provider seams
(SURVEY.md §7 stage 2).

Reference behavior: ledger/tree_hasher.py:4 — RFC-6962 domain separation:
    leaf hash     = SHA256(0x00 || data)
    interior hash = SHA256(0x01 || left || right)
Two backends: `cpu` (hashlib, scalar) and `jax` (batched device kernels from
plenum_tpu.ops.sha256). The batch API is the contract — `hash_leaves` /
`hash_children_batch` take whole vectors so the device backend issues one
dispatch per call, never one per hash.
"""
from __future__ import annotations

import hashlib
from typing import Sequence


class TreeHasher:
    """CPU backend (hashlib)."""

    def hash_empty(self) -> bytes:
        return hashlib.sha256(b"").digest()

    def hash_leaf(self, data: bytes) -> bytes:
        return hashlib.sha256(b"\x00" + data).digest()

    def hash_children(self, left: bytes, right: bytes) -> bytes:
        return hashlib.sha256(b"\x01" + left + right).digest()

    # batch API (scalar loop on CPU; one device call on JAX backend)
    def hash_leaves(self, leaves: Sequence[bytes]) -> list[bytes]:
        return [self.hash_leaf(l) for l in leaves]

    def hash_children_batch(self, pairs: Sequence[tuple[bytes, bytes]]) -> list[bytes]:
        return [self.hash_children(l, r) for l, r in pairs]


def fused_wave_levels(new_hashes, bounds, offs, counts, note_shape=None):
    """One fused device program for an append wave's interior levels
    (ops/sha256.merkle_wave) — the shared implementation behind every
    hasher's `hash_wave_levels`.

    new_hashes: the wave's new level-0 digests (32-byte each).
    bounds[l]:  the old left-boundary digest level l pairs with, or None.
    offs[l]:    1 when level l uses its boundary.
    counts[l]:  how many parents level l really forms (the valid prefix).

    Returns per-level lists of parent digests for the first
    min(len(counts), log2(bucket)) levels; the CALLER finishes any deeper
    (single-node spine) levels on host. note_shape, when given, is called
    with the compiled-shape key so the pipeline's recompile guard can
    count it.
    """
    import jax.numpy as jnp
    import numpy as np

    from plenum_tpu.ops.sha256 import (bytes_to_digests, digests_to_bytes,
                                       merkle_wave)
    n = len(new_hashes)
    bucket = _pow2_at_least(max(2, n))
    depth = bucket.bit_length() - 1          # log2(bucket) program levels
    if note_shape is not None:
        note_shape(("merkle", bucket))
    new0 = np.zeros((bucket, 8), dtype=np.uint32)
    new0[:n] = bytes_to_digests(list(new_hashes))
    bnd = np.zeros((depth, 8), dtype=np.uint32)
    off = np.zeros(depth, dtype=np.int32)
    levels = min(depth, len(counts))
    for l in range(levels):
        if offs[l] and bounds[l] is not None:
            bnd[l] = bytes_to_digests([bounds[l]])[0]
            off[l] = 1
    outs = merkle_wave(jnp.asarray(new0), jnp.asarray(bnd),
                       jnp.asarray(off))
    result = []
    for l in range(levels):
        want = counts[l]
        result.append(digests_to_bytes(np.asarray(outs[l])[:want])
                      if want else [])
    return result


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class JaxTreeHasher(TreeHasher):
    """Device backend: batched SHA-256 (plenum_tpu/ops/sha256.py).

    Scalar calls fall back to hashlib (correctness identical); the wins come
    from the batch entry points used by Ledger.extend_batch and the catchup
    verifier.
    """

    def __init__(self, min_batch: int = 1024, fuse_min: int = None):
        # Below min_batch the dispatch overhead beats the VPU win — hashlib
        # does 1024 sha256 in under a millisecond while one tunneled-TPU
        # dispatch costs tens of milliseconds, so only catchup-scale batch
        # verification and bulk appends go to the device.
        self._min_batch = min_batch
        # fused append waves pay ONE dispatch for all interior levels, so
        # they amortize earlier than the flat batch threshold
        self._fuse_min = min_batch if fuse_min is None else fuse_min

    def hash_wave_levels(self, new_hashes, bounds, offs, counts):
        """Fused interior levels for one append wave, or None to decline
        (small waves stay on the hashlib per-level path)."""
        if len(new_hashes) < self._fuse_min:
            return None
        return fused_wave_levels(new_hashes, bounds, offs, counts)

    def hash_leaves(self, leaves: Sequence[bytes]) -> list[bytes]:
        if len(leaves) < self._min_batch:
            return [self.hash_leaf(l) for l in leaves]
        from plenum_tpu.ops.sha256 import sha256_batch
        return sha256_batch(list(leaves), prefix=b"\x00")

    def hash_children_batch(self, pairs: Sequence[tuple[bytes, bytes]]) -> list[bytes]:
        if len(pairs) < self._min_batch:
            return [self.hash_children(l, r) for l, r in pairs]
        import jax.numpy as jnp
        from plenum_tpu.ops.sha256 import (hash_interior, bytes_to_digests,
                                           digests_to_bytes)
        n = len(pairs)
        n_pad = 1
        while n_pad < n:
            n_pad *= 2
        lefts = bytes_to_digests([p[0] for p in pairs] + [b"\x00" * 32] * (n_pad - n))
        rights = bytes_to_digests([p[1] for p in pairs] + [b"\x00" * 32] * (n_pad - n))
        out = digests_to_bytes(hash_interior(jnp.asarray(lefts), jnp.asarray(rights)))
        return out[:n]


def make_tree_hasher(backend: str) -> TreeHasher:
    if backend in ("jax", "jax-sharded"):
        return JaxTreeHasher()
    return TreeHasher()
