"""Merkle tree hashing seam — the first of the three crypto provider seams
(SURVEY.md §7 stage 2).

Reference behavior: ledger/tree_hasher.py:4 — RFC-6962 domain separation:
    leaf hash     = SHA256(0x00 || data)
    interior hash = SHA256(0x01 || left || right)
Two backends: `cpu` (hashlib, scalar) and `jax` (batched device kernels from
plenum_tpu.ops.sha256). The batch API is the contract — `hash_leaves` /
`hash_children_batch` take whole vectors so the device backend issues one
dispatch per call, never one per hash.
"""
from __future__ import annotations

import hashlib
from typing import Sequence


class TreeHasher:
    """CPU backend (hashlib)."""

    def hash_empty(self) -> bytes:
        return hashlib.sha256(b"").digest()

    def hash_leaf(self, data: bytes) -> bytes:
        return hashlib.sha256(b"\x00" + data).digest()

    def hash_children(self, left: bytes, right: bytes) -> bytes:
        return hashlib.sha256(b"\x01" + left + right).digest()

    # batch API (scalar loop on CPU; one device call on JAX backend)
    def hash_leaves(self, leaves: Sequence[bytes]) -> list[bytes]:
        return [self.hash_leaf(l) for l in leaves]

    def hash_children_batch(self, pairs: Sequence[tuple[bytes, bytes]]) -> list[bytes]:
        return [self.hash_children(l, r) for l, r in pairs]


class JaxTreeHasher(TreeHasher):
    """Device backend: batched SHA-256 (plenum_tpu/ops/sha256.py).

    Scalar calls fall back to hashlib (correctness identical); the wins come
    from the batch entry points used by Ledger.extend_batch and the catchup
    verifier.
    """

    def __init__(self, min_batch: int = 1024):
        # Below min_batch the dispatch overhead beats the VPU win — hashlib
        # does 1024 sha256 in under a millisecond while one tunneled-TPU
        # dispatch costs tens of milliseconds, so only catchup-scale batch
        # verification and bulk appends go to the device.
        self._min_batch = min_batch

    def hash_leaves(self, leaves: Sequence[bytes]) -> list[bytes]:
        if len(leaves) < self._min_batch:
            return [self.hash_leaf(l) for l in leaves]
        from plenum_tpu.ops.sha256 import sha256_batch
        return sha256_batch(list(leaves), prefix=b"\x00")

    def hash_children_batch(self, pairs: Sequence[tuple[bytes, bytes]]) -> list[bytes]:
        if len(pairs) < self._min_batch:
            return [self.hash_children(l, r) for l, r in pairs]
        import jax.numpy as jnp
        from plenum_tpu.ops.sha256 import (hash_interior, bytes_to_digests,
                                           digests_to_bytes)
        n = len(pairs)
        n_pad = 1
        while n_pad < n:
            n_pad *= 2
        lefts = bytes_to_digests([p[0] for p in pairs] + [b"\x00" * 32] * (n_pad - n))
        rights = bytes_to_digests([p[1] for p in pairs] + [b"\x00" * 32] * (n_pad - n))
        out = digests_to_bytes(hash_interior(jnp.asarray(lefts), jnp.asarray(rights)))
        return out[:n]


def make_tree_hasher(backend: str) -> TreeHasher:
    if backend in ("jax", "jax-sharded"):
        return JaxTreeHasher()
    return TreeHasher()
