"""Durable store of Merkle leaf and interior-node hashes.

Reference behavior: ledger/hash_stores/hash_store.py:7 — leaf hashes by
sequence number plus interior hashes, enabling tree recovery on restart and
O(log n) proof generation without rehashing the log.

Layout here: leaves keyed `l<idx>` (0-based), interior nodes keyed by
(level, index) where node (k, i) is the root of leaves [i*2^k, (i+1)*2^k) —
only complete subtrees are stored, which is exactly the set of hashes the
append path computes anyway.
"""
from __future__ import annotations

from typing import Optional

from plenum_tpu.storage.kv_store import KeyValueStorage
from plenum_tpu.storage.kv_memory import KvMemory


class HashStore:
    def __init__(self, kv: Optional[KeyValueStorage] = None):
        self._kv = kv if kv is not None else KvMemory()

    @property
    def kv(self) -> KeyValueStorage:
        """Backing store — exposed so the commit path can group this
        store's rows into the per-3PC-batch atomic write."""
        return self._kv

    @staticmethod
    def _leaf_key(idx: int) -> bytes:
        return b"l" + idx.to_bytes(8, "big")

    @staticmethod
    def _node_key(level: int, idx: int) -> bytes:
        return b"n" + level.to_bytes(1, "big") + idx.to_bytes(8, "big")

    def put_leaf(self, idx: int, digest: bytes) -> None:
        self._kv.put(self._leaf_key(idx), digest)

    def get_leaf(self, idx: int) -> bytes:
        return self._kv.get(self._leaf_key(idx))

    def put_node(self, level: int, idx: int, digest: bytes) -> None:
        self._kv.put(self._node_key(level, idx), digest)

    def get_node(self, level: int, idx: int) -> bytes:
        return self._kv.get(self._node_key(level, idx))

    def try_get_node(self, level: int, idx: int) -> Optional[bytes]:
        return self._kv.try_get(self._node_key(level, idx))

    @property
    def leaf_count(self) -> int:
        # binary search for the first missing leaf
        lo, hi = 0, 1
        while self._kv.has_key(self._leaf_key(hi - 1)):
            lo, hi = hi, hi * 2
        # invariant: leaf lo-1 exists (or lo==0), leaf hi-1 doesn't
        while lo < hi:
            mid = (lo + hi) // 2
            if self._kv.has_key(self._leaf_key(mid)):
                lo = mid + 1
            else:
                hi = mid
        return lo

    def reset(self) -> None:
        for k in list(self._kv.iterator(include_value=False)):
            self._kv.remove(k)

    def close(self) -> None:
        self._kv.close()


class OverlayHashStore(HashStore):
    """Copy-on-write view over a base store: reads fall through, writes stay in
    memory. Backs the uncommitted shadow tree (3PC staging) so computing an
    uncommitted root never touches durable storage."""

    def __init__(self, base: HashStore):
        super().__init__(KvMemory())
        self._base = base

    def get_leaf(self, idx: int) -> bytes:
        v = self._kv.try_get(self._leaf_key(idx))
        return v if v is not None else self._base.get_leaf(idx)

    def try_get_node(self, level: int, idx: int) -> Optional[bytes]:
        v = self._kv.try_get(self._node_key(level, idx))
        return v if v is not None else self._base.try_get_node(level, idx)

    def get_node(self, level: int, idx: int) -> bytes:
        v = self.try_get_node(level, idx)
        if v is None:
            raise KeyError((level, idx))
        return v

    @property
    def leaf_count(self) -> int:
        raise NotImplementedError("overlay store has no independent leaf count")
