"""Append-only transaction ledger: KV txn log + compact Merkle tree.

Reference behavior: ledger/ledger.py:17 — txns keyed by 1-based seq_no in a KV
log, every append updates the Merkle tree and returns merkle info (root + audit
path); supports an uncommitted staging area (appendTxns → commitTxns /
discardTxns) used by 3PC dynamic validation, genesis loading, and recovery from
the hash store with txn-log replay as fallback (ledger.py:70-113).

TPU angle: `append_txns` stages and `commit_txns` extends the tree with ALL
the batch's leaves through the hasher's batch API — with the jax backend this
is the one-dispatch Merkle append of the north star.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

from plenum_tpu.common.serialization import pack, unpack
from plenum_tpu.storage.kv_store import KeyValueStorage
from plenum_tpu.storage.kv_memory import KvMemory

from .compact_merkle_tree import CompactMerkleTree
from .hash_store import HashStore
from .tree_hasher import TreeHasher


def txn_to_leaf(txn: dict) -> bytes:
    return pack(txn)


class Ledger:
    def __init__(self,
                 tree: Optional[CompactMerkleTree] = None,
                 txn_log: Optional[KeyValueStorage] = None,
                 genesis_txns: Sequence[dict] = ()):
        self.tree = tree or CompactMerkleTree()
        self.hasher = self.tree.hasher
        self._log = txn_log if txn_log is not None else KvMemory()
        self.seq_no = 0                      # last committed seq_no (1-based)
        self._uncommitted: list[dict] = []   # staged txns
        self._uncommitted_tree: Optional[CompactMerkleTree] = None
        # txns staged with defer_hash=True: in _uncommitted (and the
        # shadow's root once hashed) but NOT yet extended into the
        # shadow tree — the commit wave hashes their leaves in one
        # fused dispatch (uncommitted_root_staged); the host path folds
        # them in lazily, so both paths stay byte-identical
        self._shadow_pending: list[dict] = []
        self.recover()
        if self.size == 0 and genesis_txns:
            for txn in genesis_txns:
                self.append(txn)

    # --- recovery (ref ledger.py:70-113) ----------------------------------

    def recover(self) -> None:
        log_size = self._log.size
        self.seq_no = log_size
        # First sync the tree with its own hash store: a fresh CompactMerkleTree
        # handed a persisted store must pick up the stored leaves (callers need
        # not remember CompactMerkleTree.recover()).
        hs_count = self.tree.hash_store.leaf_count
        if self.tree.tree_size < hs_count:
            self.tree = CompactMerkleTree.recover(self.hasher, self.tree.hash_store)
        if self.tree.tree_size == log_size:
            return
        if self.tree.tree_size == self.tree.hash_store.leaf_count and \
                self.tree.tree_size < log_size:
            # hash store lags the log: replay the missing tail
            missing = [self.get_by_seq_no(i)
                       for i in range(self.tree.tree_size + 1, log_size + 1)]
            self.tree.extend_batch([txn_to_leaf(t) for t in missing])
            return
        if self.tree.tree_size > log_size:
            # hash store ahead of (or inconsistent with) the log: rebuild
            self.tree.hash_store.reset()
            self.tree = CompactMerkleTree(self.hasher, self.tree.hash_store)
            all_txns = [self.get_by_seq_no(i) for i in range(1, log_size + 1)]
            self.tree.extend_batch([txn_to_leaf(t) for t in all_txns])

    # --- committed appends ------------------------------------------------

    def append(self, txn: dict) -> dict:
        """Append one committed txn; returns merkle info for the REPLY."""
        return self.append_batch([txn])[0]

    def append_batch(self, txns: Sequence[dict]) -> list[dict]:
        leaves = [txn_to_leaf(t) for t in txns]
        start = self.seq_no
        # one atomic KV batch for the txn-log rows and one for the Merkle
        # hash-store rows (leaves + interior nodes), instead of a flushed
        # append per row — with a durable backend this is the difference
        # between 2 fsync-ish flushes and ~3n per committed batch
        self._log.do_ops_in_batch(
            [("put", start + 1 + i, leaf) for i, leaf in enumerate(leaves)])
        with self.tree.hash_store.kv.write_batch():
            self.tree.extend_batch(leaves)
        self.seq_no += len(txns)
        return [self.merkle_info(start + 1 + i) for i in range(len(txns))]

    @property
    def txn_log(self) -> KeyValueStorage:
        """Backing txn-log store — exposed for the commit path's group
        flush (DatabaseManager.group_commit)."""
        return self._log

    # --- uncommitted staging (ref appendTxns/commitTxns/discardTxns) ------

    def append_txns_to_uncommitted(self, txns: Sequence[dict],
                                   defer_hash: bool = False):
        """Stage txns; returns (uncommitted_root, uncommitted_size).
        With defer_hash=True the leaf hashing is left for the commit
        wave (`uncommitted_root_staged`) — no root is computed here and
        None is returned in its place; reading `uncommitted_root_hash`
        before the wave drains folds the pending leaves in on host, so
        the deferral can never be observed as a different root."""
        if defer_hash:
            self._uncommitted.extend(txns)
            if self._uncommitted_tree is not None:
                self._shadow_pending.extend(txns)
            return None, self.uncommitted_size
        if self._uncommitted_tree is not None:
            self._fold_shadow_pending()
            # shadow exists: extend incrementally instead of rebuilding
            self._uncommitted_tree.extend_batch([txn_to_leaf(t) for t in txns])
        self._uncommitted.extend(txns)
        return self.uncommitted_root_hash, self.uncommitted_size

    def _fold_shadow_pending(self) -> None:
        """Host-side catch-up for leaves staged with defer_hash=True:
        extend the shadow with anything the commit wave has not hashed
        yet (the wave's degrade-to-host path, and any host read that
        races a staged-but-undrained wave)."""
        if self._shadow_pending and self._uncommitted_tree is not None:
            pending, self._shadow_pending = self._shadow_pending, []
            self._uncommitted_tree.extend_batch(
                [txn_to_leaf(t) for t in pending])

    def commit_txns(self, count: int) -> tuple[list[dict], list[dict]]:
        """Commit the first `count` staged txns; returns (txns, merkle_infos)."""
        if count > len(self._uncommitted):
            raise ValueError(f"commit {count} > {len(self._uncommitted)} staged")
        txns = self._uncommitted[:count]
        self._uncommitted = self._uncommitted[count:]
        self._uncommitted_tree = None
        self._shadow_pending = []
        infos = self.append_batch(txns)
        return txns, infos

    def discard_txns(self, count: int) -> None:
        """Drop the LAST `count` staged txns (revert on 3PC reject)."""
        if count > len(self._uncommitted):
            raise ValueError(f"discard {count} > {len(self._uncommitted)} staged")
        if count:
            self._uncommitted = self._uncommitted[:-count]
            self._uncommitted_tree = None
            self._shadow_pending = []

    def reset_uncommitted(self) -> None:
        self._uncommitted = []
        self._uncommitted_tree = None
        self._shadow_pending = []

    @property
    def uncommitted_size(self) -> int:
        """TOTAL size including staged txns (committed size + staged count)."""
        return self.seq_no + len(self._uncommitted)

    @property
    def uncommitted_txns(self) -> list[dict]:
        return list(self._uncommitted)

    @property
    def uncommitted_root_hash(self) -> bytes:
        if not self._uncommitted:
            return self.root_hash
        if self._uncommitted_tree is None:
            shadow = self.tree.fork()
            shadow.extend_batch([txn_to_leaf(t) for t in self._uncommitted])
            self._uncommitted_tree = shadow
            self._shadow_pending = []
        else:
            self._fold_shadow_pending()
        return self._uncommitted_tree.root_hash

    def uncommitted_root_staged(self):
        """Commit-wave family (parallel/commit_wave.py): the staged twin
        of `uncommitted_root_hash` for leaves staged with
        defer_hash=True. Yields ONE ("hlev", "sha256", <leaf preimages>)
        cmt job — every pending txn's domain-prefixed leaf bytes —
        receives the leaf digests back, extends the shadow through the
        precomputed-hash entry point (`_extend_hashes`, whose interior
        sweep rides the fused merkle kernel when the tree's hasher is
        device-backed), and returns the uncommitted root."""
        if not self._uncommitted:
            return self.root_hash
        shadow = self._uncommitted_tree
        pending = self._shadow_pending if shadow is not None \
            else list(self._uncommitted)
        if shadow is None:
            shadow = self.tree.fork()
        if pending:
            res = yield [("hlev", "sha256",
                          tuple(b"\x00" + txn_to_leaf(t) for t in pending))]
            shadow._extend_hashes(list(res[0]))
        self._uncommitted_tree = shadow
        self._shadow_pending = []
        return shadow.root_hash

    # --- reads ------------------------------------------------------------

    @property
    def size(self) -> int:
        return self.seq_no

    @property
    def root_hash(self) -> bytes:
        return self.tree.root_hash

    def get_by_seq_no(self, seq_no: int) -> dict:
        if not (1 <= seq_no <= self.seq_no):
            raise KeyError(seq_no)
        return unpack(self._log.get(seq_no))

    def get_all_txns(self, start: int = 1, end: Optional[int] = None):
        end = self.seq_no if end is None else min(end, self.seq_no)
        for i in range(start, end + 1):
            yield i, self.get_by_seq_no(i)

    def merkle_info(self, seq_no: int) -> dict:
        """Root + audit path for the txn at seq_no, as wire-friendly hex."""
        path = self.tree.inclusion_proof(seq_no - 1)
        return {"seqNo": seq_no,
                "rootHash": self.root_hash.hex(),
                "auditPath": [h.hex() for h in path],
                "treeSize": self.tree.tree_size}

    def consistency_proof(self, old_size: int, new_size: Optional[int] = None) -> list[str]:
        return [h.hex() for h in self.tree.consistency_proof(
            old_size, new_size if new_size is not None else self.tree.tree_size)]

    def close(self) -> None:
        self._log.close()
        self.tree.hash_store.close()
