"""Append-only Merkle tree in the RFC-6962 (Certificate Transparency) shape.

Reference behavior: ledger/compact_merkle_tree.py:13 + merkle_verifier.py —
incremental appends keeping O(log n) frontier peaks, inclusion (audit) proofs,
and consistency proofs between two tree sizes. Tree recovery from the hash
store on restart (ref ledger/ledger.py:70-113).

The tree hash of leaves D[0:n] follows the spec recursion: split at the largest
power of two k < n, MTH(D) = H(0x01 || MTH(D[0:k]) || MTH(D[k:n])); the peaks
list is that recursion's right spine.

`extend_batch` is the TPU entry point: leaf hashes for a whole 3PC batch are
computed in one device call, and each interior level's new nodes in one more
(SURVEY.md §2.1 "vectorized SHA-256 Merkle appends").
"""
from __future__ import annotations

from typing import Optional, Sequence

from .hash_store import HashStore
from .tree_hasher import TreeHasher


def _largest_pow2_below(n: int) -> int:
    assert n >= 2
    k = 1
    while k * 2 < n:
        k *= 2
    return k


class CompactMerkleTree:
    def __init__(self, hasher: Optional[TreeHasher] = None,
                 hash_store: Optional[HashStore] = None):
        self.hasher = hasher or TreeHasher()
        self.hash_store = hash_store or HashStore()
        self.tree_size = 0
        # peaks[i] = root of a complete subtree; sizes strictly decreasing
        # powers of two summing to tree_size, leftmost first.
        self._peaks: list[bytes] = []

    # --- appends ----------------------------------------------------------

    def append(self, leaf: bytes) -> None:
        self.extend_batch([leaf])

    def append_hash(self, leaf_hash: bytes) -> None:
        self._extend_hashes([leaf_hash])

    def extend_batch(self, leaves: Sequence[bytes]) -> None:
        """Append many leaves; leaf hashing is one batched hasher call."""
        if not leaves:
            return
        self._extend_hashes(self.hasher.hash_leaves(list(leaves)))

    def _extend_hashes(self, leaf_hashes: list[bytes]) -> None:
        store = self.hash_store
        base = self.tree_size
        for i, h in enumerate(leaf_hashes):
            store.put_leaf(base + i, h)
        # Level-by-level: nodes of level k+1 whose children (level k) are now
        # all present. One batched hash call per level — the device path.
        level = 0
        level_start = base          # first index at this level that is new
        level_count = base + len(leaf_hashes)   # total nodes at this level
        get = self._level_hash
        new_at_level: dict[int, bytes] = {i: h for i, h in
                                          zip(range(base, level_count), leaf_hashes)}
        # Fused wave (MTU-style): a hasher advertising hash_wave_levels
        # computes ALL wide interior levels in ONE device program — the
        # per-level host hops below then only run for the narrow top-of-
        # tree spine (<=1 new node per level) the fused program leaves.
        fused = getattr(self.hasher, "hash_wave_levels", None)
        if fused is not None and len(leaf_hashes) >= 2:
            state = self._extend_fused(fused, level, level_start,
                                       level_count, new_at_level)
            if state is not None:
                level, level_start, level_count, new_at_level = state
        while level_count >= 2:
            parent_first = level_start // 2
            parent_count = level_count // 2
            pairs = []
            idxs = []
            for pi in range(parent_first, parent_count):
                if self.hash_store.try_get_node(level + 1, pi) is not None:
                    continue
                l = new_at_level.get(2 * pi) or get(level, 2 * pi)
                r = new_at_level.get(2 * pi + 1) or get(level, 2 * pi + 1)
                pairs.append((l, r))
                idxs.append(pi)
            parents = self.hasher.hash_children_batch(pairs) if pairs else []
            new_parent: dict[int, bytes] = {}
            for pi, h in zip(idxs, parents):
                store.put_node(level + 1, pi, h)
                new_parent[pi] = h
            level += 1
            level_start = parent_first
            level_count = parent_count
            new_at_level = new_parent
        self.tree_size += len(leaf_hashes)
        self._peaks = self._compute_peaks(self.tree_size)

    def _extend_fused(self, fused, level, level_start, level_count,
                      new_at_level):
        """Run the wide levels of one append wave through the hasher's
        fused device program; -> the per-level loop's continuation state,
        or None when the fused path declines (small wave / missing
        boundary / already-stored parent) and the loop runs from scratch.

        The metadata mirrors the loop exactly: a wave's new nodes are a
        contiguous suffix [level_start, level_count) per level, so at most
        one OLD node (the left boundary at level_start-1, present iff
        level_start is odd) joins each level's pairing, and the count of
        parents formed is (level_count//2) - (level_start//2)."""
        store = self.hash_store
        new_hashes = [new_at_level[i]
                      for i in range(level_start, level_count)]
        bounds, offs, counts = [], [], []
        starts = []                # level_start per fused level
        ls, cnt, m = level_start, level_count, len(new_hashes)
        while m >= 2 and cnt >= 2:
            parent_first = ls // 2
            parent_count = cnt // 2
            p = parent_count - parent_first
            if p <= 0:
                break
            if store.try_get_node(level + len(counts) + 1,
                                  parent_first) is not None:
                return None        # overlap with stored nodes: slow path
            off = ls & 1
            bound = None
            if off:
                try:
                    bound = self._level_hash(level + len(counts), ls - 1)
                except KeyError:
                    return None    # boundary missing: slow path
            starts.append(parent_first)
            bounds.append(bound)
            offs.append(off)
            counts.append(p)
            ls, cnt, m = parent_first, parent_count, p
        if not counts:
            return None
        got = fused(new_hashes, bounds, offs, counts)
        if got is None:
            return None            # hasher declined (below its threshold)
        out_level = level
        new_parent: dict[int, bytes] = new_at_level
        ls2, cnt2 = level_start, level_count
        for l, parents in enumerate(got):
            new_parent = {}
            for j, h in enumerate(parents):
                store.put_node(out_level + 1, starts[l] + j, h)
                new_parent[starts[l] + j] = h
            out_level += 1
            ls2, cnt2 = starts[l], cnt2 // 2
        return out_level, ls2, cnt2, new_parent

    def _level_hash(self, level: int, idx: int) -> bytes:
        if level == 0:
            return self.hash_store.get_leaf(idx)
        h = self.hash_store.try_get_node(level, idx)
        if h is None:
            raise KeyError((level, idx))
        return h

    def _range_root(self, lo: int, hi: int) -> bytes:
        """MTH of leaves [lo, hi): uses stored complete nodes, recursing on the
        (right-edge) incomplete ranges."""
        n = hi - lo
        assert n >= 1
        if n == 1:
            return self.hash_store.get_leaf(lo)
        # complete aligned subtree?
        if n & (n - 1) == 0 and lo % n == 0:
            level = n.bit_length() - 1
            h = self.hash_store.try_get_node(level, lo >> level)
            if h is not None:
                return h
        k = _largest_pow2_below(n)
        return self.hasher.hash_children(self._range_root(lo, lo + k),
                                         self._range_root(lo + k, hi))

    def _compute_peaks(self, size: int) -> list[bytes]:
        peaks = []
        lo = 0
        while size > 0:
            p = 1 << (size.bit_length() - 1)
            peaks.append(self._range_root(lo, lo + p))
            lo += p
            size -= p
        return peaks

    # --- roots and proofs -------------------------------------------------

    @property
    def root_hash(self) -> bytes:
        if self.tree_size == 0:
            return self.hasher.hash_empty()
        root = self._peaks[-1]
        for peak in reversed(self._peaks[:-1]):
            root = self.hasher.hash_children(peak, root)
        return root

    def merkle_tree_hash(self, lo: int, hi: int) -> bytes:
        if lo == hi == 0:
            return self.hasher.hash_empty()
        return self._range_root(lo, hi)

    def inclusion_proof(self, m: int, n: Optional[int] = None) -> list[bytes]:
        """Audit path for leaf index m (0-based) in the size-n tree
        (RFC 6962 §2.1.1 PATH(m, D[n]))."""
        n = self.tree_size if n is None else n
        if not (0 <= m < n <= self.tree_size):
            raise ValueError(f"leaf {m} out of range for size {n} "
                             f"(tree has {self.tree_size})")
        return self._path(m, 0, n)

    def _path(self, m: int, lo: int, hi: int) -> list[bytes]:
        n = hi - lo
        if n == 1:
            return []
        k = _largest_pow2_below(n)
        if m - lo < k:
            return self._path(m, lo, lo + k) + [self._range_root(lo + k, hi)]
        return self._path(m, lo + k, hi) + [self._range_root(lo, lo + k)]

    def consistency_proof(self, m: int, n: Optional[int] = None) -> list[bytes]:
        """PROOF(m, D[n]) that the size-m tree is a prefix of the size-n tree
        (RFC 6962 §2.1.2)."""
        n = self.tree_size if n is None else n
        if not (0 < m <= n <= self.tree_size):
            raise ValueError(f"bad consistency range {m}..{n} "
                             f"(tree has {self.tree_size})")
        if m == n:
            return []
        return self._subproof(m, 0, n, True)

    def _subproof(self, m: int, lo: int, hi: int, b: bool) -> list[bytes]:
        n = hi - lo
        if m == n:
            return [] if b else [self._range_root(lo, hi)]
        k = _largest_pow2_below(n)
        if m <= k:
            return self._subproof(m, lo, lo + k, b) + [self._range_root(lo + k, hi)]
        return (self._subproof(m - k, lo + k, hi, False)
                + [self._range_root(lo, lo + k)])

    def fork(self) -> "CompactMerkleTree":
        """Copy-on-write fork: shares committed hashes, stages new ones in
        memory. The uncommitted-root path of 3PC batching."""
        from .hash_store import OverlayHashStore
        t = CompactMerkleTree(self.hasher, OverlayHashStore(self.hash_store))
        t.tree_size = self.tree_size
        t._peaks = list(self._peaks)
        return t

    # --- recovery (ref ledger.py:70-113) ----------------------------------

    @classmethod
    def recover(cls, hasher: TreeHasher, hash_store: HashStore) -> "CompactMerkleTree":
        tree = cls(hasher, hash_store)
        size = hash_store.leaf_count
        tree.tree_size = size
        tree._peaks = tree._compute_peaks(size) if size else []
        return tree
