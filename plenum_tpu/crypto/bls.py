"""BLS multi-signatures over BN254.

Reference behavior: crypto/bls/bls_crypto.py (BlsCryptoSigner/BlsCryptoVerifier
ABCs) + crypto/bls/indy_crypto/bls_crypto_indy_crypto.py (Ursa impl: sign :68,
verify :79, verify_multi_sig :94, aggregate MultiSignature.new :101, PoP :107).
Scheme: signatures in G1, verkeys in G2; aggregation is plain point addition,
multi-sig verification is a 2-pairing product check. Proof-of-possession binds
a verkey to its secret key under a separate hash domain, defeating rogue-key
attacks exactly as the reference's PoP does.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

from plenum_tpu.utils.base58 import b58decode, b58encode

from . import bn254 as c

_MSG_DOMAIN = b"plenum_tpu/bls/msg/v1"
_POP_DOMAIN = b"plenum_tpu/bls/pop/v1"


# --- point serialization (uncompressed, infinity-flagged) --------------------

def g1_to_bytes(pt: c.G1Point) -> bytes:
    if pt is None:
        return b"\x00" * 64
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def g1_from_bytes(data: bytes) -> c.G1Point:
    if len(data) != 64:
        raise ValueError("G1 point must be 64 bytes")
    if data == b"\x00" * 64:
        return None
    pt = (int.from_bytes(data[:32], "big"), int.from_bytes(data[32:], "big"))
    if not c.g1_is_on_curve(pt):
        raise ValueError("G1 point not on curve")
    return pt


def g2_to_bytes(pt: c.G2Point) -> bytes:
    if pt is None:
        return b"\x00" * 128
    (x0, x1), (y0, y1) = pt
    return b"".join(v.to_bytes(32, "big") for v in (x0, x1, y0, y1))


def g2_from_bytes(data: bytes) -> c.G2Point:
    if len(data) != 128:
        raise ValueError("G2 point must be 128 bytes")
    if data == b"\x00" * 128:
        return None
    vals = [int.from_bytes(data[i:i + 32], "big") for i in range(0, 128, 32)]
    pt = ((vals[0], vals[1]), (vals[2], vals[3]))
    if not c.g2_is_on_curve(pt):
        raise ValueError("G2 point not on curve")
    return pt


# --- keys and signatures -----------------------------------------------------

class BlsSignKey:
    def __init__(self, seed: Optional[bytes] = None):
        seed = seed if seed is not None else os.urandom(32)
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self.seed = seed
        self.sk = (int.from_bytes(seed, "big") % (c.R - 1)) + 1
        self._pk = c.g2_mul(c.G2_GEN, self.sk)

    @property
    def verkey(self) -> str:
        return b58encode(g2_to_bytes(self._pk))

    def sign(self, message: bytes) -> str:
        sig = c.g1_mul(c.hash_to_g1(message, _MSG_DOMAIN), self.sk)
        return b58encode(g1_to_bytes(sig))

    def generate_pop(self) -> str:
        """Proof of possession: sign the verkey bytes under the PoP domain."""
        h = c.hash_to_g1(g2_to_bytes(self._pk), _POP_DOMAIN)
        return b58encode(g1_to_bytes(c.g1_mul(h, self.sk)))


def _decode_sig(signature: str) -> c.G1Point:
    return g1_from_bytes(b58decode(signature))


def _decode_vk(verkey: str) -> c.G2Point:
    pt = g2_from_bytes(b58decode(verkey))
    if pt is None or not c.g2_in_subgroup(pt):
        raise ValueError("verkey not in G2 subgroup")
    return pt


def verify(signature: str, message: bytes, verkey: str) -> bool:
    """e(σ, G2) == e(H(m), pk)  ⇔  e(σ, G2)·e(-H(m), pk)... — done as one
    2-pair product check with a shared final exponentiation."""
    try:
        sig = _decode_sig(signature)
        pk = _decode_vk(verkey)
    except (ValueError, KeyError):
        return False
    h = c.hash_to_g1(message, _MSG_DOMAIN)
    return c.pairing_check([(c.G2_GEN, c.g1_neg(sig)), (pk, h)])


def verify_pop(pop: str, verkey: str) -> bool:
    try:
        sig = _decode_sig(pop)
        pk = _decode_vk(verkey)
    except (ValueError, KeyError):
        return False
    h = c.hash_to_g1(b58decode(verkey), _POP_DOMAIN)
    return c.pairing_check([(c.G2_GEN, c.g1_neg(sig)), (pk, h)])


def aggregate_sigs(signatures: Sequence[str]) -> str:
    agg: c.G1Point = None
    for s in signatures:
        agg = c.g1_add(agg, _decode_sig(s))
    return b58encode(g1_to_bytes(agg))


def aggregate_verkeys(verkeys: Sequence[str]) -> c.G2Point:
    agg: c.G2Point = None
    for v in verkeys:
        agg = c.g2_add(agg, _decode_vk(v))
    return agg


def batch_coefficients(n: int) -> list[int]:
    """n fresh 128-bit odd (hence nonzero) scalars for the random-linear-
    combination batch check. They MUST be unpredictable and freshly drawn
    per batch: under a fixed or replayable combination an adversary who
    learns the coefficients can submit a signature pair whose errors cancel
    under exactly that combination and have both accepted. 128 bits keeps
    the cheat probability at 2^-127 while the G1/G2 ladders stay half the
    length of full-width R scalars."""
    return [int.from_bytes(os.urandom(16), "big") | 1 for _ in range(n)]


def _combined_pairs(entries: Sequence[tuple]) -> list:
    """THE random-linear-combination construction, shared by every batch
    check (soundness-critical — one copy only): decoded (sig_pt, msg_bytes,
    pk_pt) triples -> the pairing_check pair list
    [(G2, -Σrᵢσᵢ)] + [(Σ_{mᵢ=m} rᵢpkᵢ, H(m)) per distinct m], under fresh
    coefficients."""
    coeffs = batch_coefficients(len(entries))
    agg_sig: c.G1Point = None
    by_msg: dict[bytes, c.G2Point] = {}
    for (sig, msg, pk), r in zip(entries, coeffs):
        agg_sig = c.g1_add(agg_sig, c.g1_mul(sig, r))
        by_msg[msg] = c.g2_add(by_msg.get(msg), c.g2_mul(pk, r))
    return [(c.G2_GEN, c.g1_neg(agg_sig))] + \
        [(pk, c.hash_to_g1(msg, _MSG_DOMAIN)) for msg, pk in by_msg.items()]


def batch_verify_combined(items: Sequence[tuple[str, bytes, str]]) -> bool:
    """ONE pairing_check over n (signature, message, verkey) triples.

    Random linear combination (Benitez-Correa et al., arXiv:2302.00418 —
    batched verification is the deciding factor for committee-consensus
    throughput): draw fresh rᵢ, then every σᵢ is simultaneously valid
    (w.p. 1 - 2^-127) iff

        e(-Σ rᵢσᵢ, G2) · ∏_m e(H(m), Σ_{i: mᵢ=m} rᵢ·pkᵢ) == 1.

    Grouping by distinct message means the commit path — n signatures over
    ONE state-root value — costs 2 pairings total (amortized O(1) in n),
    plus n short half-width scalar ladders. Unlike plain aggregation
    (Σσᵢ vs Σpkᵢ), a passing combined check certifies each signature
    INDIVIDUALLY: a pair of bad signatures whose errors cancel under plain
    addition cannot cancel under unknown fresh coefficients.

    False on any malformed input (same contract as verify); raises nothing.
    """
    items = list(items)
    if not items:
        return True
    try:
        entries = [(_decode_sig(s), m, _decode_vk(v)) for s, m, v in items]
    except (ValueError, KeyError):
        return False
    return c.pairing_check(_combined_pairs(entries))


def verify_multi_sig(signature: str, message: bytes,
                     verkeys: Sequence[str]) -> bool:
    """Verify an aggregated signature by all of `verkeys` over one message
    (ref Bls.verify_multi_sig :94 — PoP model, so plain key aggregation)."""
    if not verkeys:
        return False
    try:
        sig = _decode_sig(signature)
        pk = aggregate_verkeys(verkeys)
    except (ValueError, KeyError):
        return False
    h = c.hash_to_g1(message, _MSG_DOMAIN)
    return c.pairing_check([(c.G2_GEN, c.g1_neg(sig)), (pk, h)])


# --- provider seam (ref crypto/bls/bls_crypto.py ABCs) ----------------------

class BlsCryptoSigner:
    """Holds this node's BLS secret; signs state roots during COMMIT."""

    def __init__(self, seed: Optional[bytes] = None):
        self._key = BlsSignKey(seed)

    @property
    def pk(self) -> str:
        return self._key.verkey

    def sign(self, message: bytes) -> str:
        return self._key.sign(message)

    def generate_pop(self) -> str:
        return self._key.generate_pop()

    @staticmethod
    def generate_keys(seed: Optional[bytes] = None) -> tuple[str, str]:
        """(verkey, pop) for key-distribution txns (ref bls_key_manager)."""
        key = BlsSignKey(seed)
        return key.verkey, key.generate_pop()


# Process-wide verdict cache for the per-batch pairing checks, shared by
# every BlsCryptoVerifier: in a co-hosted topology each node runs the
# IDENTICAL aggregate check (same multi-sig, same state root, same
# participant set) at order time, and a pairing costs ~4 ms. One shared
# digest/eviction implementation (crypto/ed25519.py) serves every
# verdict cache in the package.
from plenum_tpu.crypto.ed25519 import (content_digest as _bls_verdict_key,
                                       verdict_cache_put as _cache_put)

_BLS_VERDICTS: dict[bytes, bool] = {}
_BLS_VERDICTS_MAX = 16384

# Process-wide named counters for the BLS batch-verify plane: how often
# the one-pairing combined fast path settled a batch vs fell back to
# per-signature culprit naming (malformed input or a failing combined
# check). Sampled by the node's metric flush as cumulative gauges — a
# rising fallback rate is the operator's first sign of a bad signer (or
# a bug) long before throughput moves.
BATCH_STATS = {"batches": 0, "combined_ok": 0, "fallbacks": 0,
               "per_sig_checks": 0}


def _bls_cache_put(key: bytes, verdict: bool) -> bool:
    return _cache_put(_BLS_VERDICTS, _BLS_VERDICTS_MAX, key, verdict)


class BlsCryptoVerifier:
    """Stateless verification provider; caches decoded verkeys."""

    def __init__(self):
        self._vk_cache: dict[str, c.G2Point] = {}

    def _pk(self, verkey: str) -> c.G2Point:
        pt = self._vk_cache.get(verkey)
        if pt is None:
            pt = _decode_vk(verkey)
            self._vk_cache[verkey] = pt
        return pt

    def evict_key(self, verkey) -> None:
        """Key rotation: drop the rotated-out verkey's decoded point from
        the key table (node._on_pool_changed calls this for every BLS
        rotation it observes). Verdict caches are content-keyed — they
        cannot return a wrong answer for the new key — but a dead key's
        warm decode row is cache budget a Byzantine signer leans on."""
        if isinstance(verkey, str):
            self._vk_cache.pop(verkey, None)

    def is_wellformed_sig(self, signature: str) -> bool:
        """Structural check only (b58 + on-curve): the cheap gate used by
        deferred COMMIT validation; the pairing runs later in aggregate."""
        try:
            _decode_sig(signature)
            return True
        except (ValueError, KeyError):
            return False

    def verify_sig(self, signature: str, message: bytes, verkey: str) -> bool:
        key = _bls_verdict_key(b"sig", signature.encode(), message,
                               verkey.encode())
        hit = _BLS_VERDICTS.get(key)
        if hit is not None:
            return hit
        try:
            sig = _decode_sig(signature)
            pk = self._pk(verkey)
        except (ValueError, KeyError):
            return _bls_cache_put(key, False)
        h = c.hash_to_g1(message, _MSG_DOMAIN)
        return _bls_cache_put(key, c.pairing_check(
            [(c.G2_GEN, c.g1_neg(sig)), (pk, h)]))

    def verify_multi_sig(self, signature: str, message: bytes,
                         verkeys: Sequence[str]) -> bool:
        if not verkeys:
            return False
        key = _bls_verdict_key(b"multi", signature.encode(), message,
                               *sorted(v.encode() for v in verkeys))
        hit = _BLS_VERDICTS.get(key)
        if hit is not None:
            return hit
        try:
            sig = _decode_sig(signature)
            pk: c.G2Point = None
            for v in verkeys:
                pk = c.g2_add(pk, self._pk(v))
        except (ValueError, KeyError):
            return _bls_cache_put(key, False)
        h = c.hash_to_g1(message, _MSG_DOMAIN)
        return _bls_cache_put(key, c.pairing_check(
            [(c.G2_GEN, c.g1_neg(sig)), (pk, h)]))

    def batch_verify(self, items: Sequence[tuple[str, bytes, str]]
                     ) -> list[bool]:
        """Verdicts for n (signature, message, verkey) triples.

        Happy path — every signature honest — is ONE combined pairing_check
        (2 pairings when all messages agree, as Commit sigs do; see
        batch_verify_combined). Only when the combined check fails (or an
        input is malformed) does it fall back to per-signature 2-pairing
        checks, which name the culprit(s) exactly; those verdicts ride the
        process-wide cache, so re-checking a batch after evicting a bad
        signer costs one fresh combined check, not n pairings."""
        items = list(items)
        if not items:
            return []
        # A passing combined check certifies each signature INDIVIDUALLY
        # (unlike plain aggregation), so per-signature verdicts are shared
        # with verify_sig through the process-wide cache: co-hosted nodes
        # batch-checking the identical COMMIT set (sim pools, multi-replica
        # hosts) pay the pairings once per host, dict hits after.
        verdicts: list[Optional[bool]] = []
        cache_keys: list[bytes] = []
        for sig_b58, msg, vk_b58 in items:
            k = _bls_verdict_key(b"sig", sig_b58.encode(), msg,
                                 vk_b58.encode())
            cache_keys.append(k)
            verdicts.append(_BLS_VERDICTS.get(k))
        todo = [i for i, vd in enumerate(verdicts) if vd is None]
        if not todo:
            return [bool(v) for v in verdicts]
        BATCH_STATS["batches"] += 1
        decoded: dict[int, tuple] = {}
        malformed = False
        for i in todo:
            sig_b58, msg, vk_b58 = items[i]
            try:
                decoded[i] = (_decode_sig(sig_b58), msg, self._pk(vk_b58))
            except (ValueError, KeyError):
                malformed = True
        if not malformed:
            if c.pairing_check(_combined_pairs([decoded[i] for i in todo])):
                BATCH_STATS["combined_ok"] += 1
                for i in todo:
                    _bls_cache_put(cache_keys[i], True)
                    verdicts[i] = True
                return [bool(v) for v in verdicts]
        # combined check failed or input malformed: per-signature culprit
        # naming — counted, never silent (a rising rate flags a bad signer)
        BATCH_STATS["fallbacks"] += 1
        BATCH_STATS["per_sig_checks"] += len(todo)
        for i in todo:
            s, m, v = items[i]
            verdicts[i] = (i in decoded) and self.verify_sig(s, m, v)
        return [bool(v) for v in verdicts]

    def create_multi_sig(self, signatures: Sequence[str]) -> str:
        return aggregate_sigs(signatures)

    def verify_key_proof_of_possession(self, pop: str, verkey: str) -> bool:
        return verify_pop(pop, verkey)
