"""BN254 (alt_bn128) pairing arithmetic, built as an Fq2/Fq6/Fq12 tower.

Replaces the reference's native Hyperledger Ursa dependency
(crypto/bls/indy_crypto/bls_crypto_indy_crypto.py:6-10, Rust/AMCL BN254) with
an in-tree implementation: affine G1/G2 group law, optimal-Ate Miller loop on
twist coordinates with sparse line evaluations, and a split easy/hard final
exponentiation. Scalars and field elements are Python bigints on the host —
pairing stays CPU-side by design; only the batched signature planes
(Ed25519/SHA-256) go to the device (SURVEY.md §7 stage 2).

Curve: y² = x³ + 3 over Fq;  twist: y² = x³ + 3/ξ over Fq2, ξ = 9 + i,
D-type, untwist (x,y) → (x·w², y·w³) with w² = v, v³ = ξ.
"""
from __future__ import annotations

import ctypes
from typing import Optional, Tuple

try:
    from plenum_tpu.native import bn254_lib as _NATIVE
except Exception:                      # toolchain missing: pure Python only
    _NATIVE = None

# --- base field --------------------------------------------------------------

P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617
U = 4965661367192848881              # BN parameter
ATE_LOOP = 6 * U + 2                 # 29793968203157093288
B1 = 3                               # G1 curve coefficient

G1_GEN = (1, 2)
# Standard alt_bn128 G2 generator (x = x0 + x1·i, y = y0 + y1·i)
G2_GEN = (
    (10857046999023057135944570762232829481370756359578518086990519993285655852781,
     11559732032986387107991004021392285783925812861821192530917403151452391805634),
    (8495653923123431417604973247489272438418190587263600148770280649306958101930,
     4082367875863433681332203403145435568316851327593401208105741076214120093531),
)

Fq2 = Tuple[int, int]


def _inv(a: int) -> int:
    return pow(a, -1, P)


# --- native bridge (encodings match plenum_tpu/native/bn254.cpp) -------------

def _enc_g1(pt) -> bytes:
    if pt is None:
        return b"\x00" * 64
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def _dec_g1(data: bytes):
    if data == b"\x00" * 64:
        return None
    return (int.from_bytes(data[:32], "big"), int.from_bytes(data[32:], "big"))


def _enc_g2(pt) -> bytes:
    if pt is None:
        return b"\x00" * 128
    (x0, x1), (y0, y1) = pt
    return b"".join(v.to_bytes(32, "big") for v in (x0, x1, y0, y1))


def _dec_g2(data: bytes):
    if data == b"\x00" * 128:
        return None
    vals = [int.from_bytes(data[i:i + 32], "big") for i in range(0, 128, 32)]
    return ((vals[0], vals[1]), (vals[2], vals[3]))


def _native_call(fn, *args_then_outsize) -> Optional[bytes]:
    """Call fn(*byte_args, out_buffer); None on native failure (falls back)."""
    *args, out_size = args_then_outsize
    buf = ctypes.create_string_buffer(out_size)
    if fn(*args, buf) != 0:
        return None
    return buf.raw


# --- Fq2 = Fq[i]/(i²+1) ------------------------------------------------------

F2_ZERO: Fq2 = (0, 0)
F2_ONE: Fq2 = (1, 0)


def f2_add(a: Fq2, b: Fq2) -> Fq2:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a: Fq2, b: Fq2) -> Fq2:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a: Fq2) -> Fq2:
    return (-a[0] % P, -a[1] % P)


def f2_mul(a: Fq2, b: Fq2) -> Fq2:
    # Karatsuba: (a0+a1 i)(b0+b1 i) with i² = -1
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def f2_sqr(a: Fq2) -> Fq2:
    # (a0+a1 i)² = (a0+a1)(a0-a1) + 2 a0 a1 i
    t = a[0] * a[1]
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, (t + t) % P)


def f2_scalar(a: Fq2, k: int) -> Fq2:
    return (a[0] * k % P, a[1] * k % P)


def f2_conj(a: Fq2) -> Fq2:
    return (a[0], -a[1] % P)


def f2_inv(a: Fq2) -> Fq2:
    # 1/(a0+a1 i) = conj / (a0²+a1²)
    d = _inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * d % P, -a[1] * d % P)


def f2_pow(a: Fq2, e: int) -> Fq2:
    out = F2_ONE
    while e:
        if e & 1:
            out = f2_mul(out, a)
        a = f2_sqr(a)
        e >>= 1
    return out


XI: Fq2 = (9, 1)                     # the sextic-twist non-residue


def f2_mul_xi(a: Fq2) -> Fq2:
    # (a0 + a1 i)(9 + i) = 9a0 - a1 + (a0 + 9a1) i
    return ((9 * a[0] - a[1]) % P, (a[0] + 9 * a[1]) % P)


# --- Fq6 = Fq2[v]/(v³-ξ) -----------------------------------------------------

Fq6 = Tuple[Fq2, Fq2, Fq2]
F6_ZERO: Fq6 = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE: Fq6 = (F2_ONE, F2_ZERO, F2_ZERO)


def f6_add(a: Fq6, b: Fq6) -> Fq6:
    return (f2_add(a[0], b[0]), f2_add(a[1], b[1]), f2_add(a[2], b[2]))


def f6_sub(a: Fq6, b: Fq6) -> Fq6:
    return (f2_sub(a[0], b[0]), f2_sub(a[1], b[1]), f2_sub(a[2], b[2]))


def f6_neg(a: Fq6) -> Fq6:
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def f6_mul(a: Fq6, b: Fq6) -> Fq6:
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(t0, f2_mul_xi(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)),
                                     f2_add(t1, t2))))
    c1 = f2_add(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), f2_add(t0, t1)),
                f2_mul_xi(t2))
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def f6_sqr(a: Fq6) -> Fq6:
    return f6_mul(a, a)


def f6_mul_v(a: Fq6) -> Fq6:
    """Multiply by v: (c0,c1,c2) → (ξ·c2, c0, c1)."""
    return (f2_mul_xi(a[2]), a[0], a[1])


def f6_inv(a: Fq6) -> Fq6:
    a0, a1, a2 = a
    c0 = f2_sub(f2_sqr(a0), f2_mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_inv(f2_add(f2_mul(a0, c0),
                      f2_add(f2_mul_xi(f2_mul(a2, c1)), f2_mul_xi(f2_mul(a1, c2)))))
    return (f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t))


# --- Fq12 = Fq6[w]/(w²-v) ----------------------------------------------------

Fq12 = Tuple[Fq6, Fq6]
F12_ONE: Fq12 = (F6_ONE, F6_ZERO)


def f12_mul(a: Fq12, b: Fq12) -> Fq12:
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_v(t1))
    c1 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), f6_add(t0, t1))
    return (c0, c1)


def f12_sqr(a: Fq12) -> Fq12:
    a0, a1 = a
    t = f6_mul(a0, a1)
    c0 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(a0, f6_mul_v(a1))),
                f6_add(t, f6_mul_v(t)))
    return (c0, f6_add(t, t))


def f12_inv(a: Fq12) -> Fq12:
    a0, a1 = a
    t = f6_inv(f6_sub(f6_sqr(a0), f6_mul_v(f6_sqr(a1))))
    return (f6_mul(a0, t), f6_neg(f6_mul(a1, t)))


def f12_conj(a: Fq12) -> Fq12:
    """a^(p⁶): conjugation over Fq6 (negate the w-odd half)."""
    return (a[0], f6_neg(a[1]))


def f12_pow(a: Fq12, e: int) -> Fq12:
    if e < 0:
        return f12_pow(f12_conj(a), -e)  # valid only for unitary elements
    out = F12_ONE
    while e:
        if e & 1:
            out = f12_mul(out, a)
        a = f12_sqr(a)
        e >>= 1
    return out


# Frobenius coefficients: γ1[j] = ξ^(j(p-1)/6), j = 1..5 (computed once).
_G1C = [f2_pow(XI, j * (P - 1) // 6) for j in range(6)]
_G2C = [f2_mul(f2_conj(c), c) for c in _G1C]          # γ2[j] = γ1[j]^(p+1) — norm, in Fq
_G3C = [f2_mul(f2_conj(_G2C[j]), _G1C[j]) for j in range(6)]


def f12_frobenius(a: Fq12, power: int = 1) -> Fq12:
    """a^(p^power) for power in {1, 2, 3}."""
    coeffs = (None, _G1C, _G2C, _G3C)[power]
    conj = power % 2 == 1
    # a = Σ_{j=0..5} c_j · w^j with c_j ∈ Fq2 laid out as:
    # w⁰→a0.c0, w¹→a1.c0, w²→a0.c1, w³→a1.c1, w⁴→a0.c2, w⁵→a1.c2
    (c0, c2, c4), (c1, c3, c5) = a
    cs = [c0, c1, c2, c3, c4, c5]
    out = []
    for j, c in enumerate(cs):
        if conj:
            c = f2_conj(c)
        if j:
            c = f2_mul(c, coeffs[j])
        out.append(c)
    return ((out[0], out[2], out[4]), (out[1], out[3], out[5]))


# --- G1 (affine, None = infinity) -------------------------------------------

G1Point = Optional[Tuple[int, int]]


def g1_is_on_curve(pt: G1Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B1) % P == 0


def g1_add(a: G1Point, b: G1Point) -> G1Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * _inv(2 * y1) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def g1_neg(a: G1Point) -> G1Point:
    return None if a is None else (a[0], -a[1] % P)


def g1_mul(a: G1Point, k: int) -> G1Point:
    k %= R
    if _NATIVE is not None and a is not None and k:
        out = _native_call(_NATIVE.pc_g1_mul, _enc_g1(a),
                           k.to_bytes(32, "big"), 64)
        if out is not None:
            return _dec_g1(out)
    out: G1Point = None
    while k:
        if k & 1:
            out = g1_add(out, a)
        a = g1_add(a, a)
        k >>= 1
    return out


# --- G2 (affine on the twist, None = infinity) -------------------------------

G2Point = Optional[Tuple[Fq2, Fq2]]
B2: Fq2 = f2_mul((3, 0), f2_inv(XI))


def g2_is_on_curve(pt: G2Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    return f2_sub(f2_sqr(y), f2_add(f2_mul(f2_sqr(x), x), B2)) == F2_ZERO


def g2_add(a: G2Point, b: G2Point) -> G2Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if f2_add(y1, y2) == F2_ZERO:
            return None
        lam = f2_mul(f2_scalar(f2_sqr(x1), 3), f2_inv(f2_scalar(y1, 2)))
    else:
        lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    x3 = f2_sub(f2_sqr(lam), f2_add(x1, x2))
    return (x3, f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1))


def g2_neg(a: G2Point) -> G2Point:
    return None if a is None else (a[0], f2_neg(a[1]))


def g2_mul(a: G2Point, k: int) -> G2Point:
    k %= R
    if _NATIVE is not None and a is not None and k:
        out = _native_call(_NATIVE.pc_g2_mul, _enc_g2(a),
                           k.to_bytes(32, "big"), 128)
        if out is not None:
            return _dec_g2(out)
    out: G2Point = None
    while k:
        if k & 1:
            out = g2_add(out, a)
        a = g2_add(a, a)
        k >>= 1
    return out


def g2_in_subgroup(pt: G2Point) -> bool:
    if not g2_is_on_curve(pt):
        return False
    if _NATIVE is not None and pt is not None:
        return bool(_NATIVE.pc_g2_in_subgroup(_enc_g2(pt)))
    return g2_mul(pt, R) is None


def g2_frobenius(pt: G2Point) -> G2Point:
    """π(x,y) = (x̄·ξ^((p-1)/3), ȳ·ξ^((p-1)/2)) — the untwist-Frobenius-twist map."""
    if pt is None:
        return None
    x, y = pt
    return (f2_mul(f2_conj(x), _FROB_X), f2_mul(f2_conj(y), _FROB_Y))


_FROB_X = f2_pow(XI, (P - 1) // 3)
_FROB_Y = f2_pow(XI, (P - 1) // 2)


# --- pairing -----------------------------------------------------------------

def _line(t: G2Point, q: G2Point, p1: Tuple[int, int]) -> Fq12:
    """Sparse Fq12 value of the line through T and Q (on the twist), evaluated
    at the G1 point P. Layout per untwist (x·w², y·w³):
    l = -yP + (λ'xP)·w + (yT' - λ'xT')·w³."""
    xp, yp = p1
    xt, yt = t
    if t == q:
        lam = f2_mul(f2_scalar(f2_sqr(xt), 3), f2_inv(f2_scalar(yt, 2)))
    elif xt == q[0]:
        # vertical line: l = xP - xT·w²
        return (((xp, 0), f2_neg(xt), F2_ZERO), F6_ZERO)
    else:
        lam = f2_mul(f2_sub(q[1], yt), f2_inv(f2_sub(q[0], xt)))
    c0: Fq2 = (-yp % P, 0)
    c1 = f2_scalar(lam, xp)
    c3 = f2_sub(yt, f2_mul(lam, xt))
    return ((c0, F2_ZERO, F2_ZERO), (c1, c3, F2_ZERO))


def miller_loop(q: G2Point, p1: G1Point) -> Fq12:
    if q is None or p1 is None:
        return F12_ONE
    f = F12_ONE
    t = q
    for i in range(ATE_LOOP.bit_length() - 2, -1, -1):
        f = f12_mul(f12_sqr(f), _line(t, t, p1))
        t = g2_add(t, t)
        if (ATE_LOOP >> i) & 1:
            f = f12_mul(f, _line(t, q, p1))
            t = g2_add(t, q)
    q1 = g2_frobenius(q)
    q2 = g2_neg(g2_frobenius(q1))
    f = f12_mul(f, _line(t, q1, p1))
    t = g2_add(t, q1)
    f = f12_mul(f, _line(t, q2, p1))
    return f


_HARD_EXP = (P ** 4 - P ** 2 + 1) // R


def final_exponentiation(f: Fq12) -> Fq12:
    # easy part: f^((p⁶-1)(p²+1))
    f = f12_mul(f12_conj(f), f12_inv(f))          # f^(p⁶-1); result is unitary
    f = f12_mul(f12_frobenius(f, 2), f)           # ^(p²+1)
    # hard part: plain square-and-multiply over (p⁴-p²+1)/r
    return f12_pow(f, _HARD_EXP)


def pairing(q: G2Point, p1: G1Point) -> Fq12:
    return final_exponentiation(miller_loop(q, p1))


def multi_pairing(pairs) -> Fq12:
    """∏ e(Qᵢ, Pᵢ) with a single shared final exponentiation."""
    f = F12_ONE
    for q, p1 in pairs:
        f = f12_mul(f, miller_loop(q, p1))
    return final_exponentiation(f)


# Process-wide pairing accounting. A pairing is the unit the commit path's
# cost is measured in (~2.6 ms native, ~100x that pure-Python), so the
# counters are cheap ints bumped once per check: `checks` = pairing_check
# calls, `pairings` = Miller loops inside them, split by which engine ran.
# Readers (bls_bft_replica's per-batch delta, the node's flush gauges) take
# snapshots; nothing resets these during a process lifetime.
PAIRING_STATS = {"checks": 0, "pairings": 0, "native": 0, "python": 0}


def pairing_check(pairs) -> bool:
    """True iff ∏ e(Qᵢ, Pᵢ) == 1 — the shape every BLS verification reduces to.

    Dispatches to the in-tree C++ library (plenum_tpu/native/bn254.cpp) when
    it built: the aggregate COMMIT check sits on the 3PC hot path, and the
    native multi-pairing is ~20× the pure-Python one. Falls back to the
    Python twin (the differential-testing reference) otherwise."""
    pairs = list(pairs)
    PAIRING_STATS["checks"] += 1
    PAIRING_STATS["pairings"] += len(pairs)
    if _NATIVE is not None:
        g2_bytes = b"".join(_enc_g2(q) for q, _ in pairs)
        g1_bytes = b"".join(_enc_g1(p) for _, p in pairs)
        res = _NATIVE.pc_pairing_check(g2_bytes, g1_bytes, len(pairs))
        if res >= 0:          # -1 = malformed input: let Python decide
            PAIRING_STATS["native"] += len(pairs)
            return bool(res)
    PAIRING_STATS["python"] += len(pairs)
    return multi_pairing(pairs) == F12_ONE


# --- hashing to G1 -----------------------------------------------------------

def g1_from_x(x: int) -> G1Point:
    """Lift x to a curve point if x³+3 is a QR (p ≡ 3 mod 4)."""
    y2 = (x * x * x + B1) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    return (x, y)


def hash_to_g1(data: bytes, domain: bytes = b"") -> Tuple[int, int]:
    """Try-and-increment hashing; deterministic, ~2 attempts expected."""
    import hashlib
    counter = 0
    while True:
        h = hashlib.sha256(domain + counter.to_bytes(4, "big") + data).digest()
        x = int.from_bytes(h, "big") % P
        pt = g1_from_x(x)
        if pt is not None:
            # canonicalize sign from one more hash bit for determinism
            if h[0] & 1:
                pt = g1_neg(pt)
            return pt
        counter += 1
